"""Classic node-path benchmark — the ra_bench parity run.

The reference's only benchmark drives REAL server processes over a
cluster with pipelined clients and a credit window
(/root/reference/src/ra_bench.erl:84-129, 153-190): `degree` client
processes each keep `pipe` commands in flight at low priority, counting
applied notifications; the workload target is 20,000 commands/sec
sustained (ra_bench.erl:54-69).  ra_tpu's lane engine benches the
vectorized path; THIS file benches the full-featured classic path — the
one that carries every feature (durable WAL + segments, membership,
snapshots) — in two phases:

  A. "local": 1 cluster x 3 members CO-HOSTED on one RaNode over one
     RaSystem — the shared-WAL deployment the group-commit fan-in is
     built for (ISSUE 13): every member's batch-appends land in ONE
     Wal, so one fdatasync covers all three members' bursts.  (Through
     r05 this phase ran 3 RaNodes with 3 private WALs; the co-hosted
     protocol measures the deployment the classic plane actually
     ships, see docs/BENCHMARKS.md.)
  B. "tcp": 1 cluster x 3 members, each member its own OS process
     behind a TcpRouter (the erlang-dist role), the client in the
     parent process pipelining over real sockets via the remote
     pipeline fan-in (multi-command frames, batch-encoded wire).

Machine: ra_bench's noop counter with a release_cursor every 100k
applies (ra_bench.erl:43-49); payloads are 256-byte blobs
(?DATA_SIZE, ra_bench.erl:34).  The machine implements the batched
apply fold (Machine.apply_batch) — order-equivalent to the per-entry
fold, exercised continuously by the oracle tests.

Prints ONE JSON line:
  {"metric": "classic_node_committed_cmds_per_sec", "value": <tcp phase>,
   "unit": "cmds/s", "vs_baseline": value/20000, "detail": {...}}
vs_baseline is against the reference workload target, 20k cmds/s.
Always exits 0; phase failures appear in detail.errors.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEGREE = int(os.environ.get("RA_TPU_CLASSIC_DEGREE", "5"))
PIPE = int(os.environ.get("RA_TPU_CLASSIC_PIPE", "500"))
SECONDS = float(os.environ.get("RA_TPU_CLASSIC_SECONDS", "10.0"))
DATA_SIZE = int(os.environ.get("RA_TPU_CLASSIC_DATA_SIZE", "256"))
RELEASE_EVERY = 100_000
TARGET = 20_000.0

#: commands a client sends per credit draw — amortizes the credit
#: lock/wake over a burst while keeping at most ``pipe`` in flight
BURST = 64


def _noop_machine():
    """ra_bench's machine: state counts applies, cursor released every
    100k so the log truncates (ra_bench.erl:43-49).  Implements the
    batched fold (ISSUE 13): replies are the running count, exactly
    what folding apply() over the run yields."""
    from ra_tpu.core.machine import Machine
    from ra_tpu.core.types import ReleaseCursor

    class NoopBench(Machine):
        def init(self, config):
            return 0

        def apply(self, meta, command, state):
            new = state + 1
            if meta.index % RELEASE_EVERY == 0:
                return new, new, [ReleaseCursor(meta.index, new)]
            return new, new

        def apply_batch(self, meta, commands, state):
            n = len(commands)
            new = state + n
            replies = list(range(state + 1, new + 1))
            # the run crosses at most one release point (runs are
            # bounded by the flush size << 100k): emit the same cursor
            # the per-entry fold would have
            base = meta.index
            k = ((base + n - 1) // RELEASE_EVERY) * RELEASE_EVERY
            if k >= base:
                return new, replies, [ReleaseCursor(k, state + k - base + 1)]
            return new, replies

    return NoopBench()


class _Client:
    """One pipelining client: keeps up to ``pipe`` commands in flight,
    counts applied notifications, samples enqueue->applied latency 1/16
    (ra_bench.erl:153-190 measures the same edge via ra_event applied
    batches).  Credit is drawn in bursts so the per-command cost of the
    measuring client itself stays off the measured plane's budget."""

    def __init__(self, cid: int, pipe: int):
        self.cid = cid
        self.pipe = pipe
        self.credit = pipe
        self.applied = 0
        self.lats: list = []
        self.inflight: dict = {}   # sampled corr -> t0 (1/16 of sends)
        #: credit-starvation resets: pipelined casts are fire-and-forget,
        #: so a dropped frame (full peer queue, broken conn) loses its
        #: acks and leaks credit — after 2s of zero credit with no acks
        #: the window refills and the reset is COUNTED, so a lossy run
        #: is visible in the row instead of silently idling a client
        self.credit_resets = 0
        self._lock = threading.Lock()
        self._have = threading.Event()

    def on_notify(self, batch) -> None:
        now = time.perf_counter()
        n = len(batch)
        with self._lock:
            self.applied += n
            inflight = self.inflight
            if inflight:
                for corr, _reply in batch:
                    t0 = inflight.pop(corr, None)
                    if t0 is not None:
                        self.lats.append(now - t0)
            self.credit += n
        self._have.set()

    def run(self, send, stop_evt, payload) -> None:
        seq = 0
        starved_since = None
        while not stop_evt.is_set():
            with self._lock:
                take = self.credit if self.credit < BURST else BURST
                self.credit -= take
            if take <= 0:
                self._have.clear()
                if self._have.wait(0.02):
                    starved_since = None
                    continue
                now = time.perf_counter()
                if starved_since is None:
                    starved_since = now
                elif now - starved_since > 2.0:
                    # leaked credits (dropped fire-and-forget frames):
                    # refill the window and count the reset
                    with self._lock:
                        self.credit = self.pipe
                        self.credit_resets += 1
                    starved_since = None
                continue
            starved_since = None
            corrs = []
            sampled = []
            for _ in range(take):
                corr = (self.cid, seq)
                if not (seq & 15):  # sample 1/16
                    sampled.append(corr)
                seq += 1
                corrs.append(corr)
            if sampled:
                t0 = time.perf_counter()
                with self._lock:
                    for corr in sampled:
                        self.inflight[corr] = t0
            try:
                send(payload, corrs, self.on_notify)
            except Exception:  # noqa: BLE001 — leader moved; retry path
                with self._lock:
                    self.credit += take
                    for corr in sampled:
                        self.inflight.pop(corr, None)
                time.sleep(0.05)


def _drive(send, warm_send) -> dict:
    """Run DEGREE clients against ``send`` for SECONDS; return the row."""
    payload = bytes(DATA_SIZE)
    clients = [_Client(i, PIPE) for i in range(DEGREE)]
    stop_evt = threading.Event()
    warm_send(payload)
    threads = [threading.Thread(target=c.run,
                                args=(send, stop_evt, payload), daemon=True)
               for c in clients]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(SECONDS)
    stop_evt.set()
    for t in threads:
        t.join(timeout=5)
    # drain: credit released after stop still counts applied work
    time.sleep(0.5)
    elapsed = time.perf_counter() - t0
    applied = sum(c.applied for c in clients)
    lats = sorted(x for c in clients for x in c.lats)
    n = len(lats)
    return {
        "value": round(applied / elapsed, 1),
        "applied": applied,
        "elapsed_s": round(elapsed, 3),
        "p50_applied_latency_ms":
            round(1000 * lats[n // 2], 3) if n else -1.0,
        "p99_applied_latency_ms":
            round(1000 * lats[min(n - 1, int(n * 0.99))], 3) if n else -1.0,
        "latency_samples": n,
        "degree": DEGREE, "pipe": PIPE, "data_size": DATA_SIZE,
        "seconds": SECONDS,
        # nonzero = the run lost fire-and-forget frames (see _Client)
        "credit_resets": sum(c.credit_resets for c in clients),
        "meets_reference_target": applied / elapsed >= TARGET,
    }


# ---------------------------------------------------------------------------
# phase A: in-process, co-hosted members over one shared-WAL RaSystem
# ---------------------------------------------------------------------------

def _phase_local() -> dict:
    import ra_tpu
    from ra_tpu.core.types import ServerId
    from ra_tpu.node import LocalRouter, RaNode
    from ra_tpu.system import RaSystem

    tmp = tempfile.mkdtemp(prefix="ra_classic_local_")
    router = LocalRouter()
    # ONE node + ONE system: the three members share the node's event
    # loop and — the group-commit fan-in (ISSUE 13) — one Wal, so every
    # member's batch-append rides the same fsync group
    system = RaSystem(tmp)
    node = RaNode("bn", router=router, log_factory=system.log_factory)
    sids = [ServerId(f"b{i}", "bn") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("classic", _noop_machine, sids, router=router,
                             election_timeout_ms=500, tick_interval_ms=100)
        res = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                res = ra_tpu.process_command(sids[0], bytes(8),
                                             router=router, timeout=5.0)
                break
            except TimeoutError:
                pass
        assert res is not None, "no leader elected"
        leader = res.leader

        def send(payload, corrs, cb):
            # untraced bulk pipelining (the reference's cast carries no
            # tracing either) — the measured path is the data plane,
            # not the per-command observability plane; the whole credit
            # burst rides ONE ingress call (ISSUE 18)
            ra_tpu.pipeline_commands(leader, [(payload, c) for c in corrs],
                                     notify_to=cb, router=router,
                                     trace_ctx=False)

        def warm(payload):
            ra_tpu.process_command(leader, payload, router=router)

        row = _drive(send, warm)
        row["members"] = 3
        row["transport"] = "in-process (co-hosted, shared WAL)"
        row["durable"] = True
        # replication-batching health (CLASSIC_FIELDS, ISSUE 13): AER
        # batch sizes from the cores + the shared WAL's group-commit
        # fan-in factor, stamped next to each other
        wal_stats = system.wal.stats()
        row["classic_batch"] = {
            **node.classic_stats(),
            "records_per_fsync": wal_stats["records_per_fsync"],
        }
        # codec encode share at the row top level (ISSUE 18): the
        # lower-better key bench_diff compares across rounds
        row["encode_share_pct"] = row["classic_batch"].get(
            "encode_share_pct", -1.0)
        # unified Observatory snapshot of the shared system (WAL fsync
        # p50/p99 + queue depth, segment writer, disk faults) with the
        # classic batching stats wired in as their own source
        obs = system.observatory()
        obs.add_source("classic", node.classic_stats)
        row["observatory"] = obs.snapshot()
        obs.close()
        return row
    finally:
        node.stop()
        system.close()
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# phase B: one OS process per member over TCP
# ---------------------------------------------------------------------------

def _tcp_member_main(node_name, port_map, data_dir, ready_q, stop_q):
    """One cluster member in its own process (the ct_slave peer-VM role,
    erlang_node_helpers.erl:12-48)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ra_tpu.core.types import ServerConfig, ServerId
    from ra_tpu.node import RaNode
    from ra_tpu.system import RaSystem
    from ra_tpu.transport.tcp import TcpRouter

    router = TcpRouter(("127.0.0.1", port_map[node_name]),
                       {n: ("127.0.0.1", p) for n, p in port_map.items()
                        if n != node_name})
    system = RaSystem(data_dir)
    node = RaNode(node_name, router=router, log_factory=system.log_factory)
    member_names = sorted(n for n in port_map if n != "client")
    sids = [ServerId(f"m_{n}", n) for n in member_names]
    me = ServerId(f"m_{node_name}", node_name)
    node.start_server(ServerConfig(
        server_id=me, uid=f"uid_{node_name}", cluster_name="classic_tcp",
        initial_members=tuple(sids), machine=_noop_machine(),
        election_timeout_ms=800, tick_interval_ms=200,
        log_init_args={"data_dir": data_dir}))
    ready_q.put(("ready", node_name))
    stop_q.get()          # block until the parent says stop
    node.stop()
    router.stop()
    ready_q.put(("stopped", node_name))


def _phase_tcp() -> dict:
    import multiprocessing as mp

    import ra_tpu
    from ra_tpu.core.types import ForceElectionEvent, ServerId
    from ra_tpu.transport.tcp import TcpRouter

    ctx = mp.get_context("spawn")
    names = ["cn1", "cn2", "cn3"]
    # bind ephemeral listeners up front so the port map is collision-free
    import socket as _socket
    socks = []
    port_map = {}
    for n in names + ["client"]:
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port_map[n] = s.getsockname()[1]
        socks.append(s)
    for s in socks:
        s.close()

    tmp = tempfile.mkdtemp(prefix="ra_classic_tcp_")
    ready_q = ctx.Queue()
    stop_qs = {n: ctx.Queue() for n in names}
    procs = [ctx.Process(target=_tcp_member_main,
                         args=(n, port_map, os.path.join(tmp, n),
                               ready_q, stop_qs[n]), daemon=True)
             for n in names]
    for p in procs:
        p.start()
    client = None
    try:
        for _ in names:   # readiness handshake (1-core box: slow imports)
            msg = ready_q.get(timeout=180)
            assert msg[0] == "ready", msg
        client = TcpRouter(("127.0.0.1", port_map["client"]),
                           {n: ("127.0.0.1", port_map[n]) for n in names})
        sids = [ServerId(f"m_{n}", n) for n in names]
        client.send("?", sids[0], ForceElectionEvent())
        res = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                res = ra_tpu.process_command(sids[0], bytes(8),
                                             router=client, timeout=5.0)
                break
            except TimeoutError:
                client.send("?", sids[0], ForceElectionEvent())
        assert res is not None, "no leader elected over TCP"
        leader = res.leader

        def send(payload, corrs, cb):
            # the remote pipeline fan-in (ISSUE 13): commands buffer
            # client-side and ship as multi-command frames; followers
            # relay a stale-leader batch, so a mid-run election costs
            # one hop, not an exception storm; the burst rides ONE
            # buffer-lock cycle (ISSUE 18)
            ra_tpu.pipeline_commands(leader, [(payload, c) for c in corrs],
                                     notify_to=cb, router=client,
                                     trace_ctx=False)

        def warm(payload):
            ra_tpu.process_command(leader, payload, router=client)

        row = _drive(send, warm)
        row["members"] = 3
        row["transport"] = "tcp (3 OS processes)"
        row["durable"] = True
        # frame-loss visibility for the fire-and-forget client path
        # (pairs with the row's credit_resets counter)
        row["client_dropped_sends"] = client.dropped_sends
        # the leader worker's replication-batching health over the
        # control plane (ISSUE 13 — the tail carries the same
        # CLASSIC_FIELDS shape as the local phase)
        try:
            row["classic_batch"] = ra_tpu.node_call(
                leader.node, "classic_stats", {}, router=client,
                timeout=30)
        except (RuntimeError, TimeoutError) as exc:
            row["classic_batch"] = {"error": repr(exc)[:200]}
        # codec encode share at the row top level (ISSUE 18), same key
        # as the local phase so bench_diff tracks both rows
        row["encode_share_pct"] = row["classic_batch"].get(
            "encode_share_pct", -1.0)
        # client-side Observatory: the reliable-RPC counters (retries,
        # dedup hits, unreachable) ride the classic JSON tail like the
        # WAL stats do on the local phase (ISSUE 7 satellite — the
        # member systems live in worker processes, so the client
        # router's control-plane view is what this process can stamp)
        from ra_tpu.telemetry import Observatory
        obs = Observatory.for_system(None, router=client)
        row["observatory"] = obs.snapshot()
        obs.close()
        return row
    finally:
        if client is not None:
            client.stop()
        for n in names:
            stop_qs[n].put("stop")
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
        shutil.rmtree(tmp, ignore_errors=True)


def _host_meta() -> dict:
    import bench
    return bench._host_meta()


def main() -> None:
    # the classic plane co-hosts with the lane engine on one node, so
    # the round JSON records the system-level dispatch-pipeline tunables
    # (superstep_k/dispatch_ahead) the lane plane would resolve on this
    # host — cross-round comparisons need both planes' config in one doc
    from ra_tpu.system import engine_pipeline_defaults
    detail: dict = {"host": _host_meta(), "errors": {},
                    "engine_pipeline": engine_pipeline_defaults()}
    for name, phase in (("local", _phase_local), ("tcp", _phase_tcp)):
        try:
            detail[name] = phase()
        except Exception as exc:  # noqa: BLE001 — contract: always JSON
            detail["errors"][name] = repr(exc)[:500]
    value = (detail.get("tcp") or detail.get("local") or {}).get("value", 0.0)
    # device-plane tail (ISSUE 16): the classic plane is host-only, so
    # these stamp as zeros on purpose — a nonzero n_compiles here means
    # something dragged jit dispatch into the classic path
    from ra_tpu import devicewatch
    print(json.dumps({
        "metric": "classic_node_committed_cmds_per_sec",
        "value": value,
        "unit": "cmds/s",
        "vs_baseline": round(value / TARGET, 4),
        "detail": detail,
        **devicewatch.bench_tail_keys(),
    }))


if __name__ == "__main__":
    try:
        main()
    except BaseException as exc:  # noqa: BLE001
        print(json.dumps({
            "metric": "classic_node_committed_cmds_per_sec",
            "value": 0.0, "unit": "cmds/s", "vs_baseline": 0.0,
            "error": f"crashed: {type(exc).__name__}",
            "detail": {"exception": repr(exc)[:500]},
        }))
    sys.exit(0)
