"""ra-tpu headline benchmark.

The ra_bench-equivalent workload at the BASELINE.md north-star config:
N concurrent M-member Raft clusters, counter machine (ra_bench's noop/'+'
machine, /root/reference/src/ra_bench.erl:43-49), sustained pipelined
commands, measuring **committed commands/sec** with quorum decisions
computed on-TPU.

Baseline (BASELINE.md): 10,000 clusters x 5 members >= 1,000,000 committed
cmds/sec on a single chip.  vs_baseline = value / 1e6.

Prints ONE JSON line.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

N_LANES = 10_000
N_MEMBERS = 5
CMDS_PER_STEP = 128          # per-lane pipelined batch per round
WARMUP_STEPS = 5
MEASURE_SECONDS = 5.0
BASELINE = 1_000_000.0       # north-star committed cmds/sec


def main() -> None:
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    import os
    quorum_impl = os.environ.get("RA_TPU_QUORUM_IMPL", "xla")
    eng = LockstepEngine(CounterMachine(), N_LANES, N_MEMBERS,
                         ring_capacity=1024, max_step_cmds=CMDS_PER_STEP,
                         apply_window=CMDS_PER_STEP + 2, write_delay=1,
                         quorum_impl=quorum_impl)

    n_new = jnp.full((N_LANES,), CMDS_PER_STEP, jnp.int32)
    payloads = jnp.ones((N_LANES, CMDS_PER_STEP, 1), jnp.int32)

    for _ in range(WARMUP_STEPS):
        eng.step(n_new, payloads)
    eng.block_until_ready()
    start_committed = eng.committed_total()

    steps = 0
    t0 = time.perf_counter()
    while True:
        eng.step(n_new, payloads)
        steps += 1
        if steps % 20 == 0:
            eng.block_until_ready()
            if time.perf_counter() - t0 >= MEASURE_SECONDS:
                break
    eng.block_until_ready()
    elapsed = time.perf_counter() - t0
    committed = eng.committed_total() - start_committed

    # latency phase: per-step wall times with a sync per step; a command
    # enqueued at step k commits at step k+1 (write_delay=1), so commit
    # latency ~= 2 step times.  p99 over the measured distribution.
    lat = []
    for _ in range(50):
        t1 = time.perf_counter()
        eng.step(n_new, payloads)
        eng.block_until_ready()
        lat.append(time.perf_counter() - t1)
    lat.sort()
    p99_step = lat[int(len(lat) * 0.99) - 1]
    p50_step = lat[len(lat) // 2]

    value = committed / elapsed
    print(json.dumps({
        "metric": "committed_cmds_per_sec_10k_clusters_5_members",
        "value": round(value, 1),
        "unit": "cmds/s",
        "vs_baseline": round(value / BASELINE, 4),
        "detail": {
            "quorum_impl": quorum_impl,
            "lanes": N_LANES, "members": N_MEMBERS,
            "cmds_per_step": CMDS_PER_STEP, "steps": steps,
            "elapsed_s": round(elapsed, 3),
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "p50_commit_latency_ms": round(2000.0 * p50_step, 3),
            "p99_commit_latency_ms": round(2000.0 * p99_step, 3),
        },
    }))


if __name__ == "__main__":
    main()
