"""ra-tpu headline benchmark.

The ra_bench-equivalent workload at the BASELINE.md north-star config:
N concurrent M-member Raft clusters, counter machine (ra_bench's noop/'+'
machine, /root/reference/src/ra_bench.erl:43-49), sustained pipelined
commands, measuring **committed commands/sec** with quorum decisions
computed on-TPU.

Baseline (BASELINE.md): 10,000 clusters x 5 members >= 1,000,000 committed
cmds/sec on a single chip.  vs_baseline = value / 1e6.

Robustness contract (this script must never leave the driver without a
number): the parent process never imports jax — it probes the backend in a
subprocess under a timeout, runs each measurement in a child under a
timeout, retries once, and on TPU unavailability emits a valid JSON line
with an explicit ``"error": "tpu_unavailable"`` marker plus a CPU smoke
datapoint (run with the axon site hook stripped so backend init cannot
hang).  Always prints ONE JSON line; always exits 0.

Latency is measured honestly AND without serializing the dispatch
pipeline (ISSUE 5): per sample, the batch is enqueued at step E and the
engine's per-lane committed watermark is harvested through ASYNC
readbacks only — the first readback step O whose cumulative count
covers the batch is the observed-commit step, and p50/p99 derive from
(O - E + 1) x the sample's measured per-step time.  Host syncs happen
only at sample window boundaries (lint rule RA04 polices this).

Superstep mode (``--superstep [K]`` or RA_TPU_BENCH_SUPERSTEP): the
throughput phase fuses K engine rounds per XLA dispatch and drives them
through the dispatch-ahead staging driver (see
ra_tpu/engine/lockstep.py), reporting the single-step reference value
and the realized speedup alongside.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE = 1_000_000.0       # north-star committed cmds/sec
N_LANES = 10_000
N_MEMBERS = 5
CMDS_PER_STEP = 128          # per-lane pipelined batch per round

PROBE_TIMEOUT_S = 120
CHILD_TIMEOUT_S = 480

#: the documented latency-mode operating point (docs/BENCHMARKS.md):
#: pipelined batches of 32 cmds/lane with a 4-deep unacked window
FRONTIER_DEFAULT_CMDS = 32
FRONTIER_DEFAULT_WINDOW = 4


#: committed real-TPU capture dir for THIS round (tools/tpu_watch.sh)
CAPTURE_DIR = "tpu_rows_r05"


def _load_captured_tpu_rows():
    """Summarize the committed real-TPU rows in ``CAPTURE_DIR`` (written
    by tools/tpu_watch.sh) as a name->row dict, or None if no TPU
    headline row exists.  A corrupt/partial secondary row is skipped,
    not fatal.  These are PRIOR measurements: the caller must report
    them as supplementary evidence (detail), never as the live headline
    value — bench.py cannot prove they were produced by the current
    code revision."""
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        CAPTURE_DIR)
    keep = ("value", "p50_commit_latency_ms", "p99_commit_latency_ms",
            "platform", "machine", "lanes", "members", "durable",
            "quorum_impl", "fifo_capacity", "host")
    rows = {}
    for name in ("headline_xla", "headline_pallas", "fifo_5k",
                 "kv_2k", "durable", "frontier"):
        path = os.path.join(base, f"{name}.json")
        try:
            with open(path) as f:
                row = json.load(f)
            if not isinstance(row, dict) or row.get("platform") != "tpu":
                continue
            rows[name] = {k: row[k] for k in keep if k in row}
            if name == "frontier":
                rows[name]["best_point"] = row.get("best_point")
                rows[name]["default_point"] = row.get("default_point")
        except (OSError, ValueError, KeyError, TypeError):
            continue
    headline = rows.get("headline_xla")
    if not headline or not headline.get("value"):
        return None
    return rows


def _host_meta() -> dict:
    """Environment stamp for cross-round comparability: the same config
    read 112.8M cmds/s in BENCH_r02 but 33.7M in BENCH_r04 because the
    host differed — without this stamp a reader cannot tell environment
    drift from regression."""
    meta = {"unknown": True}
    try:
        import platform as _pf
        model = ""
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("model name"):
                        model = line.split(":", 1)[1].strip()
                        break
        except OSError:
            pass
        meta = {
            "hostname": _pf.node(),
            "cpu_model": model,
            "cpu_count": os.cpu_count(),
            "loadavg_1m": round(os.getloadavg()[0], 2),
        }
        try:
            # host envelope (ISSUE 13 satellite): fd cap + core count,
            # the cross-host drift dimensions — one shared impl
            from ra_tpu.utils import host_envelope
            meta.update(host_envelope())
        except Exception:  # noqa: BLE001 — optional on exotic platforms
            pass
    except Exception:  # noqa: BLE001 — metadata must never kill a bench
        pass
    return meta


# ---------------------------------------------------------------------------
# child mode: one measurement in one process (safe to kill from the parent)
# ---------------------------------------------------------------------------

def _child_main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    n_lanes = int(os.environ.get("RA_TPU_BENCH_LANES", N_LANES))
    n_members = int(os.environ.get("RA_TPU_BENCH_MEMBERS", N_MEMBERS))
    cmds = int(os.environ.get("RA_TPU_BENCH_CMDS", CMDS_PER_STEP))
    measure_s = float(os.environ.get("RA_TPU_BENCH_SECONDS", "5.0"))
    quorum_impl = os.environ.get("RA_TPU_QUORUM_IMPL", "xla")
    machine_name = os.environ.get("RA_TPU_BENCH_MACHINE", "counter")
    # fused-dispatch config (ISSUE 5): K rounds per XLA dispatch + the
    # dispatch-ahead staging depth; "auto" resolves the system-level
    # tunables (ra_tpu.system.engine_pipeline_defaults)
    from ra_tpu.system import engine_pipeline_defaults
    pipe_defaults = engine_pipeline_defaults()
    ss_env = os.environ.get("RA_TPU_BENCH_SUPERSTEP", "0")
    superstep_k = pipe_defaults["superstep_k"] if ss_env == "auto" \
        else int(ss_env)
    da_env = os.environ.get("RA_TPU_BENCH_DISPATCH_AHEAD", "auto")
    dispatch_ahead = pipe_defaults["dispatch_ahead"] if da_env == "auto" \
        else int(da_env)

    # BASELINE.md rows: counter (north star), fifo (5k x 5 enqueue/
    # dequeue), kv (2k mixed put/get with jittable apply)
    if machine_name == "fifo":
        from ra_tpu.models import JitFifoMachine
        # capacity 256: a realistic queue depth for the BASELINE row
        # (the round-4 review called the former 64 dimensionally a toy)
        machine = JitFifoMachine(
            capacity=int(os.environ.get("RA_TPU_BENCH_FIFO_CAP", "256")),
            checkout_slots=8)
        import numpy as np
        host_payloads = np.zeros((n_lanes, cmds, 3), np.int32)
        host_payloads[:, 0::2] = (1, 7, 0)     # enqueue 7
        host_payloads[:, 1::2] = (2, 0, 0)     # dequeue settled
        payloads = jnp.asarray(host_payloads)
    elif machine_name == "kv":
        from ra_tpu.models import JitKvMachine
        machine = JitKvMachine(n_keys=64)
        import numpy as np
        rng = np.random.default_rng(0)
        host_payloads = np.zeros((n_lanes, cmds, 4), np.int32)
        host_payloads[..., 0] = rng.integers(1, 3, (n_lanes, cmds))  # put/get
        host_payloads[..., 1] = rng.integers(0, 64, (n_lanes, cmds))
        host_payloads[..., 2] = rng.integers(0, 1000, (n_lanes, cmds))
        payloads = jnp.asarray(host_payloads)
    else:
        machine = CounterMachine()
        payloads = jnp.ones((n_lanes, cmds, 1), jnp.int32)

    durable = os.environ.get("RA_TPU_BENCH_DURABLE") == "1"
    if durable:
        # fsync-backed mode: every step's accepted entries go through the
        # sharded fan-in WAL and commits gate on the real confirm
        # (ra_log_wal.erl:753-800 — an entry counts only after
        # write(2)+fsync).  Lane shards each own their file, writer
        # thread and fsync, group-committing independently.
        import shutil
        import tempfile

        from ra_tpu.engine import open_engine
        dur_dir = tempfile.mkdtemp(prefix="ra_tpu_bench_wal_")
        sync_mode = int(os.environ.get("RA_TPU_BENCH_SYNC_MODE", "1"))
        wal_strategy = os.environ.get("RA_TPU_BENCH_WAL_STRATEGY",
                                      "default")
        # wal_shards defaults by core budget: each shard costs a writer
        # thread + an encode worker, and concurrent fsyncs only overlap
        # when the host has cores (and a disk) to run them — on the
        # 1-2 core CI boxes the sharding win is the compacted readback,
        # not fsync parallelism, so default to a single shard there
        auto_shards = min(4, max(1, (os.cpu_count() or 1) // 2))
        wal_shards = int(os.environ.get("RA_TPU_BENCH_WAL_SHARDS",
                                        str(auto_shards)))
        eng = open_engine(machine, dur_dir, n_lanes, n_members,
                          sync_mode=sync_mode,
                          write_strategy=wal_strategy, ring_capacity=1024,
                          max_step_cmds=cmds, apply_window=cmds + 2,
                          wal_shards=wal_shards,
                          # superstep: step_seq advances K per dispatch,
                          # so the unconfirmed-step window must cover a
                          # few dispatches or backpressure serializes
                          # the fused pipeline
                          max_pending=max(8, 4 * superstep_k),
                          quorum_impl=quorum_impl)
        import atexit
        atexit.register(lambda: shutil.rmtree(dur_dir, ignore_errors=True))
    else:
        eng = LockstepEngine(machine, n_lanes, n_members,
                             ring_capacity=1024, max_step_cmds=cmds,
                             apply_window=cmds + 2, write_delay=1,
                             quorum_impl=quorum_impl)

    # device-resident telemetry plane (ISSUE 6): ON by default at the
    # standard cadence — the headline number carries the observability
    # cost real deployments pay (<3% bound is test-pinned), and the
    # final Observatory snapshot lands in the JSON tail so cross-round
    # comparisons stop hand-collecting fsync/pipeline fields
    sampler = observatory = slo = tuner = None
    if os.environ.get("RA_TPU_BENCH_TELEMETRY", "1") != "0":
        from ra_tpu.telemetry import Observatory, TelemetrySampler
        sampler = TelemetrySampler(eng)
        observatory = Observatory.for_engine(eng, sampler=sampler)
        # SLO engine over the Observatory ring (ISSUE 9): periodic
        # snapshots during the measured phases feed the ring, and the
        # verdicts land in the JSON tail next to the phase attribution
        from ra_tpu.slo import SloEngine
        slo = SloEngine(observatory)
        if os.environ.get("RA_TPU_BENCH_AUTOTUNE") == "1":
            # opt-in closed loop: the tuner ticks at snapshot cadence
            # and its decisions/knobs ride the tail.  Knobs the loop
            # cannot APPLY are frozen via bounds: cmds_per_step is
            # baked into the staged payload buffers, and superstep_k
            # is only re-stageable on the fused path — a recorded
            # decision that changes nothing measured would make the
            # tail's knob stamps a lie.  The wal batch interval always
            # applies live (set_batch_interval_ms).
            from ra_tpu.autotune import AutoTuner
            k0 = max(1, superstep_k)
            tuner = AutoTuner(slo, observatory,
                              durability=eng._dur if durable else None,
                              bounds={"cmds_per_step": (cmds, cmds),
                                      "superstep_k": (1, 64)
                                      if superstep_k else (k0, k0)},
                              knobs={"superstep_k": k0,
                                     "cmds_per_step": cmds})

    # window-cadence observation: a host-only dict merge (the sources
    # read harvested sampler data + host counters — no device sync, so
    # the measured pipeline is untouched; the <3% A/B pin covers it)
    _obs_last = [0.0]

    def maybe_observe() -> None:
        now = time.perf_counter()
        if observatory is not None and now - _obs_last[0] >= 0.2:
            _obs_last[0] = now
            observatory.snapshot()
            if tuner is not None:
                tuner.tick()

    if durable:
        # host-resident batches: the per-step H2D copy is the honest
        # ingestion path (entries arrive from the host), and the durable
        # bridge needs the host bytes for the WAL record anyway
        import numpy as np
        payloads = np.asarray(payloads)
        n_new = np.full((n_lanes,), cmds, np.int32)
        zero_n = np.zeros((n_lanes,), np.int32)
        zero_p = np.zeros_like(payloads)
    else:
        n_new = jnp.full((n_lanes,), cmds, jnp.int32)
        zero_n = jnp.zeros((n_lanes,), jnp.int32)
        zero_p = jnp.zeros_like(payloads)

    for _ in range(5):
        eng.step(n_new, payloads)
    eng.block_until_ready()

    # -- throughput phase (BOUNDED in-flight window — the headline) -------
    # Dispatch runs at most `window` steps ahead of an observed commit
    # readback: the old unbounded loop let the tail commit sit in flight
    # for seconds (the 6,395ms p99 behind the round-5 112.4M headline),
    # so the headline row is now the bounded one and the unbounded
    # number is reported separately as an explicitly-labeled ceiling.
    # Durable mode is already window-bounded by the bridge's max_pending
    # backpressure (8 steps of unconfirmed WAL), so it keeps the plain
    # loop — adding a readback bound on top would double-serialize.
    import collections as _collections
    window = int(os.environ.get("RA_TPU_BENCH_THROUGHPUT_WINDOW", "8"))

    def run_unbounded(seconds: float):
        """Back-to-back dispatch with a device barrier every 20 steps —
        the unbounded measurement protocol, shared by the durable
        throughput phase (where the bridge's max_pending backpressure
        is the bound) and the ceiling phase."""
        n = 0
        t_start = time.perf_counter()
        while True:
            eng.step(n_new, payloads)
            n += 1
            if n % 20 == 0:
                eng.block_until_ready()  # ra04-ok: 20-step window boundary
                maybe_observe()
                if time.perf_counter() - t_start >= seconds:
                    break
        eng.block_until_ready()
        return n, time.perf_counter() - t_start

    def run_single_step(seconds: float):
        """The single-step measurement protocol: window-bounded async
        readbacks (volatile) or max_pending backpressure (durable)."""
        if durable:
            return run_unbounded(seconds)
        readbacks: "_collections.deque" = _collections.deque()
        n = 0
        t_start = time.perf_counter()
        while time.perf_counter() - t_start < seconds:
            eng.step(n_new, payloads)
            n += 1
            readbacks.append(eng.committed_lanes_async())
            while len(readbacks) > window:
                np.asarray(readbacks.popleft())  # ra04-ok: window boundary
            maybe_observe()
        eng.block_until_ready()
        return n, time.perf_counter() - t_start

    single_step_ref = None
    driver = None
    if superstep_k:
        # single-step reference at the same config first, so the fused
        # row carries its own dispatch-amortization evidence
        base_ref = eng.committed_total()
        ref_steps, ref_el = run_single_step(min(measure_s, 2.0))
        single_step_ref = {
            "value": round((eng.committed_total() - base_ref) / ref_el, 1),
            "steps": ref_steps,
            "elapsed_s": round(ref_el, 3),
        }
        # fused phase: K rounds per dispatch, host staging one block
        # ahead of device execution (the dispatch-ahead driver)
        from ra_tpu.engine import DispatchAheadDriver
        n_new_host = np.asarray(n_new)
        pay_host = np.asarray(payloads)
        n_new_blk = np.broadcast_to(n_new_host,
                                    (superstep_k,) + n_new_host.shape)
        pay_blk = np.broadcast_to(pay_host,
                                  (superstep_k,) + pay_host.shape)
        driver = DispatchAheadDriver(eng, max_in_flight=dispatch_ahead)
        for _ in range(2):
            driver.submit(n_new_blk, pay_blk)
        driver.drain()
        start_committed = eng.committed_total()
        dispatches = 0
        cur_k = superstep_k
        steps = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < measure_s:
            if tuner is not None and \
                    tuner.knobs["superstep_k"] != cur_k:
                # apply the controller's decision BETWEEN dispatches:
                # restage the block at the new fusion depth (broadcast
                # views — no payload copy)
                cur_k = tuner.knobs["superstep_k"]
                n_new_blk = np.broadcast_to(
                    n_new_host, (cur_k,) + n_new_host.shape)
                pay_blk = np.broadcast_to(
                    pay_host, (cur_k,) + pay_host.shape)
            driver.submit(n_new_blk, pay_blk)
            dispatches += 1
            steps += cur_k
            maybe_observe()
        driver.drain()  # run-end window boundary
        elapsed = time.perf_counter() - t0
    else:
        start_committed = eng.committed_total()
        steps, elapsed = run_single_step(measure_s)
    committed = eng.committed_total() - start_committed
    value = committed / elapsed

    # -- unbounded ceiling (capacity measurement, NOT an operating point)
    ceiling = None
    ceiling_s = float(os.environ.get("RA_TPU_BENCH_CEILING_SECONDS",
                                     str(min(measure_s, 2.0))))
    if ceiling_s > 0 and not durable:  # durable is window-bounded anyway
        base_c = eng.committed_total()
        csteps, celapsed = run_unbounded(ceiling_s)
        ceiling = {
            "value": round((eng.committed_total() - base_c) / celapsed, 1),
            "steps": csteps,
            "note": "unbounded in-flight window: a capacity ceiling "
                    "whose tail commits sit in flight for the whole "
                    "run (p99 collapse) — quote the bounded headline "
                    "value instead (docs/BENCHMARKS.md)",
        }

    # -- latency phase: on-device step stamping (ISSUE 5) -----------------
    # The old protocol spun on committed_total() — a blocking device
    # sync per spin that serialized the very pipeline the superstep
    # path builds.  Now a sample enqueues its batch at step E, drives
    # empty rounds each starting one ASYNC per-lane committed readback,
    # and syncs only at sample window boundaries.  The observed-commit
    # step O is the first readback whose cumulative count covers the
    # batch (inner-step resolution in superstep mode via the stacked
    # [K, N] watermark), and the sample's latency derives from step
    # counts x measured step time:
    #   latency = sample_elapsed * O / steps_in_sample.
    # The enqueue->commit edge in STEPS is exact; the milliseconds come
    # from the sample's own pipelined step rate.
    expected_per_sample = n_lanes * cmds
    lats = []
    truncated = 0
    spin = 32 if durable else 8  # durable: confirm lag is real
    max_windows = 4 if durable else 2
    if superstep_k:
        zp_host = np.asarray(zero_p)
        zero_nb = np.zeros((superstep_k, n_lanes), np.int32)
        zero_pb = np.zeros((superstep_k,) + zp_host.shape, zp_host.dtype)
        batch_nb = zero_nb.copy()
        batch_nb[0] = np.asarray(n_new)
        batch_pb = zero_pb.copy()
        batch_pb[0] = np.asarray(payloads)
    for _ in range(40):
        before = eng.committed_total()  # ra04-ok: pre-sample baseline
        handles = []  # (steps covered through, watermark readback)
        obs_step = None
        steps_done = 0
        checked = 0
        elapsed_sample = 0.0
        t1 = time.perf_counter()
        if superstep_k:
            aux = eng.superstep(batch_nb, batch_pb)
            steps_done += superstep_k
            handles.append((steps_done, aux["committed_lanes"] + 0))
        else:
            eng.step(n_new, payloads)
            steps_done += 1
            handles.append((steps_done, eng.committed_lanes_async()))
        for _w in range(max_windows):
            if superstep_k:
                for _ in range(max(1, spin // superstep_k)):
                    aux = eng.superstep(zero_nb, zero_pb)
                    steps_done += superstep_k
                    handles.append((steps_done,
                                    aux["committed_lanes"] + 0))
            else:
                for _ in range(spin):
                    eng.step(zero_n, zero_p)
                    steps_done += 1
                    handles.append((steps_done,
                                    eng.committed_lanes_async()))
            eng.block_until_ready()  # ra04-ok: sample window boundary
            elapsed_sample = time.perf_counter() - t1
            while checked < len(handles) and obs_step is None:
                hi_step, h = handles[checked]
                arr = np.asarray(h).astype(np.int64)  # ra04-ok: post-boundary harvest (already synced)
                if arr.ndim == 2:  # stacked [K, N]: inner-step resolution
                    cums = arr.sum(axis=1) - before
                    for k_in in range(arr.shape[0]):
                        if cums[k_in] >= expected_per_sample:
                            obs_step = hi_step - arr.shape[0] + k_in + 1
                            break
                elif int(arr.sum()) - before >= expected_per_sample:
                    obs_step = hi_step
                checked += 1
            if obs_step is not None:
                break
        if obs_step is None:
            # a sample whose commit was never observed must not pollute
            # the distribution with a bogus-low value
            truncated += 1
        else:
            lats.append(elapsed_sample * obs_step / steps_done)
        maybe_observe()  # sample boundary: feed the SLO ring a window
    lats.sort()
    p50 = lats[len(lats) // 2] if lats else -1.0
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))] if lats else -1.0

    if sampler is not None:
        sampler.drain()  # run-end barrier, after measurement
    overview = eng.overview()
    # device-plane tail (ISSUE 16): process-lifetime compile/transfer/
    # watermark totals — bench_diff flags round-over-round n_compiles
    # growth as a retrace regression
    from ra_tpu import devicewatch
    print(json.dumps({
        "value": round(value, 1),
        "committed": int(committed),
        "steps": steps,
        "elapsed_s": round(elapsed, 3),
        # durable: the max_pending WAL backpressure is the bound
        "in_flight_window_steps": "max_pending" if durable else (
            f"dispatch_ahead*{superstep_k}" if superstep_k else window),
        # fused-dispatch stamps (ISSUE 5): K=0 means the classic
        # single-step path; the pipeline dict carries the realized
        # dispatch/inner-step counters and driver sync counts
        "superstep_k": superstep_k,
        "dispatch_ahead": dispatch_ahead if superstep_k else 0,
        "pipeline": overview["pipeline"],
        **({"single_step_ref": single_step_ref,
            "speedup_vs_single_step":
                round(value / single_step_ref["value"], 3)
                if single_step_ref["value"] else -1.0}
           if single_step_ref else {}),
        **({"unbounded_ceiling": ceiling} if ceiling else {}),
        "latency_mode": "step_stamped",
        "p50_commit_latency_ms": round(1000.0 * p50, 3),
        "p99_commit_latency_ms": round(1000.0 * p99, 3),
        "latency_samples": len(lats),
        "latency_samples_dropped": truncated,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "quorum_impl": quorum_impl, "machine": machine_name,
        **({"fifo_capacity": machine.capacity,
            "fifo_checkout_slots": machine.checkout_slots,
            "fifo_consumer_slots": machine.consumer_slots}
           if machine_name == "fifo" else {}),
        "lanes": n_lanes, "members": n_members, "cmds_per_step": cmds,
        "durable": durable, "host": _host_meta(),
        **({"sync_mode": sync_mode,
            "wal_strategy": wal_strategy,
            "wal_shards": wal_shards,
            "wal": overview["wal"]} if durable else {}),
        # the unified snapshot (telemetry summary + sampler health +
        # pipeline + per-shard WAL stats + phase attribution) —
        # ISSUE 6's one-stop tail, ISSUE 9's phases ride inside it
        **({"observatory": observatory.snapshot()}
           if observatory is not None else {}),
        # SLO verdicts over the run's ring windows (ISSUE 9) + the
        # opt-in autotuner's decisions/knobs
        **({"slo": slo.evaluate()} if slo is not None else {}),
        **({"autotune": tuner.overview()} if tuner is not None else {}),
        **devicewatch.bench_tail_keys(commands=int(committed)),
    }))
    sys.stdout.flush()
    # join the WAL plane's worker/supervisor threads before interpreter
    # teardown: a daemon thread still inside an XLA readback while the
    # CPU client destructs aborts the whole child ("terminate called
    # without an active exception") — rarely, but the driver runs this
    # unattended and a dead child costs the round its measurement
    eng.close()


# ---------------------------------------------------------------------------
# multichip mode: the sharded-mesh frontier sweep (ISSUE 11)
# ---------------------------------------------------------------------------

#: the MULTICHIP_r05 2x4 throughput phase this sweep is measured
#: against (single-step mesh driver, 1024 lanes x 4 members, cmds=8,
#: 8 forced host devices on the builder box) — the acceptance bar is
#: >= 5x this at equal lanes/members on the same host
R05_2X4_CMDS_PER_S = 1_611_936.9


def _multichip_point(mesh, lanes: int, members: int, cmds: int,
                     superstep_k: int, dispatch_ahead: int,
                     seconds: float, autotune: bool) -> dict:
    """One frontier point: single-step reference, then the
    superstep+dispatch-ahead mesh pipeline (optionally autotuner-driven
    K walk), then step-stamped latency — all on state sharded over
    ``mesh`` with blocks staged pre-partitioned (zero resharding)."""
    import collections

    import numpy as np

    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine
    from ra_tpu.parallel.mesh import (drive_uniform_window,
                                      mesh_superstep_driver,
                                      shard_engine_state)

    eng = LockstepEngine(CounterMachine(), lanes, members,
                         ring_capacity=max(64, 4 * cmds),
                         max_step_cmds=cmds, apply_window=cmds + 2,
                         write_delay=1)
    shard_engine_state(eng, mesh)
    n_new = np.full((lanes,), cmds, np.int32)
    payloads = np.ones((lanes, cmds, 1), np.int32)
    for _ in range(3):
        eng.step(n_new, payloads)
    eng.block_until_ready()  # warmup boundary (outside the measured loop)

    # -- single-step reference (the MULTICHIP_r05 protocol, made
    # window-bounded): same mesh, same shardings, one round per
    # dispatch — the denominator of speedup_vs_single_step
    readbacks: "collections.deque" = collections.deque()
    ref_s = min(seconds, 1.5)
    base = eng.committed_total()  # pre-phase baseline (outside the loop)
    ref_steps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < ref_s:
        eng.step(n_new, payloads)
        ref_steps += 1
        readbacks.append(eng.committed_lanes_async())
        while len(readbacks) > 8:
            np.asarray(readbacks.popleft())  # ra04-ok: window boundary
    eng.block_until_ready()  # phase-end boundary (outside the loop)
    ref_el = time.perf_counter() - t0
    ref_value = (eng.committed_total() - base) / ref_el

    # -- fused pipeline: dispatch-ahead staging against the mesh
    # shardings; the autotuner walks K off the throughput floor
    driver = mesh_superstep_driver(eng, mesh,
                                   max_in_flight=dispatch_ahead)
    observatory = slo = tuner = None
    cur_k = [superstep_k]
    if autotune:
        from ra_tpu.autotune import AutoTuner
        from ra_tpu.slo import SloEngine, default_objectives
        from ra_tpu.telemetry import Observatory, TelemetrySampler
        # sampler cadence of ONE inner step: a window without a fresh
        # sample rates as no_data and stalls the walk, and at the top
        # ladder rung a single fused dispatch outlasts several snapshot
        # windows — only a per-dispatch sample keeps every window live.
        # Tune-phase only: the measured phase detaches the sampler.
        sampler = TelemetrySampler(eng, cadence_steps=1)
        observatory = Observatory.for_engine(eng, sampler=sampler)
        # the throughput floor the walk chases: past any realizable
        # mesh rate, so the tuner keeps fusing while the plant is
        # dispatch-bound and stops only at the K bound / latency wall
        slo = SloEngine(observatory,
                        default_objectives(
                            min_cmds_per_s=16.0 * max(1.0, ref_value)),
                        fast_windows=2, slow_windows=4, burn_fast=0.5)
        # K's upper bound shrinks with lane count: one fused dispatch
        # at the 64k rung already runs for most of a second per 8
        # inner steps, and a 64-deep dispatch there would swallow the
        # whole measured window (the walk is for the dispatch-bound
        # low rungs; the compute-bound top rung has nothing to fuse)
        k_hi = 32 if lanes <= 1024 else (16 if lanes <= 8192 else 8)
        tuner = AutoTuner(slo, observatory,
                          bounds={"cmds_per_step": (cmds, cmds),
                                  "superstep_k": (1, k_hi)},
                          knobs={"superstep_k": 1, "cmds_per_step": cmds},
                          cooldown_windows=1, breach_windows=1,
                          incident_freeze_s=0.0)
        cur_k = [1]

    def mk_blocks(k: int):
        return (np.broadcast_to(n_new, (k,) + n_new.shape),
                np.broadcast_to(payloads, (k,) + payloads.shape))

    _last_obs = [0.0, 0.0]  # (last tick ts, last observed committed)
    _rate_by_k: dict = {}

    def observe():
        """Window-cadence host work between dispatches: snapshot the
        ring, tick the controller, record the realized rate at the
        current K (from ``driver.last_committed`` — the EXISTING async
        watermark readbacks, no new sync), restage on a K decision."""
        now = time.perf_counter()
        if observatory is None or now - _last_obs[0] < 0.2:
            return None
        lc = driver.last_committed
        if lc is not None and _last_obs[0] > 0.0:
            done = float(lc.astype("int64").sum())
            if _last_obs[1] > 0.0:
                acc = _rate_by_k.setdefault(cur_k[0], [0.0, 0.0])
                acc[0] += done - _last_obs[1]
                acc[1] += now - _last_obs[0]
            _last_obs[1] = done
        _last_obs[0] = now
        observatory.snapshot()
        tuner.tick()
        if tuner.knobs["superstep_k"] != cur_k[0]:
            cur_k[0] = tuner.knobs["superstep_k"]
            # discard the first window at the new K: it contains the
            # new block shape's jit compile, which would poison the
            # per-K rate the argmax selection reads
            _last_obs[1] = 0.0
            return mk_blocks(cur_k[0])
        return None

    nb, pb = mk_blocks(cur_k[0])
    for _ in range(2):
        driver.submit(nb, pb)
    driver.drain()
    if tuner is not None:
        # tune phase (not measured): the controller proposes the K
        # walk; the realized per-K rates select the operating point —
        # on a dispatch-bound mesh the walk's converged K IS the
        # argmax, while on a compute-bound plant (forced-host devices
        # on a small box) the floor is unreachable, the walk pegs at
        # its bound, and the argmax keeps the sweep honest
        # budgeted to cover the jit compiles the walk triggers (each
        # new K is a fresh block shape) plus a few clean windows per K
        tune_s = float(os.environ.get("RA_TPU_BENCH_MESH_TUNE_S", "6.0"))
        drive_uniform_window(driver, nb, pb, max(tune_s, seconds),
                             observe=observe)
        driver.drain()
        measured = {k: a[0] / a[1] for k, a in _rate_by_k.items()
                    if a[1] > 0.05 and a[0] > 0}
        if measured:
            cur_k[0] = max(measured, key=lambda k: measured[k])
        # the knob stamps must describe the MEASURED dispatches (the
        # RA07 discipline): pin the controller to the selected K so
        # tail readers see one consistent operating point
        tuner.knobs["superstep_k"] = cur_k[0]
        tuner.bounds["superstep_k"] = (cur_k[0], cur_k[0])
        nb, pb = mk_blocks(cur_k[0])
        # the MEASURED phase runs exactly like the single-step ref:
        # no sampler dispatches, no snapshot/tick work — the sweep's
        # speedup_vs_single_step compares pipelines, not telemetry
        # overhead (the ref ran before the sampler was attached)
        eng._telemetry = None
        observatory_final = observatory
        observatory = None
    base = eng.committed_total()  # pre-measure baseline (outside the loop)
    t_meas = time.perf_counter()
    dispatches, inner, _loop_el = drive_uniform_window(
        driver, nb, pb, seconds, observe=observe)
    driver.drain()
    # elapsed includes the drain: up to max_in_flight+1 dispatches are
    # unobserved at loop exit, and at the 64k rung a single fused
    # dispatch is most of the window — excluding their completion
    # would overstate the rate ~2x at the top rung
    elapsed = time.perf_counter() - t_meas
    committed = eng.committed_total() - base  # post-drain (outside the loop)
    value = committed / elapsed
    k_final = cur_k[0]

    # -- solo-dispatch tail probe -> the effective p99 bar (the PR 3
    # discipline: the bar is lifted to the backend's own pipeline
    # floor, measured UNPIPELINED so a regression cannot hide in it)
    nb1, pb1 = mk_blocks(max(1, k_final))
    stimes = []
    probe_reps = 8 if lanes <= 8192 else 4
    for _ in range(probe_reps):
        ts = time.perf_counter()
        driver.submit(nb1, pb1)
        driver.drain()  # ra04-ok: solo-dispatch probe, deliberately sync
        stimes.append(time.perf_counter() - ts)
    solo_p99_ms = 1000 * sorted(stimes)[-1]
    bar = max(25.0, (dispatch_ahead + 1) * solo_p99_ms * 1.5)

    # -- step-stamped latency: a batch enters at inner step E of a
    # fused dispatch; the stacked [K, N] committed watermarks give the
    # observed-commit inner step O, and ms = sample time * O / steps
    expected = lanes * cmds
    k_lat = max(1, k_final)
    zero_nb = np.zeros((k_lat, lanes), np.int32)
    zero_pb = np.zeros((k_lat,) + payloads.shape, payloads.dtype)
    batch_nb = zero_nb.copy()
    batch_nb[0] = n_new
    batch_pb = zero_pb.copy()
    batch_pb[0] = payloads
    lats = []
    dropped = 0
    n_samples = 12 if lanes <= 8192 else 4  # top-rung steps are ~100x
    for _ in range(n_samples):
        before = eng.committed_total()  # ra04-ok: pre-sample baseline
        handles = []
        steps_done = 0
        t1 = time.perf_counter()
        aux = eng.superstep(batch_nb, batch_pb)
        steps_done += k_lat
        handles.append((steps_done, aux["committed_lanes"] + 0))
        for _w in range(max(1, 8 // k_lat)):
            aux = eng.superstep(zero_nb, zero_pb)
            steps_done += k_lat
            handles.append((steps_done, aux["committed_lanes"] + 0))
        eng.block_until_ready()  # ra04-ok: sample window boundary
        el = time.perf_counter() - t1
        obs_step = None
        for hi_step, h in handles:
            arr = np.asarray(h).astype(np.int64)  # ra04-ok: post-boundary harvest
            cums = arr.sum(axis=1) - before
            for k_in in range(arr.shape[0]):
                if cums[k_in] >= expected:
                    obs_step = hi_step - arr.shape[0] + k_in + 1
                    break
            if obs_step is not None:
                break
        if obs_step is None:
            dropped += 1
        else:
            lats.append(el * obs_step / steps_done)
    lats.sort()
    p50 = 1000 * lats[len(lats) // 2] if lats else -1.0
    p99 = 1000 * lats[min(len(lats) - 1, int(len(lats) * 0.99))] \
        if lats else -1.0

    pipeline = eng.overview()["pipeline"]
    row = {
        "mesh": eng.mesh_shape(),
        "lanes": lanes,
        "members": members,
        "cmds_per_step": cmds,
        "value": round(value, 1),
        "committed": int(committed),
        "dispatches": dispatches,
        "steps": inner,
        "elapsed_s": round(elapsed, 3),
        "single_step_ref": {"value": round(ref_value, 1),
                            "steps": ref_steps,
                            "elapsed_s": round(ref_el, 3)},
        "speedup_vs_single_step": round(value / ref_value, 3)
        if ref_value else -1.0,
        "latency_mode": "step_stamped",
        "p50_commit_latency_ms": round(p50, 3),
        "p99_commit_latency_ms": round(p99, 3),
        "latency_samples": len(lats),
        "latency_samples_dropped": dropped,
        "p99_bar_effective_ms": round(bar, 3),
        "meets_p99_bar": bool(0 < p99 < bar),
        "pipeline": pipeline,
        # cross-round attribution stamp (ISSUE 11 satellite): the
        # realized pipeline config next to the rate it produced, so
        # tools/bench_diff.py deltas are attributable to a config
        # change vs a real regression
        "engine_pipeline": {
            "superstep_k": k_final,
            "dispatch_ahead": dispatch_ahead,
            "donation": bool(eng._superstep_donate),
            "wal_shard_layout": "volatile",
            "mesh_shape": eng.mesh_shape(),
        },
    }
    if tuner is not None:
        row["autotune"] = tuner.overview()
        # the tune phase's realized per-K rates (the frontier search
        # evidence behind the chosen operating point)
        row["tune_k_rates"] = {
            str(k): round(a[0] / a[1], 1)
            for k, a in sorted(_rate_by_k.items()) if a[1] > 0.05}
        observatory_final.close()
    return row


def _multichip_main() -> None:
    """The multichip frontier sweep promoted into bench.py proper
    (ROADMAP item 1): per mesh shape x lane-ladder rung, the
    superstep+dispatch-ahead pipeline over sharded state vs the
    single-step reference, with the PR 8 autotuner walking K and the
    same p99-bar/window/step-stamped discipline as the single-device
    frontier.  One JSON line: ``multichip`` rows + the best point."""
    import jax

    devices = jax.devices()
    n_dev = len(devices)
    seconds = float(os.environ.get("RA_TPU_BENCH_SECONDS", "2.0"))
    cmds = int(os.environ.get("RA_TPU_BENCH_CMDS", "8"))
    from ra_tpu.system import engine_pipeline_defaults
    pipe_defaults = engine_pipeline_defaults()
    ss_env = os.environ.get("RA_TPU_BENCH_SUPERSTEP", "auto")
    superstep_k = pipe_defaults["superstep_k"] if ss_env == "auto" \
        else max(1, int(ss_env))
    da_env = os.environ.get("RA_TPU_BENCH_DISPATCH_AHEAD", "auto")
    dispatch_ahead = pipe_defaults["dispatch_ahead"] if da_env == "auto" \
        else int(da_env)
    # the lane ladder (ISSUE 11 satellite): shared with the dryrun
    # phases via ra_tpu.parallel.mesh.lane_ladder so the per-rung
    # bench_diff row keys pair across capture formats; the
    # bench-specific RA_TPU_BENCH_MESH_LANES override wins, a
    # malformed/empty spec degrades to the default ladder
    from ra_tpu.parallel.mesh import (ladder_rungs, lane_ladder,
                                      lane_mesh, mesh_shapes)
    ladder = lane_ladder(os.environ.get("RA_TPU_BENCH_MESH_LANES"))
    autotune = os.environ.get("RA_TPU_BENCH_AUTOTUNE", "1") != "0"
    rows = []
    # shapes + rung clamp/dedupe shared with dryrun_multichip — the
    # two capture formats must emit identical per-shape/per-rung keys
    for m_ax, l_ax, members in mesh_shapes(n_dev):
        mesh = lane_mesh(devices, member_axis=m_ax)
        for lanes in ladder_rungs(ladder, l_ax):
            row = _multichip_point(mesh, lanes, members, cmds,
                                   superstep_k, dispatch_ahead,
                                   seconds, autotune)
            if row["mesh"] == "2x4" and row["lanes"] == 1024 and \
                    cmds == 8:
                # the acceptance-bar comparison at the r05 config
                # (equal lanes/members/cmds; same-host caveat rides
                # the host stamp)
                row["speedup_vs_r05"] = round(
                    row["value"] / R05_2X4_CMDS_PER_S, 3)
            rows.append(row)
            print(f"  point {row['mesh']} lanes={row['lanes']}: "
                  f"{row['value']:.0f} cmds/s "
                  f"({row['speedup_vs_single_step']}x single-step)",
                  file=sys.stderr)
    ok = [r for r in rows if r["meets_p99_bar"]]
    best = max(ok or rows, key=lambda r: r["value"])
    from ra_tpu import devicewatch
    print(json.dumps({
        "value": best["value"],
        "best_point": {"mesh": best["mesh"], "lanes": best["lanes"]},
        "multichip": rows,
        "n_devices": n_dev,
        "superstep_k": superstep_k,
        "dispatch_ahead": dispatch_ahead,
        "cmds_per_step": cmds,
        "autotune": autotune,
        "r05_2x4_cmds_per_s": R05_2X4_CMDS_PER_S,
        "platform": devices[0].platform,
        "host": _host_meta(),
        # the sweep's whole-process compile budget: every frontier point
        # reuses the jit cache, so n_compiles growing with the ladder
        # length (instead of with the distinct-config count) is the
        # retrace regression bench_diff flags
        **devicewatch.bench_tail_keys(),
    }))


# ---------------------------------------------------------------------------
# wire mode: the socket-path frontier (ISSUE 12, ROADMAP item 2)
# ---------------------------------------------------------------------------

def _wire_bench_main() -> None:
    """One rung of the wire connection ladder as a bench phase: the
    full wire path (fixed-stride frames → per-connection rings →
    vectorized sweep → ingress → fused dispatch) with a reconnect
    storm mid-run, measured end to end through a durable engine by
    default.  The tail carries ``wire_cmds_per_s`` /
    ``wire_shed_rate`` / ``wire_reconnect_recovery_s`` so
    tools/bench_diff.py tracks the wire frontier like any other."""
    import tempfile

    from ra_tpu.wire.soak import run_wire_soak

    conns = int(os.environ.get("RA_TPU_BENCH_WIRE_CONNS", "100000"))
    lanes = int(os.environ.get("RA_TPU_BENCH_WIRE_LANES", "1024"))
    waves = int(os.environ.get("RA_TPU_BENCH_WIRE_WAVES", "12"))
    durable = os.environ.get("RA_TPU_BENCH_WIRE_DURABLE", "1") == "1"
    seed = int(os.environ.get("RA_TPU_BENCH_WIRE_SEED", "0"))
    kw = dict(conns=conns, lanes=lanes, waves=waves,
              wave_ops=max(20_000, conns // 2),
              ring_records=16 if conns >= 1 << 19 else 32,
              socket_conns=32, socket_ops=16)
    if durable:
        with tempfile.TemporaryDirectory(prefix="bench_wire_") as d:
            row = run_wire_soak(seed, durable_dir=d, **kw)
    else:
        row = run_wire_soak(seed, **kw)
    row["metric"] = "wire_committed_cmds_per_sec"
    row["unit"] = "cmds/s"
    row["host"] = _host_meta()
    print(json.dumps(row))


# ---------------------------------------------------------------------------
# reads mode: the mixed read/write frontier (ISSUE 20)
# ---------------------------------------------------------------------------

def _reads_bench_main() -> None:
    """Mixed consistent-read / write workload through the ingress plane
    (ISSUE 20): per wave, ``read_share`` of the rows are lease/read-index
    reads riding the SAME fused dispatches as the writes, the rest are
    durable puts.  Three measured sections on one warm engine:

    1. per-call baseline — ``consistent_read`` one lane at a time, the
       host-path consistent_query it replaces (``percall_reads_per_s``);
    2. write-only reference — the write plane alone at the mixed run's
       write arrival rate (``write_only_p99_ms``: the frontier the mixed
       run must stay within 10% of);
    3. the mixed run — stamps ``read_cmds_per_s`` / ``read_p99_ms``
       (per-read submit→reply e2e, measured at the reply callback) /
       ``reads_per_dispatch`` / ``read_plane_speedup_vs_percall`` plus
       the write keys and BOTH SLO verdicts from the live SloEngine.

    The tail carries the devicewatch stamp and a ``steady_state_*``
    compile delta over the measured sections — reads interleaving with
    writes must not retrace the fused step."""
    import collections
    import tempfile

    import numpy as np

    import jax

    from ra_tpu import devicewatch
    from ra_tpu.engine.durable import open_engine
    from ra_tpu.ingress import IngressPlane
    from ra_tpu.models import JitKvMachine
    from ra_tpu.slo import SloEngine
    from ra_tpu.telemetry import Observatory

    lanes = int(os.environ.get("RA_TPU_BENCH_LANES", "1024"))
    members = int(os.environ.get("RA_TPU_BENCH_MEMBERS", "3"))
    seconds = float(os.environ.get("RA_TPU_BENCH_SECONDS", "3.0"))
    read_share = min(0.99, max(0.01, float(
        os.environ.get("RA_TPU_BENCH_READ_SHARE", "0.9"))))
    kr = int(os.environ.get("RA_TPU_BENCH_READ_WINDOW", "16"))
    cmds = int(os.environ.get("RA_TPU_BENCH_CMDS", "8"))
    superstep_k = int(os.environ.get("RA_TPU_BENCH_SUPERSTEP", "4")
                      if os.environ.get("RA_TPU_BENCH_SUPERSTEP", "4")
                      .isdigit() else 4)
    # rows offered per wave: ~2 rows/lane keeps a single read block
    # (<= Kr rows/lane) carrying the whole wave's read half — the
    # >=1000 reads/dispatch shape at 1024 lanes
    wave_rows = int(os.environ.get("RA_TPU_BENCH_READS_WAVE",
                                   str(2 * lanes)))
    n_w = max(1, int(round(wave_rows * (1.0 - read_share))))
    n_r = max(1, wave_rows - n_w)
    n_keys = 64
    rng = np.random.default_rng(
        int(os.environ.get("RA_TPU_BENCH_SEED", "0")))

    with tempfile.TemporaryDirectory(prefix="bench_reads_") as wal_dir:
        eng = open_engine(JitKvMachine(n_keys=n_keys), wal_dir, lanes,
                          members, wal_shards=2,
                          ring_capacity=max(64, superstep_k * cmds * 4),
                          max_step_cmds=cmds, max_step_reads=kr,
                          lease_ttl=8, donate=False)
        plane = IngressPlane(eng, superstep_k=superstep_k,
                             window_s=0.001, soft_credit=1 << 20,
                             hard_credit=1 << 20)
        obs = Observatory.for_engine(eng)
        # verdict stamping only — deliberately NOT wired into the
        # plane's credit ladder: on an oversubscribed host the write
        # p99 breaches its objective, the ladder bias would shed every
        # read at admission, and the frontier this mode exists to
        # measure would read 0.  The bias itself is test-pinned.
        slo = SloEngine(obs)
        sess = plane.directory.connect_bulk(4096, key="bench-reads")
        n_sess = len(sess)

        # write-plane wave latency: cumulative accepted-row targets
        # joined against the block-commit callback's released rows
        # (the frontier's observed-commit edge, through ingress)
        write_waves: collections.deque = collections.deque()
        write_lats: list = []
        released_rows = 0

        def _on_commit(handles) -> None:
            nonlocal released_rows
            released_rows += len(handles)
            t = time.perf_counter()
            while write_waves and write_waves[0][0] <= released_rows:
                _tgt, ts = write_waves.popleft()
                write_lats.append(t - ts)

        plane.on_block_committed = _on_commit

        # read e2e: submit wall clock per read wave (seqnos encode the
        # wave), latency measured at the reply callback for SERVED rows
        SEQ_STRIDE = 1 << 20
        wave_t = np.zeros(1 << 16, np.float64)
        read_lats: list = []

        def _on_reads(handles, seqnos, statuses, wms, payloads) -> None:
            now = time.perf_counter()
            ok = np.asarray(statuses) == 0
            if ok.any():
                w = np.asarray(seqnos)[ok] // SEQ_STRIDE
                read_lats.extend((now - wave_t[w]).tolist())

        plane.on_reads_done = _on_reads

        wave_idx = 0
        last_snap = 0.0

        def _wave(do_reads: bool) -> None:
            nonlocal wave_idx, last_snap
            wh = sess[rng.choice(n_sess, size=n_w, replace=False)]
            pay = np.zeros((n_w, 4), np.int32)
            pay[:, 0] = 1  # put
            pay[:, 1] = rng.integers(0, n_keys, n_w)
            pay[:, 2] = rng.integers(0, 1 << 20, n_w)
            plane.submit_auto(wh, pay)
            write_waves.append((plane.counters["accepted"],
                                time.perf_counter()))
            if do_reads:
                rh = sess[rng.choice(n_sess, size=n_r, replace=False)]
                q = np.zeros((n_r, 2), np.int32)
                q[:, 0] = 1  # get
                q[:, 1] = rng.integers(0, n_keys, n_r)
                seq = wave_idx * SEQ_STRIDE + np.arange(n_r)
                wave_t[wave_idx] = time.perf_counter()
                plane.submit_reads(rh, seq, q)
            wave_idx += 1
            plane.pump(force=True)
            now = time.perf_counter()
            if now - last_snap > 0.1:
                last_snap = now
                obs.snapshot()

        # -- warmup: compile the mixed-dispatch shapes ------------------
        for _ in range(3):
            _wave(do_reads=True)
        plane.settle(timeout=120.0)

        # -- per-call host-path baseline (the path reads replace) -------
        eng.consistent_read([0])  # warm the single-step path
        n_calls = 5
        t0 = time.perf_counter()
        for i in range(n_calls):
            eng.consistent_read([i % lanes])
        percall_s = (time.perf_counter() - t0) / n_calls

        # measured sections start here: fresh percentile reservoirs
        # (warmup/compile samples out of the p99 tails) and the
        # steady-state compile baseline — reads interleaved with writes
        # must not retrace past this line
        eng.phases.reset_reservoirs()
        write_lats.clear()
        read_lats.clear()
        dw0 = dict(devicewatch.WATCH.counters)

        # -- write-only reference at the mixed run's write rate ---------
        t_w0 = time.perf_counter()
        while time.perf_counter() - t_w0 < seconds * 0.5:
            _wave(do_reads=False)
        plane.settle(timeout=120.0)
        wl = sorted(write_lats)
        write_only_p99_ms = round(
            1000 * wl[min(len(wl) - 1, int(len(wl) * 0.99))], 3) \
            if wl else -1.0

        # -- the mixed run ---------------------------------------------
        eng.phases.reset_reservoirs()
        write_lats.clear()
        rc0 = dict(plane.read_counters)
        wrote0 = plane.counters["accepted"]
        t_mix = time.perf_counter()
        while time.perf_counter() - t_mix < seconds:
            _wave(do_reads=True)
        plane.settle(timeout=120.0)
        elapsed = time.perf_counter() - t_mix
        obs.snapshot()
        verdicts = {name: o["verdict"] for name, o in
                    slo.evaluate()["objectives"].items()}

        rc = plane.read_counters
        served = rc["served"] - rc0["served"]
        submitted = max(1, rc["submitted"] - rc0["submitted"])
        blocks = max(1, rc["blocks_built"] - rc0["blocks_built"])
        block_rows = rc["block_rows"] - rc0["block_rows"]
        wrote = plane.counters["accepted"] - wrote0
        read_cmds_per_s = served / max(elapsed, 1e-9)
        percall_reads_per_s = 1.0 / max(percall_s, 1e-9)
        rl = sorted(read_lats)
        wl = sorted(write_lats)
        read_p99_ms = round(
            1000 * rl[min(len(rl) - 1, int(len(rl) * 0.99))], 3) \
            if rl else -1.0
        write_p99_ms = round(
            1000 * wl[min(len(wl) - 1, int(len(wl) * 0.99))], 3) \
            if wl else -1.0
        dw = devicewatch.WATCH.counters
        ov = plane.read_overview()
        print(json.dumps({
            "metric": "read_cmds_per_sec_mixed",
            "value": round(read_cmds_per_s, 1),
            "unit": "reads/s",
            "read_cmds_per_s": round(read_cmds_per_s, 1),
            "read_p99_ms": read_p99_ms,
            "read_e2e_phase_p99_ms":
                eng.phases.overview()["read_e2e"]["p99_ms"],
            "read_share": read_share,
            "reads_per_dispatch": round(block_rows / blocks, 1),
            "read_served": int(served),
            "read_shed_rate": round(
                (rc["shed"] - rc0["shed"]) / submitted, 6),
            "read_stale_refused": int(
                rc["stale_refused"] - rc0["stale_refused"]),
            "lease_coverage_pct": ov.get("lease_coverage_pct", -1.0),
            "write_cmds_per_s": round(wrote / max(elapsed, 1e-9), 1),
            "write_p99_ms": write_p99_ms,
            "write_only_p99_ms": write_only_p99_ms,
            "write_p99_vs_write_only": round(
                write_p99_ms / write_only_p99_ms, 3)
                if write_only_p99_ms > 0 and write_p99_ms > 0 else -1.0,
            "percall_read_ms": round(1000 * percall_s, 3),
            "percall_reads_per_s": round(percall_reads_per_s, 1),
            "read_plane_speedup_vs_percall": round(
                read_cmds_per_s / percall_reads_per_s, 1),
            "slo": verdicts,
            "slo_read_verdict": verdicts.get("read_p99_ms", "no_data"),
            "slo_write_verdict": verdicts.get("commit_p99_ms", "no_data"),
            "lanes": lanes, "members": members,
            "cmds_per_step": cmds, "read_window": kr,
            "superstep_k": superstep_k, "durable": True,
            "wave_rows": wave_rows,
            "steady_state_compiles": dw["compiles"] - dw0["compiles"],
            "steady_state_recompiles":
                dw["recompiles"] - dw0["recompiles"],
            "platform": jax.devices()[0].platform,
            "host": _host_meta(),
            **devicewatch.bench_tail_keys(int(wrote + served)),
        }))


# ---------------------------------------------------------------------------
# frontier mode: the latency/throughput frontier (one child, four points)
# ---------------------------------------------------------------------------

def _frontier_main() -> None:
    """Continuous pipelined measurement at several step sizes.

    For each step size, the host dispatches batches back-to-back with a
    bounded un-acknowledged window (client-side pipelining, the credit
    window of ra_bench.erl:84-129) and harvests *asynchronous* commit
    readbacks — dispatch of step N+1 never waits for the readback of
    step N.  Per-batch commit latency is the wall clock from dispatch to
    the first harvested readback whose cumulative count covers the
    batch.  Reports cmds/s + p50/p99 per point: the frontier."""
    import collections

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    n_lanes = int(os.environ.get("RA_TPU_BENCH_LANES", N_LANES))
    n_members = int(os.environ.get("RA_TPU_BENCH_MEMBERS", N_MEMBERS))
    seconds = float(os.environ.get("RA_TPU_BENCH_SECONDS", "3.0"))
    window = int(os.environ.get("RA_TPU_BENCH_WINDOW", "4"))
    sizes = [int(s) for s in os.environ.get(
        "RA_TPU_BENCH_SIZES", "1,8,32,128").split(",")]

    # measure the backend's synchronous dispatch+readback round trip:
    # on a tunneled TPU this is the hard floor under any observed-commit
    # latency (~68ms measured on the axon tunnel) — it bounds p50/p99
    # below regardless of engine step time, so record it alongside
    x = jnp.ones((8,), jnp.int32)
    f = jax.jit(lambda a: a + 1)
    np.asarray(f(x))
    rtts = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(f(x))
        rtts.append(time.perf_counter() - t0)
    rtts.sort()
    sync_rtt_ms = round(1000 * rtts[len(rtts) // 2], 3)

    points = []
    for cmds in sizes:
        eng = LockstepEngine(CounterMachine(), n_lanes, n_members,
                             ring_capacity=1024, max_step_cmds=cmds,
                             apply_window=cmds + 2, write_delay=1)
        n_new = jnp.full((n_lanes,), cmds, jnp.int32)
        payloads = jnp.ones((n_lanes, cmds, 1), jnp.int32)
        zero_n = jnp.zeros((n_lanes,), jnp.int32)
        for _ in range(5):
            eng.step(n_new, payloads)
        for _ in range(4):
            eng.step(zero_n, payloads)  # settle: warmup entries commit
        eng.block_until_ready()  # ra04-ok: per-point warmup boundary
        # solo (unpipelined) step-time tail at this config: with a
        # window of W, the oldest in-flight batch is W rounds from its
        # readback, so W * step_p99 is the p99 floor THIS BACKEND can
        # reach regardless of the pipeline's health — the effective bar
        # takes it in alongside the RTT floor.  Probed with the REAL
        # append workload (n_new, not empty rounds — empty steps read
        # several times faster and under-state the floor), and solo, so
        # a pipelining/readback regression (what the bar guards) cannot
        # hide in it.
        stimes = []
        for _ in range(12):
            ts = time.perf_counter()
            eng.step(n_new, payloads)
            eng.block_until_ready()  # ra04-ok: solo step-time probe,
            # deliberately synchronous — it measures the UNPIPELINED
            # step tail the effective p99 bar is derived from
            stimes.append(time.perf_counter() - ts)
        step_p99_ms = round(1000 * sorted(stimes)[-1], 3)
        for _ in range(4):
            eng.step(zero_n, payloads)  # settle the probe's appends
        eng.block_until_ready()  # ra04-ok: pre-measurement boundary
        base = eng.committed_total()  # ra04-ok: pre-measurement baseline

        per_batch = n_lanes * cmds
        batches = collections.deque()    # (target_cum, t_dispatch)
        readbacks = collections.deque()  # device arrays, dispatch order
        lats = []
        dispatched = 0
        obs_cum = 0
        t_last_obs = None  # wall time the newest commit was observed

        def harvest(block: bool) -> None:
            nonlocal obs_cum, t_last_obs
            while readbacks:
                tc = readbacks[0]
                if not block and not tc.is_ready():
                    return
                readbacks.popleft()
                cum = int(np.asarray(tc).astype(np.int64).sum()) - base  # ra04-ok: ready (or window boundary)
                t_obs = time.perf_counter()
                if cum > obs_cum:
                    obs_cum = cum
                    t_last_obs = t_obs
                while batches and batches[0][0] <= obs_cum:
                    _tgt, t_disp = batches.popleft()
                    lats.append(t_obs - t_disp)
                if block:
                    return

        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            while len(batches) >= window:
                if not readbacks:
                    # commit lag >= window: drive an empty round so a
                    # readback exists to cover the oldest batch (else
                    # this wait would spin forever)
                    eng.step(zero_n, payloads)
                    readbacks.append(eng.committed_lanes_async())
                harvest(block=True)
            t = time.perf_counter()
            eng.step(n_new, payloads)
            dispatched += 1
            batches.append((dispatched * per_batch, t))
            readbacks.append(eng.committed_lanes_async())
            harvest(block=False)
        # flush: empty steps until every dispatched batch is observed
        flush_spins = 0
        while batches and flush_spins < 64:
            eng.step(zero_n, payloads)
            readbacks.append(eng.committed_lanes_async())
            harvest(block=True)
            flush_spins += 1
        elapsed = time.perf_counter() - t0
        committed = eng.committed_total() - base  # ra04-ok: post-flush readback
        # The flush loop is capped, so batches may remain unflushed:
        # their dispatch time would sit in the denominator (plus up to
        # 64 spins of flush time) with their commands missing from the
        # numerator, silently skewing the rate.  Compute the rate over
        # the observed-commit edge instead — numerator is what the
        # harvests actually saw, denominator ends at the last observed
        # commit — and report the unflushed remainder explicitly.
        rate_elapsed = (t_last_obs - t0) if t_last_obs is not None \
            else elapsed
        lats.sort()
        n = len(lats)
        points.append({
            "cmds_per_step": cmds,
            "value": round(obs_cum / rate_elapsed, 1)
                if rate_elapsed > 0 else 0.0,
            "p50_commit_latency_ms":
                round(1000 * lats[n // 2], 3) if n else -1.0,
            "p99_commit_latency_ms":
                round(1000 * lats[min(n - 1, int(n * 0.99))], 3)
                if n else -1.0,
            "batches_measured": n,
            "batches_unflushed": len(batches),
            "unflushed_cmds": len(batches) * per_batch,
            "committed_total": int(committed),
            "step_p99_ms": step_p99_ms,
            "window": window,
        })
        del eng

    # headline frontier value: best throughput among points meeting the
    # p99 < 25 ms latency bar (BASELINE.md "without p99 collapse").
    # Per point the bar is lifted to the backend's own pipeline floor:
    # the oldest in-flight batch is `window` rounds from its readback,
    # and on an oversubscribed host the pipelined tail additionally
    # stacks dispatch-queue depth on the solo step tail — hence the
    # (window+1) * solo-step-p99 * 1.5 queueing margin (measured on the
    # 2-core CI box; solo steps never queue, so a pipelining/readback
    # regression cannot hide in the probe).  On real hardware steps are
    # sub-ms and the 25ms/RTT term dominates — the bar is unchanged
    # where it matters.
    bar = max(25.0, 3 * sync_rtt_ms)
    for p in points:
        floor = (p["window"] + 1) * p["step_p99_ms"] * 1.5
        eff = max(bar, floor)
        p["p99_bar_effective_ms"] = round(eff, 3)
        p["meets_p99_bar"] = bool(0 < p["p99_commit_latency_ms"] < eff)
    ok = [p for p in points if p["meets_p99_bar"]]
    best = max(ok or points, key=lambda p: p["value"])
    # the documented DEFAULT operating point (docs/BENCHMARKS.md):
    # cmds_per_step=32 with a window of 4 — deep enough batching to
    # amortize dispatch, shallow enough that the oldest in-flight batch
    # is never more than 4 device rounds from its readback
    default_point = next(
        (p for p in points if p["cmds_per_step"] == FRONTIER_DEFAULT_CMDS),
        None)
    print(json.dumps({
        "value": best["value"],
        "best_point": best,
        "default_point": default_point,
        "p99_bar_ms": round(bar, 3),
        "points": points,
        # the frontier sweeps the BATCHING axis (cmds_per_step) on the
        # single-step path; the fused-dispatch axis (superstep_k) is
        # covered by the throughput child's --superstep row — see
        # docs/BENCHMARKS.md "choosing superstep_k vs cmds_per_step"
        "superstep_k": 0,
        "sync_rtt_ms": sync_rtt_ms,
        "note": "observed-commit latency floor ~= sync_rtt_ms on "
                "tunneled backends; p99 bar is max(25ms, 3*rtt)",
        "platform": jax.devices()[0].platform,
        "lanes": n_lanes, "members": n_members, "host": _host_meta(),
    }))


# ---------------------------------------------------------------------------
# parent mode: orchestration that cannot hang
# ---------------------------------------------------------------------------

_CHILD_ERRORS: list = []  # (config, rc/timeout, stderr tail) of failed runs


def _run_child(env_extra: dict, timeout_s: float):
    """Run one measurement child; return its parsed JSON or None (the
    failure reason is recorded in _CHILD_ERRORS for the output detail)."""
    env = {**os.environ, **env_extra, "RA_TPU_BENCH_CHILD": "1"}
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           capture_output=True, text=True, timeout=timeout_s,
                           env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        _CHILD_ERRORS.append({"config": env_extra, "rc": "timeout"})
        return None
    if r.returncode != 0:
        _CHILD_ERRORS.append({"config": env_extra, "rc": r.returncode,
                              "stderr_tail": r.stderr[-2000:]})
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                if isinstance(parsed, dict) and "value" in parsed:
                    return parsed
            except json.JSONDecodeError:
                pass
            break
    _CHILD_ERRORS.append({"config": env_extra, "rc": 0,
                          "note": "no parsable result line"})
    return None


#: the device-plane bench-tail keys (ISSUE 16, devicewatch.bench_tail_keys)
_DEVICE_TAIL_KEYS = ("n_compiles", "n_recompiles", "compile_time_s",
                     "transfer_bytes", "transfer_bytes_per_cmd",
                     "peak_live_bytes")


def _promote_device_keys(child_row: dict) -> dict:
    """Copy the device-plane tail keys from the child whose ``value``
    becomes the parent headline onto the parent line itself — counters
    are per-PROCESS, so the parent (which never dispatches) must
    promote the measuring child's stamp for bench_diff to compare
    headline rows across rounds."""
    return {k: child_row[k] for k in _DEVICE_TAIL_KEYS if k in child_row}


def _probe_platform() -> str | None:
    """Return the default jax platform, or None if backend init hangs/fails.
    Runs in a subprocess so a dead axon tunnel cannot hang the parent."""
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def _parse_flags(argv) -> None:
    """--superstep [K]: turn on the fused-dispatch throughput row (K
    defaults to "auto" = the system-level superstep_k tunable).  Set as
    env so measurement children inherit it.  --multichip: run the
    sharded-mesh frontier sweep instead of the headline matrix."""
    if "--superstep" in argv:
        i = argv.index("--superstep")
        k = "auto"
        if i + 1 < len(argv) and argv[i + 1].isdigit():
            k = argv[i + 1]
        os.environ["RA_TPU_BENCH_SUPERSTEP"] = k
    if "--multichip" in argv:
        os.environ["RA_TPU_BENCH_MODE"] = "multichip"
    if "--wire" in argv:
        os.environ["RA_TPU_BENCH_MODE"] = "wire"
    if "--reads" in argv:
        # the mixed read/write frontier (ISSUE 20); --read-share tunes
        # the read fraction of every wave (default 0.9 — the 90/10 mix)
        os.environ["RA_TPU_BENCH_MODE"] = "reads"
    if "--read-share" in argv:
        i = argv.index("--read-share")
        if i + 1 < len(argv):
            os.environ["RA_TPU_BENCH_READ_SHARE"] = argv[i + 1]


MULTICHIP_TIMEOUT_S = 1200


def main() -> None:
    _parse_flags(sys.argv[1:])
    if os.environ.get("RA_TPU_BENCH_CHILD"):
        mode = os.environ.get("RA_TPU_BENCH_MODE")
        if mode == "frontier":
            _frontier_main()
        elif mode == "multichip":
            _multichip_main()
        elif mode == "wire":
            _wire_bench_main()
        elif mode == "reads":
            _reads_bench_main()
        else:
            _child_main()
        return

    if os.environ.get("RA_TPU_BENCH_MODE") == "wire":
        # the wire ladder is host-side + engine: CPU-safe everywhere,
        # one child (retry once), always a JSON tail
        env = {"RA_TPU_BENCH_MODE": "wire"}
        if _probe_platform() in (None, "cpu"):
            env.update({"PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
        res = _run_child(env, CHILD_TIMEOUT_S) or \
            _run_child(env, CHILD_TIMEOUT_S)
        if res is not None:
            print(json.dumps(res))
        else:
            print(json.dumps({
                "value": 0.0, "error": "wire_children_failed",
                "detail": {"child_errors": _CHILD_ERRORS[-2:]}}))
        return

    if os.environ.get("RA_TPU_BENCH_MODE") == "reads":
        # the read-plane frontier (ISSUE 20): host ingress + durable
        # engine — CPU-safe everywhere, one child (retry once)
        env = {"RA_TPU_BENCH_MODE": "reads"}
        for k in ("RA_TPU_BENCH_READ_SHARE", "RA_TPU_BENCH_LANES",
                  "RA_TPU_BENCH_SECONDS"):
            if os.environ.get(k):
                env[k] = os.environ[k]
        if _probe_platform() in (None, "cpu"):
            env.update({"PYTHONPATH": "", "JAX_PLATFORMS": "cpu"})
        res = _run_child(env, CHILD_TIMEOUT_S) or \
            _run_child(env, CHILD_TIMEOUT_S)
        if res is not None:
            print(json.dumps(res))
        else:
            print(json.dumps({
                "value": 0.0, "error": "reads_children_failed",
                "detail": {"child_errors": _CHILD_ERRORS[-2:]}}))
        return

    if os.environ.get("RA_TPU_BENCH_MODE") == "multichip":
        # explicit mode: one multichip sweep child, forced-host devices
        # when no real multi-device backend is reachable (the dryrun's
        # continuity posture — same step, same shardings, wall-clocked)
        platform = _probe_platform()
        env = {"RA_TPU_BENCH_MODE": "multichip"}
        if platform is None or platform == "cpu":
            env.update({
                "PYTHONPATH": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            })
        res = _run_child(env, MULTICHIP_TIMEOUT_S) or \
            _run_child(env, MULTICHIP_TIMEOUT_S)
        if res is not None:
            print(json.dumps(res))
        else:
            print(json.dumps({
                "value": 0.0, "error": "multichip_children_failed",
                "detail": {"child_errors": _CHILD_ERRORS[-2:]}}))
        return

    platform = _probe_platform()
    tpu_up = platform is not None and platform not in ("cpu",)

    if tpu_up:
        # full-config run; retry each impl once.  The pallas kernel is
        # a demoted experiment (measured ~10% below XLA, round 5 — see
        # docs/BENCHMARKS.md): it only re-enters the comparison when
        # RA_TPU_ENABLE_PALLAS_QUORUM=1 opts back in
        impls = ("xla", "pallas") if os.environ.get(
            "RA_TPU_ENABLE_PALLAS_QUORUM", "") not in ("", "0") \
            else ("xla",)
        results = {}
        for impl in impls:
            for _attempt in range(2):
                res = _run_child({"RA_TPU_QUORUM_IMPL": impl},
                                 CHILD_TIMEOUT_S)
                if res is not None:
                    results[impl] = res
                    break
        if results:
            best_impl = max(results, key=lambda k: results[k]["value"])
            best = results[best_impl]
            value = best["value"]
            detail = {"best_quorum_impl": best_impl, "host": _host_meta()}
            for impl, res in results.items():
                detail[impl] = res
            # secondary BASELINE.md rows (short windows): 5k x 5 fifo
            # enqueue/dequeue and 2k-lane kv mixed put/get
            for row, env in (
                ("durable_10k_x5", {"RA_TPU_BENCH_DURABLE": "1",
                                    "RA_TPU_BENCH_SECONDS": "4.0"}),
                ("frontier", {"RA_TPU_BENCH_MODE": "frontier",
                              "RA_TPU_BENCH_SECONDS": "3.0"}),
                ("fifo_5k_x5", {"RA_TPU_BENCH_MACHINE": "fifo",
                                "RA_TPU_BENCH_LANES": "5000",
                                "RA_TPU_BENCH_SECONDS": "2.0"}),
                ("kv_2k", {"RA_TPU_BENCH_MACHINE": "kv",
                           "RA_TPU_BENCH_LANES": "2000",
                           "RA_TPU_BENCH_SECONDS": "2.0"}),
            ):
                res = _run_child({**env, "RA_TPU_QUORUM_IMPL": best_impl},
                                 CHILD_TIMEOUT_S)
                if res is not None:
                    detail[row] = res
            print(json.dumps({
                "metric": "committed_cmds_per_sec_10k_clusters_5_members",
                "value": value,
                "unit": "cmds/s",
                "vs_baseline": round(value / BASELINE, 4),
                **_promote_device_keys(best),
                "detail": detail,
            }))
            return
        # TPU probed up but every child failed — a bench/engine problem,
        # not a tunnel problem; report it as such (with the children's
        # stderr) rather than masquerading as tpu_unavailable
        print(json.dumps({
            "metric": "committed_cmds_per_sec_10k_clusters_5_members",
            "value": 0.0,
            "unit": "cmds/s",
            "error": "bench_children_failed",
            "vs_baseline": 0.0,
            "detail": {"note": "TPU backend is reachable but the "
                               "measurement children failed",
                       "platform": platform,
                       "child_errors": _CHILD_ERRORS[-4:]},
        }))
        return

    # CPU fallback: strip the axon site hook so backend init cannot hang
    # (the sitecustomize PJRT registration blocks on a dead tunnel even for
    # JAX_PLATFORMS=cpu), run a scaled-down smoke config, and mark the
    # result clearly so the driver knows no hardware number was captured.
    smoke_env = {
        "PYTHONPATH": "",
        "JAX_PLATFORMS": "cpu",
        "RA_TPU_BENCH_LANES": "512",
        "RA_TPU_BENCH_MEMBERS": str(N_MEMBERS),
        "RA_TPU_BENCH_CMDS": "64",
        "RA_TPU_BENCH_SECONDS": "3.0",
    }
    res = _run_child(smoke_env, CHILD_TIMEOUT_S) or \
        _run_child(smoke_env, CHILD_TIMEOUT_S)
    if res is not None:
        detail = {
            "note": "TPU backend unreachable; value is a CPU smoke "
                    "datapoint at 512 lanes (not the headline config)",
            "retry_schedule": "tools/tpu_watch.sh probes the tunnel on "
                              "a fixed schedule all session and captures "
                              "the full TPU matrix (headline xla+pallas, "
                              "fifo 5k, frontier, durable, kv) into "
                              f"{CAPTURE_DIR}/ the moment it is reachable",
            "cpu_smoke": res,
            "host": _host_meta(),
        }
        captured = _load_captured_tpu_rows()
        if captured is not None:
            # supplementary evidence only — PRIOR real-TPU rows committed
            # by the capture harness; never promoted to the live value
            # (bench.py cannot prove they match the current revision)
            detail["captured_tpu_rows"] = captured
            detail["captured_tpu_rows_note"] = (
                f"prior real-TPU capture from {CAPTURE_DIR}/ "
                f"(capture log: {CAPTURE_DIR}/log); measured by "
                "tools/tpu_watch.sh on the code revision current at "
                "capture time, NOT re-measured now")
        # protocol-complete evidence even off-hardware: fsync-backed
        # commits and the sequential-machine (fifo) apply path.  Tight
        # per-row timeout: these are supplementary — they must never
        # push the (already measured) primary line past an outer
        # harness deadline.
        for row, extra in (
            ("cpu_smoke_durable", {"RA_TPU_BENCH_DURABLE": "1",
                                   "RA_TPU_BENCH_SECONDS": "2.0"}),
            ("cpu_smoke_fifo", {"RA_TPU_BENCH_MACHINE": "fifo",
                                "RA_TPU_BENCH_LANES": "256",
                                "RA_TPU_BENCH_SECONDS": "2.0"}),
        ):
            r = _run_child({**smoke_env, **extra}, PROBE_TIMEOUT_S)
            if r is not None:
                detail[row] = r
        print(json.dumps({
            "metric": "committed_cmds_per_sec_10k_clusters_5_members",
            "value": res["value"],
            "unit": "cmds/s",
            "error": "tpu_unavailable",
            "vs_baseline": round(res["value"] / BASELINE, 4),
            **_promote_device_keys(res),
            "detail": detail,
        }))
    else:
        print(json.dumps({
            "metric": "committed_cmds_per_sec_10k_clusters_5_members",
            "value": 0.0,
            "unit": "cmds/s",
            "error": "tpu_unavailable",
            "vs_baseline": 0.0,
            "detail": {"note": "TPU backend unreachable and CPU smoke "
                               "fallback failed",
                       "child_errors": _CHILD_ERRORS[-4:]},
        }))


if __name__ == "__main__":
    if os.environ.get("RA_TPU_BENCH_CHILD"):
        # children may crash loudly — the parent captures rc + stderr
        main()
    else:
        try:
            main()
        except BaseException as exc:  # noqa: BLE001 — contract: always JSON
            print(json.dumps({
                "metric": "committed_cmds_per_sec_10k_clusters_5_members",
                "value": 0.0,
                "unit": "cmds/s",
                "error": f"bench_parent_crashed: {type(exc).__name__}",
                "vs_baseline": 0.0,
                "detail": {"exception": repr(exc)[:500]},
            }))
        sys.exit(0)
