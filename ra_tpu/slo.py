"""SLO engine over the Observatory time-series ring (ISSUE 9).

The Observatory ring was built as "the substrate a future SLO autotuner
reads" (telemetry.py); this module closes the first half of that loop:
declarative objectives — a flat ring key, a comparison, a threshold —
evaluated PER WINDOW over the ring, with multi-window burn-rate
alerting (the Google SRE workbook shape: a breach only pages when both
a fast window and a slow window are burning, so a single noisy window
neither pages nor hides a sustained regression).

Two objective kinds:

* ``value`` — the key's value in each ring entry is compared against
  the threshold (latency percentiles: ``engine_phases_commit_e2e_p99_ms``,
  per-shard ``fsync_p99_ms``...).  Negative values are the repo-wide
  "never measured" sentinel and skip the window rather than counting as
  a pass.
* ``rate`` — the key is differentiated between consecutive ring
  entries via :meth:`Observatory.window_rates` (which owns the
  stale-sample omission and the counter-reset guard), and the RATE is
  compared (minimum throughput: ``engine_telemetry_committed_total``).

Keys may carry one ``*`` wildcard (``engine_wal_shards_*_fsync_p99_ms``)
aggregated by ``agg`` (max for latencies, sum for rates) — a 4-shard
WAL plane is one objective, not four.

Verdicts land in the Observatory snapshot (the engine registers itself
as a ``slo`` source), the Prometheus exposition and time-series ring
(``slo_objectives_<name>_ok`` flattens like any numeric), ra_top's SLO
panel, and the bench JSON tail.  The autotuner
(:mod:`ra_tpu.autotune`) reads the same verdict dict.
"""
from __future__ import annotations

from typing import Optional

#: default burn-rate windows: the fast window catches "breaching right
#: now", the slow window proves "and it has been for a while" — both
#: must burn past their fraction for the ``alert`` verdict
DEFAULT_FAST_WINDOWS = 5
DEFAULT_SLOW_WINDOWS = 30
DEFAULT_BURN_FAST = 0.6
DEFAULT_BURN_SLOW = 0.3


class Objective:
    """One declarative objective: ``key op threshold`` per window.

    ``name`` is the registry handle (ra_top column, verdict dict key);
    ``key`` a flat ring key, optionally with one ``*`` wildcard;
    ``op`` is ``"<="`` (latency ceilings) or ``">="`` (rate floors);
    ``kind`` ``"value"`` or ``"rate"``; ``agg`` resolves wildcard
    matches (``max``/``sum``/``min``)."""

    __slots__ = ("name", "key", "op", "threshold", "kind", "agg")

    def __init__(self, name: str, key: str, op: str, threshold: float,
                 *, kind: str = "value", agg: str = "max") -> None:
        if op not in ("<=", ">="):
            raise ValueError(f"objective op must be <= or >=; got {op!r}")
        if kind not in ("value", "rate"):
            raise ValueError(f"objective kind {kind!r}")
        self.name = name
        self.key = key
        self.op = op
        self.threshold = float(threshold)
        self.kind = kind
        self.agg = agg

    def describe(self) -> dict:
        return {"name": self.name, "key": self.key, "op": self.op,
                "threshold": self.threshold, "kind": self.kind,
                "agg": self.agg}


def default_objectives(*, commit_p99_ms: float = 25.0,
                       fsync_p99_ms: float = 50.0,
                       min_cmds_per_s: float = 1000.0,
                       read_p99_ms: float = 10.0) -> tuple:
    """The standard lane-engine objective set (docs/OBSERVABILITY.md
    "SLOs"): commit latency from the always-on phase attribution,
    fsync latency from the per-shard WAL stats, a throughput floor
    rated from the device telemetry's committed counter, and the
    device-plane compile-stability pin (ISSUE 16): a warm dispatch
    loop must not retrace, so the recompile-sentinel counter's rate
    over any window must stay 0 — the runtime twin of static gate
    RA13.  Absent devicewatch wiring the key never appears and the
    objective reads ``no_data`` (which is ok), so classic-plane
    deployments are unaffected.

    ``read_p99_ms`` (ISSUE 20) ceilings the read plane's submit→serve
    latency from the ``read_e2e`` phase (stamped only for dispatches
    that served reads); on a write-only engine the key never appears
    and the objective reads ``no_data``.  Its verdict is the read half
    of the ladder bias: ingress sheds reads outright at any tightened
    level, so a read_p99 breach never delays the write plane."""
    return (
        Objective("commit_p99_ms",
                  "engine_phases_commit_e2e_p99_ms", "<=", commit_p99_ms),
        Objective("fsync_p99_ms",
                  "engine_wal_shards_*_fsync_p99_ms", "<=", fsync_p99_ms),
        Objective("cmds_per_s",
                  "engine_telemetry_committed_total", ">=",
                  min_cmds_per_s, kind="rate", agg="sum"),
        Objective("steady_state_recompiles",
                  "device_recompiles", "<=", 0.0, kind="rate"),
        Objective("read_p99_ms",
                  "engine_phases_read_e2e_p99_ms", "<=", read_p99_ms),
    )


def _match_keys(flat: dict, pattern: str) -> list:
    if "*" not in pattern:
        return [pattern] if pattern in flat else []
    pre, _star, suf = pattern.partition("*")
    return [k for k in flat
            if k.startswith(pre) and k.endswith(suf)
            and len(k) >= len(pre) + len(suf)]


def _aggregate(vals: list, agg: str) -> Optional[float]:
    if not vals:
        return None
    if agg == "sum":
        return float(sum(vals))
    if agg == "min":
        return float(min(vals))
    return float(max(vals))


class SloEngine:
    """Evaluate a set of objectives per window over an Observatory's
    ring, with multi-window burn-rate verdicts.

    Construction registers the engine as the Observatory's ``slo``
    source, so every snapshot embeds the verdicts computed over the
    ring as of the PREVIOUS snapshots — the verdict always describes
    completed windows, never the half-built one."""

    def __init__(self, observatory, objectives=None, *,
                 fast_windows: int = DEFAULT_FAST_WINDOWS,
                 slow_windows: int = DEFAULT_SLOW_WINDOWS,
                 burn_fast: float = DEFAULT_BURN_FAST,
                 burn_slow: float = DEFAULT_BURN_SLOW) -> None:
        self.obs = observatory
        self.objectives = tuple(objectives if objectives is not None
                                else default_objectives())
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.fast_windows = max(1, int(fast_windows))
        self.slow_windows = max(self.fast_windows, int(slow_windows))
        self.burn_fast = float(burn_fast)
        self.burn_slow = float(burn_slow)
        #: evaluate() memo for an unchanged ring: the Observatory's
        #: slo source and the autotuner's tick both evaluate at every
        #: window boundary — the second call must not pay the full
        #: multi-window sweep again (the <3% plane-overhead pin)
        self._cache: tuple = (None, None)
        observatory.add_source("slo", self.evaluate)

    # -- per-window evaluation --------------------------------------------

    def _window_value(self, obj: Objective, i: int, ring: list,
                      wanted: list) -> Optional[float]:
        """Objective value at ring window ``i`` (the pair ``i-1 -> i``
        for rates, the entry ``i`` for values), or None when the
        window carries no signal for it (missing key, -1 sentinel,
        stale sample / counter reset omission).  ``wanted`` is the
        objective's matched key list, resolved ONCE per evaluate
        against the newest entry — re-globbing every key of every
        window would put O(windows x keys) string work on the
        snapshot path (a window lacking a matched key simply
        contributes fewer values)."""
        if obj.kind == "rate":
            rates = self.obs.window_rates(end=i, keys=wanted)
            vals = [rates[k] for k in wanted if k in rates]
        else:
            flat = ring[i][1]
            # the repo-wide "never measured" sentinel (-1 fsync p50 on
            # a sync_mode=0 WAL, -1 phase p99 before the first sample)
            # is absence of signal, not a zero-latency pass
            vals = [flat[k] for k in wanted
                    if k in flat and flat[k] >= 0]
        return _aggregate(vals, obj.agg)

    def _breaches(self, obj: Objective, val: float) -> bool:
        return not (val <= obj.threshold if obj.op == "<="
                    else val >= obj.threshold)

    def evaluate(self) -> dict:
        """Verdict per objective over the ring: the newest window's
        value, breach burn fractions over the fast and slow windows,
        and the verdict — ``ok`` / ``breach`` (newest window breaches
        and the fast window burns) / ``alert`` (fast AND slow windows
        both burn: sustained, page-worthy).  Windows with no signal
        are skipped, never counted as passes."""
        ring = self.obs.ring()
        n = len(ring)
        # keyed by the Observatory's snapshot seq: a ring that has not
        # grown yields the memoized verdicts (an id()-based key could
        # alias a recycled dict; seq never repeats)
        cache_key = (n, getattr(self.obs, "_seq", 0))
        if self._cache[0] == cache_key:
            return self._cache[1]
        out: dict = {"objectives": {}, "windows": max(0, n - 1)}
        breaches = 0
        alerts = 0
        for obj in self.objectives:
            wanted = _match_keys(ring[-1][1], obj.key) if n else []
            # a value objective reads single entries (the first snapshot
            # is already a window); a rate objective needs a pair
            lo = max(0 if obj.kind == "value" else 1,
                     n - self.slow_windows)
            fast_hits = fast_seen = slow_hits = slow_seen = 0
            newest_val = None
            newest_breach = newest_live = False
            for i in range(lo, n):
                val = self._window_value(obj, i, ring, wanted)
                if val is None:
                    continue
                bad = self._breaches(obj, val)
                slow_seen += 1
                slow_hits += int(bad)
                if i >= n - self.fast_windows:
                    fast_seen += 1
                    fast_hits += int(bad)
                newest_val, newest_breach = val, bad
                newest_live = i == n - 1
            burn_f = fast_hits / fast_seen if fast_seen else 0.0
            burn_s = slow_hits / slow_seen if slow_seen else 0.0
            if not newest_live:
                # the NEWEST window carries no signal (sentinel,
                # stale sample, counter reset): the verdict must say
                # so rather than re-issue a stale ok/breach — the
                # omission guards' discipline carried into verdicts
                verdict = "no_data"
            elif newest_breach and burn_f >= self.burn_fast \
                    and burn_s >= self.burn_slow:
                verdict = "alert"
            elif newest_breach and burn_f >= self.burn_fast:
                verdict = "breach"
            else:
                verdict = "ok"
            breaches += int(verdict in ("breach", "alert"))
            alerts += int(verdict == "alert")
            out["objectives"][obj.name] = {
                **obj.describe(),
                "value": round(newest_val, 4)
                if newest_val is not None else None,
                "ok": verdict in ("ok", "no_data"),
                "verdict": verdict,
                "burn_fast": round(burn_f, 4),
                "burn_slow": round(burn_s, 4),
                "windows_seen": slow_seen,
            }
        out["breaches"] = breaches
        out["alerts"] = alerts
        out["ok"] = breaches == 0
        self._cache = (cache_key, out)
        return out

    def verdict(self, name: str) -> str:
        """One objective's current verdict string (``ok`` / ``breach``
        / ``alert`` / ``no_data``), or ``no_data`` for an unknown name
        — the accessor the ingress backpressure ladder polls (memoized
        with evaluate(), so a per-wave poll costs one dict lookup)."""
        obj = self.evaluate()["objectives"].get(name)
        return obj["verdict"] if obj else "no_data"
