from .rpc import (  # noqa: F401
    FaultPlan,
    FaultSpec,
    RemoteError,
    RpcError,
    RpcTimeout,
    Unreachable,
    reliable_node_call,
)
from .tcp import TcpRouter  # noqa: F401
