from .tcp import TcpRouter
