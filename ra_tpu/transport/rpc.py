"""Reliable control-plane RPC + deterministic transport fault injection.

The reference rides node-lifecycle calls over ``rpc:call`` on Erlang
distribution, which transparently re-establishes the connection to a
restarted peer before delivering (ra_server_sup_sup.erl:42-130).  The
TCP fabric here is deliberately lossy for Raft DATA traffic (the
[noconnect, nosuspend] cast semantics — pipeline catch-up recovers),
but a lifecycle RPC that silently vanishes into a half-dead socket is
a 60s hang, not a recoverable drop.  This module builds the reliable
request/response channel the control plane needs, distinct from the
best-effort replication plane — the same control/data-plane split
hierarchical Raft designs make explicit (Fast Raft, arxiv 2506.17793;
CD-Raft, arxiv 2603.10555).

Three pieces:

* **Sender**: :func:`reliable_node_call` — per-request ids, retry with
  exponential backoff + jitter, deadline propagation (the remaining
  budget travels inside the request), reconnect-aware routing (a retry
  against a peer the failure detector holds suspect/down invalidates
  the cached connection first), and typed error surfaces —
  :class:`Unreachable` vs :class:`RpcTimeout` vs :class:`RemoteError` —
  instead of a silent hang.
* **Receiver**: :class:`RpcReceiver` — an at-most-once execution guard:
  a bounded LRU of request ids maps retries of an already-executed
  request onto its cached response (dedup), and retries of an
  in-flight request onto nothing (the completion will answer), so a
  lifecycle verb never runs twice no matter how often the sender
  retries.
* **FaultPlan**: a seeded, deterministic fault-injection plan the
  transport consults at send/recv — drop / delay / duplicate / reorder
  / partition, keyed by (peer, frame-class, direction) so each stream
  draws from its own RNG and a schedule replays identically regardless
  of thread interleaving elsewhere.  The in-process chaos counterpart
  of tests/test_engine_chaos.py for the wire.
"""
from __future__ import annotations

import random
import threading
import time
import uuid
import weakref
from collections import OrderedDict
from dataclasses import dataclass, replace
from typing import Any, Callable, Optional

from .. import trace
from ..blackbox import RECORDER, record

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "RemoteError",
    "RpcError",
    "RpcReceiver",
    "RpcRequest",
    "RpcResponse",
    "RpcTimeout",
    "Unreachable",
    "reliable_node_call",
]


# ---------------------------------------------------------------------------
# Error surfaces (ra.erl's {error, noproc|nodedown|timeout} triad)
# ---------------------------------------------------------------------------

class RpcError(RuntimeError):
    """Base class for control-plane RPC failures."""


class Unreachable(RpcError):
    """The target node cannot be reached: no route, or the failure
    detector holds it suspect/down at the deadline (nodedown)."""


class RpcTimeout(RpcError, TimeoutError):
    """The call's deadline elapsed while the peer looked reachable —
    requests were sent but no response arrived in time."""


class RemoteError(RpcError):
    """The remote executor itself failed; carries the remote repr."""


# ---------------------------------------------------------------------------
# Wire records
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RpcRequest:
    """One control-plane request.  ``rid`` is stable across retries —
    it is the at-most-once dedup key.  ``deadline_unix`` propagates the
    caller's remaining budget (wall clock: monotonic clocks are not
    comparable across processes; cross-host skew makes this advisory)."""

    rid: str
    node: str                 # target node name (the $node scope)
    op: str
    args: dict
    deadline_unix: float = 0.0
    attempt: int = 1
    origin: tuple = ()        # sender's listen addr, filled by transport
    origin_router: str = ""   # sender's router id (wildcard-bind safe)
    #: causal trace context (ISSUE 7): minted at the sender's ingress,
    #: STABLE across retries like ``rid`` — a duplicate delivery dedups
    #: receiver-side and records as a ``rpc.dup`` event under the same
    #: trace id, so at-most-once execution is visible, not just true
    trace_ctx: str = ""


@dataclass(frozen=True)
class RpcResponse:
    rid: str
    ok: bool
    value: Any = None
    error: str = ""
    #: a retryable failure means "not executed, try again" (e.g. the
    #: target RaNode is not registered on that host YET — a restarting
    #: worker); non-retryable means the executor crashed or refused
    retryable: bool = False


# ---------------------------------------------------------------------------
# Receiver-side at-most-once guard
# ---------------------------------------------------------------------------

class RpcReceiver:
    """Dedup/response cache giving retried requests at-most-once
    execution.  ``execute(req, done)`` starts the operation and calls
    ``done(result)`` exactly once when finished; it returns False when
    the target is not hosted here (retryable, NOT cached — a later
    retry may find the node registered)."""

    CACHE_MAX = 1024

    def __init__(self, execute: Callable[[RpcRequest, Callable], bool],
                 counters: Optional[dict] = None) -> None:
        self._execute = execute
        self._cache: OrderedDict = OrderedDict()  # rid -> (status, resp)
        self._lock = threading.Lock()
        self.counters = counters if counters is not None else {}

    def _note(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def handle(self, req: RpcRequest,
               respond: Callable[[RpcResponse], None]) -> None:
        with self._lock:
            ent = self._cache.get(req.rid)
            if ent is not None:
                # a retry of something we already saw: never re-execute
                self._cache.move_to_end(req.rid)
                self._note("rpc_dedup_hits")
                # the duplicate delivery is VISIBLE under the same
                # trace id while the verb still runs at most once
                record("rpc.dup", trace=req.trace_ctx, rid=req.rid,
                       op=req.op, attempt=req.attempt)
                status, resp = ent
                if status == "done":
                    self._note("rpc_responses_resent")
                    respond(resp)
                # in-flight: say nothing — completion will respond, and
                # any later retry lands on the cached response
                return
            self._cache[req.rid] = ("running", None)
            while len(self._cache) > self.CACHE_MAX:
                # evict oldest DONE entry only: evicting a 'running'
                # rid would let its retry re-execute the verb — the
                # exact double-execution this cache exists to prevent.
                # If everything is in flight the cache grows past the
                # cap, bounded by concurrent executions.
                for key, (status, _resp) in self._cache.items():
                    if status != "running":
                        del self._cache[key]
                        break
                else:
                    break
        if req.deadline_unix and time.time() > req.deadline_unix:
            # the sender's budget is spent: executing now could only
            # produce a zombie side effect nobody awaits
            self._note("rpc_expired")
            record("rpc.expired", trace=req.trace_ctx, rid=req.rid,
                   op=req.op)
            resp = RpcResponse(req.rid, ok=False, error="deadline_expired")
            with self._lock:
                self._cache[req.rid] = ("done", resp)
            respond(resp)
            return

        def done(result: Any) -> None:
            resp = RpcResponse(req.rid, ok=True, value=result)
            with self._lock:
                self._cache[req.rid] = ("done", resp)
            respond(resp)

        self._note("rpc_requests_executed")
        record("rpc.recv", trace=req.trace_ctx, rid=req.rid, op=req.op,
               attempt=req.attempt)
        try:
            started = self._execute(req, done)
        except Exception as exc:  # noqa: BLE001 — travels to the caller
            resp = RpcResponse(req.rid, ok=False, error=repr(exc)[:400])
            with self._lock:
                self._cache[req.rid] = ("done", resp)
            respond(resp)
            return
        if not started:
            # target node not hosted here (yet): forget the rid so a
            # retry can execute once it registers, and tell the sender
            # to keep trying
            self._note("rpc_requests_executed", -1)
            with self._lock:
                self._cache.pop(req.rid, None)
            respond(RpcResponse(req.rid, ok=False, retryable=True,
                                error=f"node {req.node!r} not hosted"))

    def overview(self) -> dict:
        with self._lock:
            return {"cached": len(self._cache), **dict(self.counters)}


# ---------------------------------------------------------------------------
# Sender-side retry loop
# ---------------------------------------------------------------------------

#: per-attempt response wait: grows exponentially from FIRST to CAP so a
#: lost first request retries fast while a genuinely slow executor
#: (start_server recovering a long log) is not hammered
ATTEMPT_WAIT_FIRST = 0.3
ATTEMPT_WAIT_CAP = 3.0
#: sleep between attempts: exponential with full jitter, capped
BACKOFF_FIRST = 0.05
BACKOFF_CAP = 1.0


def _attempt_wait(attempt: int) -> float:
    return min(ATTEMPT_WAIT_FIRST * (2 ** (attempt - 1)), ATTEMPT_WAIT_CAP)


def reliable_node_call(router, node: str, op: str, args: dict,
                       timeout: float = 60.0,
                       trace_ctx: Optional[str] = None) -> Any:
    """Call ``op`` on ``node``'s control plane with retries, dedup and
    typed failures — the rpc:call-over-distribution role.  The router
    must provide the RPC transport surface (TcpRouter does); a router
    without it (LocalRouter reaching for a remote node) is Unreachable
    by construction.  A trace context (minted here if the caller did
    not propagate one) rides every attempt's frame: retries and
    duplicate deliveries record under ONE id."""
    if getattr(router, "rpc_register", None) is None:
        raise Unreachable(
            f"node {node} is unreachable for {op}: router has no RPC "
            "transport (in-process LocalRouter has no remote reach)")
    if not router.rpc_routable(node):
        router.rpc_note("rpc_unreachable")
        raise Unreachable(
            f"node {node} is unreachable for {op}: not in the address "
            "book")
    router.rpc_note("rpc_calls")
    rid = uuid.uuid4().hex
    ctx = trace_ctx or trace.new_trace_ctx()
    rng = random.Random(rid)
    deadline = time.monotonic() + timeout
    fut = router.rpc_register(rid)
    attempt = 0
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            attempt += 1
            if attempt > 1:
                router.rpc_note("rpc_retries")
                # reconnect-aware routing: a cached connection to a
                # peer the detector suspects is exactly the half-dead
                # socket that eats one-shot sends
                router.rpc_invalidate_peer(node)
            req = RpcRequest(rid=rid, node=node, op=op, args=dict(args),
                             deadline_unix=time.time() + remaining,
                             attempt=attempt, trace_ctx=ctx)
            record("rpc.send", trace=ctx, rid=rid, op=op, node=node,
                   attempt=attempt)
            router.rpc_send(node, req)
            try:
                resp = fut.wait(min(_attempt_wait(attempt), remaining))
            except TimeoutError:
                pause = rng.uniform(0.5, 1.0) * min(
                    BACKOFF_FIRST * (2 ** (attempt - 1)), BACKOFF_CAP)
                pause = min(pause, max(deadline - time.monotonic(), 0.0))
                if pause > 0:
                    time.sleep(pause)
                continue
            if resp.ok:
                return resp.value
            if resp.retryable:
                fut = router.rpc_register(rid)  # re-arm for the retry
                # same exponential schedule as the timeout branch: a
                # restarting worker can take tens of seconds to
                # register its node — constant 50ms pacing would hammer
                # it with hundreds of round trips
                pause = rng.uniform(0.5, 1.0) * min(
                    BACKOFF_FIRST * (2 ** (attempt - 1)), BACKOFF_CAP)
                time.sleep(min(pause,
                               max(deadline - time.monotonic(), 0.0)))
                continue
            if resp.error == "deadline_expired":
                break  # surfaces as RpcTimeout below
            router.rpc_note("rpc_remote_errors")
            raise RemoteError(
                f"rpc {op} on {node} failed remotely: {resp.error}")
    finally:
        router.rpc_forget(rid)
    state = router.rpc_peer_state(node) if \
        hasattr(router, "rpc_peer_state") else None
    if state in ("suspect", "down", "never-connected"):
        router.rpc_note("rpc_unreachable")
        raise Unreachable(
            f"node {node} is unreachable for {op} "
            f"(peer state: {state}, {attempt} attempts)")
    router.rpc_note("rpc_timeouts")
    raise RpcTimeout(
        f"rpc {op} to {node} timed out after {timeout:.1f}s "
        f"({attempt} attempts)")


# ---------------------------------------------------------------------------
# Deterministic fault injection
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FaultSpec:
    """Per-stream fault probabilities.  ``limit`` bounds the TOTAL
    number of faults this spec may inject on one stream (0 = unbounded)
    — a limit of 3 with drop=1.0 means 'drop exactly the first three
    frames', which is how tests script deterministic scenarios."""

    drop: float = 0.0
    delay: float = 0.0
    delay_ms: tuple = (1.0, 10.0)
    duplicate: float = 0.0
    reorder: float = 0.0
    limit: int = 0


@dataclass(frozen=True)
class FaultDecision:
    action: str = "deliver"        # "deliver" | "drop"
    delay_s: float = 0.0
    duplicate: bool = False
    reorder: bool = False


_DELIVER = FaultDecision()
_DROP = FaultDecision(action="drop")


#: live FaultPlans (weak: a dropped plan leaves the bundle) — the
#: "active FaultPlan state" source every post-mortem bundle embeds
_LIVE_PLANS: "weakref.WeakSet" = weakref.WeakSet()
RECORDER.add_source(
    "net_fault_plans",
    lambda: [p.overview() for p in list(_LIVE_PLANS)])


def live_fault_plans() -> list:
    """The transport FaultPlans still alive in this process — what the
    post-mortem bundle source embeds, and the autotuner's freeze guard
    reads ("hard freeze while any FaultPlan is active": a controller
    must never chase chaos-injected latency with knob turns).  Weakly
    tracked: a plan with no remaining strong referent drops out."""
    return list(_LIVE_PLANS)


class FaultPlan:
    """Seeded fault schedule consulted by the transport.

    Specs resolve most-specific-first: ``(peer, frame_class)`` then
    ``peer`` then ``frame_class`` then the default.  Every
    ``(peer, frame_class, direction)`` stream owns a private RNG seeded
    from the plan seed + the key, so one stream's draws never perturb
    another's — the same schedule replays identically whatever the
    thread interleaving (the wire counterpart of the engine chaos
    schedule's seeded rounds, tests/test_engine_chaos.py).

    Frame classes: ``msg`` (Raft data), ``rpc_req``/``rpc_resp``
    (control plane), ``reply``, ``notify``, ``ping``, ``hello``.
    Partitions are binary per peer: every frame both ways drops until
    :meth:`heal`.

    **Latency domains** (ISSUE 19): ``domains`` declares a named-domain
    delay matrix so a whole geo topology is one object::

        FaultPlan(seed, domains={
            "local": "ctl",                        # where THIS plan runs
            "members": {"ctl": ["ctl0"],
                        "geo": ["gf1", "gf2"],
                        "eng": ["engA", "engB"]},
            "matrix": {("ctl", "geo"):             # per (src, dst) pair
                       {"delay_ms": 80.0, "jitter_ms": 70.0}},
        })

    Matrix values are :class:`FaultSpec` objects or dicts compiled to
    one (``delay_ms`` as a number with optional ``jitter_ms``, or an
    explicit ``(lo, hi)`` tuple; optional ``drop`` probability; a pure
    delay spec gets ``delay=1.0`` — network distance is deterministic,
    not probabilistic).  Resolution: an exact ``(src, dst)`` key wins,
    else the reversed pair (cross-domain RTT is symmetric unless the
    matrix says otherwise).  A peer's domain comes from ``members``;
    ``send`` frames cross ``(local, domain_of(peer))``, ``recv`` frames
    ``(domain_of(peer), local)``.  The matrix ranks below every
    explicit per-peer/per-class spec and above the default, and it
    compiles onto the SAME per-(peer, frame-class, direction) RNG
    streams as everything else (docs/INTERNALS.md §20) — no new
    replay machinery, and a matrix delay records ``rpc.domain_delay``
    so timelines show which domain crossing stretched a frame.
    """

    def __init__(self, seed: int = 0,
                 default: Optional[FaultSpec] = None,
                 by_class: Optional[dict] = None,
                 by_peer: Optional[dict] = None,
                 by_peer_class: Optional[dict] = None,
                 domains: Optional[dict] = None) -> None:
        self.seed = seed
        self.default = default or FaultSpec()
        self.by_class = dict(by_class or {})
        self.by_peer = dict(by_peer or {})
        self.by_peer_class = dict(by_peer_class or {})
        self._rngs: dict = {}
        self._spent: dict = {}       # stream key -> faults injected
        self._lock = threading.Lock()
        self.partitioned: set = set()
        #: injected-fault counters by kind (drop/delay/duplicate/
        #: reorder/partition), merged into the router overview
        self.counters: dict = {}
        self.domains = dict(domains or {})
        self._local_domain = self.domains.get("local", "")
        #: peer name -> domain name (compiled from domains["members"])
        self._domain_of: dict = {
            peer: dname
            for dname, peers in self.domains.get("members", {}).items()
            for peer in peers}
        #: (src, dst) -> FaultSpec (compiled from domains["matrix"])
        self._matrix: dict = {
            tuple(pair): self._compile_domain_spec(v)
            for pair, v in self.domains.get("matrix", {}).items()}
        _LIVE_PLANS.add(self)  # post-mortem bundles name active plans

    @staticmethod
    def _compile_domain_spec(value) -> FaultSpec:
        """A matrix cell → FaultSpec.  Dicts name network distance
        declaratively: ``delay_ms`` (number → uniform over
        [delay, delay + jitter_ms], or an explicit (lo, hi) tuple) and
        an optional ``drop`` probability.  Any nonzero delay range gets
        probability 1.0 — every frame crossing the boundary pays the
        distance."""
        if isinstance(value, FaultSpec):
            return value
        v = dict(value)
        delay_ms = v.get("delay_ms", 0.0)
        if isinstance(delay_ms, (tuple, list)):
            lo, hi = float(delay_ms[0]), float(delay_ms[1])
        else:
            lo = float(delay_ms)
            hi = lo + float(v.get("jitter_ms", 0.0))
        drop = float(v.get("drop", 0.0))
        return FaultSpec(drop=drop,
                         delay=1.0 if hi > 0.0 else 0.0,
                         delay_ms=(lo, hi))

    # -- schedule control ---------------------------------------------------

    def quiet(self) -> bool:
        """True when this plan can no longer inject anything: every
        spec carries zero probabilities and no partition is standing.
        A healed partition-only plan, or an all-defaults plan, is
        quiet — the autotuner's freeze guard reads this, because a
        plan object pinned by a router after the chaos exercise ended
        must not freeze the controller for the rest of the process
        (liveness is not activity).  Domain matrices are judged from
        THIS plan's vantage: only cells touching the local domain can
        ever inject here, so a standing 100 ms control-tier matrix
        leaves an engine-tier plan (same topology, different
        ``local``) quiet — the freeze guard must not freeze the
        engine hosts' tuners for latency they never see."""
        if self.partitioned:
            return False
        specs = [self.default, *self.by_class.values(),
                 *self.by_peer.values(), *self.by_peer_class.values()]
        specs += [spec for (src, dst), spec in self._matrix.items()
                  if self._local_domain in (src, dst)]
        return all(s.drop == 0 and s.delay == 0 and s.duplicate == 0
                   and s.reorder == 0 for s in specs)

    def unregister(self) -> None:
        """Drop this plan from the live-plan registry (the bundle
        source and the autotuner freeze guard stop seeing it) without
        disturbing transports still holding it.  Test scoping uses
        this: the registry is process-global and weakly held, so a
        plan pinned by a leaked router would otherwise freeze every
        later tuner and skip the quiet-plan probes — conftest
        unregisters plans a test created once the test ends."""
        _LIVE_PLANS.discard(self)

    def partition(self, peer: str) -> None:
        self.partitioned.add(peer)

    def heal(self, peer: Optional[str] = None) -> None:
        if peer is None:
            self.partitioned.clear()
        else:
            self.partitioned.discard(peer)

    # -- decision -----------------------------------------------------------

    def _spec_for(self, peer: str, frame_class: str) -> FaultSpec:
        return self._resolve(peer, frame_class, "send")[0]

    def _domain_pair(self, peer: str, direction: str):
        """The (src, dst) matrix cell a frame to/from ``peer`` crosses,
        or None when the peer has no domain or no cell applies.  An
        exact key wins; the reversed pair covers the symmetric-RTT
        common case."""
        dom = self._domain_of.get(peer)
        if dom is None or not self._matrix:
            return None
        pair = (self._local_domain, dom) if direction == "send" \
            else (dom, self._local_domain)
        if pair in self._matrix:
            return pair
        rev = (pair[1], pair[0])
        if rev in self._matrix:
            return rev
        return None

    def _resolve(self, peer: str, frame_class: str, direction: str):
        """(spec, domain_pair) — domain_pair is the matrix cell the
        spec came from, None for explicitly-keyed specs (which rank
        above the matrix) and the default (which ranks below)."""
        for key in ((peer, frame_class),):
            if key in self.by_peer_class:
                return self.by_peer_class[key], None
        if peer in self.by_peer:
            return self.by_peer[peer], None
        if frame_class in self.by_class:
            return self.by_class[frame_class], None
        pair = self._domain_pair(peer, direction)
        if pair is not None:
            return self._matrix[pair], pair
        return self.default, None

    def _note(self, kind: str, peer: str = "",
              frame_class: str = "") -> None:
        self.counters[kind] = self.counters.get(kind, 0) + 1
        # every injected wire fault is a flight-recorder event: a
        # post-mortem timeline shows WHICH frame the chaos hit
        record("net.fault", kind=kind, peer=peer, cls=frame_class)

    def is_partitioned(self, peer: str) -> bool:
        if peer in self.partitioned:
            self._note("partition", peer)
            return True
        return False

    def recv_peer(self, names) -> str:
        """Fault-stream key for an INBOUND connection whose hello named
        ``names`` (co-hosted routers announce every node behind one
        conn): the first name the plan explicitly targets (partition or
        per-peer spec), else the first name.  Recv granularity is the
        connection — per-peer specs for co-hosted nodes are only
        distinguishable when the plan targets one of them."""
        for n in names:
            if n in self.partitioned or n in self.by_peer or \
                    any(k[0] == n for k in self.by_peer_class):
                return n
        return names[0] if names else "?"

    #: every fault kind a call site may honor; paths that can only
    #: drop (recv, detector pings) pass honor={"drop"} so un-honorable
    #: decisions neither spend the spec's limit nor count as injected
    ALL_FAULTS = frozenset({"drop", "delay", "duplicate", "reorder"})

    def decide(self, peer: str, frame_class: str,
               direction: str = "send",
               honor: frozenset = ALL_FAULTS) -> FaultDecision:
        if peer in self.partitioned:
            self._note("partition", peer, frame_class)
            return _DROP
        spec, domain_pair = self._resolve(peer, frame_class, direction)
        if spec.drop == spec.delay == spec.duplicate == spec.reorder == 0:
            return _DELIVER
        key = (peer, frame_class, direction)
        with self._lock:
            rng = self._rngs.get(key)
            if rng is None:
                rng = self._rngs[key] = random.Random(
                    f"{self.seed}:{peer}:{frame_class}:{direction}")
            if spec.limit and self._spent.get(key, 0) >= spec.limit:
                return _DELIVER
            roll = rng.random()
            edge = 0.0
            for kind, prob in (("drop", spec.drop),
                               ("delay", spec.delay),
                               ("duplicate", spec.duplicate),
                               ("reorder", spec.reorder)):
                edge += prob
                if roll >= edge:
                    continue
                if kind not in honor:
                    return _DELIVER
                self._spent[key] = self._spent.get(key, 0) + 1
                self._note(kind, peer, frame_class)
                if kind == "drop":
                    return _DROP
                if kind == "delay":
                    lo, hi = spec.delay_ms
                    delay_s = rng.uniform(lo, hi) / 1000.0
                    if domain_pair is not None:
                        # a matrix-sourced stretch is geography, not
                        # chaos — timelines name the domain crossing
                        record("rpc.domain_delay", peer=peer,
                               cls=frame_class, src=domain_pair[0],
                               dst=domain_pair[1],
                               delay_ms=round(delay_s * 1000.0, 3))
                    return FaultDecision(delay_s=delay_s)
                if kind == "duplicate":
                    return FaultDecision(duplicate=True)
                return FaultDecision(reorder=True)
        return _DELIVER

    def overview(self) -> dict:
        out = {"seed": self.seed,
               "partitioned": sorted(self.partitioned),
               "injected": dict(self.counters)}
        if self._matrix:
            out["local_domain"] = self._local_domain
            out["domain_matrix"] = sorted(
                f"{src}->{dst}" for src, dst in self._matrix)
        return out


def stamp_origin(req: RpcRequest, origin: tuple,
                 router_id: str) -> RpcRequest:
    """Fill the transport-owned origin fields just before the wire."""
    return replace(req, origin=tuple(origin), origin_router=router_id)
