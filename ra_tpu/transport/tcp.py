"""TCP transport + node failure detection — the cross-host fabric.

Replicates the semantics the reference gets from Erlang distribution +
aten (SURVEY.md §2.4 'Distributed communication backend'):

* sends are NEVER blocking: each peer has a bounded outbound queue and a
  sender thread; a full queue or broken/unreachable connection drops the
  message and counts it (the [noconnect, nosuspend] cast semantics of
  ra_server_proc.erl:1317-1341 — Raft's pipeline catch-up recovers)
* per-peer connection status (normal | disconnected) feeds drop decisions
  and metrics (ra.hrl:329-330 drop counters)
* a lightweight heartbeat failure detector stands in for aten: every
  connected peer is pinged on an interval; silence beyond a threshold
  emits NodeEvent(node, "down") to every local server shell, recovery
  emits NodeEvent(node, "up") (aten's poll-interval role,
  ra_server_proc.erl:790-810, 1690-1700)
* frames are length-prefixed pickles between cluster hosts — the same
  mutual-trust model as Erlang distribution inside a cluster; do not
  expose the port beyond it

TcpRouter extends the in-process LocalRouter: ServerIds whose node is
hosted locally are delivered directly; remote nodes resolve through the
address book.
"""
from __future__ import annotations

import logging
import pickle
import queue
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import replace as _dc_replace
from typing import Optional

from ..core.types import (
    CommandEvent,
    CommandsEvent,
    NodeEvent,
    ServerId,
    strip_msg_handles,
)
from ..node import LocalRouter

logger = logging.getLogger("ra_tpu.transport")

_LEN = struct.Struct("<I")
FRAME_MSG = 0
FRAME_PING = 1
FRAME_HELLO = 2
FRAME_REPLY = 3
FRAME_NOTIFY = 4

SEND_QUEUE_MAX = 10_000
MAX_FRAME = 64 * 1024 * 1024  # snapshot chunks are 1MB; generous headroom
PING_INTERVAL = 0.5
DOWN_AFTER = 2.0          # silence threshold (aten default poll is 1s)
CONNECT_TIMEOUT = 1.0
RECONNECT_BACKOFF = 0.5


class _Peer:
    __slots__ = ("name", "addr", "queue", "sock", "thread", "status",
                 "last_attempt", "lock", "send_lock")

    def __init__(self, name: str, addr: tuple) -> None:
        self.name = name
        self.addr = addr
        self.queue: "queue.Queue" = queue.Queue(maxsize=SEND_QUEUE_MAX)
        self.sock: Optional[socket.socket] = None
        self.thread: Optional[threading.Thread] = None
        self.status = "disconnected"
        self.last_attempt = 0.0
        self.lock = threading.Lock()
        # serializes sendall between the sender and detector threads: an
        # interleaved ping inside a partially-sent frame corrupts the stream
        self.send_lock = threading.Lock()


class TcpRouter(LocalRouter):
    """LocalRouter + TCP reach to remote nodes."""

    def __init__(self, listen_addr: tuple, address_book: dict) -> None:
        super().__init__()
        self.listen_addr = listen_addr
        self.address_book = dict(address_book)  # node name -> (host, port)
        self.peers: dict[str, _Peer] = {}
        self.dropped_sends = 0
        self.last_heard: dict[str, float] = {}
        self.node_status: dict[str, str] = {}
        #: nemesis hook: nodes whose traffic is blocked at the socket
        #: level (the inet_tcp_proxy role the reference's
        #: partitions_SUITE uses, partitions_SUITE.erl:29-57) — sends
        #: drop+count, inbound frames are ignored, the failure detector
        #: sees silence and rules the node down
        self.blocked_nodes: set = set()
        self._stop = False
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(listen_addr)
        self._server.listen(64)
        self.listen_addr = self._server.getsockname()
        # outstanding cross-host client calls: call id -> Future
        self._calls: dict = {}
        self._call_seq = 0
        self._call_lock = threading.Lock()
        # durable applied-notification sinks for pipelined commands that
        # cross hosts: nid -> callable, id(callable) -> nid.  Unlike
        # _calls these are multi-shot (one client receives many Notify
        # batches), so they persist; an LRU cap bounds them when callers
        # pass a fresh callable per command instead of reusing a sink
        self._notify_handles: OrderedDict = OrderedDict()
        self._notify_ids: dict = {}
        self._notify_seq = 0
        # distinguishes this router in rnotify handles: bind-address
        # equality is unreliable under wildcard binds (0.0.0.0 on every
        # host would alias all routers)
        self._router_id = uuid.uuid4().hex[:12]
        # lazily-created peers keyed by raw address (reply routing)
        self._addr_peers: dict[tuple, _Peer] = {}
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="ra-tcp-accept")
        self._accept_thread.start()
        self._detector_thread = threading.Thread(target=self._detector_loop,
                                                 daemon=True,
                                                 name="ra-failure-detector")
        self._detector_thread.start()

    # ------------------------------------------------------------------
    # send path
    # ------------------------------------------------------------------

    def block_node(self, node: str) -> None:
        """Partition this host from ``node``: drop the live connection,
        purge already-queued frames, and refuse traffic both ways until
        :meth:`unblock_node`."""
        self.blocked_nodes.add(node)
        victims = [self.peers.get(node)]
        addr = self.address_book.get(node)
        if addr is not None:  # reply/notify links to the same host too
            victims.append(self._addr_peers.get(tuple(addr)))
        for peer in victims:
            if peer is None:
                continue
            self._close_peer(peer)
            while True:  # frames queued pre-partition must not flush out
                try:
                    peer.queue.get_nowait()
                    self.dropped_sends += 1
                except queue.Empty:
                    break

    def unblock_node(self, node: str) -> None:
        self.blocked_nodes.discard(node)

    def send(self, src_node: str, to: ServerId, msg) -> bool:
        if to.node in self.blocked_nodes:
            self.dropped_sends += 1
            return False
        if to.node in self.nodes or (src_node, to.node) in self.blocked:
            return super().send(src_node, to, msg)
        peer = self._peer_for(to.node)
        if peer is None:
            self.dropped_sends += 1
            return False
        try:
            peer.queue.put_nowait((to, self._rewrite_for_wire(msg),
                                   src_node))
        except queue.Full:
            # nosuspend: never block the Raft loop on a slow connection
            self.dropped_sends += 1
            return False
        self._ensure_sender(peer)
        return True

    def _rewrite_for_wire(self, msg):
        """Relayed command events carry local ack sinks (notify_to
        callables); swap them for ('rnotify', addr, id) handles so
        applied-notifications route back across hosts instead of landing
        on an orphan unpickled copy."""
        if isinstance(msg, CommandsEvent):
            return CommandsEvent(tuple(self._rewrite_cmd(c)
                                       for c in msg.commands))
        if isinstance(msg, CommandEvent):
            return _dc_replace(msg, command=self._rewrite_cmd(msg.command))
        return msg

    def _rewrite_cmd(self, cmd):
        nt = getattr(cmd, "notify_to", None)
        if nt is not None and callable(nt):
            handle = ("rnotify", tuple(self.listen_addr), self._router_id,
                      self._notify_id(nt))
            return _dc_replace(cmd, notify_to=handle)
        return cmd

    NOTIFY_SINK_MAX = 4096

    def _notify_id(self, fn) -> int:
        with self._call_lock:
            nid = self._notify_ids.get(id(fn))
            if nid is None:
                self._notify_seq += 1
                nid = self._notify_seq
                self._notify_ids[id(fn)] = nid
                self._notify_handles[nid] = fn
                while len(self._notify_handles) > self.NOTIFY_SINK_MAX:
                    old_nid, old_fn = self._notify_handles.popitem(last=False)
                    self._notify_ids.pop(id(old_fn), None)
            else:
                self._notify_handles.move_to_end(nid)
            return nid

    def _peer_for(self, node: str) -> Optional[_Peer]:
        peer = self.peers.get(node)
        if peer is None:
            addr = self.address_book.get(node)
            if addr is None:
                return None
            peer = self.peers.setdefault(node, _Peer(node, tuple(addr)))
        return peer

    def _ensure_sender(self, peer: _Peer) -> None:
        with peer.lock:
            if peer.thread is None or not peer.thread.is_alive():
                peer.thread = threading.Thread(
                    target=self._sender_loop, args=(peer,), daemon=True,
                    name=f"ra-tcp-send-{peer.name}")
                peer.thread.start()

    #: frames coalesced into one sendall by the sender loop — the
    #: gen_batch_server shape on the wire: whatever accumulated while
    #: the previous syscall ran goes out as one write
    SEND_COALESCE = 64

    def _sender_loop(self, peer: _Peer) -> None:
        while not self._stop:
            try:
                item = peer.queue.get(timeout=1.0)
            except queue.Empty:
                continue
            items = [item]
            while len(items) < self.SEND_COALESCE:
                try:
                    items.append(peer.queue.get_nowait())
                except queue.Empty:
                    break
            if not self._send_items(peer, items):
                # drop the batch (and drain cheaply while down: pipeline
                # catch-up will resend what matters)
                self.dropped_sends += len(items)

    def _encode_item(self, item) -> Optional[bytes]:
        to, msg, src = (item if len(item) == 3 else (*item, None))
        try:
            if to == "__reply__":
                frame = bytes([FRAME_REPLY]) + pickle.dumps(
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            elif to == "__notify__":
                frame = bytes([FRAME_NOTIFY]) + pickle.dumps(
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            else:
                payload = pickle.dumps((to, src, strip_msg_handles(msg)),
                                       protocol=pickle.HIGHEST_PROTOCOL)
                frame = bytes([FRAME_MSG]) + payload
        except (pickle.PicklingError, TypeError, AttributeError):
            # per-message failure: drop it, the connection is healthy
            return None
        return _LEN.pack(len(frame)) + frame

    def _send_items(self, peer: _Peer, items: list) -> bool:
        if peer.name in self.blocked_nodes or \
                self._addr_blocked(tuple(peer.addr)):
            return False  # partitioned: no redial, no flush
        sock = self._peer_sock(peer)
        if sock is None:
            return False
        buf = bytearray()
        for item in items:
            encoded = self._encode_item(item)
            if encoded is not None:
                buf += encoded
        if not buf:
            return True  # every item unpicklable: dropped individually
        try:
            with peer.send_lock:
                sock.sendall(bytes(buf))
            return True
        except OSError:
            self._close_peer(peer)
            return False

    def _peer_sock(self, peer: _Peer) -> Optional[socket.socket]:
        if peer.sock is not None:
            return peer.sock
        now = time.monotonic()
        if now - peer.last_attempt < RECONNECT_BACKOFF:
            return None
        peer.last_attempt = now
        try:
            sock = socket.create_connection(peer.addr,
                                            timeout=CONNECT_TIMEOUT)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = bytes([FRAME_HELLO]) + self._my_name().encode()
            sock.sendall(_LEN.pack(len(hello)) + hello)
            peer.sock = sock
            peer.status = "normal"
            self._mark_heard(peer.name)
            return sock
        except OSError:
            peer.status = "disconnected"
            return None

    def _close_peer(self, peer: _Peer) -> None:
        if peer.sock is not None:
            try:
                peer.sock.close()
            except OSError:
                pass
            peer.sock = None
        peer.status = "disconnected"

    def _my_name(self) -> str:
        return ",".join(sorted(self.nodes)) or "?"

    # ------------------------------------------------------------------
    # cross-host client calls (the gen_statem:call-over-dist role)
    # ------------------------------------------------------------------

    def remote_call(self, target: ServerId, make_event):
        """Send a client event to a server on a remote node; returns a
        Future resolved by the FRAME_REPLY, or None when unroutable."""
        from ..node import Future
        peer = self._peer_for(target.node)
        if peer is None:
            return None
        with self._call_lock:
            self._call_seq += 1
            call_id = self._call_seq
            fut = Future()
            self._calls[call_id] = fut
        handle = ("rcall", tuple(self.listen_addr), call_id)
        event = make_event(handle)
        if not self.send("?", target, event):
            with self._call_lock:
                self._calls.pop(call_id, None)
            return None
        return fut

    def forget_call(self, fut) -> None:
        with self._call_lock:
            for cid, f in list(self._calls.items()):
                if f is fut:
                    del self._calls[cid]

    def _addr_blocked(self, origin: tuple) -> bool:
        """True when the node listening at ``origin`` is partitioned off
        (replies/notifies must not tunnel through a blocked link)."""
        if not self.blocked_nodes:
            return False
        for node, addr in self.address_book.items():
            if tuple(addr) == origin:
                return node in self.blocked_nodes
        return False

    def reply_remote(self, handle: tuple, msg) -> None:
        _tag, origin, call_id = handle
        origin = tuple(origin)
        if origin == tuple(self.listen_addr):
            with self._call_lock:
                fut = self._calls.pop(call_id, None)
            if fut is not None:
                fut.set(msg)
            return
        if self._addr_blocked(origin):
            self.dropped_sends += 1
            return
        peer = self._addr_peers.get(origin)
        if peer is None:
            peer = self._addr_peers.setdefault(
                origin, _Peer(f"addr:{origin[0]}:{origin[1]}", origin))
        try:
            peer.queue.put_nowait(("__reply__", (call_id, msg)))
        except queue.Full:
            self.dropped_sends += 1
            return
        self._ensure_sender(peer)

    def notify_remote(self, handle: tuple, correlations) -> None:
        """Route an applied-notification batch back to the host that
        registered the sink (see _rewrite_cmd)."""
        _tag, origin, router_id, nid = handle
        origin = tuple(origin)
        if router_id == self._router_id:
            fn = self._notify_handles.get(nid)
            if fn is not None:
                fn(correlations)
            return
        if self._addr_blocked(origin):
            self.dropped_sends += 1
            return
        peer = self._addr_peers.get(origin)
        if peer is None:
            peer = self._addr_peers.setdefault(
                origin, _Peer(f"addr:{origin[0]}:{origin[1]}", origin))
        try:
            peer.queue.put_nowait(("__notify__", (nid, correlations)))
        except queue.Full:
            self.dropped_sends += 1
            return
        self._ensure_sender(peer)

    # ------------------------------------------------------------------
    # receive path
    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._recv_loop, args=(conn,),
                                 daemon=True, name="ra-tcp-recv")
            t.start()

    def _recv_loop(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        remote_names: list = []  # every node co-hosted behind this conn
        try:
            while not self._stop:
                hdr = self._recv_exact(conn, _LEN.size)
                if hdr is None:
                    break
                (length,) = _LEN.unpack(hdr)
                if length == 0 or length > MAX_FRAME:
                    break  # corrupt/hostile header: drop the connection
                frame = self._recv_exact(conn, length)
                if frame is None:
                    break
                kind = frame[0]
                if kind == FRAME_HELLO:
                    remote_names = frame[1:].decode().split(",")
                    for name in remote_names:
                        if name not in self.blocked_nodes:
                            self._mark_heard(name)
                    continue
                if remote_names and \
                        all(n in self.blocked_nodes for n in remote_names):
                    continue  # partitioned: total inbound silence
                if kind == FRAME_MSG:
                    to, src, msg = pickle.loads(frame[1:])
                    if src in self.blocked_nodes:
                        continue  # per-source drop (co-hosted routers)
                    for name in remote_names:
                        if name not in self.blocked_nodes:
                            self._mark_heard(name)
                    node = self.nodes.get(to.node)
                    if node is not None:
                        node.deliver(to, msg)
                elif kind == FRAME_REPLY:
                    call_id, reply = pickle.loads(frame[1:])
                    with self._call_lock:
                        fut = self._calls.pop(call_id, None)
                    if fut is not None:
                        fut.set(reply)
                elif kind == FRAME_NOTIFY:
                    nid, correlations = pickle.loads(frame[1:])
                    fn = self._notify_handles.get(nid)
                    if fn is not None:
                        fn(correlations)
                elif kind == FRAME_PING:
                    for name in remote_names:
                        if name not in self.blocked_nodes:
                            self._mark_heard(name)
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # ------------------------------------------------------------------
    # failure detector (the aten role)
    # ------------------------------------------------------------------

    def _mark_heard(self, node: str) -> None:
        self.last_heard[node] = time.monotonic()
        if self.node_status.get(node) == "down":
            self.node_status[node] = "up"
            self._broadcast_node_event(node, "up")
        else:
            self.node_status.setdefault(node, "up")

    def _detector_loop(self) -> None:
        while not self._stop:
            time.sleep(PING_INTERVAL)
            now = time.monotonic()
            # ping every peer we have a live connection to
            for peer in list(self.peers.values()):
                if peer.name in self.blocked_nodes:
                    continue
                sock = peer.sock
                if sock is not None:
                    try:
                        frame = bytes([FRAME_PING])
                        with peer.send_lock:
                            sock.sendall(_LEN.pack(len(frame)) + frame)
                    except OSError:
                        self._close_peer(peer)
            # verdicts
            for node, heard in list(self.last_heard.items()):
                if node in self.nodes:
                    continue
                status = self.node_status.get(node, "up")
                if status != "down" and now - heard > DOWN_AFTER:
                    self.node_status[node] = "down"
                    self._broadcast_node_event(node, "down")

    def _broadcast_node_event(self, node: str, status: str) -> None:
        evt = NodeEvent(node, status)
        for ranode in list(self.nodes.values()):
            for name in list(ranode.shells):
                ranode.submit(name, evt)

    # ------------------------------------------------------------------

    def stop(self) -> None:
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass
        for peer in self.peers.values():
            self._close_peer(peer)

    def overview(self) -> dict:
        return {
            "listen": self.listen_addr,
            "dropped_sends": self.dropped_sends,
            "peers": {p.name: p.status for p in self.peers.values()},
            "node_status": dict(self.node_status),
        }
