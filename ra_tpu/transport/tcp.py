"""TCP transport + node failure detection — the cross-host fabric.

Replicates the semantics the reference gets from Erlang distribution +
aten (SURVEY.md §2.4 'Distributed communication backend'):

* sends are NEVER blocking: each peer has a bounded outbound queue and a
  sender thread; a full queue or broken/unreachable connection drops the
  message and counts it (the [noconnect, nosuspend] cast semantics of
  ra_server_proc.erl:1317-1341 — Raft's pipeline catch-up recovers)
* per-peer connection status (normal | disconnected) feeds drop decisions
  and metrics (ra.hrl:329-330 drop counters)
* a lightweight heartbeat failure detector stands in for aten: every
  connected peer is pinged on an interval; silence beyond SUSPECT_AFTER
  marks the node "suspect" (internal pre-down state the reliable RPC
  layer uses to invalidate cached connections before retrying), silence
  beyond DOWN_AFTER emits NodeEvent(node, "down") to every local server
  shell and closes the cached connection, recovery emits
  NodeEvent(node, "up") (aten's poll-interval role,
  ra_server_proc.erl:790-810, 1690-1700)
* node-LIFECYCLE calls ride the reliable RPC frames (FRAME_RPC_REQ/
  FRAME_RPC_RESP, transport/rpc.py): retried by the sender under one
  request id, deduplicated by the receiver — control-plane traffic must
  survive a peer restart that Raft data traffic merely drops through
* an optional seeded FaultPlan (transport/rpc.py) is consulted on the
  send and recv paths: deterministic drop/delay/duplicate/reorder/
  partition per (peer, frame-class) stream for in-process chaos tests
* frames are length-prefixed pickles between cluster hosts — the same
  mutual-trust model as Erlang distribution inside a cluster; do not
  expose the port beyond it

TcpRouter extends the in-process LocalRouter: ServerIds whose node is
hosted locally are delivered directly; remote nodes resolve through the
address book.
"""
from __future__ import annotations

import logging
import pickle
import queue
import socket
import struct
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import replace as _dc_replace
from typing import Optional

from ..blackbox import record
from ..core.types import (
    AppendEntriesRpc,
    CommandEvent,
    CommandsEvent,
    Entry,
    NODE_SCOPE,
    NodeControlEvent,
    NodeEvent,
    ReplyMode,
    ServerId,
    UserCommand,
    strip_msg_handles,
)
from ..codec import decode_command, decode_user_parts, encode_user
from ..metrics import RPC_FIELDS
from ..node import LocalRouter
from .rpc import RpcReceiver, stamp_origin

logger = logging.getLogger("ra_tpu.transport")

_LEN = struct.Struct("<I")
FRAME_MSG = 0
FRAME_PING = 1
FRAME_HELLO = 2
FRAME_REPLY = 3
FRAME_NOTIFY = 4
FRAME_RPC_REQ = 5
FRAME_RPC_RESP = 6
#: batch-encoded data frame (ISSUE 13): ONE pickle + ONE length prefix
#: for every plain routed message the sender loop coalesced — the
#: per-item _encode_item path paid a pickle and a frame header per
#: message, which at batched-AER rates dominated the sender thread
FRAME_MSG_BATCH = 7

#: fault kinds the recv/ping paths can honor (they cannot delay,
#: duplicate or reorder — see FaultPlan.decide's honor contract)
_DROP_ONLY = frozenset({"drop"})

#: frame kind -> FaultPlan frame class (rpc.FaultPlan keys decisions by
#: (peer, frame-class, direction) so chaos schedules can target the
#: control plane, the data plane, or the detector independently)
_FRAME_CLASS = {FRAME_MSG: "msg", FRAME_PING: "ping",
                FRAME_HELLO: "hello", FRAME_REPLY: "reply",
                FRAME_NOTIFY: "notify", FRAME_RPC_REQ: "rpc_req",
                FRAME_RPC_RESP: "rpc_resp", FRAME_MSG_BATCH: "msg"}

SEND_QUEUE_MAX = 10_000
MAX_FRAME = 64 * 1024 * 1024  # snapshot chunks are 1MB; generous headroom
PING_INTERVAL = 0.5
SUSPECT_AFTER = 1.0       # silence before the RPC layer distrusts the conn
DOWN_AFTER = 2.0          # silence threshold (aten default poll is 1s)
CONNECT_TIMEOUT = 1.0
RECONNECT_BACKOFF = 0.5


class _FaultHeld:
    """Wrapper marking a queue item the FaultPlan already processed
    (delayed frames re-enter the send queue exempt from a second
    decision, or they would be re-delayed/dropped forever)."""

    __slots__ = ("item",)

    def __init__(self, item) -> None:
        self.item = item


class _Peer:
    __slots__ = ("name", "addr", "queue", "sock", "thread", "status",
                 "last_attempt", "lock", "send_lock")

    def __init__(self, name: str, addr: tuple) -> None:
        self.name = name
        self.addr = addr
        self.queue: "queue.Queue" = queue.Queue(maxsize=SEND_QUEUE_MAX)
        self.sock: Optional[socket.socket] = None
        self.thread: Optional[threading.Thread] = None
        self.status = "disconnected"
        self.last_attempt = 0.0
        self.lock = threading.Lock()
        # serializes sendall between the sender and detector threads: an
        # interleaved ping inside a partially-sent frame corrupts the stream
        self.send_lock = threading.Lock()


class TcpRouter(LocalRouter):
    """LocalRouter + TCP reach to remote nodes."""

    def __init__(self, listen_addr: tuple, address_book: dict) -> None:
        super().__init__()
        self.listen_addr = listen_addr
        self.address_book = dict(address_book)  # node name -> (host, port)
        self.peers: dict[str, _Peer] = {}
        self.dropped_sends = 0
        self.last_heard: dict[str, float] = {}
        self.node_status: dict[str, str] = {}
        #: detector windows — instance-configurable (ISSUE 17): the
        #: module constants stay the defaults; ``detector_hysteresis``
        #: is the minimum CONTINUOUS suspect time before a down
        #: verdict, so a latency spike (slow fsync, injected delay)
        #: that clears within the window never escalates.  0.0
        #: preserves the historical silence-only behavior.
        self.suspect_after = SUSPECT_AFTER
        self.down_after = DOWN_AFTER
        self.detector_hysteresis = 0.0
        #: node -> monotonic time it ENTERED suspect (hysteresis clock)
        self._suspect_since: dict[str, float] = {}
        #: nemesis hook: nodes whose traffic is blocked at the socket
        #: level (the inet_tcp_proxy role the reference's
        #: partitions_SUITE uses, partitions_SUITE.erl:29-57) — sends
        #: drop+count, inbound frames are ignored, the failure detector
        #: sees silence and rules the node down
        self.blocked_nodes: set = set()
        self._stop = False
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(listen_addr)
        self._server.listen(64)
        self.listen_addr = self._server.getsockname()
        # outstanding cross-host client calls: call id -> Future
        self._calls: dict = {}
        self._call_seq = 0
        self._call_lock = threading.Lock()
        # remote pipeline fan-in (ISSUE 13): per-target buffers of
        # pipelined commands flushed as {commands, Batch} events — the
        # cross-host twin of RaNode's low-priority flush, so a wire
        # client's casts ride multi-command frames instead of one
        # CommandEvent frame per command
        self._pipe_bufs: dict = {}
        self._pipe_lock = threading.Lock()
        self._pipe_evt = threading.Event()
        self._pipe_thread: Optional[threading.Thread] = None
        # durable applied-notification sinks for pipelined commands that
        # cross hosts: nid -> callable, id(callable) -> nid.  Unlike
        # _calls these are multi-shot (one client receives many Notify
        # batches), so they persist; an LRU cap bounds them when callers
        # pass a fresh callable per command instead of reusing a sink
        self._notify_handles: OrderedDict = OrderedDict()
        self._notify_ids: dict = {}
        self._notify_seq = 0
        # distinguishes this router in rnotify handles: bind-address
        # equality is unreliable under wildcard binds (0.0.0.0 on every
        # host would alias all routers)
        self._router_id = uuid.uuid4().hex[:12]
        # lazily-created peers keyed by raw address (reply routing)
        self._addr_peers: dict[tuple, _Peer] = {}
        # reliable control-plane RPC (transport/rpc.py): pending sender
        # futures by request id, shared counters, receiver-side dedup
        self._rpc_pending: dict = {}
        self.rpc_counters: dict = {f: 0 for f in RPC_FIELDS}
        self._rpc_receiver = RpcReceiver(self._rpc_execute,
                                         counters=self.rpc_counters)
        #: optional seeded FaultPlan consulted at send/recv (rpc.py)
        self.fault_plan = None
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="ra-tcp-accept")
        self._accept_thread.start()
        self._detector_thread = threading.Thread(target=self._detector_loop,
                                                 daemon=True,
                                                 name="ra-failure-detector")
        self._detector_thread.start()

    # ------------------------------------------------------------------
    # send path
    # ------------------------------------------------------------------

    def block_node(self, node: str) -> None:
        """Partition this host from ``node``: drop the live connection,
        purge already-queued frames, and refuse traffic both ways until
        :meth:`unblock_node`."""
        self.blocked_nodes.add(node)
        victims = [self.peers.get(node)]
        addr = self.address_book.get(node)
        if addr is not None:  # reply/notify links to the same host too
            victims.append(self._addr_peers.get(tuple(addr)))
        for peer in victims:
            if peer is None:
                continue
            self._close_peer(peer)
            while True:  # frames queued pre-partition must not flush out
                try:
                    peer.queue.get_nowait()
                    self.dropped_sends += 1
                except queue.Empty:
                    break

    def unblock_node(self, node: str) -> None:
        self.blocked_nodes.discard(node)

    def send(self, src_node: str, to: ServerId, msg) -> bool:
        if to.node in self.blocked_nodes:
            self.dropped_sends += 1
            return False
        if to.node in self.nodes or (src_node, to.node) in self.blocked:
            return super().send(src_node, to, msg)
        peer = self._peer_for(to.node)
        if peer is None:
            self.dropped_sends += 1
            return False
        try:
            peer.queue.put_nowait((to, self._rewrite_for_wire(msg),
                                   src_node))
        except queue.Full:
            # nosuspend: never block the Raft loop on a slow connection
            self.dropped_sends += 1
            return False
        self._ensure_sender(peer)
        return True

    def _rewrite_for_wire(self, msg):
        """Relayed command events carry local ack sinks (notify_to
        callables); swap them for ('rnotify', addr, id) handles so
        applied-notifications route back across hosts instead of landing
        on an orphan unpickled copy.  CommandsEvent batches are left to
        the SENDER thread's compact wire form (ISSUE 13), which does
        the same handle swap per batch instead of one dataclass-replace
        per command here on the caller's thread."""
        if isinstance(msg, CommandsEvent):
            return msg
        if isinstance(msg, CommandEvent):
            return _dc_replace(msg, command=self._rewrite_cmd(msg.command))
        return msg

    def _rewrite_cmd(self, cmd):
        nt = getattr(cmd, "notify_to", None)
        if nt is not None and callable(nt):
            handle = ("rnotify", tuple(self.listen_addr), self._router_id,
                      self._notify_id(nt))
            return _dc_replace(cmd, notify_to=handle)
        return cmd

    NOTIFY_SINK_MAX = 4096

    def _notify_id(self, fn) -> int:
        with self._call_lock:
            nid = self._notify_ids.get(id(fn))
            if nid is None:
                self._notify_seq += 1
                nid = self._notify_seq
                self._notify_ids[id(fn)] = nid
                self._notify_handles[nid] = fn
                while len(self._notify_handles) > self.NOTIFY_SINK_MAX:
                    old_nid, old_fn = self._notify_handles.popitem(last=False)
                    self._notify_ids.pop(id(old_fn), None)
            else:
                self._notify_handles.move_to_end(nid)
            return nid

    def _peer_for(self, node: str) -> Optional[_Peer]:
        peer = self.peers.get(node)
        if peer is None:
            addr = self.address_book.get(node)
            if addr is None:
                return None
            peer = self.peers.setdefault(node, _Peer(node, tuple(addr)))
        return peer

    def _ensure_sender(self, peer: _Peer) -> None:
        with peer.lock:
            if peer.thread is None or not peer.thread.is_alive():
                peer.thread = threading.Thread(
                    target=self._sender_loop, args=(peer,), daemon=True,
                    name=f"ra-tcp-send-{peer.name}")
                peer.thread.start()

    #: frames coalesced into one sendall by the sender loop — the
    #: gen_batch_server shape on the wire: whatever accumulated while
    #: the previous syscall ran goes out as one write; plain routed
    #: messages additionally share ONE batch frame + ONE pickle
    #: (FRAME_MSG_BATCH, ISSUE 13), so deeper coalescing amortizes
    #: encode setup as well as the syscall
    SEND_COALESCE = 256

    def _sender_loop(self, peer: _Peer) -> None:
        while not self._stop:
            try:
                item = peer.queue.get(timeout=1.0)
            except queue.Empty:
                continue
            items = [item]
            while len(items) < self.SEND_COALESCE:
                try:
                    items.append(peer.queue.get_nowait())
                except queue.Empty:
                    break
            plan = self.fault_plan
            if plan is not None:
                # fault filtering happens HERE, before the socket, so a
                # later socket failure counts only the frames actually
                # attempted: fault drops count once (inside the filter)
                # and delayed frames (a Timer re-queues them) never
                # count as connection losses.  Plan-level partition
                # also suppresses the redial handshake: a partitioned
                # peer must go silent for the detector.
                if plan.is_partitioned(self._fault_peer_name(peer)):
                    self.dropped_sends += len(items)
                    continue
                items = self._apply_send_faults(plan, peer, items)
                if not items:
                    continue
            if not self._send_items(peer, items):
                # drop the batch (and drain cheaply while down: pipeline
                # catch-up will resend what matters)
                self.dropped_sends += len(items)

    def _wire_form(self, to, msg, src):
        """Routed-message wire image, built on the SENDER thread.  Two
        compact forms (ISSUE 13):

        * an AppendEntries batch carrying its encoded durable payloads
          ships as index base + per-entry terms + payload bytes instead
          of pickled command objects — pickling bytes is a memcpy while
          pickling a dataclass per entry dominated the sender loop, and
          the payload IS the handle-stripped durable image so no strip
          pass is needed;
        * a CommandsEvent of plain pipelined notify-mode commands ships
          as per-command codec payload images (``__cmds2__``, ISSUE 18)
          — the SAME bytes the leader will append, the WAL will write,
          and segments will store, so this one encode is the only
          object-encode the command ever sees.  The notify-handle swap
          (_notify_id) happens first, memoized per batch, so the remote
          handle is baked into the image.  A CommandsEvent that already
          CARRIES images (a follower relaying a wire batch to the
          leader) re-ships them byte-for-byte — relay is a memcpy.

        The receiver thread rebuilds the objects (decode off BOTH
        nodes' event-loop threads)."""
        tm = type(msg)
        if tm is AppendEntriesRpc and msg.payloads is not None \
                and msg.entries:
            ents = msg.entries
            return (to, src, ("__aer__", msg.term, msg.leader_id,
                              msg.prev_log_index, msg.prev_log_term,
                              msg.leader_commit, ents[0].index,
                              tuple(e.term for e in ents),
                              msg.payloads))
        if tm is CommandsEvent:
            cmds = msg.commands
            images = msg.images
            if images is not None and len(images) == len(cmds):
                traces = tuple(c.trace for c in cmds) \
                    if any(c.trace is not None for c in cmds) else None
                return (to, src, ("__cmds2__", images, traces))
            handles: dict = {}  # per-batch memo: id(fn) -> handle
            rows = []
            any_trace = False
            for c in cmds:
                if type(c) is not UserCommand or \
                        c.reply_mode is not ReplyMode.NOTIFY or \
                        c.from_ is not None or c.reply_from is not None:
                    rows = None
                    break
                nt = c.notify_to
                if nt is not None and callable(nt):
                    h = handles.get(id(nt))
                    if h is None:
                        h = handles[id(nt)] = (
                            "rnotify", tuple(self.listen_addr),
                            self._router_id, self._notify_id(nt))
                    nt = h
                img = encode_user(c.data, ReplyMode.NOTIFY,
                                  c.correlation, nt, None, None)
                if img is None:  # shape outside the fixed layout
                    rows = None
                    break
                rows.append(img)
                if c.trace is not None:
                    any_trace = True
            if rows is not None:
                traces = tuple(c.trace for c in cmds) if any_trace \
                    else None
                return (to, src, ("__cmds2__", tuple(rows), traces))
            # mixed batch (rare): the legacy per-command rewrite + strip
            msg = CommandsEvent(tuple(self._rewrite_cmd(c)
                                      for c in cmds))
            return (to, src, msg)
        return (to, src, strip_msg_handles(msg))

    @staticmethod
    def _from_wire(msg):
        """Inverse of _wire_form, run on the receiver thread."""
        if type(msg) is tuple and msg:
            tag = msg[0]
            if tag == "__aer__":
                (_tag, term, leader_id, pli, plt, commit, first, terms,
                 payloads) = msg
                entries = tuple(
                    Entry(first + i, terms[i],
                          decode_command(payloads[i]))
                    for i in range(len(payloads)))
                return AppendEntriesRpc(
                    term=term, leader_id=leader_id, prev_log_index=pli,
                    prev_log_term=plt, leader_commit=commit,
                    entries=entries, payloads=payloads)
            if tag == "__cmds2__":
                _tag, images, traces = msg
                if traces is None:
                    cmds = tuple(decode_command(img) for img in images)
                else:
                    cmds = tuple(
                        UserCommand(*decode_user_parts(img), trace=tr)
                        for img, tr in zip(images, traces))
                # keep the shipped images: the leader appends these
                # exact bytes (no re-encode), a relaying follower
                # re-ships them
                return CommandsEvent(cmds, images)
            if tag == "__cmds__":
                # pre-codec compact form — decode-only compatibility
                return CommandsEvent(tuple(
                    UserCommand(data, reply_mode=ReplyMode.NOTIFY,
                                correlation=corr, notify_to=nt,
                                trace=tr)
                    for data, corr, nt, tr in msg[1]))
        return msg

    def _encode_item(self, item) -> Optional[bytes]:
        if isinstance(item, _FaultHeld):  # plan cleared mid-delay
            item = item.item
        to, msg, src = (item if len(item) == 3 else (*item, None))
        try:
            if to == "__reply__":
                frame = bytes([FRAME_REPLY]) + pickle.dumps(  # ra10-ok: control-plane single (reply), rare by design
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            elif to == "__notify__":
                frame = bytes([FRAME_NOTIFY]) + pickle.dumps(  # ra10-ok: control-plane single (notify), rare by design
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            elif to == "__rpc_req__":
                frame = bytes([FRAME_RPC_REQ]) + pickle.dumps(  # ra10-ok: control-plane single (rpc req), rare by design
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            elif to == "__rpc_resp__":
                frame = bytes([FRAME_RPC_RESP]) + pickle.dumps(  # ra10-ok: control-plane single (rpc resp), rare by design
                    msg, protocol=pickle.HIGHEST_PROTOCOL)
            else:
                payload = pickle.dumps(self._wire_form(to, msg, src),  # ra10-ok: ONE frame envelope; command payloads inside are codec images (bytes)
                                       protocol=pickle.HIGHEST_PROTOCOL)
                frame = bytes([FRAME_MSG]) + payload
        except (pickle.PicklingError, TypeError, AttributeError):
            # per-message failure: drop it, the connection is healthy
            return None
        return _LEN.pack(len(frame)) + frame

    def _encode_msg_batch(self, items: list) -> Optional[bytes]:
        """ONE frame for a run of plain routed messages: the batch is
        pickled in a single dumps call with a shared length prefix, so
        the pickle setup and the per-frame header amortize across
        everything the sender loop coalesced (ISSUE 13 / rule RA10).
        Falls back to per-item encoding when any message in the batch
        refuses to pickle (the per-item path then drops just that
        message)."""
        try:
            triples = [self._wire_form(to, msg, src)
                       for to, msg, src in items]
            frame = bytes([FRAME_MSG_BATCH]) + pickle.dumps(  # ra10-ok: ONE envelope per coalesced batch; commands inside are codec images
                triples, protocol=pickle.HIGHEST_PROTOCOL)
        except (pickle.PicklingError, TypeError, AttributeError):
            return None
        return _LEN.pack(len(frame)) + frame

    def _send_items(self, peer: _Peer, items: list) -> bool:
        if peer.name in self.blocked_nodes or \
                self._addr_blocked(tuple(peer.addr)):
            return False  # partitioned: no redial, no flush
        sock = self._peer_sock(peer)
        if sock is None:
            return False
        buf = bytearray()
        # routed messages batch into one frame; control-plane singles
        # (reply/notify/rpc frames — rare) keep their per-item frames
        plain: list = []
        for item in items:  # per-ITEM partition of control-plane singles (the encodes inside carry the ra10 tags); data frames batch below
            if isinstance(item, _FaultHeld):  # plan cleared mid-delay
                item = item.item
            if isinstance(item[0], str) and item[0].startswith("__"):
                encoded = self._encode_item(item)  # ra10-ok: control-plane singles (reply/notify/rpc) are rare
                if encoded is not None:
                    buf += encoded
            else:
                plain.append(item if len(item) == 3 else (*item, None))
        if len(plain) == 1:
            encoded = self._encode_item(plain[0])
            if encoded is not None:
                buf += encoded
        elif plain:
            encoded = self._encode_msg_batch(plain)
            if encoded is None:
                for item in plain:
                    encoded = self._encode_item(item)  # ra10-ok: fallback after a batch pickling failure
                    if encoded is not None:
                        buf += encoded
            else:
                buf += encoded
        if not buf:
            return True  # every item unpicklable: dropped individually
        try:
            with peer.send_lock:
                sock.sendall(bytes(buf))
            return True
        except OSError:
            self._close_peer(peer)
            return False

    def _peer_sock(self, peer: _Peer) -> Optional[socket.socket]:
        if peer.sock is not None:
            return peer.sock
        now = time.monotonic()
        if now - peer.last_attempt < RECONNECT_BACKOFF:
            return None
        peer.last_attempt = now
        try:
            sock = socket.create_connection(peer.addr,
                                            timeout=CONNECT_TIMEOUT)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = bytes([FRAME_HELLO]) + self._my_name().encode()
            sock.sendall(_LEN.pack(len(hello)) + hello)
            peer.sock = sock
            peer.status = "normal"
            self._mark_heard(peer.name)
            return sock
        except OSError:
            peer.status = "disconnected"
            return None

    def _close_peer(self, peer: _Peer) -> None:
        if peer.sock is not None:
            try:
                peer.sock.close()
            except OSError:
                pass
            peer.sock = None
        peer.status = "disconnected"

    def _my_name(self) -> str:
        return ",".join(sorted(self.nodes)) or "?"

    # ------------------------------------------------------------------
    # cross-host client calls (the gen_statem:call-over-dist role)
    # ------------------------------------------------------------------

    def remote_call(self, target: ServerId, make_event):
        """Send a client event to a server on a remote node; returns a
        Future resolved by the FRAME_REPLY, or None when unroutable."""
        from ..node import Future
        peer = self._peer_for(target.node)
        if peer is None:
            return None
        with self._call_lock:
            self._call_seq += 1
            call_id = self._call_seq
            fut = Future()
            self._calls[call_id] = fut
        handle = ("rcall", tuple(self.listen_addr), call_id)
        event = make_event(handle)
        if not self.send("?", target, event):
            with self._call_lock:
                self._calls.pop(call_id, None)
            return None
        return fut

    def forget_call(self, fut) -> None:
        with self._call_lock:
            for cid, f in list(self._calls.items()):
                if f is fut:
                    del self._calls[cid]

    # ------------------------------------------------------------------
    # remote pipeline fan-in (api.pipeline_command's cross-host half)
    # ------------------------------------------------------------------

    #: commands per flushed {commands, Batch} frame and the straggler
    #: flush cadence — PIPELINE_FLUSH_SIZE matches the server-side
    #: command_flush_size default so one wire frame fills one leader
    #: batch append (ISSUE 13)
    PIPELINE_FLUSH_SIZE = 512
    PIPELINE_FLUSH_INTERVAL_S = 0.002

    def pipeline_cast(self, target: ServerId, cmd) -> bool:
        """Buffer one fire-and-forget command toward ``target``; full
        buffers flush inline as a CommandsEvent, stragglers are flushed
        by a small cadence thread within ~PIPELINE_FLUSH_INTERVAL_S.
        Same at-most-once posture as every data-plane cast: a dropped
        frame is the client's timeout/retry problem.  The steady-state
        cast is one lock cycle + one list append: the flusher wake and
        the thread-liveness check run only on a buffer's FIRST fill.
        Full-buffer flushes send INSIDE the buffer lock — the cadence
        flusher sends under the same lock, so one caller's casts reach
        the peer queue in submission order (an inline flush racing a
        swapped-but-unsent cadence batch would otherwise overtake it);
        send() is nonblocking (put_nowait), so the hold is short."""
        with self._pipe_lock:
            buf = self._pipe_bufs.get(target)
            if buf is None:
                buf = self._pipe_bufs[target] = []
            buf.append(cmd)
            n = len(buf)
            if n >= self.PIPELINE_FLUSH_SIZE:
                del self._pipe_bufs[target]
                return self.send("?", target, CommandsEvent(tuple(buf)))
        if n == 1:
            if self._pipe_thread is None or \
                    not self._pipe_thread.is_alive():
                with self._pipe_lock:
                    if self._pipe_thread is None or \
                            not self._pipe_thread.is_alive():
                        self._pipe_thread = threading.Thread(
                            target=self._pipe_flusher, daemon=True,
                            name="ra-tcp-pipe-flush")
                        self._pipe_thread.start()
            self._pipe_evt.set()
        return True

    def pipeline_cast_many(self, target: ServerId, cmds) -> bool:
        """Burst twin of pipeline_cast: one lock cycle and one extend for
        the whole batch (api.pipeline_commands' cross-host half).  A
        burst may overfill the buffer past PIPELINE_FLUSH_SIZE; it
        flushes as one oversized CommandsEvent rather than splitting —
        the leader's batcher re-chunks on its side."""
        with self._pipe_lock:
            buf = self._pipe_bufs.get(target)
            if buf is None:
                buf = self._pipe_bufs[target] = []
            n0 = len(buf)
            buf.extend(cmds)
            if len(buf) >= self.PIPELINE_FLUSH_SIZE:
                del self._pipe_bufs[target]
                return self.send("?", target, CommandsEvent(tuple(buf)))
        if n0 == 0:
            if self._pipe_thread is None or \
                    not self._pipe_thread.is_alive():
                with self._pipe_lock:
                    if self._pipe_thread is None or \
                            not self._pipe_thread.is_alive():
                        self._pipe_thread = threading.Thread(
                            target=self._pipe_flusher, daemon=True,
                            name="ra-tcp-pipe-flush")
                        self._pipe_thread.start()
            self._pipe_evt.set()
        return True

    def _pipe_flusher(self) -> None:
        while not self._stop:
            time.sleep(self.PIPELINE_FLUSH_INTERVAL_S)
            with self._pipe_lock:
                # swap AND send under the buffer lock: see pipeline_cast
                # — an inline full-buffer flush must not overtake a
                # swapped-but-unsent cadence batch
                bufs, self._pipe_bufs = self._pipe_bufs, {}
                for target, buf in bufs.items():
                    if buf:
                        self.send("?", target, CommandsEvent(tuple(buf)))
            if not bufs:
                # idle: park until the next cast instead of spinning
                self._pipe_evt.wait(0.25)
                self._pipe_evt.clear()

    # ------------------------------------------------------------------
    # reliable control-plane RPC (transport/rpc.py rides these)
    # ------------------------------------------------------------------

    def set_fault_plan(self, plan) -> None:
        """Install (or clear, with None) a seeded FaultPlan; consulted
        on every send/recv until replaced."""
        self.fault_plan = plan

    def rpc_routable(self, node: str) -> bool:
        return node in self.nodes or node in self.address_book

    def rpc_note(self, field: str, n: int = 1) -> None:
        self.rpc_counters[field] = self.rpc_counters.get(field, 0) + n

    def rpc_register(self, rid: str):
        """Arm (or re-arm, across retryable responses) the future a
        response to ``rid`` resolves."""
        from ..node import Future
        fut = Future()
        with self._call_lock:
            self._rpc_pending[rid] = fut
        return fut

    def rpc_forget(self, rid: str) -> None:
        with self._call_lock:
            self._rpc_pending.pop(rid, None)

    def rpc_send(self, node: str, req) -> bool:
        """Queue one request attempt toward ``node``; loopback requests
        (the target node hosted HERE) go straight through the receiver
        so local calls share the same at-most-once path."""
        req = stamp_origin(req, self.listen_addr, self._router_id)
        if node in self.nodes:
            self._rpc_receiver.handle(
                req, lambda resp, _r=req: self._rpc_respond(_r, resp))
            return True
        if node in self.blocked_nodes:
            self.dropped_sends += 1
            return False
        peer = self._peer_for(node)
        if peer is None:
            return False
        try:
            peer.queue.put_nowait(("__rpc_req__", req))
        except queue.Full:
            self.dropped_sends += 1
            return False
        self._ensure_sender(peer)
        return True

    def rpc_peer_state(self, node: str) -> str:
        """Classification input for the reliable RPC layer's deadline
        verdict: the detector's status when it has one, else whether a
        connection was EVER established — a peer refusing every dial is
        'never-connected' (Unreachable), not a timeout."""
        status = self.node_status.get(node)
        if status is not None:
            return status
        peer = self.peers.get(node)
        if peer is None or peer.sock is None:
            return "never-connected"
        return "up"

    def rpc_invalidate_peer(self, node: str) -> None:
        """Reconnect-aware retry: when the failure detector holds the
        peer suspect/down (or the connection already broke), drop the
        cached socket and clear the redial backoff so the next attempt
        dials fresh — a one-shot send into a half-dead socket is
        exactly the silent loss this layer exists to prevent."""
        peer = self.peers.get(node)
        if peer is None:
            return
        if self.node_status.get(node) in ("suspect", "down") or \
                peer.status == "disconnected":
            self._close_peer(peer)
            peer.last_attempt = 0.0

    def _rpc_execute(self, req, done) -> bool:
        """RpcReceiver's executor: hand the op to the local RaNode's
        control plane; False when that node is not hosted here (the
        receiver answers 'retryable' — a restarting worker may register
        it shortly)."""
        node = self.nodes.get(req.node)
        if node is None:
            return False
        return node.deliver(ServerId(NODE_SCOPE, req.node),
                            NodeControlEvent(req.op, dict(req.args),
                                             from_=done))

    def _rpc_respond(self, req, resp) -> None:
        """Route a response back to the request's origin (loopback
        resolves the local pending future directly)."""
        origin = tuple(req.origin)
        if req.origin_router == self._router_id or \
                origin == tuple(self.listen_addr):
            with self._call_lock:
                fut = self._rpc_pending.pop(resp.rid, None)
            if fut is not None:
                fut.set(resp)
            return
        self._queue_to_addr(origin, ("__rpc_resp__", resp))

    # ------------------------------------------------------------------
    # fault injection (FaultPlan hooks)
    # ------------------------------------------------------------------

    def _fault_peer_name(self, peer: _Peer) -> str:
        """Resolve reply-path peers (named addr:host:port) back to the
        node name the FaultPlan keys on, when the address book knows
        it."""
        if not peer.name.startswith("addr:"):
            return peer.name
        addr = tuple(peer.addr)
        for node, book_addr in self.address_book.items():
            if tuple(book_addr) == addr:
                return node
        return peer.name

    @staticmethod
    def _item_class(item) -> str:
        to = item[0]
        return {"__reply__": "reply", "__notify__": "notify",
                "__rpc_req__": "rpc_req",
                "__rpc_resp__": "rpc_resp"}.get(to, "msg")

    def _apply_send_faults(self, plan, peer: _Peer, items: list) -> list:
        """Filter one send batch through the plan: drops vanish (and
        count), delays re-queue exempt after a timer, duplicates send
        twice, reorders move behind the rest of the batch.  Held items
        (already-delayed) pass through untouched."""
        fault_peer = self._fault_peer_name(peer)
        out: list = []
        tail: list = []
        for item in items:
            if isinstance(item, _FaultHeld):
                out.append(item.item)
                continue
            d = plan.decide(fault_peer, self._item_class(item), "send")
            if d.action == "drop":
                self.dropped_sends += 1
                continue
            if d.delay_s > 0:
                t = threading.Timer(d.delay_s, self._requeue_held,
                                    args=(peer, item))
                t.daemon = True
                t.start()
                continue
            if d.reorder:
                tail.append(item)
                continue
            out.append(item)
            if d.duplicate:
                out.append(item)
        return out + tail

    def _requeue_held(self, peer: _Peer, item) -> None:
        try:
            peer.queue.put_nowait(_FaultHeld(item))
        except queue.Full:
            self.dropped_sends += 1
            return
        self._ensure_sender(peer)

    def _addr_blocked(self, origin: tuple) -> bool:
        """True when the node listening at ``origin`` is partitioned off
        (replies/notifies must not tunnel through a blocked link)."""
        if not self.blocked_nodes:
            return False
        for node, addr in self.address_book.items():
            if tuple(addr) == origin:
                return node in self.blocked_nodes
        return False

    def _queue_to_addr(self, origin: tuple, item: tuple) -> None:
        """Shared addr-keyed return routing for replies, notifies and
        RPC responses: lazily build the addr peer, enqueue nonblocking
        with drop accounting, honor partitions."""
        if self._addr_blocked(origin):
            self.dropped_sends += 1
            return
        peer = self._addr_peers.get(origin)
        if peer is None:
            peer = self._addr_peers.setdefault(
                origin, _Peer(f"addr:{origin[0]}:{origin[1]}", origin))
        try:
            peer.queue.put_nowait(item)
        except queue.Full:
            self.dropped_sends += 1
            return
        self._ensure_sender(peer)

    def reply_remote(self, handle: tuple, msg) -> None:
        _tag, origin, call_id = handle
        origin = tuple(origin)
        if origin == tuple(self.listen_addr):
            with self._call_lock:
                fut = self._calls.pop(call_id, None)
            if fut is not None:
                fut.set(msg)
            return
        self._queue_to_addr(origin, ("__reply__", (call_id, msg)))

    def notify_remote(self, handle: tuple, correlations) -> None:
        """Route an applied-notification batch back to the host that
        registered the sink (see _rewrite_cmd)."""
        _tag, origin, router_id, nid = handle
        origin = tuple(origin)
        if router_id == self._router_id:
            fn = self._notify_handles.get(nid)
            if fn is not None:
                fn(correlations)
            return
        self._queue_to_addr(origin, ("__notify__", (nid, correlations)))

    # ------------------------------------------------------------------
    # receive path
    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._recv_loop, args=(conn,),
                                 daemon=True, name="ra-tcp-recv")
            t.start()

    def _recv_loop(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        remote_names: list = []  # every node co-hosted behind this conn
        try:
            while not self._stop:
                hdr = self._recv_exact(conn, _LEN.size)
                if hdr is None:
                    break
                (length,) = _LEN.unpack(hdr)
                if length == 0 or length > MAX_FRAME:
                    break  # corrupt/hostile header: drop the connection
                frame = self._recv_exact(conn, length)
                if frame is None:
                    break
                kind = frame[0]
                plan = self.fault_plan
                if kind == FRAME_HELLO:
                    remote_names = frame[1:].decode().split(",")
                    for name in remote_names:
                        if name in self.blocked_nodes:
                            continue
                        if plan is not None and \
                                name in plan.partitioned:
                            # a partitioned peer must stay silent: its
                            # redial handshake cannot reset last_heard
                            # or the down verdict would oscillate
                            continue
                        self._mark_heard(name)
                    continue
                if remote_names and \
                        all(n in self.blocked_nodes for n in remote_names):
                    continue  # partitioned: total inbound silence
                if plan is not None:
                    # recv side honors drop/partition only; delay/dup/
                    # reorder are send-side faults (one injection point
                    # per fault kind keeps schedules interpretable),
                    # and un-honorable decisions must not spend the
                    # spec's limit or counters
                    pname = plan.recv_peer(remote_names)
                    cls = _FRAME_CLASS.get(kind, "msg")
                    if plan.decide(pname, cls, "recv",
                                   honor=_DROP_ONLY).action == "drop":
                        continue
                # any delivered frame proves the connection's unblocked
                # hosts alive (hoisted: every frame kind counts)
                for name in remote_names:
                    if name not in self.blocked_nodes:
                        self._mark_heard(name)
                if kind == FRAME_MSG:
                    to, src, msg = pickle.loads(frame[1:])
                    if src in self.blocked_nodes:
                        continue  # per-source drop (co-hosted routers)
                    node = self.nodes.get(to.node)
                    if node is not None:
                        node.deliver(to, self._from_wire(msg))
                elif kind == FRAME_MSG_BATCH:
                    # one frame, many routed messages (ISSUE 13): the
                    # recv-side fault decision above covered the frame
                    # as one "msg"-class delivery, matching the one
                    # syscall it rode in on
                    for to, src, msg in pickle.loads(frame[1:]):
                        if src in self.blocked_nodes:
                            continue
                        node = self.nodes.get(to.node)
                        if node is not None:
                            node.deliver(to, self._from_wire(msg))
                elif kind == FRAME_REPLY:
                    call_id, reply = pickle.loads(frame[1:])
                    with self._call_lock:
                        fut = self._calls.pop(call_id, None)
                    if fut is not None:
                        fut.set(reply)
                elif kind == FRAME_NOTIFY:
                    nid, correlations = pickle.loads(frame[1:])
                    fn = self._notify_handles.get(nid)
                    if fn is not None:
                        fn(correlations)
                elif kind == FRAME_RPC_REQ:
                    req = pickle.loads(frame[1:])
                    self._rpc_receiver.handle(
                        req,
                        lambda resp, _r=req: self._rpc_respond(_r, resp))
                elif kind == FRAME_RPC_RESP:
                    resp = pickle.loads(frame[1:])
                    with self._call_lock:
                        fut = self._rpc_pending.pop(resp.rid, None)
                    if fut is not None:
                        fut.set(resp)
        except (OSError, pickle.UnpicklingError, EOFError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # ------------------------------------------------------------------
    # failure detector (the aten role)
    # ------------------------------------------------------------------

    def _mark_heard(self, node: str) -> None:
        self.last_heard[node] = time.monotonic()
        self._suspect_since.pop(node, None)
        status = self.node_status.get(node)
        if status == "down":
            self.node_status[node] = "up"
            self._broadcast_node_event(node, "up")
        else:
            # also clears "suspect" silently — only the down->up edge
            # is a NodeEvent (aten emits verdicts, not hunches)
            self.node_status[node] = "up"

    def _detector_loop(self) -> None:
        while not self._stop:
            time.sleep(PING_INTERVAL)
            now = time.monotonic()
            # ping every peer we have a live connection to — including
            # addr-keyed reply links: a member-less client learns the
            # server's liveness only through them, and without pings a
            # verb slower than DOWN_AFTER would decay the caller's view
            # of a healthy, still-executing peer to down
            for peer in list(self.peers.values()) + \
                    list(self._addr_peers.values()):
                if peer.name in self.blocked_nodes:
                    continue
                sock = peer.sock
                if sock is not None:
                    plan = self.fault_plan
                    if plan is not None and plan.decide(
                            peer.name, "ping", "send",
                            honor=_DROP_ONLY).action == "drop":
                        continue  # injected ping loss
                    try:
                        frame = bytes([FRAME_PING])
                        with peer.send_lock:
                            sock.sendall(_LEN.pack(len(frame)) + frame)
                    except OSError:
                        self._close_peer(peer)
            # verdicts: up -> suspect (RPC retries stop trusting the
            # cached conn) -> down (NodeEvent broadcast + conn closed,
            # so the next send must redial rather than vanish into a
            # half-dead socket)
            for node, heard in list(self.last_heard.items()):
                if node in self.nodes:
                    continue
                status = self.node_status.get(node, "up")
                silent = now - heard
                if status != "down" and silent > self.down_after and \
                        now - self._suspect_since.get(node, now) >= \
                        self.detector_hysteresis:
                    # down needs BOTH silence beyond the window AND
                    # (when hysteresis is configured) a continuous
                    # suspect streak — a delayed-but-alive peer whose
                    # frames land inside the streak never escalates
                    self.node_status[node] = "down"
                    self._suspect_since.pop(node, None)
                    record("detector.down", peer=node,
                           age=round(silent, 4))
                    peer = self.peers.get(node)
                    if peer is not None:
                        self._close_peer(peer)
                    self._broadcast_node_event(node, "down")
                elif status == "up" and silent > self.suspect_after:
                    self.node_status[node] = "suspect"
                    self._suspect_since[node] = now
                    record("detector.suspect", peer=node,
                           age=round(silent, 4))

    def _broadcast_node_event(self, node: str, status: str) -> None:
        evt = NodeEvent(node, status)
        for ranode in list(self.nodes.values()):
            for name in list(ranode.shells):
                ranode.submit(name, evt)

    # ------------------------------------------------------------------

    def stop(self) -> None:
        self._stop = True
        try:
            self._server.close()
        except OSError:
            pass
        for peer in self.peers.values():
            self._close_peer(peer)

    def overview(self) -> dict:
        out = {
            "listen": self.listen_addr,
            "dropped_sends": self.dropped_sends,
            "peers": {p.name: p.status for p in self.peers.values()},
            "node_status": dict(self.node_status),
            "rpc": self._rpc_receiver.overview(),
        }
        if self.fault_plan is not None:
            out["faults"] = self.fault_plan.overview()
        return out
