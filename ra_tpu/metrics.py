"""Counters + leaderboard — the observability surface.

The reference keeps ~47 flat atomic counter fields per server behind
seshat (ra_counters.erl, field specs ra.hrl:236-390) plus lock-free ETS
tables for leader lookup (ra_leaderboard.erl).  Here: a Counters registry
of plain int dicts (GIL-atomic increments), sampled without touching the
server event loop — the same contract as ra:key_metrics (ra.erl:1229).
On the lane engine, the equivalent metrics live *on device* as the
total_committed / term / commit arrays and are sampled via readback.
"""
from __future__ import annotations

import threading
from typing import Optional

#: per-server LOG subsystem counter fields (RA_LOG_COUNTER_FIELDS,
#: ra.hrl:236-268 — same names).  Owned by the log facade (DurableLog /
#: MemoryLog keep a plain dict) and merged into key_metrics.
#: Deliberate N/A vs the reference: ``reserved_1`` (a placeholder), and
#: ``read_open_mem_tbl``/``read_closed_mem_tbl`` — the reference's
#: open/closed WAL ETS tables are merged into the DurableLog memtable
#: here (wal.py:15-21), so those reads all count as ``read_cache``.
LOG_FIELDS = (
    "write_ops", "write_resends", "read_ops", "read_cache",
    "read_segment", "fetch_term", "snapshots_written",
    "snapshot_installed", "snapshot_bytes_written", "open_segments",
    "checkpoints_written", "checkpoint_bytes_written",
    "checkpoints_promoted",
)

#: per-server raft/process counter fields (RA_SRV_COUNTER_FIELDS,
#: ra.hrl:311-357 — same names).  ``reserved_2`` omitted (placeholder);
#: ``invalid_reply_mode_commands`` stays 0 by construction — reply modes
#: are a typed enum here, so an invalid one cannot be submitted.
#: ``msgs_processed`` is ours (no reference equivalent): total events
#: through the shell, useful for busy-loop diagnostics.
SERVER_FIELDS = (
    "aer_received_follower", "aer_replies_success", "aer_replies_fail",
    "commands", "command_flushes", "aux_commands", "consistent_queries",
    "rpcs_sent", "msgs_sent", "dropped_sends", "send_msg_effects_sent",
    "pre_vote_elections", "elections", "forced_gcs", "snapshots_sent",
    "release_cursors", "aer_received_follower_empty",
    "term_and_voted_for_updates", "local_queries",
    "invalid_reply_mode_commands", "checkpoints", "msgs_processed",
)

#: per-server gauge fields (RA_SRV_METRICS_COUNTER_FIELDS,
#: ra.hrl:359-383): sampled live from server state at key_metrics time
#: rather than double-written into counters on every event.
METRIC_FIELDS = (
    "last_applied", "commit_index", "snapshot_index", "last_index",
    "last_written_index", "commit_latency", "term", "checkpoint_index",
    "effective_machine_version",
)

#: router-level reliable-RPC counter fields (transport/rpc.py): the
#: control plane's at-most-once observability.  Sender side: calls,
#: retries and the typed failure triad; receiver side: executions,
#: dedup hits (a retry mapped onto an already-seen request id — the
#: proof no lifecycle verb ran twice), responses re-sent from the
#: cache, and requests that arrived past their propagated deadline.
#: No reference equivalent: rpc:call rides Erlang distribution there.
RPC_FIELDS = (
    "rpc_calls", "rpc_retries", "rpc_timeouts", "rpc_unreachable",
    "rpc_remote_errors", "rpc_dedup_hits", "rpc_requests_executed",
    "rpc_responses_resent", "rpc_expired",
)

#: node-wide WAL counter fields (ra_log_wal.erl:32-43 — same names,
#: plus ``syncs``: fsync count, the number the reference exposes through
#: ra_file_handle instead, and ``sync_time_us``: cumulative durability-
#: syscall wall time, the wal_sync_time gauge role).  Each WAL *shard*
#: owns one counter dict (the sharded engine bridge runs S of them);
#: ``Wal.stats()`` adds the derived fsync latency p50/p99 and
#: records-per-fsync from a bounded latency reservoir.
WAL_FIELDS = ("wal_files", "batches", "writes", "bytes_written", "syncs",
              "sync_time_us")

#: engine durability-bridge counter fields (ra_tpu/engine/durable.py),
#: mirroring the RPC_FIELDS pattern: plain int dict, merged into the
#: engine overview.  ``readback_bytes`` is what the compacted device->
#: host readback actually moved for WAL encode; ``readback_bytes_full``
#: is what the pre-compaction full-ring readback would have moved on the
#: same steps (the ratio is the compaction win).  The overview adds
#: ``confirm_lag_steps`` — dispatched-but-unconfirmed steps on the
#: laggiest shard — as a DERIVED gauge sampled at overview time, not a
#: counter field.
ENGINE_WAL_FIELDS = ("readback_bytes", "readback_bytes_full",
                     "encoded_blocks", "encoded_bytes")

#: engine dispatch-pipeline counter fields (ra_tpu/engine/lockstep.py),
#: host-side ints stamped into ``engine.overview()["pipeline"]`` and the
#: bench JSON (ISSUE 5).  ``dispatches`` counts XLA dispatches (single
#: steps AND fused supersteps each count 1); ``inner_steps`` counts
#: engine rounds (a superstep of K adds K — dividing the two gives the
#: realized fusion factor); ``superstep_dispatches`` the fused subset;
#: ``blocks_staged`` host->device staging transfers started by the
#: dispatch-ahead driver; ``window_syncs`` the driver's in-flight-cap
#: waits — the ONLY host blocking points in a dispatch-ahead loop, so
#: window_syncs << dispatches is the proof the pipeline actually ran
#: ahead (the gauge twin of lint rule RA04's static guarantee).
ENGINE_PIPELINE_FIELDS = ("dispatches", "inner_steps",
                          "superstep_dispatches", "blocks_staged",
                          "window_syncs")

#: node-wide segment-writer counter fields (ra_log_segment_writer.erl:
#: 37-52 — same names)
SEGMENT_WRITER_FIELDS = ("mem_tables", "segments", "entries",
                         "bytes_written")

#: storage-plane fault observability (ra_tpu/log/faults.py): one
#: node-wide dict, the disk twin of RPC_FIELDS.  Plan-side:
#: ``faults_injected`` counts DiskFaultPlan decisions that injected a
#: fault (per-kind detail lives on the plan's own counters).  Policy
#: side: ``faults_hit`` is every I/O error the log layer *handled*
#: (poison/rollover/retry/skip — not thread death), ``crc_catches``
#: read-side corruption caught by a crc check, ``poisoned_files`` WAL
#: files poisoned by a failed durability syscall (fsyncgate: the fd is
#: never fsynced again), ``fault_rollovers`` the rollovers that poison
#: forced, ``wal_escalations`` consecutive-poison cap overflows that
#: escalate to thread death (supervisor restart), ``flush_retries``/
#: ``flush_escalations`` the segment-flush backoff ladder,
#: ``snapshot_write_failures`` failed container writes (pending-dir
#: discipline: the old snapshot stays), ``swallowed_oserrors`` the
#: audited allow-listed swallow sites (each carries a why-safe
#: comment), and ``fsync_retries_after_failure`` fsyncgate-discipline
#: violations — an fsync re-issued on a failed fd with no intervening
#: rewrite of its data; MUST stay 0.
DISK_FAULT_FIELDS = (
    "faults_injected", "faults_hit", "crc_catches", "poisoned_files",
    "fault_rollovers", "wal_escalations", "flush_retries",
    "flush_escalations", "snapshot_write_failures",
    "swallowed_oserrors", "fsync_retries_after_failure",
)

#: device-resident per-lane telemetry accumulators (ISSUE 6): the
#: ``[lanes]``-shaped int32 pytree carried through the jitted step
#: (ra_tpu/engine/lockstep.py LaneTelemetry — field parity is pinned by
#: tests).  Counters: ``elections_requested`` host-requested election
#: rounds, ``elections_won`` vote rounds that seated a leader,
#: ``leader_changes`` the subset that moved the leader to a different
#: slot (churn), ``steps`` engine rounds observed.  Gauges (rewritten
#: every step): ``leader_age`` steps since the lane's leader last
#: changed (stability), ``commit_lag`` leader tail minus leader commit
#: in entries, ``apply_lag`` leader commit minus the lane apply
#: frontier, ``stall_steps`` consecutive rounds with a nonempty commit
#: backlog and zero commit progress — a lane is flagged STALLED when it
#: crosses the sampler's ``stall_threshold``.
TELEMETRY_FIELDS = (
    "elections_requested", "elections_won", "leader_changes",
    "leader_age", "commit_lag", "apply_lag", "stall_steps", "steps",
)

#: phase-resolved latency attribution (ISSUE 9): the host-side edges of
#: the lane-engine path, each a monotonic-stamp latency sample fed into
#: a ``telemetry.PhaseStats`` accumulator (bounded reservoir + log2-ms
#: histogram + cumulative ``total_ms`` per phase).  ``total_ms`` is
#: MONOTONE, so differentiating it over the Observatory ring yields the
#: per-window budget share of each phase — "where did this window's
#: latency go" — which is exactly the autotuner's triggering-phase
#: input.  Phases: ``host_staging`` host->device block staging in the
#: dispatch-ahead driver, ``device_dispatch`` dispatch-submit to
#: async-watermark-readback-observed (PR 5's step stamps; no new host
#: syncs), ``queue_wait`` a submitted step waiting for its shard encode
#: worker, ``wal_encode`` the off-thread readback+encode+CRC of one WAL
#: block, ``fsync_wait`` the durability syscall, ``confirm_publish``
#: fsync-to-confirm-notify fan-out, ``commit_e2e`` the full
#: submit->all-shards-confirmed edge (the continuous commit-latency
#: signal the `commit_p99_ms` SLO reads), ``encode`` time spent
#: producing codec payload images (ISSUE 18) — fed by BOTH planes: the
#: classic leader/follower encode sites in DurableLog and the
#: lane-engine WAL workers' block encode; its share of total phase time
#: is the `encode_share_pct` key bench tails carry (lower is better —
#: encode-once should drive it toward zero).
PHASE_FIELDS = (
    "host_staging", "device_dispatch", "queue_wait", "wal_encode",
    "fsync_wait", "confirm_publish", "commit_e2e", "encode",
    # ``read_e2e`` (ISSUE 20): read-block submit -> serve outcome
    # observed at the driver's existing window-boundary pops — the
    # continuous read-latency signal the `read_p99_ms` SLO objective
    # evaluates (flat ring key engine_phases_read_e2e_p99_ms)
    "read_e2e",
)

#: ingress-plane counter fields (ra_tpu/ingress/, ISSUE 10): one dict
#: per IngressPlane, merged into the Observatory as the ``ingress``
#: source (flat ring keys ``ingress_<field>``).  ``submitted`` is every
#: row offered to submit(); ``accepted`` the subset that reached the
#: coalescer (placed — these and only these advance the at-most-once
#: seqno watermark); ``dup_dropped`` rows rejected by the per-session
#: dedup (a resend of an already-placed (session, seqno) — the proof
#: resends are at-most-once end-to-end); ``slow_signals`` admissions
#: past the soft credit (the generalized FifoClient "slow" verdict);
#: ``deferred`` rows parked by tenant-fairness admission at ladder
#: level >= 2; ``rejected`` rows refused at the hard credit (the
#: StopSending analogue); ``shed_rows`` rows dropped by coalescer ring
#: overflow (bounded queues shed, they never grow); ``blocks_built``
#: superstep blocks dispatched and ``block_rows`` the rows they
#: carried (rows/blocks = realized coalescing factor);
#: ``reconnects`` session epoch bumps; ``credits_released`` per-row
#: credit returns at block-commit granularity.
INGRESS_FIELDS = (
    "submitted", "accepted", "dup_dropped", "slow_signals", "deferred",
    "rejected", "shed_rows", "blocks_built", "block_rows", "reconnects",
    "credits_released",
)

#: wire-plane counter fields (ra_tpu/wire/, ISSUE 12): one dict per
#: WireListener, the Observatory ``wire`` source (flat ring keys
#: ``wire_<field>``).  Pool lifecycle: ``conns_opened``/
#: ``conns_closed`` connection slots bound/released (socket accepts
#: AND loopback bulk connects), ``hello_reconnects`` re-binds of a
#: known connection key (the epoch-bump trigger).  Data plane:
#: ``bytes_recv`` raw bytes landed in the rings, ``sweeps`` vectorized
#: sweep passes, ``swept_rows`` DATA records decoded and submitted
#: (the wire twin of ingress ``submitted``), ``protocol_errors``
#: malformed frames/records (each closes its connection).  Feedback
#: plane: ``credit_rows``/``ack_rows`` verdict and watermark records
#: serialized back; ``credit_ok``/``credit_slow``/``credit_defer``/
#: ``credit_reject``/``credit_dup``/``credit_shed`` the credit-level
#: histogram — per-status verdict counts (ra_top renders these as the
#: wire panel's credit histogram).
WIRE_FIELDS = (
    "conns_opened", "conns_closed", "hello_reconnects", "bytes_recv",
    "sweeps", "swept_rows", "protocol_errors", "credit_rows",
    "ack_rows", "credit_ok", "credit_slow", "credit_defer",
    "credit_reject", "credit_dup", "credit_shed",
    # read plane (ISSUE 20): ``read_rows`` READ records decoded and
    # submitted by the vectorized sweep (the read twin of swept_rows),
    # ``read_reply_rows`` READ_REPLY records fanned back with their
    # certified watermark
    "read_rows", "read_reply_rows",
)

#: ingress read-lane counter fields (ra_tpu/ingress/, ISSUE 20): one
#: dict per IngressPlane read lane, the Observatory ``read`` source
#: (flat ring keys ``read_<field>``).  Admission: ``submitted`` read
#: rows offered, ``accepted`` the subset placed into the read
#: coalescer, ``shed`` rows shed by overload (the CreditLadder sheds
#: reads BEFORE it delays writes — any ladder level above green sheds),
#: ``rejected`` rows refused by coalescer ring overflow.  Dispatch:
#: ``blocks_built`` read superstep blocks dispatched and
#: ``block_rows`` the rows they carried.  Settlement (from the
#: device's cumulative serve/refuse watermarks): ``served`` reads
#: answered at a certified watermark, ``stale_refused`` reads the
#: device refused rather than serve stale (lease expired / quorum
#: lost / timeout — the oracle pins consistent reads to 0 stale
#: SERVES; refusals are the safe outcome), ``lease_served`` the
#: served-under-lease subset (lease coverage), ``replies_sent``
#: READ_REPLY rows fanned back to clients.
READ_FIELDS = (
    "submitted", "accepted", "shed", "rejected", "blocks_built",
    "block_rows", "served", "stale_refused", "lease_served",
    "replies_sent",
)

#: the on-device aggregation of TELEMETRY_FIELDS (lockstep's jitted
#: telemetry summary): scalar rollups plus the fixed-size lag histogram
#: and the lax.top_k offender slots.  ``stalled_lanes`` lanes at or
#: past the stall threshold; ``commit_lag_hist`` log2-bucket counts of
#: per-lane commit lag; ``top_lanes`` the K worst lane ids by
#: (stall, lag) offender score with their ``top_commit_lag``/
#: ``top_apply_lag``/``top_stall_steps`` gauges; ``committed_total``
#: cumulative committed commands (float32 — the per-window rate
#: substrate the Observatory ring derives throughput from).
TELEMETRY_SUMMARY_FIELDS = (
    "steps", "elections_requested", "elections_won", "leader_changes",
    "stalled_lanes", "commit_lag_max", "commit_lag_mean",
    "apply_lag_max", "apply_lag_mean", "leader_age_min",
    "commit_lag_hist", "top_lanes", "top_commit_lag", "top_apply_lag",
    "top_stall_steps", "committed_total",
    "read_served_total", "read_shed_total", "read_stale_total",
    "read_leased_total",
)

#: classic replication-batching health (ISSUE 13): the shape of
#: ``RaNode.classic_stats()`` — stamped into bench_classic's JSON tail
#: (both phases) and wired into the leader system's Observatory as the
#: ``classic`` source.  ``aer_batches_sent`` counts multi-entry
#: AppendEntries frames built by leaders hosted on the node and
#: ``aer_batch_entries`` the entries they carried (their ratio is the
#: realized AER batching factor); ``entries_per_batch_p50``/
#: ``entries_per_batch_p99``/``entries_per_batch_mean`` come from the
#: cores' bounded batch-size reservoirs; ``records_per_fsync`` — the
#: group-commit fan-in half of the pair — is Wal.stats()'s
#: amortization factor, stamped next to the AER numbers by the
#: embedding bench so one doc answers "how batched was replication,
#: end to end".
CLASSIC_FIELDS = (
    "aer_batches_sent", "aer_batch_entries", "entries_per_batch_p50",
    "entries_per_batch_p99", "entries_per_batch_mean",
    "records_per_fsync",
)

#: device-plane runtime observatory (ra_tpu/devicewatch.py, ISSUE 16):
#: one process-wide dict behind the ``WATCH`` singleton, the runtime
#: mirror of the jit-plane static gates (RA04/RA13/RA14 are proof-only
#: — these fields are the measurement).  Recompile sentinel:
#: ``compiles`` counts XLA compiles observed across every wrapped jit
#: entry point (lockstep step/superstep, telemetry summary — warm-up
#: compiles land here), ``recompiles`` the subset BEYOND the first per
#: wrapped callable (a retrace: steady-state MUST stay 0, the runtime
#: twin of RA13, and the ``steady_state_recompiles`` SLO objective),
#: ``compile_ms`` cumulative wall time of compiling calls.  Transfer
#: ledger (the measured number behind RA04's lint promise):
#: ``h2d_events``/``h2d_bytes`` host->device transfers (driver block
#: staging, mesh state sharding), ``d2h_events``/``d2h_bytes``
#: device->host readbacks (driver window readbacks, telemetry
#: harvests, WAL encode readbacks).  Memory watermarks (sampled on the
#: TelemetrySampler harvest tick — zero new syncs): ``live_buffers``/
#: ``live_bytes`` gauges of live device buffers at the last sample,
#: ``peak_live_bytes`` the high-water mark, ``buffers_freed``
#: cumulative buffer releases observed between samples (donation
#: effectiveness, the runtime twin of RA14), ``watermark_samples``
#: samples taken.
DEVICE_FIELDS = (
    "compiles", "recompiles", "compile_ms", "h2d_events", "h2d_bytes",
    "d2h_events", "d2h_bytes", "live_buffers", "live_bytes",
    "peak_live_bytes", "buffers_freed", "watermark_samples",
)

#: placement failover control plane (ra_tpu/placement/, ISSUE 17):
#: the EngineSupervisor's counter group.  Detector tier:
#: ``heartbeats`` probe responses heard (delayed arrivals count when
#: they land), ``suspects``/``downs`` verdict escalations (a suspect
#: that recovers inside the hysteresis window never becomes a down —
#: the slow-fsync guard), ``recoveries`` suspect→up de-escalations.
#: Re-placement tier: ``migrations`` lane-range re-placements
#: committed through the placement table, ``migrate_retries`` extra
#: attempts the bounded commit loop needed beyond the first,
#: ``giveups`` bounded loops that exhausted their deadline (each also
#: emits ``placement.giveup``), ``adopts`` victim engines restored
#: into a survivor's lane space, ``rehomed_sessions`` sessions
#: re-bound to a new home (epoch bump + slot claim).  Cross-host tier
#: (ISSUE 19): ``stale_probe_drops`` probe replies discarded because
#: the slot was re-provisioned to a newer generation while the probe
#: was in flight (each also emits ``placement.stale_probe``), and
#: ``rehome_hints`` frames refused by a serving listener with a typed
#: REHOME hint because the lane's home moved (each refusal batch also
#: emits ``placement.rehome_hint``).
PLACEMENT_FIELDS = (
    "heartbeats", "suspects", "downs", "recoveries", "migrations",
    "migrate_retries", "giveups", "adopts", "rehomed_sessions",
    "stale_probe_drops", "rehome_hints",
)

#: the complete field-group registry (rule RA05): every counter-field
#: tuple in this module MUST be listed here, covered by the registry
#: parity test (tests/test_telemetry.py) and documented in
#: docs/OBSERVABILITY.md — tools/lint.py statically enforces both.
#: Its event-plane sibling is ra_tpu/blackbox.py's EVENT_REGISTRY
#: (rule RA06): counters answer "how many", flight-recorder events
#: answer "which one, when" — one registry discipline for both.
FIELD_REGISTRY = {
    "log": LOG_FIELDS,
    "server": SERVER_FIELDS,
    "metric": METRIC_FIELDS,
    "rpc": RPC_FIELDS,
    "wal": WAL_FIELDS,
    "engine_wal": ENGINE_WAL_FIELDS,
    "engine_pipeline": ENGINE_PIPELINE_FIELDS,
    "segment_writer": SEGMENT_WRITER_FIELDS,
    "disk_faults": DISK_FAULT_FIELDS,
    "telemetry": TELEMETRY_FIELDS,
    "telemetry_summary": TELEMETRY_SUMMARY_FIELDS,
    "phase": PHASE_FIELDS,
    "ingress": INGRESS_FIELDS,
    "read": READ_FIELDS,
    "wire": WIRE_FIELDS,
    "classic": CLASSIC_FIELDS,
    "device": DEVICE_FIELDS,
    "placement": PLACEMENT_FIELDS,
}


class Counters:
    """Named counter groups (the seshat role)."""

    def __init__(self) -> None:
        self._groups: dict[str, dict] = {}
        self._lock = threading.Lock()
        #: increments addressed to an unknown group or field.  The old
        #: behaviour silently dropped them — a typo'd field name lost
        #: its events with no trace; now every drop is itself counted
        #: (the seshat-style self-metric; asserted 0 under the normal
        #: workloads in tests).
        self.dropped = 0

    def new(self, name: str, fields=SERVER_FIELDS) -> dict:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                g = {f: 0 for f in fields}
                self._groups[name] = g
            return g

    def incr(self, name: str, field: str, n: int = 1) -> None:
        g = self._groups.get(name)
        if g is None or field not in g:
            self.dropped += 1
            return
        g[field] += n

    def self_metrics(self) -> dict:
        """The registry's own health: ``telemetry_dropped`` counts
        increments lost to unknown group/field names (MUST stay 0 — a
        nonzero value means an instrumentation site and the field
        registry disagree)."""
        return {"telemetry_dropped": self.dropped}

    def fetch(self, name: str) -> Optional[dict]:
        g = self._groups.get(name)
        return dict(g) if g is not None else None

    def delete(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)

    def overview(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._groups.items()}


class Leaderboard:
    """cluster name -> (leader, members); written on leader change, read
    lock-free by clients (ra_leaderboard.erl:23-34)."""

    def __init__(self) -> None:
        self._tab: dict[str, tuple] = {}

    def record(self, cluster_name: str, leader, members) -> None:
        self._tab[cluster_name] = (leader, tuple(members))

    def lookup_leader(self, cluster_name: str):
        got = self._tab.get(cluster_name)
        return got[0] if got else None

    def lookup_members(self, cluster_name: str):
        got = self._tab.get(cluster_name)
        return got[1] if got else None

    def overview(self) -> dict:
        return dict(self._tab)
