"""Counters + leaderboard — the observability surface.

The reference keeps ~47 flat atomic counter fields per server behind
seshat (ra_counters.erl, field specs ra.hrl:236-390) plus lock-free ETS
tables for leader lookup (ra_leaderboard.erl).  Here: a Counters registry
of plain int dicts (GIL-atomic increments), sampled without touching the
server event loop — the same contract as ra:key_metrics (ra.erl:1229).
On the lane engine, the equivalent metrics live *on device* as the
total_committed / term / commit arrays and are sampled via readback.
"""
from __future__ import annotations

import threading
from typing import Optional

#: counter fields kept per server (subset of ra.hrl:236-390, same names)
SERVER_FIELDS = (
    "commands", "command_flushes", "aer_received_follower",
    "aer_replies_success", "aer_replies_failed", "elections",
    "pre_vote_elections", "snapshots_written", "snapshot_installed",
    "dropped_sends", "msgs_processed",
)


class Counters:
    """Named counter groups (the seshat role)."""

    def __init__(self) -> None:
        self._groups: dict[str, dict] = {}
        self._lock = threading.Lock()

    def new(self, name: str, fields=SERVER_FIELDS) -> dict:
        with self._lock:
            g = self._groups.get(name)
            if g is None:
                g = {f: 0 for f in fields}
                self._groups[name] = g
            return g

    def incr(self, name: str, field: str, n: int = 1) -> None:
        g = self._groups.get(name)
        if g is not None and field in g:
            g[field] += n

    def fetch(self, name: str) -> Optional[dict]:
        g = self._groups.get(name)
        return dict(g) if g is not None else None

    def delete(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)

    def overview(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._groups.items()}


class Leaderboard:
    """cluster name -> (leader, members); written on leader change, read
    lock-free by clients (ra_leaderboard.erl:23-34)."""

    def __init__(self) -> None:
        self._tab: dict[str, tuple] = {}

    def record(self, cluster_name: str, leader, members) -> None:
        self._tab[cluster_name] = (leader, tuple(members))

    def lookup_leader(self, cluster_name: str):
        got = self._tab.get(cluster_name)
        return got[0] if got else None

    def lookup_members(self, cluster_name: str):
        got = self._tab.get(cluster_name)
        return got[1] if got else None

    def overview(self) -> dict:
        return dict(self._tab)
