"""The at-least-once wire client library (ISSUE 12 — the client half
docs/INGRESS.md specifies).

Delivery contract (the reference's split, PAPER.md §1): the server
gate is at-most-once, so the client owns redelivery —

* commands pipeline freely under per-session seqnos (the
  ``pipeline_command`` discipline);
* every command is an **op** with a monotone per-session ``op_id`` and
  stays in the client's replay window until *acked* (its session's
  committed-row watermark covers it);
* a **refusal** (defer/reject/shed credit verdict) re-queues the op —
  its seqno is burned, the resend gets a fresh one;
* a **reconnect** observes the epoch bump in HELLO_ACK and re-enqueues
  every unacked op — including placed-but-unacked ones, whose first
  copy may still commit: the duplicate is absorbed MACHINE-side
  (:class:`~ra_tpu.wire.dedup.DedupCounterMachine`), which is what
  upgrades end-to-end semantics to exactly-once-observable.

Two implementations share the contract:

* :class:`WireClient` — one real TCP connection (blocking socket,
  per-frame Python): the integration-test / example client.
* :class:`LoopbackFleet` — N in-process connections driven as flat
  numpy arrays (the C100k→C1M ladder client): every step — op
  creation, seqno minting, DATA encode, credit/ack decode, replay
  bookkeeping — is a vectorized sweep over the whole fleet, mirroring
  the server's RA09 discipline from the client side.
"""
from __future__ import annotations

import socket
from typing import Optional

import numpy as np

from ..ingress.coalesce import batch_rank
from .framing import (DEFER, DUP, OK, REJECT, SHED, SLOW, T_ACK, T_CREDIT,
                      T_ERR, T_HELLO_ACK, T_REHOME, decode_ack,
                      decode_credit, decode_error, decode_hello_ack,
                      decode_rehome, encode_data, encode_hello,
                      read_frame)

#: op replay states
QUEUED, SENT, PLACED = 0, 1, 2


class WireClient:
    """One TCP connection, ``n_sessions`` multiplexed wire sessions,
    at-least-once op replay."""

    def __init__(self, address, key: str, *, n_sessions: int = 1,
                 tenants: int = 1, payload_width: int = 3,
                 timeout: float = 10.0) -> None:
        self.address = tuple(address)
        self.key = key
        self.n_sessions = int(n_sessions)
        self.tenants = int(tenants)
        self.payload_width = int(payload_width)
        self.timeout = float(timeout)
        self.epoch = 0
        self.handle_base = -1
        self.slots: Optional[np.ndarray] = None
        self.next_seq = np.ones(self.n_sessions, np.int64)
        self.next_op = np.ones(self.n_sessions, np.int64)
        self.placed_cnt = np.zeros(self.n_sessions, np.int64)
        self.watermark = np.zeros(self.n_sessions, np.int64)
        self.reconnects = 0
        #: ops: parallel lists (a client is per-connection scale — the
        #: vectorized bookkeeping lives in LoopbackFleet)
        self.op_sess: list = []
        self.op_id: list = []
        self.op_pay: list = []
        self.op_state: list = []
        self.op_rank: list = []       # placement rank per session
        #: ever placed on SOME home (survives the rank reset a re-home
        #: performs): such an op's refused replay is dropped, never
        #: re-keyed — its first copy is placed and will commit
        self.op_ever: list = []
        self._queued: list = []       # op indices awaiting (re)send
        self._pending: dict = {}      # (sess, seqno) -> op index
        self._placed_order: dict = {} # sess -> [op index] in rank order
        self._rx = b""
        self.last_credit_level = 0
        #: REHOME hint handling (ISSUE 19): ``rehome_resolver`` maps an
        #: engine id to its listener address (the client's service-
        #: discovery hook); a received hint is followed — reconnect to
        #: the resolved home, epoch bump, unacked window replayed — AT
        #: MOST ONCE per connection epoch, so a burst of hints from
        #: frames already on the wire cannot reconnect-storm the client
        self.rehome_resolver = None
        self.rehome_hint = None          # latest (engine, gen, rev)
        self.rehome_follows = 0
        self._followed_epoch = -1
        self.sock: Optional[socket.socket] = None
        self._connect()

    # -- connection lifecycle ----------------------------------------------

    def _connect(self) -> None:
        # a fresh socket is a fresh frame stream: a stale partial
        # frame kept from the old connection would swallow the new
        # HELLO_ACK bytes as its body and desynchronize every frame
        # after it
        self._rx = b""
        self.sock = socket.create_connection(self.address,
                                             timeout=self.timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(encode_hello(self.key, self.n_sessions,
                                       tenants=self.tenants,
                                       payload_width=self.payload_width))
        body = self._read_frame_blocking()
        if body is None:
            raise ConnectionError("wire: no HELLO_ACK")
        if body[0] == T_ERR:
            # the listener refused the handshake (version or
            # payload-width mismatch): surface its reason verbatim
            err = decode_error(body[1])
            raise ConnectionError("wire: refused: %s" % err["message"])
        if body[0] != T_HELLO_ACK:
            raise ConnectionError("wire: no HELLO_ACK")
        ack = decode_hello_ack(body[1])
        srv_width = ack.get("payload_width", 0)
        if srv_width and srv_width != self.payload_width:
            raise ConnectionError(
                "wire: payload_width %d != listener's %d"
                % (self.payload_width, srv_width))
        new_epoch = ack["epoch"]
        self.handle_base = ack["handle_base"]
        self.slots = ack["slots"][:self.n_sessions] \
            if ack["slots"] is not None else None
        if self.epoch and new_epoch > self.epoch:
            # the at-least-once pivot: everything unacked replays under
            # fresh seqnos; machine-level dedup absorbs the duplicates
            self._requeue_unacked()
        self.epoch = new_epoch

    def reconnect(self) -> None:
        """Drop the connection and redial under the SAME key: the
        server bumps the session epoch and the client re-enqueues its
        unacked window (the docs/INGRESS.md client contract).  Pending
        verdicts are drained first (best effort); one genuinely lost
        with the wire is covered by the one-batch-per-session flush
        gate — the un-credited window is always a send-order SUFFIX,
        so the old-id replay is gap-free and machine-dedup exact."""
        try:
            self.poll()
        except OSError:
            pass
        self.close(keep_state=True)
        self.reconnects += 1
        self._connect()

    def _requeue_unacked(self) -> None:
        self._pending.clear()
        requeue = [i for i in range(len(self.op_state))
                   if self.op_state[i] != QUEUED and not self._acked(i)]
        for i in requeue:
            self.op_state[i] = QUEUED
        self._queued = sorted(set(self._queued) | set(requeue))

    def rehome_to(self, address, durable=None) -> None:
        """Move this client to a NEW home serving its recovered
        session state (placement failover over TCP, ISSUE 19) — the
        WireClient twin of :meth:`LoopbackFleet.rehome`.  The new
        listener must have PRE-CLAIMED this client's session block
        (:meth:`WireListener.claim_sessions` — the ``host_rehome``
        control verb) with the old dedup slots and the acked
        watermarks, so replayed payloads hit the recovered machine's
        per-(lane, slot) dedup.

        Rank bookkeeping restarts at the acked watermark (ranks the
        old home burned on rows it never durably committed die with
        it), the pending window drops (old-home credits never
        arrive), and every unacked op requeues for at-least-once
        replay.  ``durable`` — the per-session durably-applied op-id
        watermarks ``claim_sessions`` returned — re-bases
        ``op_ever``: an op the old home placed but never fsynced is
        gone from every durable record, so its replay may re-key on
        refusal like any never-placed op.  Without it (``None``, the
        self-serve hint-follow path) every previously-placed op stays
        ever-placed — conservatively never double-applies, at the
        cost that a shed replay of a LOST copy is dropped rather than
        re-keyed."""
        self.address = tuple(address)
        n = len(self.op_state)
        dur = None if durable is None else np.asarray(durable, np.int64)
        for i in range(n):
            ever = self.op_rank[i] >= 0 or self.op_ever[i]
            if dur is not None:
                ever = ever and \
                    self.op_id[i] <= int(dur[self.op_sess[i]])
            self.op_ever[i] = ever
            if self.op_state[i] != QUEUED and not self._acked(i):
                self.op_state[i] = QUEUED
                self.op_rank[i] = -1
                self._queued.append(i)
        self._queued = sorted(set(self._queued))
        self._pending.clear()
        self.placed_cnt[:] = self.watermark
        self._placed_order = {}
        self.close(keep_state=True)
        self.reconnects += 1
        self._connect()

    def close(self, keep_state: bool = False) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if not keep_state:
            self._rx = b""

    # -- enqueue / flush ----------------------------------------------------

    def enqueue(self, delta: int, sess: int = 0) -> int:
        """Queue one op; returns its op index.  Payload layout follows
        the DedupCounterMachine contract when the server handed out
        dedup slots (``[slot, op_id, delta]``), else a bare counter
        increment."""
        op = int(self.next_op[sess])
        self.next_op[sess] += 1
        idx = len(self.op_sess)
        self.op_sess.append(int(sess))
        self.op_id.append(op)
        self.op_pay.append(int(delta))
        self.op_state.append(QUEUED)
        self.op_rank.append(-1)
        self.op_ever.append(False)
        self._queued.append(idx)
        return idx

    def _payload(self, idx_list) -> np.ndarray:
        n = len(idx_list)
        pay = np.zeros((n, self.payload_width), np.int32)
        deltas = np.array([self.op_pay[i] for i in idx_list], np.int32)
        if self.payload_width >= 3 and self.slots is not None:
            sess = np.array([self.op_sess[i] for i in idx_list])
            pay[:, 0] = self.slots[sess]
            pay[:, 1] = np.array([self.op_id[i] for i in idx_list])
            pay[:, 2] = deltas
        else:
            pay[:, 0] = deltas
        return pay

    def flush(self) -> int:
        """Encode + send every queued op (pipelined, fresh seqnos);
        returns the number of records sent."""
        if not self._queued or self.sock is None:
            return 0
        # one outstanding un-credited batch per session (the gap-free
        # crash-replay discipline, docs/INGRESS.md): a session with
        # verdicts still in flight must not layer NEW ops above a
        # possible unknown refusal — its un-credited window then stays
        # a send-order SUFFIX, so an old-id replay after a crash can
        # never be watermark-skipped below a later commit
        busy = {self.op_sess[i] for i in self._pending.values()}
        held = [i for i in set(self._queued)
                if self.op_sess[i] in busy]
        # per-session ascending op ids (see LoopbackFleet.send_queued:
        # replays below an already-placed id must only ever be placed
        # dups, never droppable fresh ops)
        idx = sorted(set(self._queued) - set(held),
                     key=lambda i: (self.op_sess[i], self.op_id[i]))
        self._queued = held
        if not idx:
            return 0
        sess = np.array([self.op_sess[i] for i in idx], np.int64)
        seq = self.next_seq[sess] + batch_rank(sess)
        np.add.at(self.next_seq, sess, 1)
        for i, s, q in zip(idx, sess.tolist(), seq.tolist()):
            self._pending[(s, q)] = i
            self.op_state[i] = SENT
        try:
            self.sock.sendall(encode_data(sess, seq,
                                          self._payload(idx)))
        except OSError:
            # connection died mid-send: ops stay pending; the epoch
            # bump at reconnect() replays them
            pass
        return len(idx)

    # -- receive ------------------------------------------------------------

    def _read_frame_blocking(self):
        self.sock.settimeout(self.timeout)
        while True:
            got = read_frame(self._rx)
            if got is not None:
                t, body, off = got
                self._rx = self._rx[off:]
                return t, body
            chunk = self.sock.recv(1 << 16)
            if not chunk:
                return None
            self._rx += chunk

    def poll(self, max_frames: int = 64) -> int:
        """Drain available CREDIT/ACK frames without blocking; returns
        the number of frames processed."""
        if self.sock is None:
            return 0
        self.sock.settimeout(0.0)
        try:
            while len(self._rx) < 1 << 20:
                chunk = self.sock.recv(1 << 16)
                if not chunk:
                    break
                self._rx += chunk
        except (BlockingIOError, socket.timeout, OSError):
            pass
        done = 0
        while done < max_frames:
            got = read_frame(self._rx)
            if got is None:
                break
            t, body, off = got
            self._rx = self._rx[off:]
            self._handle_frame(t, body)
            done += 1
        return done

    def _handle_frame(self, t: int, body: bytes) -> None:
        if t == T_CREDIT:
            _level, rec = decode_credit(body)
            self.last_credit_level = _level
            for r in rec:
                self._on_verdict(int(r["sess"]), int(r["seqno"]),
                                 int(r["status"]))
        elif t == T_ACK:
            for r in decode_ack(body):
                s = int(r["sess"])
                self.watermark[s] = max(self.watermark[s],
                                        int(r["acked"]))
        elif t == T_REHOME:
            hint = decode_rehome(body)
            self.rehome_hint = (hint["engine"], hint["generation"],
                                hint["rev"])
            self._maybe_follow_rehome(hint)

    def _maybe_follow_rehome(self, hint: dict) -> None:
        """Follow a REHOME hint at most once per connection epoch
        (ISSUE 19).  The gate is recorded BEFORE the redial: any
        further hints already buffered from the old socket (or drained
        by reconnect()'s best-effort poll) find the epoch spent and
        are kept as data only — no reconnect storm.  Without a
        resolver the hint is surfaced (``rehome_hint``) but never
        acted on; the caller owns service discovery."""
        if self.rehome_resolver is None:
            return
        if self._followed_epoch == self.epoch:
            return
        addr = self.rehome_resolver(hint["engine"])
        if addr is None:
            return
        self._followed_epoch = self.epoch
        self.rehome_follows += 1
        self.rehome_to(addr)

    def _on_verdict(self, sess: int, seqno: int, status: int) -> None:
        i = self._pending.pop((sess, seqno), None)
        if i is None:
            return
        if status in (OK, SLOW):
            self.op_state[i] = PLACED
            self.op_rank[i] = int(self.placed_cnt[sess])
            self.op_ever[i] = True
            self.placed_cnt[sess] += 1
            self._placed_order.setdefault(sess, []).append(i)
        elif status in (DEFER, REJECT, SHED):
            if self.op_rank[i] >= 0 or self.op_ever[i]:
                # refused REPLAY of an ever-placed op: the first copy
                # is placed and will commit — drop the replay
                self.op_state[i] = PLACED
                return
            # a refusal of a never-placed op re-keys: the machine's
            # per-slot watermark dedup requires op ids to reach it
            # monotonically, and a stale id replayed after later ops
            # committed would be skipped as a duplicate — a lost
            # command (re-keying a possibly-placed op would instead
            # double-apply; only never-placed refusals may re-key)
            self.op_state[i] = QUEUED
            self.op_id[i] = int(self.next_op[sess])
            self.next_op[sess] += 1
            self._queued.append(i)
        elif status == DUP:
            # already placed under an earlier seqno: nothing to replay
            self.op_state[i] = PLACED
            self.op_ever[i] = True

    # -- progress -----------------------------------------------------------

    def _acked(self, i: int) -> bool:
        return self.op_state[i] == PLACED and self.op_rank[i] >= 0 and \
            self.op_rank[i] < self.watermark[self.op_sess[i]]

    def acked_count(self) -> int:
        return sum(1 for i in range(len(self.op_state))
                   if self._acked(i))

    def unacked_count(self) -> int:
        return len(self.op_state) - self.acked_count()

    def pending_count(self) -> int:
        return len(self._pending) + len(self._queued)


class LoopbackFleet:
    """N in-process wire connections as flat numpy state — the ladder
    client.  One instance drives the whole fleet: ops, seqnos, encode,
    credit/ack decode and the at-least-once replay window are all
    vectorized sweeps (no per-connection Python anywhere on the wave
    path)."""

    #: packed (handle, seqno) join key base (seqnos stay < 2^40)
    _SEQ_BITS = 40

    def __init__(self, listener, n_conns: int, *,
                 sessions_per_conn: int = 1, key: str = "fleet",
                 tenants: int = 1, seed: int = 0,
                 max_ops: int = 1 << 20) -> None:
        self.listener = listener
        self.n_conns = int(n_conns)
        self.spc = int(sessions_per_conn)
        self.key = key
        self.tenants = max(1, int(tenants))
        self.rng = np.random.default_rng(seed)
        self.conns = listener.loopback_connect(
            n_conns, sessions_per_conn=self.spc, key=key,
            tenants=tenants)
        self.n_sessions = self.n_conns * self.spc
        self.base = int(listener.hbase[self.conns[0]])
        self.handles = self.base + np.arange(self.n_sessions,
                                             dtype=np.int64)
        self.slots = listener.session_slots(self.handles)
        self.payload_width = listener.payload_width
        # per-session state
        self.next_seq = np.ones(self.n_sessions, np.int64)
        self.next_op = np.ones(self.n_sessions, np.int64)
        self.placed_cnt = np.zeros(self.n_sessions, np.int64)
        self.watermark = np.zeros(self.n_sessions, np.int64)
        # op store (preallocated; sess is the FLEET session index)
        self.max_ops = int(max_ops)
        self.op_sess = np.zeros(self.max_ops, np.int64)
        self.op_id = np.zeros(self.max_ops, np.int64)
        self.op_delta = np.zeros(self.max_ops, np.int32)
        self.op_state = np.zeros(self.max_ops, np.int8)
        self.op_rank = np.full(self.max_ops, -1, np.int64)
        #: ever placed on SOME home — survives the rank reset a
        #: re-home performs, so the refusal path can still tell "this
        #: replay's first copy may have committed" (such ops are
        #: dropped on refusal, never re-keyed; see _on_credit)
        self.op_ever = np.zeros(self.max_ops, bool)
        self.n_ops = 0
        # (packed key -> op) pending-credit join, kept sorted
        self._pend_key = np.zeros(0, np.int64)
        self._pend_op = np.zeros(0, np.int64)
        #: un-credited rows in flight per session — the one-batch
        #: flush gate (see send_queued)
        self._pend_per_sess = np.zeros(self.n_sessions, np.int64)
        self.reconnects = 0
        #: REHOME hints drained from the listener (ISSUE 19): the
        #: latest ``(slot, engine, generation, rev)`` plus a count —
        #: the driver (soak / rehome harness) owns the follow action,
        #: mirroring WireClient.rehome_resolver
        self.rehome_hint = None
        self.rehome_hints = 0
        # per-tenant verdict tallies (the soak's shed-fairness evidence)
        d = listener.plane.directory
        self.tenant_of = d.tenant[self.handles].astype(np.int64)
        nt = max(1, d.n_tenants)
        self.tenant_rows = np.zeros(nt, np.int64)
        self.tenant_shed = np.zeros(nt, np.int64)

    # -- ops ----------------------------------------------------------------

    def new_ops(self, sess_idx: np.ndarray, deltas: np.ndarray) -> None:
        """Mint one op per row (monotone per-session op ids)."""
        n = len(sess_idx)
        if self.n_ops + n > self.max_ops:
            raise RuntimeError("fleet op store full")
        lo = self.n_ops
        self.n_ops += n
        sess_idx = np.asarray(sess_idx, np.int64)
        self.op_sess[lo:lo + n] = sess_idx
        self.op_id[lo:lo + n] = self.next_op[sess_idx] + \
            batch_rank(sess_idx)
        np.add.at(self.next_op, sess_idx, 1)
        self.op_delta[lo:lo + n] = deltas
        self.op_state[lo:lo + n] = QUEUED
        self.op_rank[lo:lo + n] = -1

    def queued_ops(self) -> np.ndarray:
        return np.flatnonzero(self.op_state[:self.n_ops] == QUEUED)

    # -- send (vectorized wave) --------------------------------------------

    def send_queued(self, max_rows: int = 1 << 20) -> int:
        """Encode + feed every queued op into the server rings (fresh
        seqnos, conn-ordered records); returns rows actually placed on
        the transport (ring overflow keeps the tail queued)."""
        idx = self.queued_ops()
        if not len(idx):
            return 0
        # one outstanding un-credited batch per session (the gap-free
        # crash-replay discipline, docs/INGRESS.md): never layer new
        # sends above verdicts still in flight — the un-credited
        # window stays a send-order suffix, so a crash replay under
        # original ids can never be watermark-skipped below a later
        # commit.  (The synchronous soak cycle collects credit before
        # each wave, so this gate binds only under genuine loss.)
        idx = idx[self._pend_per_sess[self.op_sess[idx]] == 0]
        if not len(idx):
            return 0
        sess = self.op_sess[idx]
        conn_i = sess // self.spc
        # send order is per-session ASCENDING op id, not op-creation
        # order: the queue mixes storm replays (old ids) with re-keyed
        # refusals (fresh high ids), and the machine's watermark dedup
        # drops any never-placed op that arrives below an already-
        # placed id — ascending ids per session make that impossible
        # (a replayed-below-watermark op is then always a placed dup)
        order = np.lexsort((self.op_id[idx], sess, conn_i))
        idx, sess, conn_i = idx[order], sess[order], conn_i[order]
        # max_rows truncation AFTER the sort: a prefix of the sorted
        # batch keeps every surviving session's lowest ids, so a
        # truncated session still sends an ascending prefix.  (An
        # op-creation-order cut would send a re-keyed high id while a
        # newer low-id op waits — exactly the inversion the sort
        # exists to prevent; found as a real ~0.1% command loss at the
        # C1M rung.)
        if len(idx) > max_rows:
            idx = idx[:max_rows]
            sess = sess[:max_rows]
            conn_i = conn_i[:max_rows]
        seq = self.next_seq[sess] + batch_rank(sess)
        np.add.at(self.next_seq, sess, 1)
        pay = np.zeros((len(idx), self.payload_width), np.int32)
        if self.payload_width >= 3:
            pay[:, 0] = self.slots[sess]
            pay[:, 1] = self.op_id[idx]
            pay[:, 2] = self.op_delta[idx]
        else:
            pay[:, 0] = self.op_delta[idx]
        off = sess % self.spc
        rec_bytes = encode_data(off, seq, pay)
        runs, counts = _runs(conn_i)
        take = self.listener.loopback_feed(self.conns[runs], rec_bytes,
                                           counts)
        rank = np.arange(len(idx)) - \
            (np.cumsum(counts) - counts)[np.repeat(
                np.arange(len(runs)), counts)]
        fed = rank < np.repeat(take, counts)
        self.op_state[idx[fed]] = SENT
        np.add.at(self._pend_per_sess, sess[fed], 1)
        key = (self.handles[sess[fed]] << self._SEQ_BITS) | seq[fed]
        self._pend_key = np.concatenate([self._pend_key, key])
        self._pend_op = np.concatenate([self._pend_op, idx[fed]])
        order = np.argsort(self._pend_key, kind="stable")
        self._pend_key = self._pend_key[order]
        self._pend_op = self._pend_op[order]
        return int(fed.sum())

    # -- receive (vectorized credit/ack) ------------------------------------

    def collect(self) -> None:
        """Drain the listener's loopback credit/ack outboxes into the
        replay window (all joins vectorized)."""
        credit, ack = self.listener.collect_loopback()
        for conns, counts, rec in credit:
            handles = self.listener.hbase[np.repeat(conns, counts)] + \
                rec["sess"].astype(np.int64)
            self._on_credit(handles, rec["seqno"].astype(np.int64),
                            rec["status"].astype(np.int8))
        for conns, counts, rec in ack:
            handles = self.listener.hbase[np.repeat(conns, counts)] + \
                rec["sess"].astype(np.int64)
            sess = handles - self.base
            np.maximum.at(self.watermark, sess,
                          rec["acked"].astype(np.int64))
        collect_hints = getattr(self.listener, "collect_rehome_hints",
                                None)
        if collect_hints is not None:
            hints = collect_hints()
            if hints:
                self.rehome_hint = hints[-1]
                self.rehome_hints += len(hints)

    def _on_credit(self, handles, seqnos, statuses) -> None:
        key = (handles << self._SEQ_BITS) | seqnos
        pos = np.searchsorted(self._pend_key, key)
        pos = np.clip(pos, 0, max(0, len(self._pend_key) - 1))
        hit = len(self._pend_key) > 0
        match = hit & (self._pend_key[pos] == key) if hit else \
            np.zeros(len(key), bool)
        ops = self._pend_op[pos[match]]
        st = statuses[match]
        np.add.at(self._pend_per_sess, self.op_sess[ops], -1)
        tn = self.tenant_of[self.op_sess[ops]]
        np.add.at(self.tenant_rows, tn, 1)
        np.add.at(self.tenant_shed, tn[st == SHED], 1)
        placed = (st == OK) | (st == SLOW)
        # DUP is unreachable for a fresh-seqno fleet (it means a seqno
        # was replayed); defensively mark placed WITHOUT a rank so the
        # server's committed-row watermark accounting stays aligned
        self.op_state[ops[st == DUP]] = PLACED
        self.op_ever[ops[st == DUP]] = True
        p_ops = ops[placed]
        sess = self.op_sess[p_ops]
        # placement rank per session: credit rows arrive in placement
        # order, so rank = running count + within-batch rank
        self.op_rank[p_ops] = self.placed_cnt[sess] + batch_rank(sess)
        np.add.at(self.placed_cnt, sess, 1)
        self.op_state[p_ops] = PLACED
        self.op_ever[p_ops] = True
        refused = ops[~placed & (st != DUP)]
        # a refused REPLAY of an ever-placed op is simply dropped: its
        # first copy is placed and will commit — requeueing (let alone
        # re-keying) it would double-apply.  op_ever keeps this truth
        # across a re-home's rank reset.
        ever = (self.op_rank[refused] >= 0) | self.op_ever[refused]
        self.op_state[refused[ever]] = PLACED
        refused = refused[~ever]
        self.op_state[refused] = QUEUED
        # never-placed refusals re-key (see WireClient._on_verdict):
        # the machine's watermark dedup needs monotone op ids per
        # slot, and a refusal of a never-placed op means a fresh id
        # cannot double-apply.  Credit rows arrive in send order, so
        # the re-keyed ids stay monotone within the batch too.
        sess_r = self.op_sess[refused]
        self.op_id[refused] = self.next_op[sess_r] + batch_rank(sess_r)
        np.add.at(self.next_op, sess_r, 1)
        # retire matched pending entries
        keep = np.ones(len(self._pend_key), bool)
        keep[pos[match]] = False
        self._pend_key = self._pend_key[keep]
        self._pend_op = self._pend_op[keep]

    # -- reconnect storm ----------------------------------------------------

    def storm(self, frac: float) -> np.ndarray:
        """Kill ``frac`` of the fleet's connections mid-flight: unswept
        ring bytes are LOST, epochs bump, and every unacked op of the
        victims re-enters the replay queue under fresh seqnos (the
        at-least-once contract; the machine dedups the duplicates)."""
        n = max(1, int(frac * self.n_conns))
        victims = self.rng.choice(self.n_conns, size=n, replace=False)
        vconns = self.conns[victims]
        self.listener.loopback_kill(vconns)
        self.reconnects += n
        vict_sess = (victims[:, None] * self.spc
                     + np.arange(self.spc)[None, :]).ravel()
        vmask = np.zeros(self.n_sessions, bool)
        vmask[vict_sess] = True
        live = self.op_state[:self.n_ops]
        osess = self.op_sess[:self.n_ops]
        acked = (live == PLACED) & (self.op_rank[:self.n_ops] >= 0) & \
            (self.op_rank[:self.n_ops] < self.watermark[osess])
        requeue = vmask[osess] & (live != QUEUED) & ~acked
        self.op_state[:self.n_ops][requeue] = QUEUED
        # drop the victims' pending-credit entries: their ring bytes
        # are gone, the credit will never arrive (the flush gate
        # reopens with them)
        pend_sess = (self._pend_key >> self._SEQ_BITS) - self.base
        keep = ~vmask[pend_sess]
        self._pend_key = self._pend_key[keep]
        self._pend_op = self._pend_op[keep]
        self._pend_per_sess = np.bincount(
            (self._pend_key >> self._SEQ_BITS) - self.base,
            minlength=self.n_sessions)
        return np.flatnonzero(requeue)

    # -- placement re-home (ISSUE 17) ---------------------------------------

    def rehome(self, new_listener, trace_ctx=None) -> np.ndarray:
        """Move the whole fleet to a NEW home serving this fleet's
        recovered lane state (placement failover): bind the same key
        on ``new_listener`` claiming the OLD dedup slots and seeding
        the committed-row watermarks at the acked counts
        (WireListener.loopback_rehome), then carry every in-flight op
        across the move under the at-least-once contract — all unacked
        ops requeue and replay; the recovered machine's per-slot op-id
        watermarks absorb the ones whose first copy committed on the
        old home before it died.

        Rank bookkeeping restarts at the acked watermark: ranks the
        old home assigned to rows it never durably committed are
        burned with it (they would otherwise hold the cumulative ack
        watermark below the replays forever).  ``op_ever`` is re-based
        against the RECOVERED watermarks — an op the old home placed
        but never fsynced is gone from every durable record, so its
        replay is a first copy and may re-key on refusal like any
        never-placed op.

        Returns the indices of the requeued (replaying) ops."""
        old_d = self.listener.plane.directory
        old_lanes = old_d.lane[self.handles].copy()
        self.conns = new_listener.loopback_rehome(
            self.n_conns, sessions_per_conn=self.spc, key=self.key,
            tenants=self.tenants, slots=self.slots,
            committed=self.watermark, trace_ctx=trace_ctx)
        self.listener = new_listener
        self.base = int(new_listener.hbase[self.conns[0]])
        self.handles = self.base + np.arange(self.n_sessions,
                                             dtype=np.int64)
        d = new_listener.plane.directory
        lanes = d.lane[self.handles]
        if not (lanes == old_lanes).all():
            # key→lane hashing is deterministic per (seed, key): a
            # mismatch means the new home's directory was built with a
            # different seed/lane count and the recovered per-lane
            # machine state would not line up with the new placements
            raise RuntimeError(
                "rehome: lane placement diverged between homes")
        self.tenant_of = d.tenant[self.handles].astype(np.int64)
        # per-session durably-applied op-id watermark, straight from
        # the recovered machine state (the fsynced-watermark gate)
        dur_sess = np.zeros(self.n_sessions, np.int64)
        mac = getattr(new_listener.plane.engine.state, "mac", None)
        if isinstance(mac, dict) and "seq" in mac:
            seq = np.asarray(mac["seq"]).max(axis=1)
            dur_sess = seq[lanes.astype(np.int64),
                           self.slots.astype(np.int64)].astype(np.int64)
        live = self.op_state[:self.n_ops]
        rank = self.op_rank[:self.n_ops]
        osess = self.op_sess[:self.n_ops]
        acked = (live == PLACED) & (rank >= 0) & \
            (rank < self.watermark[osess])
        durable = self.op_id[:self.n_ops] <= dur_sess[osess]
        self.op_ever[:self.n_ops] = \
            ((rank >= 0) | self.op_ever[:self.n_ops]) & durable
        requeue = (live != QUEUED) & ~acked
        self.op_state[:self.n_ops][requeue] = QUEUED
        self.op_rank[:self.n_ops][requeue] = -1
        self.placed_cnt[:] = self.watermark
        # old-home credits will never arrive: drop the whole pending
        # window (the flush gate reopens with it)
        self._pend_key = np.zeros(0, np.int64)
        self._pend_op = np.zeros(0, np.int64)
        self._pend_per_sess = np.zeros(self.n_sessions, np.int64)
        self.reconnects += self.n_conns
        return np.flatnonzero(requeue)

    # -- progress / oracle --------------------------------------------------

    def acked_mask(self) -> np.ndarray:
        live = self.op_state[:self.n_ops]
        return (live == PLACED) & (self.op_rank[:self.n_ops] >= 0) & \
            (self.op_rank[:self.n_ops]
             < self.watermark[self.op_sess[:self.n_ops]])

    def unplaced_count(self) -> int:
        return int((self.op_state[:self.n_ops] != PLACED).sum())

    def expected_lane_sums(self, n_lanes: int) -> np.ndarray:
        """The exactly-once oracle's truth: every op's delta exactly
        once, summed per lane."""
        lanes = self.listener.plane.directory.lane[
            self.handles[self.op_sess[:self.n_ops]]]
        out = np.zeros(n_lanes, np.int64)
        np.add.at(out, lanes, self.op_delta[:self.n_ops].astype(np.int64))
        return out


def _runs(keys: np.ndarray) -> tuple:
    """Run-length encode a non-decreasing key array."""
    n = len(keys)
    new = np.empty(n, bool)
    new[0] = True
    new[1:] = keys[1:] != keys[:-1]
    starts = np.flatnonzero(new)
    counts = np.diff(np.append(starts, n))
    return keys[starts], counts
