"""Wire framing: the byte protocol between real clients and the
ingress plane (ISSUE 12).

The design constraint is the RA08/RA09 discipline extended to the
socket path: the server's reader loop does ZERO per-command Python
work, so the steady-state client→server stream must parse as one
vectorized numpy sweep.  That forces a **fixed-stride** data stream:
after the HELLO handshake, a connection's ingress bytes are a pure
sequence of equal-size length-prefixed DATA records —

    <u32 len> <u8 type=DATA> <u8 flags> <u16 sess> <u64 seqno> <i32 payload x C>

— so a ring buffer holding N records is decoded by ONE ``frombuffer``
view plus column slices (``decode_data``), never a per-frame walk.
``len`` counts the bytes after the length prefix (the classic
length-prefix contract); ``sess`` is the session's offset within the
connection's session block (one TCP connection may multiplex up to
65,536 wire sessions — the unit of flow control is the SESSION, the
connection is just its transport).

Control frames are variable-length and rare (connect-time / credit
return), so they may be built and parsed per frame:

* ``HELLO``      client→server  ``<ver u8> <tenants u8> <keylen u16>
  <n_sessions u32> <payload_width u8> <key bytes>`` — resolves/creates
  the connection's session block (same key ⇒ same sessions, epoch
  bumped: a reconnect).  ``payload_width`` (v2+) declares the client's
  DATA column count C; the listener refuses a mismatch with an ERR
  frame BEFORE any data record is interpreted — a C=4 client talking
  to a C=3 sweep would otherwise misparse every frame boundary.
* ``HELLO_ACK``  server→client  ``<ver u8> <flags u8>
  <payload_width u16> <epoch u32> <handle_base u64> <nslots u32>
  <i32 slot x nslots>`` — the epoch is the at-least-once client's
  re-enqueue trigger (docs/INGRESS.md "Delivery guarantees"); the
  per-session dedup SLOTS are the machine-level identity a client
  embeds in payloads for exactly-once-observable workloads
  (wire/dedup.py); ``payload_width`` echoes the server's accepted C.
* ``ERR``        server→client  ``<code u8> <msglen u16> <utf-8 msg>``
  — a refused handshake's reason (version / payload-width mismatch),
  sent once before close so the client raises a protocol error
  instead of timing out on a silently dropped connection.
* ``CREDIT``     server→client  ``<level u8> <pad u8> <count u16>`` +
  ``count`` records ``<sess u16> <seqno u64> <status u8>`` — the
  CreditLadder verdict for every swept row, serialized back per
  connection.  This frame IS the generalized ``StopSending``: the
  status enum is the ingress plane's (ok/slow/defer/reject/dup/shed),
  one enum, one encoder (:func:`encode_credit`), shared with
  :class:`~ra_tpu.models.fifo_client.FifoClient`.
* ``ACK``        server→client  ``<pad u16> <count u16>`` + ``count``
  records ``<sess u16> <acked u64>`` — per-session cumulative
  committed placed-row watermarks (flow-control grade: duplicate row
  commits can run a watermark ahead; exactness is machine-level — see
  docs/INGRESS.md).
* ``REHOME``     server→client  ``<generation u32> <revision u64>
  <namelen u16> <utf-8 engine>`` — a typed placement-staleness refusal
  (ISSUE 19): the frames the client just sent hit lanes whose home
  moved per the listener's PlacementCache view.  The named engine +
  generation + table revision are the hint a client follows (at most
  once per connection epoch) to the new home instead of silently
  misrouting into a dead engine's lanes (docs/PLACEMENT.md).

The version byte rides HELLO/HELLO_ACK; a mismatch refuses the
connection before any data record is interpreted.
"""
from __future__ import annotations

import struct

import numpy as np

# one verdict enum for the whole admission surface: the wire credit
# frame, the ingress ladder and the fifo client's ok→slow→StopSending
# protocol all speak these values (the ISSUE 12 unification satellite)
from ..ingress.backpressure import (DEFER, DUP, OK, REJECT, SHED, SLOW,
                                    STATUS_NAMES)

__all__ = [
    "WIRE_VERSION", "T_HELLO", "T_HELLO_ACK", "T_DATA", "T_CREDIT",
    "T_ACK", "T_ERR", "T_REHOME", "E_VERSION", "E_PAYLOAD_WIDTH",
    "data_dtype", "credit_dtype", "ack_dtype", "data_stride",
    "encode_hello", "decode_hello", "encode_hello_ack",
    "decode_hello_ack", "encode_error", "decode_error",
    "encode_data", "decode_data", "encode_credit",
    "decode_credit", "encode_ack", "decode_ack",
    "encode_rehome", "decode_rehome", "read_frame",
    "T_READ", "T_READ_REPLY", "read_reply_dtype",
    "encode_read", "encode_read_reply", "decode_read_reply",
    "OK", "SLOW", "DEFER", "REJECT", "DUP", "SHED", "STATUS_NAMES",
]

#: protocol version (HELLO/HELLO_ACK version byte).  v2 adds the
#: payload-width negotiation + the ERR refusal frame; a v1 HELLO still
#: parses (width reads as 0 = "not declared") but is refused with an
#: ERR so the client fails loudly instead of misparsing DATA frames.
WIRE_VERSION = 2

T_HELLO = 1
T_HELLO_ACK = 2
T_DATA = 3
T_CREDIT = 4
T_ACK = 5
T_ERR = 6
T_REHOME = 7
#: consistent read (ISSUE 20).  A READ record shares the DATA stride
#: and dtype — the type column distinguishes it — so the server's ONE
#: frombuffer sweep still holds for a mixed read/write stream; the
#: encoded query rides the leading ``pay`` columns (zero-padded to the
#: connection's C).  Reads never enter the log: they answer with a
#: READ_REPLY at a certified watermark instead of an ACK.
T_READ = 8
T_READ_REPLY = 9

#: ERR frame codes
E_VERSION = 1        # HELLO version byte != WIRE_VERSION
E_PAYLOAD_WIDTH = 2  # client's DATA column count != the listener's

_LEN = struct.Struct("<I")
_HELLO = struct.Struct("<BBBHI")       # type, ver, tenants, keylen, n_sessions
_HELLO_W = struct.Struct("<B")         # v2+: payload_width (after _HELLO)
_HELLO_ACK = struct.Struct("<BBBHIQ")  # type, ver, flags, width, epoch, base
_CREDIT_HDR = struct.Struct("<BBBH")   # type, level, pad, count
_ACK_HDR = struct.Struct("<BBHH")      # type, pad, pad, count
_ERR_HDR = struct.Struct("<BBH")       # type, code, msglen
_REHOME_HDR = struct.Struct("<BHIQH")  # type, pad, generation, rev, namelen


def data_dtype(payload_width: int) -> np.dtype:
    """Packed little-endian record dtype of one DATA frame (stride =
    16 + 4*C bytes)."""
    return np.dtype([("len", "<u4"), ("type", "u1"), ("flags", "u1"),
                     ("sess", "<u2"), ("seqno", "<u8"),
                     ("pay", "<i4", (int(payload_width),))])


def data_stride(payload_width: int) -> int:
    return data_dtype(payload_width).itemsize


#: CREDIT record: one verdict per swept row (11 bytes packed)
credit_dtype = np.dtype([("sess", "<u2"), ("seqno", "<u8"),
                         ("status", "u1")])

#: ACK record: per-session cumulative committed-row watermark
ack_dtype = np.dtype([("sess", "<u2"), ("acked", "<u8")])


# -- control frames (rare; per-frame Python is fine here) -------------------

def encode_hello(key: str, n_sessions: int, *, tenants: int = 1,
                 payload_width: int = 3) -> bytes:
    kb = key.encode()
    body = _HELLO.pack(T_HELLO, WIRE_VERSION, tenants, len(kb),
                       n_sessions) \
        + _HELLO_W.pack(payload_width) + kb
    return _LEN.pack(len(body)) + body


def decode_hello(body: bytes) -> dict:
    t, ver, tenants, keylen, n_sessions = _HELLO.unpack_from(body)
    if t != T_HELLO:
        raise ValueError(f"not a HELLO frame (type {t})")
    # v1 bodies have no width byte: report 0 ("not declared") so the
    # listener can refuse with a precise reason instead of a parse error
    off = _HELLO.size
    width = 0
    if ver >= 2:
        (width,) = _HELLO_W.unpack_from(body, off)
        off += _HELLO_W.size
    key = body[off:off + keylen].decode()
    return {"version": ver, "tenants": tenants, "key": key,
            "n_sessions": n_sessions, "payload_width": width}


def encode_hello_ack(epoch: int, handle_base: int,
                     slots=None, *, payload_width: int = 0) -> bytes:
    slots = np.zeros(0, np.int32) if slots is None else \
        np.asarray(slots, np.int32)
    body = _HELLO_ACK.pack(T_HELLO_ACK, WIRE_VERSION, 0, payload_width,
                           epoch, handle_base) \
        + struct.pack("<I", len(slots)) + slots.tobytes()
    return _LEN.pack(len(body)) + body


def decode_hello_ack(body: bytes) -> dict:
    t, ver, _fl, width, epoch, base = _HELLO_ACK.unpack_from(body)
    if t != T_HELLO_ACK:
        raise ValueError(f"not a HELLO_ACK frame (type {t})")
    (n,) = struct.unpack_from("<I", body, _HELLO_ACK.size)
    slots = np.frombuffer(body, "<i4", n, _HELLO_ACK.size + 4) \
        if n else None
    return {"version": ver, "epoch": epoch, "handle_base": base,
            "slots": slots, "payload_width": width}


def encode_error(code: int, message: str) -> bytes:
    mb = message.encode()[:65535]
    body = _ERR_HDR.pack(T_ERR, code, len(mb)) + mb
    return _LEN.pack(len(body)) + body


def decode_error(body: bytes) -> dict:
    t, code, msglen = _ERR_HDR.unpack_from(body)
    if t != T_ERR:
        raise ValueError(f"not an ERR frame (type {t})")
    msg = body[_ERR_HDR.size:_ERR_HDR.size + msglen].decode(
        errors="replace")
    return {"code": code, "message": msg}


def encode_rehome(engine: str, generation: int, rev: int) -> bytes:
    """The typed placement-staleness refusal (ISSUE 19): "your lanes'
    home is ``engine`` at ``generation`` per table revision ``rev`` —
    reconnect there".  Sent at most once per affected connection per
    sweep; a client honors it at most once per connection epoch."""
    nb = engine.encode()[:65535]
    body = _REHOME_HDR.pack(T_REHOME, 0, int(generation) & 0xFFFFFFFF,
                            int(rev) & 0xFFFFFFFFFFFFFFFF, len(nb)) + nb
    return _LEN.pack(len(body)) + body


def decode_rehome(body: bytes) -> dict:
    t, _pad, generation, rev, namelen = _REHOME_HDR.unpack_from(body)
    if t != T_REHOME:
        raise ValueError(f"not a REHOME frame (type {t})")
    engine = body[_REHOME_HDR.size:_REHOME_HDR.size + namelen].decode(
        errors="replace")
    return {"engine": engine, "generation": generation, "rev": rev}


# -- the data stream (vectorized both ways) ---------------------------------

def encode_data(sess, seqnos, payloads) -> bytes:
    """Encode a batch of commands as the fixed-stride DATA stream (one
    structured-array fill + ``tobytes`` — no per-record Python)."""
    payloads = np.asarray(payloads)
    if payloads.ndim == 1:
        payloads = payloads[:, None]
    n, c = payloads.shape
    rec = np.zeros(n, data_dtype(c))
    rec["len"] = rec.dtype.itemsize - 4
    rec["type"] = T_DATA
    rec["sess"] = np.asarray(sess)
    rec["seqno"] = np.asarray(seqnos)
    rec["pay"] = payloads
    return rec.tobytes()


def decode_data(buf, payload_width: int) -> np.ndarray:
    """View a byte block as DATA records (the sweep-side decode: one
    ``frombuffer``, zero copies).  ``buf`` length must be a whole
    number of strides."""
    return np.frombuffer(buf, data_dtype(payload_width))


# -- credit / ack (vectorized records, small per-frame headers) -------------

def encode_credit(level: int, sess, seqnos, statuses) -> bytes:
    """THE credit-frame encoder (one encoder for the whole verdict
    surface): per-row CreditLadder verdicts + the current ladder level,
    serialized as one frame."""
    rec = np.zeros(len(np.atleast_1d(np.asarray(sess))), credit_dtype)
    rec["sess"] = np.asarray(sess)
    rec["seqno"] = np.asarray(seqnos)
    rec["status"] = np.asarray(statuses)
    body = _CREDIT_HDR.pack(T_CREDIT, int(level), 0, len(rec)) \
        + rec.tobytes()
    return _LEN.pack(len(body)) + body


def decode_credit(body: bytes) -> tuple:
    """Returns ``(level, records)`` with ``records`` a credit_dtype
    array (vectorized client-side decode)."""
    t, level, _p, count = _CREDIT_HDR.unpack_from(body)
    if t != T_CREDIT:
        raise ValueError(f"not a CREDIT frame (type {t})")
    rec = np.frombuffer(body, credit_dtype, count, _CREDIT_HDR.size)
    return level, rec


def encode_ack(sess, acked) -> bytes:
    rec = np.zeros(len(np.atleast_1d(np.asarray(sess))), ack_dtype)
    rec["sess"] = np.asarray(sess)
    rec["acked"] = np.asarray(acked)
    body = _ACK_HDR.pack(T_ACK, 0, 0, len(rec)) + rec.tobytes()
    return _LEN.pack(len(body)) + body


def decode_ack(body: bytes) -> np.ndarray:
    t, _a, _b, count = _ACK_HDR.unpack_from(body)
    if t != T_ACK:
        raise ValueError(f"not an ACK frame (type {t})")
    return np.frombuffer(body, ack_dtype, count, _ACK_HDR.size)


# -- consistent reads (ISSUE 20) --------------------------------------------

def read_reply_dtype(reply_width: int) -> np.dtype:
    """Packed READ_REPLY record: one served/refused read outcome.
    ``wm`` is the commit watermark the read was served at (-1 when the
    read was refused — ``status`` then carries the ladder verdict or
    the stale-refusal marker)."""
    return np.dtype([("sess", "<u2"), ("seqno", "<u8"), ("status", "u1"),
                     ("wm", "<i4"), ("pay", "<i4", (int(reply_width),))])


_READ_REPLY_HDR = struct.Struct("<BBHH")  # type, width, pad, count


def encode_read(sess, seqnos, queries, *, payload_width: int) -> bytes:
    """Encode a batch of consistent-read queries at the connection's
    DATA stride (type=T_READ; query columns zero-padded to C) — one
    structured-array fill, no per-record Python, and the server's
    single fixed-stride sweep stays intact."""
    queries = np.asarray(queries)
    if queries.ndim == 1:
        queries = queries[:, None]
    n, cq = queries.shape
    if cq > payload_width:
        raise ValueError(
            f"query width {cq} exceeds negotiated payload width "
            f"{payload_width}")
    rec = np.zeros(n, data_dtype(payload_width))
    rec["len"] = rec.dtype.itemsize - 4
    rec["type"] = T_READ
    rec["sess"] = np.asarray(sess)
    rec["seqno"] = np.asarray(seqnos)
    rec["pay"][:, :cq] = queries
    return rec.tobytes()


def encode_read_reply(sess, seqnos, statuses, wms, payloads) -> bytes:
    """Serialize served/refused read outcomes as one READ_REPLY frame
    (vectorized records under a small header, like CREDIT/ACK)."""
    payloads = np.asarray(payloads)
    if payloads.ndim == 1:
        payloads = payloads[:, None]
    n, w = payloads.shape
    rec = np.zeros(n, read_reply_dtype(w))
    rec["sess"] = np.asarray(sess)
    rec["seqno"] = np.asarray(seqnos)
    rec["status"] = np.asarray(statuses)
    rec["wm"] = np.asarray(wms)
    rec["pay"] = payloads
    body = _READ_REPLY_HDR.pack(T_READ_REPLY, w, 0, n) + rec.tobytes()
    return _LEN.pack(len(body)) + body


def decode_read_reply(body: bytes) -> np.ndarray:
    """READ_REPLY body -> records (vectorized client-side decode)."""
    t, width, _p, count = _READ_REPLY_HDR.unpack_from(body)
    if t != T_READ_REPLY:
        raise ValueError(f"not a READ_REPLY frame (type {t})")
    return np.frombuffer(body, read_reply_dtype(width), count,
                         _READ_REPLY_HDR.size)


def read_frame(buf: bytes, offset: int = 0):
    """Client-side frame walk over a received byte buffer: returns
    ``(type, body, next_offset)`` or ``None`` when the buffer holds no
    complete frame at ``offset`` (control-plane parsing — the server
    side never walks frames, it sweeps)."""
    if len(buf) - offset < _LEN.size:
        return None
    (length,) = _LEN.unpack_from(buf, offset)
    start = offset + _LEN.size
    if len(buf) - start < length or length < 1:
        return None
    body = buf[start:start + length]
    return body[0], body, start + length
