"""The wire listener: real sockets into the ingress coalescer with
zero per-command Python work (ISSUE 12, the front half of ROADMAP
item 2).

Three tiers, mirroring the RA08 discipline one layer further out:

* **reader** — a selector loop (epoll under the hood) whose only
  per-event work is ``recv_into`` + a wrap-aware copy into the
  connection's preallocated ring slot.  It never looks INSIDE the
  bytes: per-connection work per readable socket, zero per-command
  work (a 64KB recv may carry thousands of commands for the cost of
  one Python call).  A connection whose ring is full is paused
  (unregistered) — kernel socket buffers fill and the CLIENT blocks:
  TCP itself becomes the outermost backpressure tier, below the
  credit ladder.
* **sweep** — :meth:`WireListener.sweep` drains every connection's
  buffered records in one vectorized pass (gather → ``frombuffer``
  view → column slices) into the ``SessionDirectory.submit``-shaped
  ``(handles, seqnos, payloads)`` batch the ingress plane eats, then
  serializes the per-row CreditLadder verdicts back as per-connection
  CREDIT frames.  Lint rule RA09 statically forbids per-frame Python
  loops / dict allocation in this path and its same-module closure
  (``# ra09-ok`` allowlists the per-CONNECTION socket writes — one
  syscall per connection, never per command).
* **acks** — the plane's block-commit hook
  (:meth:`IngressPlane.on_block_committed`) advances per-session
  cumulative committed-row watermarks off the driver's EXISTING async
  readbacks and fans them out as ACK frames; the at-least-once client
  retires its in-flight window against them (docs/INGRESS.md).

Connections come in two transports sharing every byte of the
ring/sweep path: real TCP sockets (``port=``) and in-process loopback
slots (:meth:`loopback_connect`) used by the C100k→C1M rungs of the
connection ladder, where two kernel fds per connection would exceed
any rlimit long before the data plane saturates — the loopback fleet
writes the SAME fixed-stride DATA records into the SAME rings and
reads the SAME credit/ack record streams, vectorized end to end.
"""
from __future__ import annotations

import selectors
import socket
import struct
import threading
from typing import Optional

import numpy as np

from ..blackbox import record
from ..metrics import WIRE_FIELDS
from .framing import (E_PAYLOAD_WIDTH, E_VERSION, SHED, T_DATA, T_READ,
                      T_READ_REPLY, WIRE_VERSION, ack_dtype,
                      credit_dtype, data_stride, decode_hello,
                      encode_error, encode_hello_ack, encode_rehome,
                      read_reply_dtype)

_LEN = struct.Struct("<I")

#: connection slot states
_S_FREE, _S_HELLO, _S_DATA = 0, 1, 2


def _ragged_arange(counts: np.ndarray) -> np.ndarray:
    """[0..c0) ++ [0..c1) ++ ... as one vectorized array."""
    total = int(counts.sum())
    starts = np.cumsum(counts) - counts
    return np.arange(total) - np.repeat(starts, counts)


def _sendall_nb(sock, data: bytes, deadline_s: float = 0.25) -> bool:
    """sendall onto a nonblocking socket with a bounded wait: a client
    slow to drain its credit stream gets ``deadline_s`` of grace, then
    the connection is declared dead (False)."""
    import time as _t
    view = memoryview(data)
    end = _t.monotonic() + deadline_s
    while view:  # ra09-ok: per-CONNECTION bounded send retry, not per command
        try:
            sent = sock.send(view)
        except (BlockingIOError, InterruptedError):
            sent = 0
        except OSError:
            return False
        view = view[sent:]
        if view:
            if _t.monotonic() > end:
                return False
            _t.sleep(0.001)
    return True


class WireListener:
    """One listener per ingress plane: owns the connection pool, the
    reader thread (when a TCP port is bound) and the sweep path."""

    def __init__(self, plane, *, host: str = "127.0.0.1",
                 port: Optional[int] = 0, max_conns: int = 1024,
                 ring_bytes: int = 4096,
                 sweep_rows: int = 1 << 20) -> None:
        self.plane = plane
        eng = plane.engine
        self.payload_width = int(eng.payload_width)
        self.stride = data_stride(self.payload_width)
        if ring_bytes < 4 * self.stride:
            raise ValueError(
                f"ring_bytes {ring_bytes} < 4 records ({4 * self.stride})")
        self.max_conns = int(max_conns)
        self.ring_bytes = int(ring_bytes)
        #: per-sweep row budget (bounds the gather transient)
        self.sweep_rows = int(sweep_rows)
        m = self.max_conns
        self.rbuf = np.zeros((m, self.ring_bytes), np.uint8)
        self.rhead = np.zeros(m, np.int64)
        self.rfill = np.zeros(m, np.int64)
        self.cstate = np.zeros(m, np.int8)
        self.hbase = np.zeros(m, np.int64)       # first session handle
        self.nsess = np.zeros(m, np.int64)       # sessions on this conn
        self._free: list = list(range(m - 1, -1, -1))
        self._lock = threading.Lock()
        self._socks: dict[int, socket.socket] = {}   # slot -> socket
        self._hello_buf: dict[int, bytearray] = {}
        self._slot_key: dict[int, str] = {}          # reverse of _keys
        #: recv'd bytes that overflowed a ring: already consumed from
        #: the kernel, so they MUST be replayed into the ring at
        #: resume — dropping them would silently lose commands
        self._overflow: dict[int, bytes] = {}
        self._keys: dict[str, int] = {}              # conn key -> slot
        self._paused: set = set()
        #: per-session cumulative committed placed rows / last acked
        #: watermark sent (handle-indexed, grown with the directory)
        self._committed = np.zeros(plane.directory.capacity, np.int64)
        self._acked_sent = np.zeros(plane.directory.capacity, np.int64)
        #: machine-level dedup identity: per-session per-LANE slot,
        #: assigned at first bind, handed to the client in HELLO_ACK
        #: (the DedupCounterMachine contract, wire/dedup.py)
        self._slot = np.full(plane.directory.capacity, -1, np.int32)
        self._lane_next = self._recovered_lane_next(eng)
        #: loopback credit/ack outboxes: (records, per-conn row counts,
        #: conn ids) collected by the fleet after each sweep/commit
        self._lb_credit: list = []
        self._lb_ack: list = []
        #: loopback rehome-hint outbox: (slot, engine, generation, rev)
        #: tuples drained via collect_rehome_hints() — the in-process
        #: twin of the TCP T_REHOME frame (ISSUE 19)
        self._lb_rehome: list = []
        #: loopback READ_REPLY outbox (ISSUE 20): (conn ids, per-conn
        #: row counts, records) drained via collect_read_replies() —
        #: the in-process twin of the TCP T_READ_REPLY frame
        self._lb_read: list = []
        #: serving-path placement view (ISSUE 19): a revision-monotone
        #: PlacementCache + the engine ids served HERE; None = every
        #: lane is local (the single-host default)
        self._placement = None
        self._local_engines: set = set()
        self._placement_rids = None
        self._placement_rev = -2      # forces a mask build on bind
        self._lane_local = None
        self._lane_home = None
        self._owner_names: list = []
        self._owner_gens: list = []
        self.rehome_hints = 0         # PLACEMENT_FIELDS counter
        self._lb_slots: set = set()
        self._lb_key: dict[int, str] = {}
        #: loopback membership as a flat mask: the sweep path fans
        #: credit out by transport without any per-connection Python
        self._is_lb = np.zeros(m, bool)
        self.counters = {f: 0 for f in WIRE_FIELDS}
        self._last_credit_level = 0
        self._shedding = False
        # conn lookup for ack fan-out: sorted handle-base intervals
        self._base_dirty = True
        self._base_sorted = np.zeros(0, np.int64)
        self._base_slot = np.zeros(0, np.int64)
        plane.on_block_committed = self._on_block_committed
        # the read plane (ISSUE 20): READ records ride the DATA stride,
        # so the encoded query must fit the negotiated payload columns
        self._query_width = int(getattr(eng, "query_width", 1))
        self._reply_width = int(getattr(eng, "query_reply_width", 1))
        self._reads_enabled = bool(getattr(plane, "reads_enabled",
                                           False))
        if self._reads_enabled:
            if self._query_width > self.payload_width:
                raise ValueError(
                    f"query width {self._query_width} exceeds the "
                    f"wire payload width {self.payload_width}: READ "
                    "records cannot carry this machine's queries")
            plane.on_reads_done = self._on_reads_served
        self._sock = None
        self._thread = None
        self._stop = False
        if port is not None:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._sock.listen(1024)
            self.address = self._sock.getsockname()
            self._thread = threading.Thread(target=self._reader_loop,
                                            daemon=True,
                                            name="ra-wire-reader")
            self._thread.start()
        else:
            self.address = None

    # ------------------------------------------------------------------
    # connection control plane (per-connection Python is fine here)
    # ------------------------------------------------------------------

    @staticmethod
    def _recovered_lane_next(eng) -> np.ndarray:
        """First free dedup slot per lane.  A recovered DURABLE engine
        carries per-slot op watermarks from past clients (machine
        state is durable, the session/slot directory is not) — a new
        listener must not hand those slots out again, or a fresh
        client's early ops would be falsely deduped against a dead
        client's watermark.  Slots with seq==0 never applied an op and
        are safe to reuse."""
        mac = getattr(eng.state, "mac", None)
        if not (isinstance(mac, dict) and "seq" in mac):
            return np.zeros(eng.n_lanes, np.int64)
        # [lanes, members, slots] -> any member's watermark counts
        used = np.asarray(mac["seq"]).max(axis=1) > 0
        rev = used[:, ::-1]
        s = used.shape[1]
        return np.where(rev.any(axis=1), s - rev.argmax(axis=1),
                        0).astype(np.int64)

    def _alloc_slot(self) -> int:
        if not self._free:
            raise RuntimeError(
                f"wire listener full ({self.max_conns} conns)")
        return self._free.pop()

    def _bind_sessions(self, slot: int, key: str, n_sessions: int,
                       tenants: int = 1) -> tuple:
        """Resolve the connection's session block (same key ⇒ same
        handles, epoch bumped — the reconnect contract)."""
        d = self.plane.directory
        reconnect = f"wire/{key}" in d._bulk
        h = self.plane.connect_bulk(n_sessions, key=f"wire/{key}",
                                    tenants=max(1, tenants))
        base = int(h[0])
        self.hbase[slot] = base
        self.nsess[slot] = n_sessions
        self._ensure_session_arrays()
        self._assign_slots(h)
        old = self._keys.get(key)
        if old is not None and old != slot:
            self._close_slot(old, reason="superseded")
        self._keys[key] = slot
        self._slot_key[slot] = key
        self._base_dirty = True
        if reconnect:
            self.counters["hello_reconnects"] += 1
        return base, int(d.epoch[base]), reconnect

    def _ensure_session_arrays(self) -> None:
        # under the pool lock: a HELLO on the reader thread may grow
        # these while the main thread's block-commit hook is doing
        # np.add.at on them — a swap mid-scatter would orphan counts
        with self._lock:
            cap = self.plane.directory.capacity
            if len(self._committed) < cap:
                for name in ("_committed", "_acked_sent"):
                    arr = getattr(self, name)
                    grown = np.zeros(cap, np.int64)
                    grown[:len(arr)] = arr
                    setattr(self, name, grown)
            if len(self._slot) < cap:
                grown = np.full(cap, -1, np.int32)
                grown[:len(self._slot)] = self._slot
                self._slot = grown

    def _assign_slots(self, handles: np.ndarray) -> None:
        """Assign per-lane dedup slots to first-seen sessions (one
        vectorized rank per bind; reconnects keep their slot)."""
        from ..ingress.coalesce import batch_rank
        handles = np.asarray(handles, np.int64)
        with self._lock:  # a socket HELLO may race a loopback connect
            fresh = handles[self._slot[handles] < 0]
            if not len(fresh):
                return
            lanes = self.plane.directory.lane[fresh].astype(np.int64)
            self._slot[fresh] = (self._lane_next[lanes]
                                 + batch_rank(lanes)).astype(np.int32)
            np.add.at(self._lane_next, lanes, 1)

    def session_slots(self, handles: np.ndarray) -> np.ndarray:
        return self._slot[np.asarray(handles, np.int64)]

    def loopback_connect(self, n_conns: int, *, sessions_per_conn: int
                         = 1, key: str = "fleet",
                         tenants: int = 1) -> np.ndarray:
        """Bulk-connect ``n_conns`` in-process connections (the
        C100k→C1M ladder transport): one control-plane call places the
        whole fleet — per-connection HELLO framing at a million
        connections would be exactly the per-object cost this plane
        exists to avoid.  Returns the conn slot ids; same key ⇒ same
        slots/sessions with every epoch bumped (a fleet reconnect)."""
        spc = int(sessions_per_conn)
        known = f"wire/{key}" in self.plane.directory._bulk
        h = self.plane.connect_bulk(n_conns * spc, key=f"wire/{key}",
                                    tenants=max(1, tenants))
        if known:
            slots = np.array(sorted(
                s for s in self._lb_slots
                if self._lb_key.get(s) == key), np.int64)
            self.counters["hello_reconnects"] += n_conns
            record("wire.conn", bulk=key, n=int(n_conns),
                   reconnect=True)
            return slots
        if len(self._free) < n_conns:
            raise RuntimeError(
                f"wire listener full ({self.max_conns} conns)")
        slots = np.array([self._alloc_slot() for _ in range(n_conns)],
                         np.int64)
        self.cstate[slots] = _S_DATA
        self.hbase[slots] = int(h[0]) + np.arange(n_conns,
                                                  dtype=np.int64) * spc
        self.nsess[slots] = spc
        self._lb_slots.update(int(s) for s in slots)
        self._is_lb[slots] = True
        for s in slots:
            self._lb_key[int(s)] = key
        self._ensure_session_arrays()
        self._assign_slots(h)
        self._base_dirty = True
        self.counters["conns_opened"] += n_conns
        record("wire.conn", bulk=key, n=int(n_conns), reconnect=False)
        return slots

    def _claim_block(self, key: str, n_sessions: int, tenants: int,
                     slots, committed) -> np.ndarray:
        """Bind ``key``'s session block on this listener with the OLD
        home's dedup slots claimed verbatim and the committed-row
        watermarks seeded at the client's acked counts — the shared
        core of :meth:`loopback_rehome` and :meth:`claim_sessions`."""
        d = self.plane.directory
        if f"wire/{key}" in d._bulk:
            raise RuntimeError(
                f"rehome of known key {key!r}: a fleet re-homes onto "
                "a listener that never served it (same-listener "
                "reconnects go through loopback_connect)")
        h = self.plane.connect_bulk(n_sessions, key=f"wire/{key}",
                                    tenants=max(1, tenants))
        handles = np.asarray(h, np.int64)
        claim = np.asarray(slots, np.int32)
        if len(claim) != len(handles):
            raise ValueError("rehome: one claimed slot per session")
        self._ensure_session_arrays()
        with self._lock:
            lanes = d.lane[handles].astype(np.int64)
            packed = (lanes << 32) | claim.astype(np.int64)
            if len(np.unique(packed)) != len(packed):
                raise ValueError(
                    "rehome: duplicate (lane, slot) claims")
            bound = np.flatnonzero(self._slot >= 0)
            bound = bound[~np.isin(bound, handles)]
            if len(bound):
                have = (d.lane[bound].astype(np.int64) << 32) | \
                    self._slot[bound].astype(np.int64)
                if np.isin(packed, have).any():
                    raise ValueError(
                        "rehome: claimed slot already bound to a "
                        "live session on this listener")
            self._slot[handles] = claim
            # later FRESH binds must allocate above every claim
            np.maximum.at(self._lane_next, lanes,
                          claim.astype(np.int64) + 1)
            c = np.asarray(committed, np.int64)
            self._committed[handles] = c
            self._acked_sent[handles] = c
        return handles

    def claim_sessions(self, key: str, n_sessions: int, *, slots,
                       committed, tenants: int = 1,
                       trace_ctx=None) -> np.ndarray:
        """Pre-claim a re-homed TCP client's session block (ISSUE 19):
        the cross-process twin of :meth:`loopback_rehome`, minus the
        loopback conn plumbing.  The orchestrator calls this on the
        NEW home (over the ``host_rehome`` control verb) before
        pointing the client at it; the client's subsequent HELLO under
        the same key then finds its sessions bound with the OLD dedup
        slots — so replayed ``[slot, op_id, delta]`` payloads still
        hit the recovered machine's per-(lane, slot) watermarks, the
        dedup that makes the at-least-once replay exactly-once.

        Returns the per-session DURABLY-APPLIED op-id watermarks from
        the recovered machine state — the client re-bases its
        ever-placed bookkeeping against these
        (:meth:`WireClient.rehome_to`)."""
        handles = self._claim_block(key, n_sessions, tenants, slots,
                                    committed)
        d = self.plane.directory
        lanes = d.lane[handles].astype(np.int64)
        dur = np.zeros(n_sessions, np.int64)
        mac = getattr(self.plane.engine.state, "mac", None)
        if isinstance(mac, dict) and "seq" in mac:
            seq = np.asarray(mac["seq"]).max(axis=1)
            dur = seq[lanes, np.asarray(slots, np.int64)] \
                .astype(np.int64)
        record("placement.rehome", trace=trace_ctx, key=key,
               sessions=int(n_sessions), conns=0)
        return dur

    def loopback_rehome(self, n_conns: int, *, sessions_per_conn: int
                        = 1, key: str = "fleet", tenants: int = 1,
                        slots: np.ndarray, committed: np.ndarray,
                        trace_ctx=None) -> np.ndarray:
        """Adopt a re-homed loopback fleet (placement failover, ISSUE
        17): bind ``key``'s session block on THIS listener while
        honoring the fleet's existing machine-level identity —

        * ``slots`` are the per-session dedup slots the OLD home
          handed out, claimed verbatim: a replayed op's payload still
          carries its old ``[slot, op_id, delta]``, and the recovered
          machine's per-(lane, slot) watermark is what absorbs the
          duplicate.  Handing out FRESH slots here would re-apply
          every replayed committed op — the double-apply this method
          exists to prevent.
        * ``committed`` seeds the per-session committed-row watermark
          at the client's ACKED count: ranks burned on the old home
          (placed rows that never committed) are dropped client-side
          at re-home, so rank ``committed[s]`` is exactly the next row
          the new home will commit for session ``s``.

        Every re-homed session's epoch bumps (the replay trigger of
        the reconnect contract).  Returns the conn slot ids."""
        spc = int(sessions_per_conn)
        d = self.plane.directory
        if len(self._free) < n_conns:
            raise RuntimeError(
                f"wire listener full ({self.max_conns} conns)")
        handles = self._claim_block(key, n_conns * spc, tenants,
                                    slots, committed)
        h = handles
        conn_slots = np.array([self._alloc_slot()
                               for _ in range(n_conns)], np.int64)
        self.cstate[conn_slots] = _S_DATA
        self.hbase[conn_slots] = int(h[0]) + np.arange(
            n_conns, dtype=np.int64) * spc
        self.nsess[conn_slots] = spc
        self._lb_slots.update(int(s) for s in conn_slots)
        self._is_lb[conn_slots] = True
        for s in conn_slots:
            self._lb_key[int(s)] = key
        self._base_dirty = True
        d.epoch[handles] += 1
        self.plane.counters["reconnects"] += len(handles)
        self.counters["conns_opened"] += n_conns
        self.counters["hello_reconnects"] += n_conns
        record("placement.rehome", trace=trace_ctx, key=key,
               sessions=len(handles), conns=int(n_conns))
        return conn_slots

    def loopback_feed(self, conns: np.ndarray, rec_bytes: bytes,
                      counts: np.ndarray) -> np.ndarray:
        """Scatter encoded DATA records into the fleet's rings (the
        loopback transport's 'send').  ``rec_bytes`` is the wave's
        records concatenated in ``conns`` order, ``counts`` records per
        connection.  Returns the per-connection count actually placed
        (a full ring refuses the tail — the same backpressure a socket
        client feels as a blocked send)."""
        conns = np.asarray(conns, np.int64)
        counts = np.asarray(counts, np.int64)
        r, b = self.stride, self.ring_bytes
        with self._lock:
            space = (b - self.rfill[conns]) // r
            take = np.minimum(counts, space)
            if not take.any():
                return take
            # record-level scatter: byte positions for every accepted
            # record, wrap-aware, one fancy-indexed store
            starts = np.cumsum(counts) - counts      # wave offsets
            rec_i = np.arange(int(take.sum()))
            conn_rep = np.repeat(np.arange(len(conns)), take)
            rank = rec_i - (np.cumsum(take) - take)[conn_rep]
            src_rec = starts[conn_rep] + rank
            tail = (self.rhead[conns] + self.rfill[conns]) % b
            dst = (tail[conn_rep, None] + rank[:, None] * r
                   + np.arange(r)[None, :]) % b
            flat = np.frombuffer(rec_bytes, np.uint8).reshape(-1, r)
            self.rbuf[conns[conn_rep, None], dst] = flat[src_rec]
            np.add.at(self.rfill, conns, take * r)
            self.counters["bytes_recv"] += int(take.sum()) * r
        return take

    def loopback_kill(self, conns: np.ndarray) -> None:
        """Kill + instantly redial a set of loopback connections (the
        reconnect-storm primitive): unswept ring bytes are LOST (the
        in-flight window a real connection drop loses) and every
        victim session's epoch bumps — the at-least-once client's
        replay trigger.  Placement, dedup watermarks and dedup slots
        all survive, per the reconnect contract."""
        conns = np.asarray(conns, np.int64)
        with self._lock:
            self.rfill[conns] = 0
            self.rhead[conns] = 0
        d = self.plane.directory
        spc = self.nsess[conns]
        h = np.repeat(self.hbase[conns], spc) + _ragged_arange(spc)
        d.epoch[h] += 1
        self.plane.counters["reconnects"] += len(h)
        self.counters["hello_reconnects"] += len(conns)
        record("wire.conn", storm=int(len(conns)), reconnect=True)

    def collect_loopback(self) -> tuple:
        """Drain the loopback credit/ack outboxes: returns
        ``(credit_chunks, ack_chunks)`` where each chunk is
        ``(conn_ids, per_conn_counts, records)`` with records a
        credit_dtype / ack_dtype array in conn order (the fleet's
        vectorized decode)."""
        credit, self._lb_credit = self._lb_credit, []
        ack, self._lb_ack = self._lb_ack, []
        return credit, ack

    def _close_slot(self, slot: int, reason: str = "closed") -> None:
        sock = self._socks.pop(slot, None)
        if sock is not None:
            sel = getattr(self, "_sel", None)
            if sel is not None:
                # a closed fd left registered would collide with the
                # next accept() reusing the same fd number
                try:
                    sel.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
            try:
                sock.close()
            except OSError:
                pass
        self._hello_buf.pop(slot, None)
        self._overflow.pop(slot, None)
        self._paused.discard(slot)
        self._lb_slots.discard(slot)
        self._lb_key.pop(slot, None)
        self._is_lb[slot] = False
        # the slot's key binding dies with it: a stale _keys entry
        # would let a later reconnect of this key close whatever
        # connection REUSED the slot number
        key = self._slot_key.pop(slot, None)
        if key is not None and self._keys.get(key) == slot:
            del self._keys[key]
        if self.cstate[slot] != _S_FREE:
            with self._lock:  # vs a concurrent sweep's ring advance
                self.cstate[slot] = _S_FREE
                self.rfill[slot] = 0
                self.rhead[slot] = 0
            self._free.append(slot)
            self.counters["conns_closed"] += 1
            record("wire.conn", slot=int(slot), closed=True,
                   reason=reason)

    def close(self) -> None:
        self._stop = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for slot in list(self._socks):
            self._close_slot(slot, reason="listener stop")
        if self.plane.on_block_committed == self._on_block_committed:
            self.plane.on_block_committed = None

    # ------------------------------------------------------------------
    # reader (per-connection work only; zero per-command work)
    # ------------------------------------------------------------------

    def _reader_loop(self) -> None:
        sel = self._sel = selectors.DefaultSelector()
        sel.register(self._sock, selectors.EVENT_READ, ("accept", None))
        scratch = bytearray(1 << 16)
        mv = memoryview(scratch)
        while not self._stop:
            for key, _ev in sel.select(timeout=0.005):
                kind, slot = key.data
                if kind == "accept":
                    self._accept(sel)
                else:
                    self._readable(sel, key.fileobj, slot, mv)
            # resume paused connections whose rings drained: replay
            # the stashed overflow first — those bytes were already
            # consumed from the kernel and only exist here
            for slot in list(self._paused):
                held = self._overflow.get(slot, b"")
                if held:
                    written = self._ring_write(slot, held)
                    if written < len(held):
                        self._overflow[slot] = held[written:]
                        continue
                    self._overflow.pop(slot, None)
                if self.ring_bytes - int(self.rfill[slot]) \
                        >= self.stride:
                    self._paused.discard(slot)
                    sock = self._socks.get(slot)
                    if sock is not None:
                        try:
                            sel.register(sock, selectors.EVENT_READ,
                                         ("conn", slot))
                        except (KeyError, ValueError, OSError):
                            pass
        sel.close()

    def _accept(self, sel) -> None:
        try:
            conn, _addr = self._sock.accept()
        except OSError:
            return
        conn.setblocking(False)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            slot = self._alloc_slot()
        except RuntimeError:
            conn.close()
            return
        self.cstate[slot] = _S_HELLO
        self._socks[slot] = conn
        self._hello_buf[slot] = bytearray()
        sel.register(conn, selectors.EVENT_READ, ("conn", slot))
        self.counters["conns_opened"] += 1
        record("wire.conn", slot=int(slot), closed=False)

    def _readable(self, sel, sock, slot: int, mv) -> None:
        try:
            n = sock.recv_into(mv)
        except BlockingIOError:
            return
        except OSError:
            n = 0
        if n == 0:
            try:
                sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            self._close_slot(slot, reason="eof")
            return
        if self.cstate[slot] == _S_HELLO:
            rest = self._handle_hello(slot, mv[:n])
            if rest is None:
                try:
                    sel.unregister(sock)
                except (KeyError, ValueError):
                    pass
                self._close_slot(slot, reason="bad hello")
                return
            if not rest:
                return
            data = rest
        else:
            data = mv[:n]
        written = self._ring_write(slot, data)
        if written < len(data):
            # ring full: stash the remainder (already consumed from
            # the kernel!) and pause the conn — the kernel buffer +
            # the client's blocked send are the backpressure tier
            # below us
            self._overflow[slot] = self._overflow.get(slot, b"") + \
                bytes(data[written:])
            try:
                sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            self._paused.add(slot)

    def _handle_hello(self, slot: int, data) -> Optional[bytes]:
        """Accumulate + parse the HELLO frame; returns leftover bytes
        (the start of the data stream), b"" when incomplete, None on a
        protocol error."""
        buf = self._hello_buf[slot]
        buf += data
        if len(buf) < _LEN.size:
            return b""
        (length,) = _LEN.unpack_from(buf)
        if length < 9 or length > 1 << 16:
            self.counters["protocol_errors"] += 1
            record("wire.error", slot=int(slot), why="hello length")
            return None
        if len(buf) < _LEN.size + length:
            return b""
        body = bytes(buf[_LEN.size:_LEN.size + length])
        rest = bytes(buf[_LEN.size + length:])
        try:
            hello = decode_hello(body)
        except (ValueError, struct.error):
            self.counters["protocol_errors"] += 1
            record("wire.error", slot=int(slot), why="hello parse")
            return None
        if hello["version"] != WIRE_VERSION:
            self.counters["protocol_errors"] += 1
            record("wire.error", slot=int(slot), why="version",
                   got=hello["version"])
            self._refuse(slot, E_VERSION,
                         "wire version %d != %d"
                         % (hello["version"], WIRE_VERSION))
            return None
        if hello["payload_width"] != self.payload_width:
            # a mismatched C would desynchronize the fixed-stride sweep
            # on the very first DATA frame — refuse loudly instead
            self.counters["protocol_errors"] += 1
            record("wire.error", slot=int(slot), why="payload_width",
                   got=hello["payload_width"], want=self.payload_width)
            self._refuse(slot, E_PAYLOAD_WIDTH,
                         "payload_width %d != listener's %d"
                         % (hello["payload_width"], self.payload_width))
            return None
        if not (1 <= hello["n_sessions"] <= 1 << 16):
            self.counters["protocol_errors"] += 1
            record("wire.error", slot=int(slot), why="n_sessions")
            return None
        base, epoch, reconnect = self._bind_sessions(
            slot, hello["key"], hello["n_sessions"], hello["tenants"])
        self.cstate[slot] = _S_DATA
        self._hello_buf.pop(slot, None)
        if reconnect:
            record("wire.conn", slot=int(slot), key=hello["key"],
                   reconnect=True, epoch=epoch)
        sock = self._socks.get(slot)
        if sock is not None:
            h = base + np.arange(hello["n_sessions"], dtype=np.int64)
            if not _sendall_nb(sock, encode_hello_ack(
                    epoch, base, slots=self.session_slots(h),
                    payload_width=self.payload_width),
                    deadline_s=2.0):
                return None
            # replay the authoritative committed watermarks: a
            # reconnecting client rebuilds its ack state from these
            # (the crash-reconnect contract, wire/client.py)
            with self._lock:
                have = np.flatnonzero(self._committed[h] > 0)
                rec = np.zeros(len(have), ack_dtype)
                rec["sess"] = have
                rec["acked"] = self._committed[h[have]]
                self._acked_sent[h[have]] = self._committed[h[have]]
            if len(rec):
                self.counters["ack_rows"] += len(rec)
                _sendall_nb(sock, self._ack_frame(rec))
        return rest

    def _refuse(self, slot: int, code: int, msg: str) -> None:
        """Best-effort ERR frame before the caller closes the slot — a
        refused client should see WHY, not a silent hangup it can only
        diagnose as a timeout."""
        sock = self._socks.get(slot)
        if sock is not None:
            _sendall_nb(sock, encode_error(code, msg), deadline_s=1.0)

    def _ring_write(self, slot: int, data) -> int:
        """Wrap-aware copy of ``data`` into the slot's ring; returns
        the byte count written (a short write means the ring is full —
        the caller stashes the remainder and pauses the connection)."""
        n = len(data)
        with self._lock:
            b = self.ring_bytes
            fill = int(self.rfill[slot])
            space = b - fill
            take = min(space, n)
            if take > 0:
                tail = (int(self.rhead[slot]) + fill) % b
                first = min(take, b - tail)
                buf = np.frombuffer(data, np.uint8, take)
                self.rbuf[slot, tail:tail + first] = buf[:first]
                if take > first:
                    self.rbuf[slot, :take - first] = buf[first:]
                self.rfill[slot] += take
                self.counters["bytes_recv"] += take
        return take

    # ------------------------------------------------------------------
    # serving-path placement view (ISSUE 19)
    # ------------------------------------------------------------------

    def bind_placement(self, cache, local_engines, rids=None) -> None:
        """Wire a revision-monotone :class:`PlacementCache` into the
        sweep: rows whose lane the cache places on an engine NOT served
        here are refused with a typed REHOME hint instead of submitted
        — a frame routed on a stale client-side view never silently
        misroutes into a foreign (possibly dead) engine's lanes.  The
        cache is shared with whatever refreshes it on table commits;
        the sweep re-derives its lane mask whenever the cache revision
        moves (including an :meth:`PlacementCache.invalidate`, which
        fails OPEN: no view is not the same as a foreign view).

        ``rids`` names the table range ids THIS listener's lane space
        belongs to.  PR 17's per-engine lane spaces overlap (every
        engine's range covers ``[0, lanes)`` under its own rid), so
        the mask must be derived only from the ranges this listener
        serves — a foreign engine's range over the same lane numbers
        says nothing about these sessions.  ``None`` keeps the
        all-ranges view (globally partitioned lane spaces)."""
        self._placement = cache
        self._local_engines = set(local_engines)
        self._placement_rids = None if rids is None else frozenset(rids)
        self._placement_rev = -2

    def add_local_engine(self, engine_id: str) -> None:
        """Adoption hook: lanes the cache places on ``engine_id`` are
        local from now on (the survivor serves the victim's ranges)."""
        self._local_engines.add(engine_id)
        self._placement_rev = -2

    def _refresh_placement_mask(self) -> None:
        cache = self._placement
        if int(cache.rev) == self._placement_rev:
            return
        n_lanes = int(self.plane.engine.n_lanes)
        local = np.ones(n_lanes, bool)   # fail open: unknown = local
        home = np.full(n_lanes, -1, np.int64)
        names: list = []
        gens: list = []
        if int(cache.rev) >= 0:
            # per-RANGE Python (a handful of ranges, control plane) —
            # the per-ROW path below stays one mask gather
            for rid, ent in sorted(cache.ranges().items()):  # ra09-ok: iterates placement RANGES (control-plane scale), rows stay vectorized
                if self._placement_rids is not None and \
                        rid not in self._placement_rids:
                    continue
                lo = max(0, int(ent["lo"]))
                hi = min(n_lanes, int(ent["hi"]))
                if hi <= lo:
                    continue
                eng = ent["engine"]
                local[lo:hi] = eng in self._local_engines
                home[lo:hi] = len(names)
                names.append(eng)
                gens.append(int(ent["generation"]))
        self._lane_local = local
        self._lane_home = home
        self._owner_names = names
        self._owner_gens = gens
        self._placement_rev = int(cache.rev)

    def _stale_rows(self, handles: np.ndarray,
                    ok: np.ndarray) -> Optional[np.ndarray]:
        """Mask of swept rows whose lane's home is NOT served here per
        the bound placement view (one gather — RA09-clean)."""
        self._refresh_placement_mask()
        if self._lane_local is None or self._lane_local.all():
            return None
        lanes = self.plane.directory.lanes_of(handles)
        return ok & ~self._lane_local[lanes]

    def _send_rehome(self, conn_of: np.ndarray, handles: np.ndarray,
                     stale: np.ndarray) -> None:
        """One typed REHOME hint per affected connection: the new home
        (engine, generation, table revision) of the FIRST refused lane
        — enough for the client to re-resolve and reconnect.  Rare
        (the post-migration window only), so per-connection Python is
        acceptable here like every other control-plane frame."""
        rows = np.flatnonzero(stale)
        lanes = self.plane.directory.lanes_of(handles[rows])
        conns, counts = self._runs(conn_of[rows])
        firsts = np.cumsum(counts) - counts
        rev = int(self._placement.rev)
        self.rehome_hints += len(conns)
        for i in range(len(conns)):  # ra09-ok: per-CONNECTION rehome hint (rare, post-migration only)
            owner = int(self._lane_home[int(lanes[firsts[i]])])
            engine = self._owner_names[owner] if owner >= 0 else ""
            gen = self._owner_gens[owner] if owner >= 0 else 0
            slot = int(conns[i])
            record("placement.rehome_hint", slot=slot, engine=engine,
                   generation=gen, rev=rev, rows=int(counts[i]))
            if self._is_lb[slot]:
                self._lb_rehome.append((slot, engine, gen, rev))
            else:
                self._send_frame_to(slot, encode_rehome(engine, gen,
                                                        rev))

    def collect_rehome_hints(self) -> list:
        """Drain the loopback rehome-hint outbox: ``(slot, engine,
        generation, rev)`` tuples (the fleet-side twin of T_REHOME)."""
        with self._lock:
            out, self._lb_rehome = self._lb_rehome, []
        return out

    # ------------------------------------------------------------------
    # sweep — the RA09-gated vectorized hot path
    # ------------------------------------------------------------------

    def sweep(self) -> int:
        """Drain whole records from every connection's ring into ONE
        ``(handles, seqnos, payloads)`` ingress batch, submit it, and
        serialize the per-row verdicts back as CREDIT frames.  Returns
        the number of rows swept.  Zero per-command Python: gathers,
        ``frombuffer`` views and column slices end to end (rule RA09)."""
        r, b = self.stride, self.ring_bytes
        with self._lock:
            counts_all = np.where(self.cstate == _S_DATA,
                                  self.rfill // r, 0)
            active = np.flatnonzero(counts_all)
            if active.size == 0:
                return 0
            counts = counts_all[active]
            budget = max(1, self.sweep_rows // max(1, active.size))
            np.minimum(counts, budget, out=counts)
            head = self.rhead[active].copy()
        maxr = int(counts.max())
        idx = (head[:, None] + np.arange(maxr * r)) % b
        chunk = self.rbuf[active[:, None], idx]
        recs = chunk.reshape(active.size, maxr, r)
        valid = np.arange(maxr)[None, :] < counts[:, None]
        flat = recs[valid]
        rec = flat.view(self._rec_dtype())[:, 0]
        conn_of = np.repeat(active, counts)
        # READ records share the DATA stride — ONE frombuffer sweep
        # covers the mixed stream, the type column splits it (ISSUE 20)
        is_read = rec["type"] == T_READ
        wf = (rec["len"] == r - 4) \
            & ((rec["type"] == T_DATA) | is_read) \
            & (rec["sess"].astype(np.int64) < self.nsess[conn_of])
        ok = wf & ~is_read
        with self._lock:
            # a conn closed/killed between the snapshot and here has
            # had its ring RESET — advancing it would drive rfill
            # negative and corrupt the slot for its next tenant; the
            # clamp covers a loopback kill (same slot, emptied ring)
            live = self.cstate[active] == _S_DATA
            a = active[live]
            self.rhead[a] = (head[live] + counts[live] * r) % b
            self.rfill[a] = np.maximum(
                self.rfill[a] - counts[live] * r, 0)
        if not wf.all():
            # AFTER the ring advance: closing resets the slot's ring
            self._protocol_errors(np.unique(conn_of[~wf]),
                                  int((~wf).sum()))
        sess = rec["sess"].astype(np.int64)
        handles = self.hbase[conn_of] + sess
        seqnos = rec["seqno"].astype(np.int64)
        if self._placement is not None and wf.any():
            # placement staleness gate (ISSUE 19): rows whose lane
            # moved to a foreign engine get a typed REHOME hint, not a
            # submit — they earn neither credit nor a shed verdict
            # (the client re-sends them at the new home after
            # following the hint).  Reads rehome too: a consistent
            # read served by a stale home would read a frozen lane
            stale = self._stale_rows(handles, wf)
            if stale is not None and stale.any():
                self._send_rehome(conn_of, handles, stale)
                wf &= ~stale
                ok &= ~stale
        rd = wf & is_read
        status = np.full(len(rec), SHED, np.int8)
        if ok.any():
            status[ok] = self.plane.submit(handles[ok], seqnos[ok],
                                           rec["pay"][ok])
        if rd.any():
            # the verdict here is ADMISSION only (ladder bias: reads
            # shed first under load); served/refused outcomes fan back
            # later as READ_REPLY records off the settlement hook
            status[rd] = self.plane.submit_reads(
                handles[rd], seqnos[rd],
                rec["pay"][rd][:, :self._query_width])
            self.counters["read_rows"] += int(rd.sum())
        self.counters["sweeps"] += 1
        self.counters["swept_rows"] += int(ok.sum())
        # malformed rows are protocol errors, NOT shed verdicts: only
        # real rows feed the credit histogram and the credit frames —
        # reads join the SAME credit fan-out (one verdict stream)
        self._note_statuses(status[wf])
        self._send_credit(conn_of[wf], sess[wf], seqnos[wf],
                          status[wf])
        return int(wf.sum())

    def _rec_dtype(self):
        from .framing import data_dtype
        return data_dtype(self.payload_width)

    def _note_statuses(self, status: np.ndarray) -> None:
        """Fold the sweep's verdicts into the credit-level histogram
        counters + the shed-transition event (transitions only — the
        emit path must not ride a million-row batch)."""
        hist = np.bincount(status, minlength=6)
        c = self.counters
        c["credit_ok"] += int(hist[0])
        c["credit_slow"] += int(hist[1])
        c["credit_defer"] += int(hist[2])
        c["credit_reject"] += int(hist[3])
        c["credit_dup"] += int(hist[4])
        c["credit_shed"] += int(hist[5])
        shedding = bool(hist[SHED])
        if shedding and not self._shedding:
            record("wire.shed", rows=int(hist[SHED]),
                   level=int(self.plane.ladder.level))
        self._shedding = shedding
        level = int(self.plane.ladder.level)
        if level != self._last_credit_level:
            record("wire.credit", old=self._last_credit_level,
                   new=level)
            self._last_credit_level = level

    def _send_credit(self, conn_of, sess, seqnos, status) -> None:
        """One CREDIT frame per connection with swept rows this pass:
        records built in one vectorized fill; socket delivery is one
        syscall per CONNECTION (never per command)."""
        n = len(sess)
        if n == 0:
            return
        rec = np.zeros(n, credit_dtype)
        rec["sess"] = sess
        rec["seqno"] = seqnos
        rec["status"] = status
        self.counters["credit_rows"] += n
        # conn_of is non-decreasing (records gathered in conn order)
        conns, counts = self._runs(conn_of)
        level = int(self.plane.ladder.level)
        lb = self._is_lb[conns]
        if lb.any():
            keep = np.repeat(lb, counts)
            self._lb_credit.append((conns[lb], counts[lb], rec[keep]))
        if (~lb).any():
            bounds = np.cumsum(counts)
            starts = bounds - counts
            for i in np.flatnonzero(~lb):  # ra09-ok: per-CONNECTION socket write (one frame/syscall per conn, never per command)
                self._send_frame_to(
                    int(conns[i]),
                    self._credit_frame(level,
                                       rec[starts[i]:bounds[i]]))

    @staticmethod
    def _runs(keys: np.ndarray) -> tuple:
        """Run-length encode a non-decreasing key array (vectorized)."""
        n = len(keys)
        new = np.empty(n, bool)
        new[0] = True
        new[1:] = keys[1:] != keys[:-1]
        starts = np.flatnonzero(new)
        counts = np.diff(np.append(starts, n))
        return keys[starts], counts

    @staticmethod
    def _credit_frame(level: int, rec: np.ndarray) -> bytes:
        body = struct.pack("<BBBH", 4, level, 0, len(rec)) \
            + rec.tobytes()
        return _LEN.pack(len(body)) + body

    def _send_frame_to(self, slot: int, frame: bytes) -> None:
        sock = self._socks.get(slot)
        if sock is None:
            return
        if not _sendall_nb(sock, frame):
            self._close_slot(slot, reason="send failed")

    def _protocol_errors(self, bad_conns: np.ndarray, rows: int) -> None:
        self.counters["protocol_errors"] += rows
        for slot in bad_conns.tolist():  # ra09-ok: per-CONNECTION close on a protocol error (rare, terminal)
            record("wire.error", slot=int(slot), why="bad record")
            self._close_slot(int(slot), reason="protocol error")

    # ------------------------------------------------------------------
    # acks — block-commit watermarks off the plane's credit release
    # ------------------------------------------------------------------

    def _on_block_committed(self, handles: np.ndarray) -> None:
        """IngressPlane retire hook: count committed placed rows per
        session and fan the advanced watermarks out as ACK frames
        (driven by the driver's EXISTING async committed-watermark
        readbacks — no new host syncs)."""
        self._ensure_session_arrays()
        with self._lock:  # vs a reader-thread HELLO growing the arrays
            np.add.at(self._committed, handles, 1)
            touched = np.unique(handles)
            moved = touched[self._committed[touched]
                            > self._acked_sent[touched]]
            if not moved.size:
                return
            acked = self._committed[moved]
            self._acked_sent[moved] = acked
        if self._base_dirty:
            live = np.flatnonzero(self.cstate == _S_DATA)
            order = np.argsort(self.hbase[live], kind="stable")
            self._base_slot = live[order]
            self._base_sorted = self.hbase[self._base_slot]
            self._base_dirty = False
        if not len(self._base_slot):
            return
        pos = np.searchsorted(self._base_sorted, moved, side="right") - 1
        pos = np.clip(pos, 0, len(self._base_sorted) - 1)
        conns = self._base_slot[pos]
        in_range = (moved >= self._base_sorted[pos]) & \
            (moved < self._base_sorted[pos] + self.nsess[conns])
        conns, moved, acked = conns[in_range], moved[in_range], \
            acked[in_range]
        if not len(conns):
            return
        order = np.argsort(conns, kind="stable")
        conns, moved, acked = conns[order], moved[order], acked[order]
        rec = np.zeros(len(moved), ack_dtype)
        rec["sess"] = moved - self.hbase[conns]
        rec["acked"] = acked
        self.counters["ack_rows"] += len(rec)
        runs, counts = self._runs(conns)
        lb = self._is_lb[runs]
        if lb.any():
            keep = np.repeat(lb, counts)
            self._lb_ack.append((runs[lb], counts[lb], rec[keep]))
        if (~lb).any():
            bounds = np.cumsum(counts)
            starts = bounds - counts
            for i in np.flatnonzero(~lb):
                self._send_frame_to(
                    int(runs[i]),
                    self._ack_frame(rec[starts[i]:bounds[i]]))

    @staticmethod
    def _ack_frame(rec: np.ndarray) -> bytes:
        body = struct.pack("<BBHH", 5, 0, 0, len(rec)) + rec.tobytes()
        return _LEN.pack(len(body)) + body

    # ------------------------------------------------------------------
    # read replies — served/refused reads off the plane's settlement
    # ------------------------------------------------------------------

    def _on_reads_served(self, handles, seqnos, statuses, wms,
                         payloads) -> None:
        """IngressPlane read-settlement hook (ISSUE 20): fan READ_REPLY
        records out per connection — the same searchsorted handle-base
        lookup as the ack path, driven by the driver's EXISTING async
        read-aux readbacks (no new host syncs).  ``wm`` carries the
        certified commit watermark each read was served at (-1 on a
        shed/stale refusal)."""
        if self._base_dirty:
            live = np.flatnonzero(self.cstate == _S_DATA)
            order = np.argsort(self.hbase[live], kind="stable")
            self._base_slot = live[order]
            self._base_sorted = self.hbase[self._base_slot]
            self._base_dirty = False
        if not len(self._base_slot) or not len(handles):
            return
        handles = np.asarray(handles, np.int64)
        pos = np.searchsorted(self._base_sorted, handles,
                              side="right") - 1
        pos = np.clip(pos, 0, len(self._base_sorted) - 1)
        conns = self._base_slot[pos]
        in_range = (handles >= self._base_sorted[pos]) & \
            (handles < self._base_sorted[pos] + self.nsess[conns])
        if not in_range.any():
            return
        conns = conns[in_range]
        order = np.argsort(conns, kind="stable")
        conns = conns[order]
        keep_ix = np.flatnonzero(in_range)[order]
        w = self._reply_width
        rec = np.zeros(len(conns), read_reply_dtype(w))
        rec["sess"] = handles[keep_ix] - self.hbase[conns]
        rec["seqno"] = np.asarray(seqnos)[keep_ix]
        rec["status"] = np.asarray(statuses)[keep_ix]
        rec["wm"] = np.asarray(wms)[keep_ix]
        pay = np.asarray(payloads)[keep_ix]
        rec["pay"][:, :pay.shape[1]] = pay[:, :w]
        self.counters["read_reply_rows"] += len(rec)
        runs, counts = self._runs(conns)
        lb = self._is_lb[runs]
        if lb.any():
            keep = np.repeat(lb, counts)
            self._lb_read.append((runs[lb], counts[lb], rec[keep]))
        if (~lb).any():
            bounds = np.cumsum(counts)
            starts = bounds - counts
            for i in np.flatnonzero(~lb):  # ra09-ok: per-CONNECTION socket write (one READ_REPLY frame/syscall per conn, never per read)
                self._send_frame_to(
                    int(runs[i]),
                    self._read_reply_frame(rec[starts[i]:bounds[i]]))

    def _read_reply_frame(self, rec: np.ndarray) -> bytes:
        body = struct.pack("<BBHH", T_READ_REPLY, self._reply_width, 0,
                           len(rec)) + rec.tobytes()
        return _LEN.pack(len(body)) + body

    def collect_read_replies(self) -> list:
        """Drain the loopback READ_REPLY outbox: a list of (conn ids,
        per-conn row counts, records) tuples, records typed
        ``read_reply_dtype(reply_width)`` (the in-process twin of the
        TCP frame — the fleet/bench harvests replies here)."""
        with self._lock:
            out, self._lb_read = self._lb_read, []
        return out

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def gauges(self) -> dict:
        live = int((self.cstate == _S_DATA).sum())
        return {
            "conns": live,
            "socket_conns": len(self._socks),
            "loopback_conns": len(self._lb_slots),
            "paused_conns": len(self._paused),
            "queue_bytes": int(self.rfill.sum()),
            "ring_bytes": self.ring_bytes,
            "max_conns": self.max_conns,
        }

    def overview(self) -> dict:
        """The Observatory ``wire`` source: WIRE_FIELDS counters + the
        connection-pool gauges (flat ring keys ``wire_<field>``)."""
        return {**self.counters, **self.gauges()}

    def attach(self, observatory) -> "WireListener":
        observatory.add_source("wire", self.overview)
        return self

    def bench_row(self, elapsed_s: float,
                  reconnect_recovery_s: float = -1.0) -> dict:
        """A bench/soak tail row carrying the wire regression keys
        tools/bench_diff.py compares (``wire_cmds_per_s`` higher-is-
        better; ``wire_shed_rate`` / ``wire_reconnect_recovery_s``
        lower-is-better)."""
        c = self.counters
        swept = c["swept_rows"]
        placed = c["credit_ok"] + c["credit_slow"]
        return {
            "value": placed / max(elapsed_s, 1e-9),
            "wire_cmds_per_s": placed / max(elapsed_s, 1e-9),
            "wire_shed_rate": c["credit_shed"] / max(1, swept),
            "wire_reconnect_recovery_s": reconnect_recovery_s,
            "wire_conns": self.gauges()["conns"],
            "wire_swept_rows": swept,
            "elapsed_s": elapsed_s,
        }
