"""Machine-level dedup: the exactly-once-observable half of the wire
contract (ISSUE 12).

The ingress gate is at-most-once (docs/INGRESS.md "Delivery
guarantees"): a placed-but-unacked command can be lost to a Raft-legal
truncation, so an at-least-once client re-enqueues unacked payloads
under FRESH seqnos after an epoch bump — and that re-enqueue may
duplicate a command whose first copy did commit.  The reference splits
the problem exactly this way: ``ra.erl pipeline_command`` resends
freely and the fifo machine dedups per-enqueuer seqnos machine-side
(PAPER.md §1).  :class:`DedupCounterMachine` is that machine-side half
for the wire plane's counter workload: every command carries a
``(slot, op_id)`` client identity and the machine applies each op at
most once, so end-to-end semantics upgrade to exactly-once-observable.

Command encoding (``command_spec`` int32[3]): ``[slot, op_id, delta]``

* ``slot`` — the session's per-lane rank (assigned at connect; unique
  within a lane, < ``slots``).  An out-of-range slot is a no-op.
* ``op_id`` — the client's monotone per-session operation id,
  **starting at 1** (0 = the noop padding the engine's election path
  feeds through empty command slots).
* ``delta`` — the increment.

State per lane: ``{"value": int32, "seq": int32[slots]}`` where
``seq[slot]`` is the highest op applied for that client.  The batch
fold is vectorized AND exactly order-equivalent to the sequential
masked apply: a row applies iff its op exceeds both the slot's
watermark at window entry and the max op of every earlier same-slot
row in the window (the running-watermark prefix max — duplicates and
stale re-sends inside one fused window are skipped just as a
sequential scan would skip them).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.machine import JitMachine

_I32 = jnp.int32


def _scatter_max(seq, slot, val):
    """Batched per-row scatter-max into the slot axis: flattens the
    leading dims and vmaps one ``at[].max`` (duplicate slots resolve by
    max, which is exactly the watermark semantics)."""
    s = seq.shape[-1]
    lead = seq.shape[:-1]
    seqf = seq.reshape((-1, s))
    slotf = slot.reshape((-1,) + slot.shape[len(lead):])
    valf = val.reshape(slotf.shape)
    out = jax.vmap(lambda q, i, v: q.at[i].max(v))(seqf, slotf, valf)
    return out.reshape(seq.shape)


class DedupCounterMachine(JitMachine):
    command_spec = ("int32", (3,))
    reply_spec = ("int32", ())
    version = 0
    supports_batch_apply = True

    def __init__(self, slots: int = 64) -> None:
        if slots < 1:
            raise ValueError("slots must be >= 1")
        self.slots = int(slots)

    def jit_init(self, n_lanes: int):
        return {"value": jnp.zeros((n_lanes,), _I32),
                "seq": jnp.zeros((n_lanes, self.slots), _I32)}

    def jit_apply(self, meta, command, state):
        s = self.slots
        raw = command[..., 0]
        ok = (raw >= 0) & (raw < s)
        slot = jnp.clip(raw, 0, s - 1)
        op = command[..., 1]
        delta = command[..., 2]
        cur = jnp.take_along_axis(state["seq"], slot[..., None],
                                  axis=-1)[..., 0]
        fresh = ok & (op > cur)
        value = state["value"] + jnp.where(fresh, delta, 0)
        seq = _scatter_max(state["seq"], slot[..., None],
                           jnp.where(fresh, op, 0)[..., None])
        return {"value": value, "seq": seq}, value

    def jit_apply_batch(self, meta, commands, mask, state):
        # commands [..., A, 3], mask bool[..., A]; exact sequential
        # equivalence via the running-watermark prefix max (see module
        # docstring) — one [A, A] pairwise block, A = apply window
        s = self.slots
        raw = commands[..., 0]
        ok = mask & (raw >= 0) & (raw < s)
        slot = jnp.clip(raw, 0, s - 1)
        op = commands[..., 1]
        delta = commands[..., 2]
        cur = jnp.take_along_axis(state["seq"], slot, axis=-1)
        a = op.shape[-1]
        same_slot = slot[..., :, None] == slot[..., None, :]
        earlier = jnp.tril(jnp.ones((a, a), bool), k=-1)
        prior_op = jnp.max(
            jnp.where(same_slot & earlier & ok[..., None, :],
                      op[..., None, :], 0), axis=-1)
        fresh = ok & (op > jnp.maximum(cur, prior_op))
        value = state["value"] + \
            jnp.sum(jnp.where(fresh, delta, 0), axis=-1)
        seq = _scatter_max(state["seq"], slot,
                           jnp.where(fresh, op, 0))
        return {"value": value, "seq": seq}

    def encode_command(self, command):
        slot, op, delta = command
        return jnp.asarray([int(slot), int(op), int(delta)], _I32)

    def decode_reply(self, reply):
        return int(reply)
