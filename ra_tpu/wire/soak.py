"""The wire-plane connection-ladder soak (ISSUE 12 acceptance).

One rung = :func:`run_wire_soak`: ``conns`` wire connections fan ops
through the full wire path — fixed-stride DATA encode → per-connection
rings → vectorized sweep → ingress dedup/admission/coalescing → fused
dispatch — with credit verdicts and commit-watermark ACKs flowing
back, a mid-run **reconnect storm** (epoch bumps + at-least-once
replay), member-failure/election chaos on the lane plane, a standing
lossy transport FaultPlan in the process registry, and (durable
variant) a seeded DiskFaultPlan injecting real WAL faults.  The
exactly-once-observable oracle closes the run: every op's delta
applied EXACTLY once (machine-level dedup absorbs the storm's
duplicate rows), every ranked op acked.

``tools/soak.py --wire`` climbs the ladder C10k → C100k → C1M;
``bench.py --wire`` runs one rung and stamps the tail
(``wire_cmds_per_s`` / ``wire_shed_rate`` /
``wire_reconnect_recovery_s``) for tools/bench_diff.py.

Transports: the C10k rung carries a real-socket side-car
(``socket_conns`` WireClients against the TCP listener) next to the
loopback fleet; the C100k/C1M rungs are loopback-only — two kernel
fds per connection exceed any rlimit (this box: 20k) three decades
before the data plane saturates, and the loopback transport shares
every byte of the ring/sweep/framing path (wire/server.py docstring).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .client import PLACED, LoopbackFleet, WireClient
from .dedup import DedupCounterMachine
from .framing import data_stride
from .server import WireListener


def _host_envelope() -> dict:
    """Host stamp for the soak tails (ISSUE 13 satellite) — the shared
    ra_tpu.utils.host_envelope implementation."""
    from ..utils import host_envelope
    return host_envelope()


def run_wire_soak(seed: int, *, conns: int = 10_000,
                  sessions_per_conn: int = 1, lanes: int = 512,
                  waves: int = 12, wave_ops: int = 50_000,
                  durable_dir: Optional[str] = None,
                  disk_faults: bool = False, superstep_k: int = 4,
                  cmds: int = 16, wal_shards: int = 2,
                  socket_conns: int = 0, socket_ops: int = 32,
                  storm_frac: float = 0.25,
                  storm_wave: Optional[int] = None,
                  ring_records: int = 32, tenants: int = 16,
                  mesh: bool = False, chaos: bool = True,
                  throughput_bar: Optional[float] = None) -> dict:
    """One ladder rung; returns a bench_diff-comparable tail row.
    See the module docstring for the scenario."""
    from ..engine import LockstepEngine
    from ..ingress import IngressPlane
    from ..transport.rpc import FaultPlan, FaultSpec
    rng = np.random.default_rng(seed)
    sessions = conns * sessions_per_conn + socket_conns
    slots = 4 * max(1, sessions // lanes) + 64
    ring = max(512, superstep_k * cmds * 4)
    machine = DedupCounterMachine(slots=slots)
    device_mesh = None
    if mesh:
        import jax

        from ..parallel.mesh import lane_mesh, per_device_wal_shards
        if len(jax.devices()) < 2:
            raise RuntimeError(
                "mesh wire soak needs >=2 devices; run with "
                "JAX_PLATFORMS=cpu XLA_FLAGS="
                "--xla_force_host_platform_device_count=8")
        device_mesh = lane_mesh(jax.devices(), member_axis=1)
        if durable_dir is not None:
            wal_shards = per_device_wal_shards(device_mesh)
    if durable_dir is not None:
        from ..engine.durable import open_engine
        eng = open_engine(machine, durable_dir, lanes,
                          wal_shards=wal_shards, ring_capacity=ring,
                          max_step_cmds=cmds, donate=False)
    else:
        eng = LockstepEngine(machine, lanes, 3, ring_capacity=ring,
                             max_step_cmds=cmds, donate=False)
    if device_mesh is not None:
        from ..parallel.mesh import shard_engine_state
        shard_engine_state(eng, device_mesh)
    disk_plan = None
    net_plan = FaultPlan(seed=seed, default=FaultSpec(drop=0.1))
    if disk_faults:
        from ..log import faults
        disk_plan = faults.DiskFaultPlan(
            seed=seed, by_class={"wal": faults.DiskFaultSpec(
                fsync_eio=0.05, short_write=0.02, limit=4)})
        faults.install_plan(disk_plan)
    plane = IngressPlane(eng, superstep_k=superstep_k,
                         window_s=0.001, soft_credit=1 << 20,
                         hard_credit=1 << 20)
    lst = WireListener(
        plane, port=0 if socket_conns else None,
        max_conns=conns + socket_conns + 8,
        ring_bytes=ring_records * data_stride(eng.payload_width))
    side_cars: list = []
    try:
        fleet = LoopbackFleet(
            lst, conns, sessions_per_conn=sessions_per_conn,
            key="ladder", tenants=tenants, seed=seed,
            max_ops=waves * wave_ops + wave_ops + 1024)
        assert int(fleet.slots.max()) < slots, "dedup slot overflow"
        for i in range(socket_conns):
            side_cars.append(WireClient(lst.address, key=f"sock/{i}",
                                        n_sessions=1))
        # warm the fused/settle/read executables outside the measured
        # window (zero-delta ops leave the oracle untouched)
        fleet.new_ops(rng.integers(0, fleet.n_sessions,
                                   min(1024, wave_ops)),
                      np.zeros(min(1024, wave_ops), np.int32))
        _cycle(fleet, lst, plane)
        plane.settle()
        fleet.collect()
        eng.consistent_read([0])
        failed_member = None
        storm_at = waves // 2 if storm_wave is None else storm_wave
        storm_ops: Optional[np.ndarray] = None
        storm_t = recovery_s = -1.0
        placed_base = lst.counters["credit_ok"] + \
            lst.counters["credit_slow"]
        work_s = 0.0
        t0 = time.perf_counter()
        for w in range(waves):
            tw = time.perf_counter()
            sess = rng.integers(0, fleet.n_sessions, wave_ops)
            fleet.new_ops(sess, rng.integers(1, 8, wave_ops)
                          .astype(np.int32))
            _cycle(fleet, lst, plane)
            work_s += time.perf_counter() - tw
            for cli in side_cars:
                for _ in range(socket_ops):
                    cli.enqueue(int(rng.integers(1, 8)))
                cli.flush()
                cli.poll()  # prompt verdict processing: refusals re-key
            if w == storm_at:
                # NO settle barrier here: a connection kill only loses
                # ring bytes (client-replayed), never committed state —
                # the settle discipline is for LEADER kills below
                storm_t = time.perf_counter()
                storm_ops = fleet.storm(storm_frac)
                for cli in side_cars:
                    cli.reconnect()
            if storm_ops is not None and recovery_s < 0:
                tw = time.perf_counter()
                _cycle(fleet, lst, plane)
                work_s += time.perf_counter() - tw
                if (fleet.op_state[storm_ops] == PLACED).all():
                    recovery_s = time.perf_counter() - storm_t
            if chaos and w % 4 == 2:
                if durable_dir is not None:
                    plane.settle(timeout=120.0)
                    fleet.collect()
                if failed_member is not None:
                    lane_c, slot = failed_member
                    if int(np.asarray(
                            eng.state.leader_slot)[lane_c]) != slot:
                        eng.recover_member(lane_c, slot)
                    failed_member = None
                lane_c = int(rng.integers(lanes))
                slot = int(np.asarray(eng.state.leader_slot)[lane_c])
                eng.fail_member(lane_c, slot)
                eng.trigger_election([lane_c])
                failed_member = (lane_c, slot)
        # drain: at-least-once means every op retries until placed
        tw = time.perf_counter()
        deadline = time.monotonic() + 120.0
        while fleet.unplaced_count() > 0:
            _cycle(fleet, lst, plane)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"wire drain: {fleet.unplaced_count()} ops "
                    "unplaced")
        plane.settle(timeout=120.0)
        fleet.collect()
        if storm_ops is not None and recovery_s < 0:
            recovery_s = time.perf_counter() - storm_t
        work_s += time.perf_counter() - tw
        elapsed = time.perf_counter() - t0
        # side-car clients drain the same way (per-conn scale)
        for cli in side_cars:
            cli_deadline = time.monotonic() + 30.0
            while cli.pending_count() or cli.unacked_count():
                cli.flush()
                lst.sweep()
                plane.pump(force=True)
                plane.settle()
                cli.poll()
                if time.monotonic() > cli_deadline:
                    raise TimeoutError("side-car drain")
        # -- the exactly-once-observable oracle -------------------------
        expected = fleet.expected_lane_sums(lanes)
        for cli in side_cars:
            h = cli.handle_base
            lane_h = int(plane.directory.lane[h])
            expected[lane_h] += sum(cli.op_pay)
        mac = eng.consistent_read(np.arange(lanes))
        got = np.asarray(mac["value"]).astype(np.int64)
        np.testing.assert_array_equal(got, expected)
        ranked = fleet.op_rank[:fleet.n_ops] >= 0
        acked = fleet.acked_mask()
        assert acked[ranked].all(), \
            f"{int((~acked[ranked]).sum())} ranked ops never acked"
        assert int(fleet.watermark.sum()) >= int(ranked.sum())
        # bounded buffers: every ring drained, no hidden queue
        assert int(lst.rfill.max(initial=0)) == 0
        assert plane.gauges()["queue_rows"] == 0
        # shed fairness: hashed placement must spread overflow — no
        # tenant eats a disproportionate share of the sheds
        fairness = _shed_fairness(fleet)
        if fairness is not None:
            assert fairness < 3.0, f"shed unfair: {fairness:.2f}"
        placed = lst.counters["credit_ok"] + \
            lst.counters["credit_slow"] - placed_base
        throughput = placed / max(work_s, 1e-9)
        if throughput_bar is not None:
            assert throughput >= throughput_bar, \
                f"{throughput:.0f} < bar {throughput_bar:.0f} cmds/s"
        row = lst.bench_row(work_s, reconnect_recovery_s=recovery_s)
        row.update({
            "value": throughput,
            "wire_cmds_per_s": throughput,
            "wire_shed_fairness": fairness if fairness is not None
            else -1.0,
            "conns": conns, "sessions": sessions, "lanes": lanes,
            "socket_conns": socket_conns,
            "ops": int(fleet.n_ops),
            "dup_rows_absorbed": int(
                lst.counters["swept_rows"] - fleet.n_ops
                - sum(len(c.op_state) for c in side_cars)),
            "storm_requeued": int(len(storm_ops))
            if storm_ops is not None else 0,
            "elapsed_s": elapsed, "work_s": work_s,
            "durable": durable_dir is not None,
            "mesh": eng.mesh_shape(),
            "wal_shards": wal_shards if durable_dir is not None else 0,
            "disk_faults_injected":
                dict(disk_plan.counters) if disk_plan else {},
        })
        return row
    finally:
        for cli in side_cars:
            cli.close()
        lst.close()
        net_plan.unregister()
        if disk_faults:
            from ..log import faults
            faults.clear_plan()
        eng.close()


def _cycle(fleet: LoopbackFleet, lst: WireListener, plane) -> None:
    """One pump of the whole loop: fleet send → sweep → credit →
    dispatch → ack."""
    fleet.send_queued()
    lst.sweep()
    fleet.collect()
    plane.pump(force=True)
    fleet.collect()


def _shed_fairness(fleet: LoopbackFleet) -> Optional[float]:
    """max tenant shed share / overall shed share; None when (almost)
    nothing was shed."""
    shed = fleet.tenant_shed
    rows = fleet.tenant_rows
    if shed.sum() < 100:
        return None
    overall = shed.sum() / max(1, rows.sum())
    seen = rows > 0
    shares = shed[seen] / rows[seen]
    return float(shares.max() / max(overall, 1e-12))


def ladder_main(seed: int, rungs, *, durable: bool = False,
                disk_faults: bool = False, socket_conns: int = 64,
                **kw) -> list:
    """Climb the ladder (tools/soak.py --wire): one soak per rung,
    socket side-car on the first (smallest) rung only, a FRESH WAL
    dir per durable rung (rungs are independent runs, not restarts)."""
    import json
    import tempfile
    out = []
    for i, conns in enumerate(rungs):
        t0 = time.time()
        with tempfile.TemporaryDirectory(prefix="wire_soak_") as d:
            res = run_wire_soak(
                seed, conns=conns,
                socket_conns=socket_conns if i == 0 else 0,
                wave_ops=max(20_000, conns // 2),
                ring_records=16 if conns >= 1 << 19 else 32,
                durable_dir=d if durable else None,
                disk_faults=disk_faults, **kw)
        res["rung"] = f"C{conns}"
        res["host"] = _host_envelope()
        print(f"wire C{conns}: {res['wire_cmds_per_s']:.0f} cmds/s  "
              f"shed={res['wire_shed_rate']:.4f}  "
              f"recovery={res['wire_reconnect_recovery_s']:.2f}s  "
              f"({time.time() - t0:.1f}s)", flush=True)
        print(json.dumps(res), flush=True)
        out.append(res)
    return out
