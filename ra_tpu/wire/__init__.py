"""The wire plane (ISSUE 12, ROADMAP item 2 front half): real sockets
— and their in-process loopback twin — into the ingress coalescer.

* :mod:`~ra_tpu.wire.framing` — the byte protocol: version byte,
  fixed-stride DATA records, CREDIT/ACK frames, ONE verdict enum +
  encoder shared with the fifo client's ``StopSending`` ladder.
* :class:`~ra_tpu.wire.server.WireListener` — zero-per-command reader
  + the RA09-gated vectorized sweep feeding ``IngressPlane.submit``.
* :class:`~ra_tpu.wire.client.WireClient` /
  :class:`~ra_tpu.wire.client.LoopbackFleet` — the at-least-once
  client library (pipelined seqnos, credit-driven replay, epoch-bump
  re-enqueue).
* :class:`~ra_tpu.wire.dedup.DedupCounterMachine` — machine-level
  dedup upgrading at-most-once to exactly-once-observable.
* :mod:`~ra_tpu.wire.soak` — the C10k→C1M loopback connection-ladder
  soak (``tools/soak.py --wire``, ``bench.py --wire``).
"""
from .client import LoopbackFleet, WireClient
from .dedup import DedupCounterMachine
from .framing import (DEFER, DUP, OK, REJECT, SHED, SLOW, STATUS_NAMES,
                      WIRE_VERSION)
from .server import WireListener

__all__ = [
    "WireListener", "WireClient", "LoopbackFleet",
    "DedupCounterMachine", "WIRE_VERSION",
    "OK", "SLOW", "DEFER", "REJECT", "DUP", "SHED", "STATUS_NAMES",
]
