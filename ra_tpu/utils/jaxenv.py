"""JAX platform selection helper.

In images where a TPU PJRT plugin (e.g. the axon tunnel) registers itself,
the ``JAX_PLATFORMS`` environment variable alone does not demote it; the
platform must also be forced through ``jax.config`` *before* the default
backend initializes.  Both the test suite and the multichip dryrun share
this single implementation so the workaround cannot drift.
"""
from __future__ import annotations

import os


def force_platform_from_env(default: str | None = None) -> None:
    """Honor JAX_PLATFORMS (or ``default`` if unset) via jax.config.

    Call before anything creates a concrete array.  No-op when neither the
    env var nor ``default`` names a platform.
    """
    platform = os.environ.get("JAX_PLATFORMS") or default
    if not platform:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platform.split(",")[0])
    except Exception:
        pass  # backend already initialized; env var had its chance
