"""Fixed-size LRU with an eviction handler.

The reference's ra_flru.erl (:8-40) — a tiny LRU used to cap the number
of open segment file descriptors per server (ra_log_reader's
open_segments).  Eviction calls the handler so the owner can close the
evicted resource.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Optional

DEFAULT_MAX_SIZE = 5  # ra_flru's default open-segment cap


class Flru:
    def __init__(self, max_size: int = DEFAULT_MAX_SIZE,
                 on_evict: Optional[Callable[[Any, Any], None]] = None):
        self.max_size = max_size
        self.on_evict = on_evict
        self._items: "OrderedDict[Any, Any]" = OrderedDict()

    def touch(self, key: Any, value: Any) -> None:
        """Insert or refresh key as most-recently-used; evicts the LRU
        item (invoking the handler) when over capacity."""
        if key in self._items:
            self._items.move_to_end(key)
            self._items[key] = value
            return
        self._items[key] = value
        while len(self._items) > self.max_size:
            old_key, old_val = self._items.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(old_key, old_val)

    def get(self, key: Any) -> Optional[Any]:
        if key not in self._items:
            return None
        self._items.move_to_end(key)
        return self._items[key]

    def pop(self, key: Any) -> Optional[Any]:
        """Remove without invoking the eviction handler (the caller is
        taking ownership)."""
        return self._items.pop(key, None)

    def evict_all(self) -> None:
        while self._items:
            key, val = self._items.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(key, val)

    def __contains__(self, key: Any) -> bool:
        return key in self._items

    def __len__(self) -> int:
        return len(self._items)
