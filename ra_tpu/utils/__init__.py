from .jaxenv import force_platform_from_env


def host_envelope() -> dict:
    """Host resource envelope for bench/soak JSON tails (ISSUE 13):
    the fd cap (the wire ladder's 20k-rlimit ceiling) and the core
    count (the 1-core partition tax) both surfaced as unexplained
    cross-host drift in round captures — every capture carries them
    so drift is attributable.  ONE implementation: bench._host_meta
    and the soak tails all merge this dict."""
    import os
    env: dict = {"cpu_count": os.cpu_count()}
    try:
        import resource
        env["rlimit_nofile"] = \
            resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except Exception:  # noqa: BLE001 — optional on exotic platforms
        pass
    # jax/jaxlib versions + backend platform (ISSUE 16): compile-time
    # and device-memory numbers are meaningless across version drift —
    # same rationale as the rlimit/cpu_count stamps above.  Guarded:
    # host_envelope must work where jax is absent or backendless.
    try:
        import jax
        env["jax_version"] = jax.__version__
        try:
            import jaxlib
            env["jaxlib_version"] = jaxlib.__version__
        except Exception:  # noqa: BLE001 — jaxlib not importable alone
            pass
        env["jax_backend"] = jax.default_backend()
    except Exception:  # noqa: BLE001 — optional: no jax / no backend
        pass
    return env
