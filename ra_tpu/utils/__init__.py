from .jaxenv import force_platform_from_env
