from .jaxenv import force_platform_from_env


def host_envelope() -> dict:
    """Host resource envelope for bench/soak JSON tails (ISSUE 13):
    the fd cap (the wire ladder's 20k-rlimit ceiling) and the core
    count (the 1-core partition tax) both surfaced as unexplained
    cross-host drift in round captures — every capture carries them
    so drift is attributable.  ONE implementation: bench._host_meta
    and the soak tails all merge this dict."""
    import os
    env: dict = {"cpu_count": os.cpu_count()}
    try:
        import resource
        env["rlimit_nofile"] = \
            resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    except Exception:  # noqa: BLE001 — optional on exotic platforms
        pass
    return env
