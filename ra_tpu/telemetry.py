"""Device-resident per-lane telemetry plane + the unified Observatory.

The reference answers "how is my cluster doing" with ra_counters
(seshat atomics, sampled off the event loop) and ra:key_metrics; this
module is the lane-engine equivalent at 100k-lane scale (ISSUE 6):

* :class:`TelemetrySampler` — drains the engine's ``LaneTelemetry``
  accumulators (the ``[lanes]`` int32 pytree that rides inside
  ``LaneState`` through every jitted step) on a step cadence.  The
  aggregation to a fixed-size snapshot (scalar rollups, log2 commit-lag
  histogram, ``lax.top_k`` offenders) happens ON DEVICE
  (``lockstep._telemetry_summary``); the sampler only starts an ASYNC
  host copy of the few-hundred-byte result and harvests it on a later
  tick once ready.  The dispatch loop never blocks: the same readback
  discipline as the dispatch-ahead driver (lint rule RA04 gates this
  file's tick path, see tools/lint.py).
* :class:`Observatory` — the host-side unification: one merged snapshot
  of engine telemetry + dispatch-pipeline counters + WAL/disk-fault
  stats + :class:`~ra_tpu.metrics.Counters` groups, with (a) Prometheus
  text exposition, (b) a bounded time-series ring yielding per-window
  rates and percentiles (the substrate a future SLO autotuner reads),
  and (c) JSONL-ring export for ``tools/ra_top.py``.

Nothing here is on the step's critical path: a sampler at the default
cadence adds one tiny extra XLA dispatch per ``cadence_steps`` engine
rounds and zero blocking syncs (pinned by tests/test_telemetry.py).
"""
from __future__ import annotations

import collections
import json
import logging
import os
import re
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from . import devicewatch, trace

logger = logging.getLogger("ra_tpu.telemetry")

#: default sampling cadence in ENGINE ROUNDS (inner steps, not
#: dispatches): one on-device aggregation + async readback per window.
#: At 64 rounds the sampler's extra dispatch is amortized to <0.2% of
#: dispatch count even on the single-step path.
DEFAULT_CADENCE_STEPS = 64

#: a lane is flagged STALLED once it has sat this many consecutive
#: rounds with a commit backlog and zero commit progress
DEFAULT_STALL_THRESHOLD = 8

#: minimum seconds between device-memory censuses on the harvest tick
#: (ISSUE 16): jax.live_arrays() is O(live buffers), so in a
#: buffer-heavy process an every-harvest walk would tax the loop the
#: watermarks exist to observe — 4 Hz bounds the walk while staying
#: far inside any human-scale observation window.  A sampler's FIRST
#: harvest censuses eagerly so short runs and tests always get one.
CENSUS_MIN_INTERVAL_S = 0.25

#: log2 millisecond buckets for phase histograms: bucket 0 = <1ms,
#: bucket b = < 2^b ms, last bucket absorbs the tail (~9 hours)
PHASE_HIST_BUCKETS = 16


class PhaseStats:
    """Phase-resolved latency attribution (ISSUE 9): where did a
    window's latency budget go — host staging, device dispatch, WAL
    encode, queue wait, fsync wait, confirm publish?

    One accumulator per engine (the durable bridge and the attached
    engine share one; each WAL shard feeds the same object).  A sample
    is a pair of ``time.monotonic()`` stamps taken at HOST-side edges
    of the dispatch/durability path — never a device sync, so the
    attribution is always-on at zero pipeline cost (lint rule RA04
    gates the stamp path like the sampler tick).

    Per phase (``metrics.PHASE_FIELDS``): a bounded latency reservoir
    (p50/p99/max), a log2-ms histogram, a sample count, and a MONOTONE
    cumulative ``total_ms``.  Differentiating ``total_ms`` over the
    Observatory time-series ring yields each phase's per-window share
    of the budget — the SLO engine's and autotuner's triggering-phase
    input."""

    def __init__(self, *, reservoir: int = 512) -> None:
        from .metrics import PHASE_FIELDS
        self._fields = PHASE_FIELDS
        self._lock = threading.Lock()
        self._res = {p: collections.deque(maxlen=reservoir)
                     for p in PHASE_FIELDS}
        self._hist = {p: [0] * PHASE_HIST_BUCKETS for p in PHASE_FIELDS}
        self._count = {p: 0 for p in PHASE_FIELDS}
        self._total_ms = {p: 0.0 for p in PHASE_FIELDS}
        #: samples addressed to an unknown phase (registry mismatch —
        #: the telemetry_dropped discipline applied to phases)
        self.dropped = 0

    def note(self, phase: str, dt_s: float) -> None:
        """Record one phase sample of ``dt_s`` seconds.  Called from
        the dispatch thread, WAL batch threads and encode workers —
        one lock + a deque append + int/float adds, nothing that can
        block on the device (rule RA04)."""
        if phase not in self._count:
            self.dropped += 1
            return
        ms = dt_s * 1000.0
        b = min(PHASE_HIST_BUCKETS - 1,
                max(0, int(ms).bit_length()))
        with self._lock:
            self._res[phase].append(ms)
            self._hist[phase][b] += 1
            self._count[phase] += 1
            self._total_ms[phase] += ms

    def overview(self) -> dict:
        """Per-phase ``{count, total_ms, p50_ms, p99_ms, max_ms,
        hist}`` — what the Observatory engine source embeds (the
        scalars flatten into the exposition/ring; the hist renders as
        a labelled Prometheus bucket family)."""
        out: dict = {}
        with self._lock:
            for p in self._fields:
                lats = sorted(self._res[p])
                n = len(lats)
                out[p] = {
                    "count": self._count[p],
                    "total_ms": round(self._total_ms[p], 3),
                    "p50_ms": round(lats[n // 2], 3) if n else -1.0,
                    "p99_ms": round(lats[min(n - 1, int(n * 0.99))], 3)
                    if n else -1.0,
                    "max_ms": round(lats[-1], 3) if n else -1.0,
                    "hist": list(self._hist[p]),
                }
        out["dropped"] = self.dropped
        return out

    def reset_reservoirs(self) -> None:
        """Clear the percentile reservoirs (p50/p99/max) while keeping
        the MONOTONE fields (count, total_ms, hist) monotone — a
        measurement-phase boundary for bench harnesses (ISSUE 20):
        warmup/compile samples must not sit in a measured phase's p99
        tail, but the Observatory ring's rate differentiation over
        ``total_ms``/``count`` must never see a counter reset.  A
        barrier-side call, never the hot path."""
        with self._lock:
            for p in self._fields:
                self._res[p].clear()

    def encode_share_pct(self) -> float:
        """Codec encode time as a percentage of ALL phase time this
        accumulator has seen (ISSUE 18) — the lower-better bench-tail
        key bench_diff tracks: encode-once should drive it toward zero
        as shipped images replace per-entry object encode.  -1.0 until
        any phase sample lands (sentinel, skipped by bench_diff)."""
        with self._lock:
            tot = sum(self._total_ms.values())
            enc = self._total_ms.get("encode", 0.0)
        return round(100.0 * enc / tot, 2) if tot > 0 else -1.0


def _host_scalar(x) -> Any:
    """Device/np scalar -> python int/float; small vectors -> lists.
    Callers pass only READY arrays (the harvest path is is_ready-gated,
    drain is an explicit barrier), so the conversions cannot block."""
    arr = np.asarray(x)  # ra04-ok: callers gate on is_ready (or drain)
    if arr.ndim == 0:
        v = arr.item()  # ra04-ok: host np scalar, already off device
        return round(v, 4) if isinstance(v, float) else v
    return arr.tolist()


class TelemetrySampler:
    """Async drain of a :class:`LockstepEngine`'s telemetry plane.

    Attach one per engine (construction attaches, like
    ``DispatchAheadDriver``); the engine calls :meth:`tick` after every
    dispatch.  Every ``cadence_steps`` engine rounds the sampler
    dispatches the jitted on-device summary over the CURRENT state and
    starts an async device->host copy; ready copies are harvested on
    later ticks (never blocking — an unready sample simply waits, and
    if more than ``max_pending`` samples are in flight the oldest is
    dropped, counted in ``samples_dropped``).  ``last`` always holds
    the newest harvested snapshot as plain host data."""

    def __init__(self, engine, *, cadence_steps: int = DEFAULT_CADENCE_STEPS,
                 top_k: int = 8, hist_buckets: int = 16,
                 stall_threshold: int = DEFAULT_STALL_THRESHOLD,
                 max_pending: int = 4) -> None:
        from .engine.lockstep import telemetry_summary_fn
        self.engine = engine
        self.cadence_steps = max(1, int(cadence_steps))
        self.top_k = min(int(top_k), engine.n_lanes)
        self.hist_buckets = int(hist_buckets)
        self.stall_threshold = int(stall_threshold)
        self.max_pending = max(1, int(max_pending))
        self._fn = telemetry_summary_fn(self.top_k, self.hist_buckets,
                                        self.stall_threshold)
        self._pending: collections.deque = collections.deque()
        self._steps_since = 0
        #: first harvest censuses device memory eagerly (ISSUE 16)
        self._censused = False
        #: newest harvested snapshot (plain dict), or None
        self.last: Optional[dict] = None
        #: sampler health (host ints): ``samples_started`` device
        #: aggregations dispatched, ``samples_harvested`` snapshots
        #: landed, ``samples_dropped`` in-flight overflow evictions,
        #: ``blocking_waits`` forced waits — stays 0 on the tick path
        #: (only :meth:`drain` blocks; the RA04 gauge twin)
        self.counters = {"samples_started": 0, "samples_harvested": 0,
                         "samples_dropped": 0, "blocking_waits": 0,
                         "observer_errors": 0}
        self._observers: list = []
        engine._telemetry = self

    # -- dispatch-loop path (called by the engine; must never block) ------

    def tick(self, k: int = 1) -> None:
        """Advance the cadence by ``k`` engine rounds (the engine calls
        this after each dispatch: k=1 single step, k=K superstep) and
        harvest any READY samples.  No host sync happens here."""
        self._steps_since += k
        if self._steps_since >= self.cadence_steps:
            # keep the overshoot: a superstep whose K does not divide
            # the cadence would otherwise stretch the effective window
            # (48-round ticks at cadence 64 -> samples every 96), and
            # the stall-detection "within one window" bound with it
            self._steps_since %= self.cadence_steps
            self._start_sample()
        self._harvest(block=False)

    def _start_sample(self) -> None:
        st = self.engine.state
        out = self._fn(st.telem, st.total_committed,
                       (st.read_served, st.read_shed, st.read_stale,
                        st.read_leased))
        for v in out.values():
            try:
                v.copy_to_host_async()
            except AttributeError:  # pragma: no cover — older jax arrays
                pass
        # transfer ledger (ISSUE 16): the telemetry harvest IS the
        # steady-state loop's other d2h budget line — one async copy
        # per summary value, counted at copy start (.nbytes = host
        # metadata, no sync; rule RA04 gates this path)
        devicewatch.record_d2h(
            "sampler_harvest",
            sum(getattr(v, "nbytes", 0) for v in out.values()),
            events=len(out))
        self.counters["samples_started"] += 1
        self._pending.append((time.time(),
                              self.engine.pipeline_counters["inner_steps"],
                              out))
        while len(self._pending) > self.max_pending:
            # never block on a slow readback: evict the oldest sample
            # instead (the snapshot is a health gauge, not a ledger)
            self._pending.popleft()
            self.counters["samples_dropped"] += 1

    def _is_ready(self, out: dict) -> bool:
        for v in out.values():
            try:
                if not v.is_ready():
                    return False
            except AttributeError:  # pragma: no cover — older jax arrays
                pass
        return True

    def _harvest(self, block: bool) -> None:
        while self._pending:
            ts, steps, out = self._pending[0]
            if not self._is_ready(out):
                if not block:
                    return
                self.counters["blocking_waits"] += 1
            self._pending.popleft()
            snap = {k: _host_scalar(v) for k, v in out.items()}  # is_ready-gated (or an explicit drain barrier); the syncs live in _host_scalar
            snap["ts"] = ts
            snap["inner_steps_at_sample"] = steps
            snap["stall_threshold"] = self.stall_threshold
            self.last = snap
            self.counters["samples_harvested"] += 1
            # device-memory watermarks ride THIS tick (ISSUE 16): the
            # harvest cadence is the one host-side rhythm the dispatch
            # loop already pays for, and the census is pure metadata
            # (jax.live_arrays + .nbytes) — zero new syncs, see
            # docs/INTERNALS.md.  Eager on the sampler's first
            # harvest, then throttled: the walk is O(live buffers)
            if devicewatch.sample_watermarks(
                    0.0 if not self._censused
                    else CENSUS_MIN_INTERVAL_S):
                self._censused = True
            self._feed_tracer(snap)
            for fn in self._observers:
                # observability must never crash the plane it observes:
                # the harvest path rides the engine's dispatch loop, so
                # a failing export (ENOSPC on a JSONL ring, a vanished
                # directory) is counted and logged, never raised
                try:
                    fn(snap)
                except Exception:  # noqa: BLE001 — observer fault isolation
                    self.counters["observer_errors"] += 1
                    logger.exception("telemetry observer failed; "
                                     "snapshot dropped from this export")

    # -- out-of-loop API ---------------------------------------------------

    def add_observer(self, fn: Callable[[dict], None]) -> None:
        """Call ``fn(snapshot)`` for every harvested sample (the
        Observatory ring and the soak JSONL export ride this).

        Observers run SYNCHRONOUSLY on the harvest path, which the
        engine's dispatch loop drives via :meth:`tick` — keep them
        cheap: host dict work, a tracer counter, or a single buffered
        append (``append_jsonl_ring`` is O(1) writes by design; no
        fsync, no readbacks).  Anything slower belongs on its own
        thread fed from a queue, or the sampler's no-stall contract
        quietly becomes the observer's problem."""
        self._observers.append(fn)

    def drain(self) -> Optional[dict]:
        """Force a sample of the CURRENT state and block until it (and
        any older in-flight samples) land.  A window-boundary/run-end
        operation — never call from a dispatch loop."""
        self._steps_since = 0
        self._start_sample()
        self._harvest(block=True)
        return self.last

    def _feed_tracer(self, snap: dict) -> None:
        """Feed the installed Tracer a lane-health counter track so
        Chrome traces carry telemetry alongside the spans (the lg
        counter-track role; no tracer installed = no cost)."""
        t = trace.get_tracer()
        if t is None:
            return
        t.counter("lane_health",
                  stalled_lanes=snap.get("stalled_lanes", 0),
                  commit_lag_max=snap.get("commit_lag_max", 0),
                  apply_lag_max=snap.get("apply_lag_max", 0),
                  leader_changes=snap.get("leader_changes", 0))


# ---------------------------------------------------------------------------
# Observatory: the merged host-side surface
# ---------------------------------------------------------------------------

class Observatory:
    """One merged snapshot of everything observable, plus derived
    per-window series.

    Sources are named zero-arg callables returning plain dicts of HOST
    data (no device syncs — the engine source reads the sampler's last
    harvested snapshot and host-side counter dicts only, so periodic
    snapshots are safe next to a running dispatch loop).  Snapshots
    land in a bounded ring; :meth:`window_rates` differentiates
    monotone counters into per-second rates between the last two ring
    entries and :meth:`percentile` reads a distribution over the ring
    — the substrate the SLO autotuner (ROADMAP item 4) will read."""

    def __init__(self, *, ring_capacity: int = 256) -> None:
        self._sources: dict[str, Callable[[], dict]] = {}
        self._ring: collections.deque = collections.deque(
            maxlen=max(2, ring_capacity))
        self._seq = 0
        # post-mortem bundles embed a fresh Observatory snapshot (the
        # flight recorder fault-isolates a failing source, so a
        # half-closed engine degrades to an ``error`` entry, not a
        # failed dump); newest-constructed Observatory wins the name,
        # and close() unhooks it — the stored bound-method ref is what
        # makes the identity-guarded removal work (a fresh
        # ``self.snapshot`` access is a NEW object every time)
        from .blackbox import RECORDER
        self._bb_src = self.snapshot
        RECORDER.add_source("observatory", self._bb_src)

    # -- wiring ------------------------------------------------------------

    def add_source(self, name: str, fn: Callable[[], dict]) -> "Observatory":
        self._sources[name] = fn
        return self

    def close(self) -> None:
        """Unhook this Observatory's flight-recorder bundle source (the
        mirror of EngineDurability.close's source removal).  Call when
        the observed engine/system is being torn down in a long-lived
        process — otherwise the source closure pins the closed engine
        (and its device buffers) for the rest of the process and every
        later bundle embeds an ``error`` entry instead of live state."""
        from .blackbox import RECORDER
        RECORDER.remove_source("observatory", self._bb_src)

    @classmethod
    def for_engine(cls, engine, *, sampler: Optional[TelemetrySampler] = None,
                   system=None, counters=None, router=None,
                   ring_capacity: int = 256) -> "Observatory":
        """The standard wiring: engine telemetry + pipeline + WAL plane,
        optionally a RaSystem's node-wide counters, a Counters registry
        (a node's per-server groups + the telemetry_dropped
        self-metric), and a router carrying the reliable-RPC counters."""
        obs = cls(ring_capacity=ring_capacity)
        sampler = sampler or getattr(engine, "_telemetry", None)

        def engine_src() -> dict:
            out: dict = {"lanes": engine.n_lanes,
                         "members": engine.n_members}
            # the autotuner-tunable knobs are stamped NEXT TO the rates
            # they move (rule RA07: no silent knob turns — every knob
            # the controller may touch is in this overview, so a ring
            # window always shows knob value + its effect together)
            dur = engine._dur
            out["pipeline"] = {
                "superstep_k": engine._superstep_k_last,
                "cmds_per_step": engine.max_step_cmds,
                "mesh_shape": engine.mesh_shape(),
                "wal_max_batch_interval_ms": (
                    dur.batch_interval_ms() if dur is not None else -1.0),
                "dispatches_in_flight": (engine._driver.in_flight()
                                         if engine._driver is not None
                                         else 0),
                **engine.pipeline_counters,
            }
            phases = getattr(engine, "phases", None)
            if phases is not None:
                out["phases"] = phases.overview()
            s = sampler or getattr(engine, "_telemetry", None)
            if s is not None:
                out["sampler"] = dict(s.counters)
                if s.last is not None:
                    out["telemetry"] = s.last
            if dur is not None:
                out["wal"] = dur.wal_overview()
            return out

        obs.add_source("engine", engine_src)
        ing = getattr(engine, "_ingress", None)
        if ing is not None:
            # the session tier (ISSUE 10): INGRESS_FIELDS counters +
            # flow gauges as their own source, so ring keys read
            # ``ingress_<field>`` (the SLO/bench_diff namespace)
            obs.add_source("ingress", ing.overview)
            if getattr(ing, "reads_enabled", False):
                # the read lane (ISSUE 20): READ_FIELDS counters +
                # lease coverage as ring keys ``read_<field>`` (the
                # ra_top read panel's namespace)
                obs.add_source("read", ing.read_overview)
        # the device plane (ISSUE 16): recompile sentinel + transfer
        # ledger + memory watermarks as their own source — ring keys
        # read ``device_<field>`` (DEVICE_FIELDS; the namespace the
        # ``steady_state_recompiles`` SLO objective and bench_diff's
        # compile/transfer keys resolve against).  Process-wide on
        # purpose: compiles and live buffers are process facts, not
        # per-engine ones.
        obs.add_source("device", devicewatch.WATCH.overview)
        cls._wire_host_sources(obs, system, counters, router)
        return obs

    @classmethod
    def for_system(cls, system, *, counters=None, router=None,
                   ring_capacity: int = 256) -> "Observatory":
        """Classic-plane wiring (no lane engine): system counters +
        an optional node Counters registry and reliable-RPC router."""
        obs = cls(ring_capacity=ring_capacity)
        cls._wire_host_sources(obs, system, counters, router)
        return obs

    @staticmethod
    def _wire_host_sources(obs: "Observatory", system, counters,
                           router=None) -> None:
        """The system/counters source wiring shared by both factories —
        one definition keeps the engine-path and classic-path snapshots
        field-for-field comparable."""
        if system is not None:
            obs.add_source("system", lambda: {
                "counters": system.counters(),
                "engine_pipeline": {
                    "superstep_k": system.superstep_k,
                    "dispatch_ahead": system.dispatch_ahead,
                    "wal_max_batch_interval_ms": getattr(
                        system, "wal_max_batch_interval_ms", -1.0),
                },
            })
        if counters is not None:
            obs.add_source("counters", lambda: {
                **counters.overview(), "self": counters.self_metrics()})
        if router is not None and \
                getattr(router, "rpc_counters", None) is not None:
            # the reliable control plane's RPC_FIELDS (retry/dedup/
            # unreachable...) flow through _flatten_numeric into the
            # Prometheus exposition and the time-series ring exactly
            # like the per-shard WAL stats (ISSUE 7 satellite; the
            # round-trip is test-pinned)
            obs.add_source("rpc", lambda: dict(router.rpc_counters))
        from .blackbox import RECORDER
        # the flight recorder's health + last incident ride every
        # snapshot so a stalled soak is explainable from the live view
        # (ra_top's incident footer reads this)
        obs.add_source("blackbox", RECORDER.overview)

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Merge every source into one dict, append the numeric
        flattening to the time-series ring, and return the snapshot.
        A failing source contributes an ``error`` entry instead of
        killing the export (observability must not crash the plane it
        observes)."""
        self._seq += 1
        snap: dict = {"seq": self._seq, "ts": time.time()}
        for name, fn in self._sources.items():
            try:
                snap[name] = fn()
            except Exception as exc:  # noqa: BLE001 — degrade, don't die
                snap[name] = {"error": repr(exc)[:200]}
        self._ring.append((snap["ts"], _flatten_numeric(snap)))
        return snap

    def ring(self) -> list:
        """The (ts, flat-numeric-dict) time series, oldest first."""
        return list(self._ring)

    #: flat-key patterns whose values are MONOTONE counters: a negative
    #: window delta on one of these is a counter reset (engine restart,
    #: a fresh bridge adopting the Observatory's source names) and must
    #: yield an OMITTED rate, never a negative one — a burn-rate
    #: evaluator fed a huge negative "rate" across a restart window
    #: would mis-verdict every objective that reads it.  Suffix-
    #: anchored where a looser match would swallow a gauge: plain
    #: substring "dispatches" also matches the dispatches_in_flight
    #: DEPTH gauge, whose negative drift (pipeline draining) is real
    #: signal a consumer must keep seeing.
    _MONOTONE_SUFFIXES = (
        "committed_total", "dispatches", "inner_steps", "_writes",
        "batches", "_syncs", "events", "_count", "total_ms",
        "blocks_staged", "seq", "telemetry_steps", "wal_files",
        "window_syncs", "leader_changes", "bytes_written",
        # ingress plane counters (ISSUE 10) — suffix-anchored so the
        # ingress_queue_rows / ingress_level DEPTH gauges keep their
        # negative drift (the dispatches_in_flight lesson)
        "submitted", "_accepted", "dup_dropped", "slow_signals",
        "_deferred", "_rejected", "shed_rows", "blocks_built",
        "block_rows", "reconnects", "credits_released",
        # device plane (ISSUE 16) — "compiles" also anchors
        # device_recompiles (the steady_state_recompiles SLO rate).
        # device_live_buffers stays an un-hinted gauge; live_bytes is
        # swallowed by the "bytes" infix, which only omits its
        # negative drift from rates — the gauge VALUE in snapshots is
        # untouched (rates of a census gauge are not a signal anyway)
        "compiles", "compile_ms", "_freed", "_samples",
    )
    _MONOTONE_INFIXES = (
        "bytes", "samples_", "encoded_", "readback_", "rpc_",
        "faults_", "elections_",
    )

    @classmethod
    def _is_monotone_key(cls, key: str) -> bool:
        return any(key.endswith(s) for s in cls._MONOTONE_SUFFIXES) \
            or any(h in key for h in cls._MONOTONE_INFIXES)

    def window_rates(self, span: int = 1, end: int = -1,
                     keys=None) -> dict:
        """Per-second deltas of every numeric key between ring entries
        ``span`` windows apart (default: the last two snapshots).
        Monotone counters (committed_total, dispatches, wal writes...)
        read as true rates; gauges read as drift — callers pick their
        keys from the field registry (docs/OBSERVABILITY.md).

        ``span`` > 1 rates over a wider window (``ring[end-span]`` ->
        ``ring[end]``) — the SLO engine's multi-window burn-rate input;
        ``end`` indexes the newer entry (negative from the newest).

        Counter-reset guard: a key the monotone-hint list recognises
        whose delta went NEGATIVE (an engine restart zeroed its
        counters mid-ring) is omitted — absent beats a bogus negative
        rate, same contract as the stale-sample omission below.

        ``engine_telemetry_*`` keys rate over the SAMPLER's own sample
        window (the embedded sample's ``ts``): snapshots taken faster
        than the harvest cadence re-embed the same sample, and the
        snapshot-ts delta would read a running engine as 0 cmds/s.
        With no fresh sample between the two snapshots those keys are
        omitted entirely — absent beats misleadingly zero.

        ``keys`` restricts the computation to an iterable of flat keys
        — the SLO engine's per-objective evaluation sweeps many ring
        windows per verdict, and differentiating every key of every
        window would put O(windows x keys) dict work on the snapshot
        path for the handful it reads."""
        span = max(1, int(span))
        n = len(self._ring)
        if end < 0:
            end = n + end
        lo = end - span
        if lo < 0 or end >= n or n < 2:
            return {}
        (t0, a), (t1, b) = self._ring[lo], self._ring[end]
        dt = max(t1 - t0, 1e-9)
        ts_key = "engine_telemetry_ts"
        tdt = (b[ts_key] - a[ts_key]
               if ts_key in a and ts_key in b else 0.0)
        out: dict = {}
        for k in (b if keys is None else keys):
            if k not in a or k not in b:
                continue
            delta = b[k] - a[k]
            if delta < 0 and self._is_monotone_key(k):
                continue  # counter reset across an engine restart
            if k.startswith("engine_telemetry_"):
                if tdt > 1e-9 and k != ts_key:
                    out[k] = round(delta / tdt, 4)
                continue
            out[k] = round(delta / dt, 4)
        return out

    def series(self, key: str) -> list:
        return [v[key] for _t, v in self._ring if key in v]

    def percentile(self, key: str, q: float) -> Optional[float]:
        """q in [0,1] percentile of ``key`` over the ring window."""
        s = sorted(self.series(key))
        if not s:
            return None
        return s[min(len(s) - 1, int(len(s) * q))]

    # -- exports -----------------------------------------------------------

    def prometheus(self, snap: Optional[dict] = None) -> str:
        """Prometheus text exposition of a snapshot (fresh one by
        default): scalars flatten to ``ra_tpu_<path>``, the commit-lag
        histogram becomes a cumulative ``_bucket{le=...}`` family, and
        the top-K offender arrays become lane-labelled gauges.
        Round-trip pinned by tests/test_telemetry.py via
        :func:`parse_prometheus`."""
        snap = snap if snap is not None else self.snapshot()
        lines = ["# ra-tpu Observatory exposition",
                 f"# seq {snap.get('seq', 0)}"]
        flat = _flatten_numeric(snap)
        for key in sorted(flat):
            lines.append(f"ra_tpu_{key} {_fmt_num(flat[key])}")
        tel = snap.get("engine", {}).get("telemetry")
        if tel:
            hist = tel.get("commit_lag_hist")
            if hist:
                # log2 buckets: bucket 0 = lag 0, bucket b = lag <
                # 2^b; cumulative counts per the exposition format
                cum = 0
                for b, count in enumerate(hist):
                    cum += count
                    le = "0" if b == 0 else (
                        "+Inf" if b == len(hist) - 1 else str(2 ** b - 1))
                    lines.append(
                        'ra_tpu_engine_commit_lag_bucket{le="%s"} %d'
                        % (le, cum))
                lines.append(f"ra_tpu_engine_commit_lag_count {cum}")
            lanes = tel.get("top_lanes") or []
            for rank, lane in enumerate(lanes):
                for field in ("top_commit_lag", "top_apply_lag",
                              "top_stall_steps"):
                    vals = tel.get(field) or []
                    if rank < len(vals):
                        lines.append(
                            'ra_tpu_engine_%s{lane="%d",rank="%d"} %s'
                            % (field, lane, rank, _fmt_num(vals[rank])))
        phases = snap.get("engine", {}).get("phases") or {}
        for pname in sorted(phases):
            ph = phases[pname]
            if not isinstance(ph, dict):
                continue
            hist = ph.get("hist")
            if not hist:
                continue
            # log2-ms buckets: bucket 0 = <1ms, bucket b = <2^b ms
            cum = 0
            for b, count in enumerate(hist):
                cum += count
                le = "+Inf" if b == len(hist) - 1 else str(2 ** b)
                lines.append(
                    'ra_tpu_engine_phase_ms_bucket{phase="%s",le="%s"}'
                    ' %d' % (pname, le, cum))
        return "\n".join(lines) + "\n"

    def to_jsonl(self, path: str, *, max_lines: int = 512) -> dict:
        """Append a fresh snapshot to a bounded JSONL ring at ``path``
        (compacted back to ``max_lines`` once it doubles) — what
        ``tools/soak.py --obs`` writes and ``tools/ra_top.py`` follows."""
        snap = self.snapshot()
        append_jsonl_ring(path, snap, max_lines=max_lines)
        return snap


# ---------------------------------------------------------------------------
# helpers: flattening, exposition formatting, parsing, JSONL ring
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _flatten_numeric(obj: Any, prefix: str = "") -> dict:
    """Nested dicts -> {'a_b_c': float} for scalar numeric leaves.
    Lists of dicts flatten with their index (``wal_shards_0_...`` —
    the per-shard fsync stats must reach the exposition and the ring);
    lists of scalars and strings are skipped (histograms and top-K
    arrays get their own labelled exposition families)."""
    out: dict = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = _NAME_RE.sub("_", str(k))
            out.update(_flatten_numeric(v, f"{prefix}{key}_"))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            if isinstance(v, dict):
                out.update(_flatten_numeric(v, f"{prefix}{i}_"))
    elif isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix[:-1]] = float(obj)
    return out


def _fmt_num(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


#: exposition line: name{labels} value — the value token is validated
#: by float() below, which accepts every form the format allows
#: (negative exponents like 5e-05, +Inf, NaN) without a lookalike
#: character-class regex drifting out of sync with it
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


def parse_prometheus(text: str) -> dict:
    """Parse Prometheus text exposition into {(name, labels): float}.
    Raises ValueError on any malformed non-comment line — the
    round-trip test runs every Observatory export through this."""
    out: dict = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            raise ValueError(f"unparsable exposition line: {raw!r}")
        name, labels, val = m.group(1), m.group(2) or "", m.group(3)
        try:
            out[(name, labels)] = float(val)
        except ValueError:
            raise ValueError(
                f"unparsable exposition value: {raw!r}") from None
    return out


#: per-path line-count cache so the steady-state append is ONE
#: buffered write — re-reading the whole ring per append would put
#: O(file) disk reads on the harvest path that observers (and through
#: them the dispatch loop) ride
_RING_LINES: dict = {}


def append_jsonl_ring(path: str, obj: dict, *, max_lines: int = 512) -> None:
    """Append one JSON line; once the file exceeds ``2*max_lines``
    lines, atomically compact it down to the newest ``max_lines`` (a
    bounded ring that tail-followers can read mid-compaction).  The
    line count is tracked in memory per path: the common call is one
    buffered append (no fsync, no re-read); the file is only read back
    at first touch of an existing ring and at compaction."""
    line = json.dumps(obj, separators=(",", ":"))
    count = _RING_LINES.get(path)
    if count is None:
        try:
            with open(path) as f:
                count = sum(1 for _ in f)
        except OSError:
            count = 0
    with open(path, "a") as f:
        f.write(line + "\n")
    count += 1
    if count > 2 * max_lines:
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            _RING_LINES[path] = count
            return
        tmp = path + ".compact"
        with open(tmp, "w") as f:
            f.writelines(lines[-max_lines:])
        os.replace(tmp, path)
        count = min(len(lines), max_lines)
    _RING_LINES[path] = count


def read_jsonl_tail(path: str, n: int = 1) -> list:
    """Newest ``n`` parsable snapshots from a JSONL ring (oldest first
    within the result); tolerant of a torn last line mid-append."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return []
    out = []
    for raw in lines[-(n + 1):]:
        try:
            out.append(json.loads(raw))
        except ValueError:
            continue
    return out[-n:]
