"""Public API — the ra.erl equivalent (cited: /root/reference/src/ra.erl).

Functions mirror the reference surface: start_cluster/4 (:374),
process_command/3 (:804-828) with follower->leader redirect,
pipeline_command/4 (:886-896), local_query (:962), leader_query (:1012),
consistent_query (:1051), members, add_member (:593), remove_member (:628),
trigger_election (:660), transfer_leadership (:687), delete_cluster (:556),
restart_server (:188), key_metrics (:1229).

All calls are synchronous wrappers around effect-routed futures; the
engine-based deployments expose the same verbs through the lane engine's
host API instead.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Optional

from .core.types import (
    CommandResult,
    ConsistentQueryEvent,
    ErrorResult,
    ForceElectionEvent,
    ForceMemberChangeEvent,
    JoinCommand,
    LeaveCommand,
    ClusterDeleteCommand,
    Membership,
    Priority,
    ReplyMode,
    ServerConfig,
    ServerId,
    TransferLeadershipEvent,
    UserCommand,
)
from . import trace
from .blackbox import record
from .node import DEFAULT_ROUTER, Future, LocalRouter, RaNode


def new_uid(prefix: str = "") -> str:
    """Unique, filesystem-safe server UID (ra:new_uid/1 :735).  The
    caller-supplied prefix (typically a server name) is sanitized to the
    base64url alphabet the storage layer enforces — uids name on-disk
    directories (RaSystem.validate_uid)."""
    import re
    safe = re.sub(r"[^A-Za-z0-9_\-=]", "_", prefix)
    return f"{safe}{uuid.uuid4().hex[:12]}"


def node_call(node_name: str, op: str, args: dict,
              router: Optional[LocalRouter] = None,
              timeout: float = 60.0) -> Any:
    """Node-lifecycle RPC — the rpc:call of ra_server_sup_sup.erl:42-130.
    Reaches a LOCAL RaNode directly; a REMOTE one rides the reliable
    control-plane RPC layer (transport/rpc.py): a stable request id
    retried with backoff until the deadline, deduplicated receiver-side
    so the op executes at most once, with reconnect-aware routing past
    peer restarts.  Raises the typed triad — ``Unreachable`` (no route
    / detector-down peer), ``RpcTimeout`` (reachable but unanswered by
    the deadline), ``RemoteError`` (the remote executor failed) — all
    RuntimeError subclasses; RpcTimeout is also a TimeoutError."""
    from .core.types import NODE_SCOPE, NodeControlEvent
    from .transport.rpc import reliable_node_call
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(node_name)
    if node is not None:
        fut = Future()
        node.deliver(ServerId(NODE_SCOPE, node_name),
                     NodeControlEvent(op, args, from_=fut))
        return fut.wait(timeout)
    return reliable_node_call(router, node_name, op, args, timeout=timeout)


def _config_snapshot_for(cluster_name: str, spec: tuple, sid: ServerId,
                         server_ids: list, uid: str,
                         election_timeout_ms: int, tick_interval_ms: int,
                         membership: Membership = Membership.VOTER) -> dict:
    return {
        "server_id": tuple(sid),
        "uid": uid,
        "cluster_name": cluster_name,
        "initial_members": tuple(tuple(m) for m in server_ids),
        "election_timeout_ms": election_timeout_ms,
        "tick_interval_ms": tick_interval_ms,
        "membership": membership.value,
        "machine_spec": spec,
    }


def start_cluster(cluster_name: str, machine_factory: Any,
                  server_ids: list, router: Optional[LocalRouter] = None,
                  election_timeout_ms: int = 100,
                  tick_interval_ms: int = 100,
                  log_init_args: Optional[dict] = None) -> list:
    """Start every member and trigger an election (ra:start_cluster/5 :374).

    ``machine_factory`` is either a zero-arg callable returning a
    Machine (members on LOCAL RaNodes only), or a machine SPEC from
    :func:`ra_tpu.machines.machine_spec` — with a spec, members whose
    node is not on this process's router are started REMOTELY over the
    control plane (the multi-node ra:start_cluster flow, which the
    reference routes through ra_server_sup_sup's rpc:call).

    Formation follows the reference (ra.erl:397-409): the cluster forms
    when MORE THAN HALF of the members started — stragglers can be
    retried with start_server later; on failure to form, every member
    that did start is force-deleted and RuntimeError('cluster_not_
    formed') is raised."""
    from .machines import is_machine_spec, resolve_machine
    router = router or DEFAULT_ROUTER
    spec = machine_factory if is_machine_spec(machine_factory) else None
    started: list = []
    failures: list = []
    for sid in server_ids:
        node = router.nodes.get(sid.node)
        uid = new_uid(f"{sid.name}_")
        try:
            if node is None:
                if spec is None:
                    raise RuntimeError(
                        f"no RaNode registered for {sid.node} and no "
                        "machine spec to start it remotely")
                res = node_call(sid.node, "start_server", {
                    "config": _config_snapshot_for(
                        cluster_name, spec, sid, server_ids, uid,
                        election_timeout_ms, tick_interval_ms)}, router)
                if isinstance(res, ErrorResult):
                    raise RuntimeError(
                        f"remote start of {sid} failed: {res.reason}")
            else:
                machine = resolve_machine(spec) if spec is not None \
                    else machine_factory()
                cfg = ServerConfig(server_id=sid, uid=uid,
                                   cluster_name=cluster_name,
                                   initial_members=tuple(server_ids),
                                   machine=machine,
                                   election_timeout_ms=election_timeout_ms,
                                   tick_interval_ms=tick_interval_ms,
                                   log_init_args=dict(log_init_args or {}))
                node.start_server(cfg)
        except (RuntimeError, TimeoutError, ValueError) as exc:
            failures.append((sid, exc))
            continue
        started.append(sid)
    if len(started) * 2 <= len(server_ids):
        # cluster_not_formed: force-delete whatever did start
        # (ra.erl:407-409 — leftovers would be amnesiac split fragments)
        for sid in started:
            try:
                force_delete_server(sid, router=router)
            except (RuntimeError, TimeoutError):
                pass
        raise RuntimeError(
            f"cluster_not_formed: {len(started)}/{len(server_ids)} "
            f"members started; failures: "
            f"{[(str(s), repr(e)[:120]) for s, e in failures]}")
    # nudge a started member so a fresh cluster elects promptly
    trigger_election(started[0], router)
    return started


def start_server(cluster_name: str, machine_factory: Any,
                 server_id: ServerId, initial_members: list,
                 router: Optional[LocalRouter] = None,
                 election_timeout_ms: int = 100,
                 tick_interval_ms: int = 100,
                 membership: Membership = Membership.VOTER,
                 log_init_args: Optional[dict] = None) -> Any:
    """Start one member without electing (ra:start_server/4) — used before
    add_member to bring the new member up.  Accepts a machine spec like
    start_cluster, and starts on remote nodes over the control plane."""
    from .machines import is_machine_spec, resolve_machine
    router = router or DEFAULT_ROUTER
    spec = machine_factory if is_machine_spec(machine_factory) else None
    node = router.nodes.get(server_id.node)
    uid = new_uid(f"{server_id.name}_")
    if node is None:
        if spec is None:
            raise RuntimeError(
                f"no RaNode registered for {server_id.node} and no "
                "machine spec to start it remotely")
        res = node_call(server_id.node, "start_server", {
            "config": _config_snapshot_for(
                cluster_name, spec, server_id, list(initial_members), uid,
                election_timeout_ms, tick_interval_ms, membership)},
            router)
        if isinstance(res, ErrorResult):
            raise RuntimeError(f"remote start of {server_id} failed: "
                               f"{res.reason}")
        return res
    machine = resolve_machine(spec) if spec is not None \
        else machine_factory()
    cfg = ServerConfig(server_id=server_id,
                       uid=uid,
                       cluster_name=cluster_name,
                       initial_members=tuple(initial_members),
                       machine=machine,
                       election_timeout_ms=election_timeout_ms,
                       tick_interval_ms=tick_interval_ms,
                       membership=membership,
                       log_init_args=dict(log_init_args or {}))
    return node.start_server(cfg)


def restart_server(server_id: ServerId,
                   router: Optional[LocalRouter] = None,
                   mutable_config: Optional[dict] = None) -> Any:
    """Stop and re-init one member over its existing log
    (ra:restart_server/2,3 :188-199).  For members on remote nodes this
    goes over the control plane, recovering the persisted config from
    the target node's disk (restart_server_rpc + recover_config,
    ra_server_sup_sup.erl:80-103).  ``mutable_config`` merges
    whitelisted keys (RaNode.MUTABLE_CONFIG_KEYS — the reference's
    ?MUTABLE_CONFIG_KEYS) into the recovered config."""
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(server_id.node)
    if node is not None:
        return node.restart_server(server_id.name, mutable=mutable_config)
    res = node_call(server_id.node, "restart_server",
                    {"name": server_id.name, "mutable": mutable_config},
                    router)
    if isinstance(res, ErrorResult):
        raise RuntimeError(f"remote restart of {server_id} failed: "
                           f"{res.reason}")
    return res


def stop_server(server_id: ServerId,
                router: Optional[LocalRouter] = None) -> None:
    """Gracefully stop one member; its durable state stays on disk
    (ra:stop_server/2).  Remote members stop over the control plane."""
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(server_id.node)
    if node is not None:
        node.stop_server(server_id.name)
        return
    node_call(server_id.node, "stop_server", {"name": server_id.name},
              router)


def force_delete_server(server_id: ServerId, system=None,
                        router: Optional[LocalRouter] = None) -> None:
    """Stop one member and wipe its durable footprint without consensus
    (ra:force_delete_server/2 — used when the cluster is already gone).
    Pass the member's RaSystem to delete its on-disk data.  Works on a
    stopped member too: the uid then resolves through the system
    directory rather than the live shell.  For a member on a REMOTE
    node, the control plane deletes against the target node's own
    system (no ``system`` argument needed)."""
    router = router or DEFAULT_ROUTER
    if router.nodes.get(server_id.node) is None:
        res = node_call(server_id.node, "force_delete_server",
                        {"name": server_id.name}, router)
        if isinstance(res, ErrorResult):
            raise RuntimeError(f"remote force_delete of {server_id} "
                               f"failed: {res.reason}")
        return
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    uid = shell.server.cfg.uid if shell is not None else None
    if uid is None and system is not None:
        uid = system.directory.where_is(server_id.name)
    node.kill_server(server_id.name)
    node.forget_server(server_id.name)
    node.wipe_member_footprint(uid, system)


def _node_of(sid: ServerId, router: LocalRouter) -> RaNode:
    node = router.nodes.get(sid.node)
    if node is None:
        raise RuntimeError(f"node {sid.node} is not running")
    return node


def _leader_call(seed: ServerId, make_event: Callable[["Future"], Any],
                 router: LocalRouter, timeout: float,
                 retry_reasons: tuple = (),
                 timeout_msg: str = "ra: command not completed",
                 trace_ctx: Optional[str] = None) -> Any:
    """Shared redirect/retry loop for leader-targeted calls — the
    equivalent of ra_server_proc's leader_call redirect machinery
    (ra_server_proc.erl:242-263).  make_event builds the event to submit
    given the reply Future.  not_leader redirects follow the hinted
    leader; reasons in retry_reasons back off and retry in place.
    ``trace_ctx`` records one ``cmd.submit`` hop event per attempt —
    redirects and retries become visible in the command's timeline."""
    deadline = time.monotonic() + timeout
    target = seed
    last_err: Any = None
    attempt = 0
    while time.monotonic() < deadline:
        node = router.nodes.get(target.node)
        attempt += 1
        if trace_ctx is not None:
            record("cmd.submit", trace=trace_ctx, target=str(target),
                   attempt=attempt,
                   transport="local" if node is not None else "remote")
        if node is not None:
            fut = Future()
            if not node.submit(target.name, make_event(fut)):
                last_err = ErrorResult("noproc", None)
                target = seed
                time.sleep(0.01)
                continue
        else:
            # remote node: full cross-host call (TcpRouter); in-process
            # routers have no reach and report noproc
            fut = router.remote_call(target, make_event)
            if fut is None:
                last_err = ErrorResult("noproc", None)
                target = seed
                time.sleep(0.01)
                continue
        try:
            result = fut.wait(min(timeout, deadline - time.monotonic()))
        except TimeoutError:
            last_err = ErrorResult("timeout", None)
            if hasattr(router, "forget_call"):
                router.forget_call(fut)
            break
        if isinstance(result, ErrorResult):
            last_err = result
            if result.reason == "not_leader":
                if result.leader is not None and result.leader != target:
                    target = result.leader
                else:
                    time.sleep(0.01)  # election in progress
                continue
            if result.reason in retry_reasons:
                time.sleep(0.02)
                continue
        return result
    raise TimeoutError(f"{timeout_msg}: {last_err}")


def process_command(server_id: ServerId, data: Any,
                    router: Optional[LocalRouter] = None,
                    timeout: float = 5.0,
                    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS,
                    reply_from: Any = None,
                    trace_ctx: Optional[str] = None) -> Any:
    """Send a command and await consensus (ra:process_command/3 :804-828),
    following not_leader redirects like the reference's leader_call loop.

    Every command gets a causal trace context at this ingress (ISSUE 7):
    ``trace_ctx`` to supply one (a client session propagating its own),
    else a deterministic id is minted here.  The context rides the
    command object end to end; hop events land in the flight recorder
    and ``tools/ra_trace.py`` reconstructs the timeline.

    ``reply_from`` picks which member answers (the reply_from command
    option, ra.erl:786-823): None/"leader" (default), ("member", sid),
    or "local" — resolved client-side to a cluster member hosted on one
    of THIS process's nodes (falling back to the leader when none is).
    A non-leader replier needs the reply handle to reach that member's
    log copy: true for in-process routing (objects travel unpickled)
    and for TCP rcall handles (tuples survive the wire/durable image);
    recovery replays suppress reply effects everywhere regardless."""
    from .core.types import CommandEvent
    router = router or DEFAULT_ROUTER
    ctx = trace_ctx or trace.new_trace_ctx()
    record("cmd.ingress", trace=ctx, op="process_command",
           target=str(server_id))
    if reply_from == "local":
        # find ANY member of the seed's cluster hosted by one of this
        # process's nodes — the seed itself need not be local; shells
        # know their whole cluster, so a co-located sibling resolves it
        reply_from = None
        for node in router.nodes.values():
            for shell in list(node.shells.values()):
                srv = shell.server
                if server_id == srv.id or server_id in srv.cluster:
                    reply_from = ("member", srv.id)
                    break
            if reply_from is not None:
                break
    elif reply_from == "leader":
        reply_from = None
    return _leader_call(
        server_id,
        lambda fut: CommandEvent(UserCommand(data, reply_mode=reply_mode,
                                             reply_from=reply_from,
                                             trace=ctx),
                                 from_=fut),
        router, timeout, timeout_msg="ra: command not completed",
        trace_ctx=ctx)


def pipeline_command(server_id: ServerId, data: Any, correlation: Any = None,
                     notify_to: Any = None,
                     priority: Priority = Priority.LOW,
                     router: Optional[LocalRouter] = None,
                     trace_ctx: Any = None) -> None:
    """Fire-and-forget with applied-notification (ra:pipeline_command/4
    :886-896).  notify_to receives [(correlation, reply)] batches.
    Like process_command, the ingress mints (or adopts) a trace context
    that rides the command through the flight-recorder hop events —
    pass ``trace_ctx=False`` to pipeline UNTRACED (the reference's cast
    carries no tracing either): at 100k cmds/s the per-command mint +
    ingress/append/apply hop records are real budget, and a bulk
    pipeliner can opt out without touching anyone else's traces.

    ``server_id`` on a node this process hosts submits through the
    node's low-priority flush; a REMOTE member (TcpRouter reach, ISSUE
    13) buffers through the router's pipeline fan-in and ships as
    multi-command {commands, Batch} frames — the cross-host twin of
    the node-side flush."""
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(server_id.node)
    if trace_ctx is False:
        ctx = None
    else:
        ctx = trace_ctx or trace.new_trace_ctx()
        record("cmd.ingress", trace=ctx, op="pipeline_command",
               target=str(server_id))
    cmd = UserCommand(data, reply_mode=ReplyMode.NOTIFY,
                      correlation=correlation, notify_to=notify_to,
                      trace=ctx)
    if node is None:
        cast = getattr(router, "pipeline_cast", None)
        if cast is None:
            raise RuntimeError(f"node {server_id.node} is not running")
        cast(server_id, cmd)
        return
    node.submit_command(server_id.name, cmd, None, priority=priority)


def pipeline_commands(server_id: ServerId, items: list,
                      notify_to: Any = None,
                      priority: Priority = Priority.LOW,
                      router: Optional[LocalRouter] = None,
                      trace_ctx: Any = False) -> None:
    """Burst twin of pipeline_command (ISSUE 18): ``items`` is
    ``[(data, correlation), ...]``, all notify-mode toward one
    ``notify_to``.  The whole burst pays ONE ingress call, one router
    lock cycle, and (cross-host) one pipeline-buffer submission —
    at pipelined rates the per-command pipeline_command round spends
    more time in call/lock/wake overhead than in the work itself, and
    that overhead lands on the same core budget as the measured plane.
    Untraced by default (the bulk-pipeliner opt-out documented on
    pipeline_command); pass ``trace_ctx=None`` to mint per-command
    contexts."""
    from .codec import build_user
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(server_id.node)
    if trace_ctx is False:
        cmds = [build_user(data, ReplyMode.NOTIFY, corr, notify_to,
                           None, None) for data, corr in items]
    else:
        cmds = []
        for data, corr in items:
            ctx = trace_ctx or trace.new_trace_ctx()
            record("cmd.ingress", trace=ctx, op="pipeline_command",
                   target=str(server_id))
            cmds.append(build_user(data, ReplyMode.NOTIFY, corr,
                                   notify_to, None, None, ctx))
    if node is None:
        cast_many = getattr(router, "pipeline_cast_many", None)
        if cast_many is not None:
            cast_many(server_id, cmds)
            return
        cast = getattr(router, "pipeline_cast", None)
        if cast is None:
            raise RuntimeError(f"node {server_id.node} is not running")
        for cmd in cmds:
            cast(server_id, cmd)
        return
    node.submit_commands(server_id.name, cmds, priority=priority)


def ping(server_id: ServerId,
         router: Optional[LocalRouter] = None) -> tuple:
    """Local liveness probe: ("pong", raft_state) for a member hosted
    on THIS process's router (the ra_server_proc:ping role, :238-240).
    Like local_query/key_metrics, this reads the shell directly and
    does not reach members on remote nodes — probe those from their own
    node (the per-node ops model the TCP workers use)."""
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    if shell is None:
        raise RuntimeError(f"no such server {server_id}")
    return ("pong", shell.server.raft_state.value)


def local_query(server_id: ServerId, query_fn: Callable,
                router: Optional[LocalRouter] = None,
                condition: Any = None, timeout: float = 5.0) -> Any:
    """Query this member's machine state directly (ra:local_query :962).

    ``condition=("applied", (idx, term))`` delays evaluation until this
    member has applied at least idx with a matching term (the
    query_condition option, ra.erl:115-131 — read-your-writes on a
    follower); raises TimeoutError if the condition never holds, and
    returns an ErrorResult if idx was applied under a DIFFERENT term
    (the awaited entry was overwritten)."""
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    if shell is None:
        raise RuntimeError(f"no such server {server_id}")
    srv = shell.server
    if condition is not None:
        kind, (idx, term) = condition
        if kind != "applied":
            raise ValueError(f"unknown query condition {kind!r}")
        deadline = time.monotonic() + timeout
        while srv.last_applied < idx:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"ra: local_query condition applied>={idx} not met "
                    f"(at {srv.last_applied})")
            time.sleep(0.005)
        actual = srv.log.fetch_term(idx)
        if actual is not None and term is not None and actual != term:
            return ErrorResult("condition_term_mismatch", srv.leader_id)
    node.counters.incr(srv.cfg.uid, "local_queries")
    return CommandResult(srv.last_applied, srv.current_term,
                         query_fn(srv.machine_state), srv.leader_id)


def leader_query(any_member: ServerId, query_fn: Callable,
                 router: Optional[LocalRouter] = None,
                 timeout: float = 5.0) -> Any:
    """Query the leader's machine state (ra:leader_query :1012)."""
    router = router or DEFAULT_ROUTER
    leader = _await_leader(any_member, router, timeout)
    return local_query(leader, query_fn, router)


def consistent_query(server_id: ServerId, query_fn: Callable,
                     router: Optional[LocalRouter] = None,
                     timeout: float = 5.0) -> Any:
    """Linearizable read via heartbeat quorum (ra:consistent_query :1051,
    core machinery ra_server.erl:3032-3190)."""
    router = router or DEFAULT_ROUTER
    return _leader_call(
        server_id,
        lambda fut: ConsistentQueryEvent(query_fn, from_=fut),
        router, timeout, timeout_msg="ra: consistent_query timed out")


def members(server_id: ServerId,
            router: Optional[LocalRouter] = None) -> list:
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    if shell is None:
        raise RuntimeError(f"no such server {server_id}")
    return list(shell.server.cluster.keys())


def members_info(server_id: ServerId,
                 router: Optional[LocalRouter] = None,
                 timeout: float = 5.0) -> dict:
    """Per-member replication detail (ra:members_info/1 :1108,
    state_query(members_info), ra_server.erl:2422-2466).  Resolved
    against the LEADER's peer table: match/next/query index, status,
    and membership per member; a follower target is first redirected
    like any leader call."""
    router = router or DEFAULT_ROUTER
    leader = _await_leader(server_id, router, timeout)
    node = _node_of(leader, router)
    shell = node.shells.get(leader.name)
    if shell is None:
        raise RuntimeError(f"no such server {leader}")
    srv = shell.server
    out: dict = {}
    for sid, peer in srv.cluster.items():
        if sid == srv.id:
            out[sid] = {
                "match_index": srv.commit_index,
                "next_index": srv.commit_index + 1,
                "query_index": srv.query_index,
                "status": "normal",
                "membership": srv.membership.value,
            }
        else:
            out[sid] = {
                "match_index": peer.match_index,
                "next_index": peer.next_index,
                "query_index": peer.query_index,
                "status": peer.status.value,
                "membership": peer.membership.value,
            }
    return out


def add_member(server_id: ServerId, new_member: ServerId,
               membership: Membership = Membership.VOTER,
               router: Optional[LocalRouter] = None,
               timeout: float = 5.0) -> Any:
    """One-at-a-time join ('$ra_join', ra.erl:593-602).  The new member's
    server must be started separately (ra:start_server then add_member)."""
    router = router or DEFAULT_ROUTER
    return _member_change(server_id, JoinCommand(new_member, membership),
                          router, timeout)


def remove_member(server_id: ServerId, old_member: ServerId,
                  router: Optional[LocalRouter] = None,
                  timeout: float = 5.0) -> Any:
    router = router or DEFAULT_ROUTER
    return _member_change(server_id, LeaveCommand(old_member), router,
                          timeout)


def _member_change(server_id: ServerId, cmd: Any, router: LocalRouter,
                   timeout: float) -> Any:
    from .core.types import CommandEvent
    return _leader_call(
        server_id, lambda fut: CommandEvent(cmd, from_=fut), router, timeout,
        retry_reasons=("cluster_change_not_permitted",),
        timeout_msg="ra: member change timed out")


def delete_cluster(server_id: ServerId,
                   router: Optional[LocalRouter] = None,
                   timeout: float = 5.0) -> Any:
    """Orderly cluster teardown ('$ra_cluster' delete, ra.erl:556)."""
    router = router or DEFAULT_ROUTER
    from .core.types import CommandEvent
    return _leader_call(
        server_id,
        lambda fut: CommandEvent(ClusterDeleteCommand(), from_=fut),
        router, timeout, timeout_msg="ra: delete_cluster timed out")


def trigger_election(server_id: ServerId,
                     router: Optional[LocalRouter] = None) -> None:
    router = router or DEFAULT_ROUTER
    node = router.nodes.get(server_id.node)
    if node is not None:
        node.submit(server_id.name, ForceElectionEvent())
        return
    # remote member: the event travels the data plane like any RPC
    if not router.send("?", server_id, ForceElectionEvent()):
        raise RuntimeError(
            f"trigger_election: {server_id} is unreachable")


def force_shrink_members_to_current_member(
        server_id: ServerId,
        router: Optional[LocalRouter] = None,
        timeout: float = 5.0) -> Any:
    """Disaster recovery: shrink ``server_id``'s cluster to itself and
    self-elect (ra_server_proc:force_shrink_members_to_current_member,
    :234-236).  For permanent majority loss ONLY — the surviving member
    unilaterally rewrites membership, so using it while the others are
    merely partitioned manufactures split-brain.  Raises if the member
    refuses (e.g. it is parked in await_condition behind a dead WAL —
    an operator must never mistake a refused escape hatch for a
    successful one)."""
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    fut = Future()
    if not node.submit(server_id.name, ForceMemberChangeEvent(from_=fut)):
        raise RuntimeError(f"force_shrink: no such server {server_id} "
                           "(noproc)")
    result = fut.wait(timeout)
    if isinstance(result, ErrorResult):
        raise RuntimeError(
            f"force_shrink refused by {server_id}: {result.reason}")
    return result


def transfer_leadership(server_id: ServerId, target: ServerId,
                        router: Optional[LocalRouter] = None,
                        timeout: float = 5.0) -> Any:
    router = router or DEFAULT_ROUTER
    leader = _await_leader(server_id, router, timeout)
    node = _node_of(leader, router)
    fut = Future()
    node.submit(leader.name, TransferLeadershipEvent(target, from_=fut))
    return fut.wait(timeout)


def _await_leader(seed: ServerId, router: LocalRouter,
                  timeout: float) -> ServerId:
    """Resolve the current leader, polling through elections."""
    deadline = time.monotonic() + timeout
    target = seed
    while time.monotonic() < deadline:
        node = router.nodes.get(target.node)
        shell = node.shells.get(target.name) if node else None
        if shell is not None:
            srv = shell.server
            if srv.raft_state == srv.raft_state.LEADER:
                return target
            if srv.leader_id is not None:
                if srv.leader_id == target:
                    return target
                target = srv.leader_id
                continue
        time.sleep(0.01)
    raise TimeoutError(f"ra: no leader found via {seed}")


def aux_command(server_id: ServerId, cmd: Any,
                router: Optional[LocalRouter] = None,
                timeout: float = 5.0) -> Any:
    """Route a command to the machine's handle_aux on a specific member
    (ra:aux_command)."""
    from .core.types import AuxCommandEvent
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    fut = Future()
    if not node.submit(server_id.name, AuxCommandEvent(cmd, from_=fut)):
        raise RuntimeError(f"no such server {server_id}")
    return fut.wait(timeout)


def cast_aux_command(server_id: ServerId, cmd: Any,
                     router: Optional[LocalRouter] = None) -> None:
    from .core.types import AuxCommandEvent
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    node.submit(server_id.name, AuxCommandEvent(cmd))


def member_overview(server_id: ServerId,
                    router: Optional[LocalRouter] = None) -> dict:
    """Full state dump of one member (ra:member_overview)."""
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    if shell is None:
        return {"state": "noproc"}
    return shell.server.overview()


def overview(router: Optional[LocalRouter] = None) -> dict:
    """Node-level overview across all local RaNodes (ra:overview), plus
    process-wide io metrics (the ra_io_metrics ETS role).

    Shape: ``{"nodes": {node_name: node_overview}, "io": io_stats}``.
    NOTE: before round 1's io-stats addition this returned the node map at
    top level; callers iterating node names must use ``overview()["nodes"]``.
    """
    from .native import IO

    router = router or DEFAULT_ROUTER
    return {
        "nodes": {name: node.overview()
                  for name, node in router.nodes.items()},
        "io": IO.stats(),
    }


def key_metrics(server_id: ServerId,
                router: Optional[LocalRouter] = None) -> dict:
    """Read metrics without touching the server's event loop
    (ra:key_metrics :1229-1257)."""
    router = router or DEFAULT_ROUTER
    node = _node_of(server_id, router)
    shell = node.shells.get(server_id.name)
    if shell is None:
        return {"state": "noproc"}
    srv = shell.server
    last = srv.log.last_index_term()
    lw = srv.log.last_written()
    # counters = shell fields + core stats + log-subsystem fields, the
    # flat union the reference samples from its single counter array
    counters = dict(node.counters.fetch(srv.cfg.uid) or {})
    counters.update(srv.stats)
    log_metrics = getattr(srv.log, "log_metrics", None)
    if log_metrics is not None:
        counters.update(log_metrics())
    checkpoint_index = getattr(srv.log, "checkpoint_index", lambda: 0)()
    return {
        "state": srv.raft_state.value,
        "raft_state": srv.raft_state.value,
        "leader": srv.leader_id,
        "term": srv.current_term,
        "commit_index": srv.commit_index,
        "last_applied": srv.last_applied,
        "last_index": last.index,
        "last_written_index": lw.index,
        "snapshot_index": srv.log.snapshot_index_term().index,
        "checkpoint_index": checkpoint_index,
        "commit_latency": srv.commit_latency,
        "commit_latency_ms": srv.commit_latency * 1000.0,
        "machine_version": srv.machine_version,
        "effective_machine_version": srv.effective_machine_version,
        "membership": srv.membership.value,
        "counters": counters,
    }
