"""Per-system server registry — the ra_directory role.

The reference keeps, per system, a UId-keyed ETS forward map
{pid, parent, server name, cluster name} plus a dets-backed reverse map
name→UId that survives restarts (ra_directory.erl:68-121).  Here both
directions live in one pickled file under the system data dir, written
with atomic replace; registration happens in RaSystem.log_factory (every
server start passes through it), and the persisted record carries the
reconstructable parts of the server config so a system restart can
revive its registered servers (the ra_system_recover `registered`
strategy + ra_server_sup_sup:recover_config, :34-68 / :80-103).

The machine itself is NOT persisted: the reference stores a module
reference, which Python lacks for closures — recovery takes a
machine resolver instead (see RaSystem.recover_servers).
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Optional


class Directory:
    def __init__(self, data_dir: str) -> None:
        self.path = os.path.join(data_dir, "directory")
        self._lock = threading.Lock()
        self._by_uid: dict[str, dict] = {}
        self._by_name: dict[str, str] = {}
        #: uids whose servers were force-deleted — recovered WAL data for
        #: these (and ONLY these) may be purged at boot.  Absence from the
        #: registry alone proves nothing: the file may predate a record,
        #: so unknown uids are kept conservatively (see RaSystem boot).
        self._tombstones: set[str] = set()
        #: True when a directory file exists but could not be read — the
        #: registry contents are unknown and nothing may be purged on its
        #: authority
        self.load_failed = False
        if os.path.exists(self.path):
            try:
                with open(self.path, "rb") as f:
                    raw = pickle.load(f)
                if isinstance(raw, dict) and "records" in raw:
                    self._by_uid = raw["records"]
                    self._tombstones = set(raw.get("tombstones", ()))
                else:  # pre-tombstone format: plain records dict
                    self._by_uid = raw
                self._by_name = {rec["name"]: uid
                                 for uid, rec in self._by_uid.items()}
            except Exception:
                self._by_uid, self._by_name = {}, {}
                self._tombstones = set()
                self.load_failed = True

    def _persist(self) -> None:
        tmp = self.path + ".partial"
        with open(tmp, "wb") as f:
            pickle.dump({"records": self._by_uid,
                         "tombstones": self._tombstones}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def register(self, uid: str, name: str, cluster_name: str,
                 config: Optional[dict] = None) -> None:
        """register_name/6 (:68-90).  A name re-registering under a new
        uid supersedes the old record (delete + re-create of a server)."""
        with self._lock:
            old_uid = self._by_name.get(name)
            if old_uid is not None and old_uid != uid:
                self._by_uid.pop(old_uid, None)
            self._by_uid[uid] = {"name": name, "cluster": cluster_name,
                                 "config": config or {}}
            self._by_name[name] = uid
            self._tombstones.discard(uid)
            self._persist()

    def unregister(self, uid: str, *, tombstone: bool = False) -> None:
        """Remove a uid; with ``tombstone=True`` (force-delete) durably
        record that this uid's WAL remnants are garbage, authorising the
        boot purge to destroy them."""
        with self._lock:
            rec = self._by_uid.pop(uid, None)
            if rec is not None and self._by_name.get(rec["name"]) == uid:
                del self._by_name[rec["name"]]
            if tombstone:
                self._tombstones.add(uid)
            self._persist()

    def is_tombstoned(self, uid: str) -> bool:
        with self._lock:
            return uid in self._tombstones

    def tombstones(self) -> set:
        with self._lock:
            return set(self._tombstones)

    def prune_tombstones(self, uids) -> None:
        """Drop tombstones that have served their purpose (their WAL data
        is gone) so the set cannot grow without bound."""
        with self._lock:
            before = len(self._tombstones)
            self._tombstones.difference_update(uids)
            if len(self._tombstones) != before:
                self._persist()

    def where_is(self, name: str) -> Optional[str]:
        """name -> uid (where_is/2 :106-121)."""
        with self._lock:
            return self._by_name.get(name)

    def name_of(self, uid: str) -> Optional[str]:
        with self._lock:
            rec = self._by_uid.get(uid)
            return rec["name"] if rec else None

    def cluster_of(self, uid: str) -> Optional[str]:
        with self._lock:
            rec = self._by_uid.get(uid)
            return rec["cluster"] if rec else None

    def config_of(self, uid: str) -> Optional[dict]:
        with self._lock:
            rec = self._by_uid.get(uid)
            return dict(rec["config"]) if rec else None

    def is_registered_uid(self, uid: str) -> bool:
        with self._lock:
            return uid in self._by_uid

    def uids(self) -> list:
        with self._lock:
            return list(self._by_uid)

    def overview(self) -> dict:
        with self._lock:
            return {uid: {"name": rec["name"], "cluster": rec["cluster"]}
                    for uid, rec in self._by_uid.items()}
