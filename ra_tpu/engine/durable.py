"""Durability bridge between the lockstep lane engine and the WAL plane.

This closes the loop the engine docstring describes: in durable mode a
step's accepted entries are compacted ON DEVICE to a dense row buffer
(a prefix-sum gather over the per-lane accept counts — the readback
carries only bytes that will hit disk), pulled off-device by per-shard
encode workers (double-buffered: the readback of step N overlaps the
dispatch of step N+1), encoded as one WAL record per step per shard,
and fed through S independent :class:`ra_tpu.log.wal.Wal` shards — each
with its own file, writer thread and fsync, running the adaptive
group-commit policy (one fdatasync per group).  Every shard's fsync
confirm comes back as a slice of the ``confirm_upto`` input of a later
step, so ``last_written`` — and therefore the commit quorum — advances
only over entries that are really on disk.  This is the engine-scale
version of the reference's written-event protocol: an entry only counts
toward the commit median after write(2)+fsync
(/root/reference/src/ra_log_wal.erl:753-800), with the single fan-in
writer multiplied across cores — the fan-in batching axis of SURVEY.md
§2.4 extended the way partitioned-serialization-point Raft variants
split their log pipeline.

Record format (one WAL payload per step per shard, uid ``__engine__``):

  RTB1:  magic(4) | n_lanes:u32 | C:u32 | dtype:8s | n_flat:u32
  RTB2:  magic(4) | n_lanes:u32 | C:u32 | dtype:8s | n_flat:u32 | lane_lo:u32
  hi:    i32[N]   leader tail after the step (per lane of the slice)
  n_app: i32[N]   entries appended this step (accepted cmds + noop)
  n_acc: i32[N]   how many of those came from the host batch
  flat:  [n_flat, C] the accepted host rows, lane-major

RTB1 is the lane_lo=0 form — byte-identical to the pre-sharding format,
which is what ``wal_shards=1`` emits (the default-compatible path).
Shards at a nonzero lane offset emit RTB2; blocks therefore fully
self-describe their lane slice and recovery can merge ANY mix of shard
layouts found on disk (a shard-count change needs no migration step).

``hi - n_app`` is the step's append base; a base below the running tail
records an election truncation (a deposed leader's unconfirmed suffix),
exactly the overwrite-invalidates-higher-indexes rule of WAL recovery
(/root/reference/src/ra_log_wal.erl:871-955) at step granularity.
Entries between ``n_acc`` and ``n_app`` are the term-opening noop
(all-zero payload, the machine-noop encoding).

Recovery (:func:`open_engine`) restores the latest checkpoint, scans
every surviving WAL shard (plus foreign-layout leftovers), stitches the
per-slice pieces into full-lane step blocks — lanes whose shard crashed
before recording a step carry their tail forward, which is safe because
the merged per-lane confirm rule means nothing beyond a shard's last
record was ever reported committed — resolves truncations, and replays
through the jitted step.  A crash (kill -9) therefore loses nothing
that was ever reported committed.

Checkpointing (:meth:`EngineDurability.checkpoint`) quiesces all
shards, snapshots the full lane state via ``engine.save`` (atomic
.npz), and prunes WAL files whose records the checkpoint covers — the
release_cursor/snapshot-truncation role of ra_snapshot.erl at the
engine scale.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import struct
import threading
import time
from typing import Optional

import numpy as np

from .. import devicewatch, trace
from ..blackbox import RECORDER, record, stamp_recovery
from ..log import faults
from ..log.wal import Wal, WalDown, scan_wal_file
from ..metrics import ENGINE_WAL_FIELDS

UID = "__engine__"

#: shard-WAL supervisor restart intensity: (max restarts, window s) —
#: the engine twin of system.WAL_RESTART_INTENSITY; beyond it the
#: supervisor backs off for the window instead of hot-looping against a
#: dead disk
SHARD_RESTART_INTENSITY = (10, 5.0)
MAGIC = b"RTB1"
MAGIC2 = b"RTB2"          # RTB1 + lane_lo:u32 (sharded lane slice)
_BLK = struct.Struct("<4sII8sI")
_BLK2 = struct.Struct("<4sII8sII")


def _is_multidevice(arr) -> bool:
    """True when a step-aux leaf lives sharded across >1 device (the
    engine state was placed on a mesh).  Host numpy (recovery replay)
    and single-device jax arrays read False.  Pure metadata — no
    device sync."""
    try:
        return len(arr.sharding.device_set) > 1
    except AttributeError:
        return False


def encode_block_flat(hi: np.ndarray, n_app: np.ndarray, n_acc: np.ndarray,
                      flat: np.ndarray, lane_lo: int = 0) -> bytes:
    """Encode one step's append outcome for a lane slice from the
    already-compacted accepted rows (lane-major).  ``lane_lo == 0``
    emits the legacy RTB1 bytes; a sharded slice carries its offset."""
    n = hi.shape[0]
    flat = np.ascontiguousarray(flat)
    if flat.ndim != 2:
        flat = flat.reshape(flat.shape[0], -1)
    c = flat.shape[1]
    dt = np.dtype(flat.dtype).str.encode().ljust(8, b"\x00")
    if lane_lo:
        head = _BLK2.pack(MAGIC2, n, c, dt, flat.shape[0], lane_lo)
    else:
        head = _BLK.pack(MAGIC, n, c, dt, flat.shape[0])
    return b"".join((head,
                     np.ascontiguousarray(hi, "<i4").tobytes(),
                     np.ascontiguousarray(n_app, "<i4").tobytes(),
                     np.ascontiguousarray(n_acc, "<i4").tobytes(),
                     flat.tobytes()))


def encode_block(hi: np.ndarray, n_app: np.ndarray, n_acc: np.ndarray,
                 payload_host: np.ndarray) -> bytes:
    """Legacy host-side path: mask the accepted rows out of the full
    [N, K, C] batch, then encode.  Byte-identical to what the device
    compaction path produces — kept for tests and offline tooling."""
    _N, K, _C = payload_host.shape
    mask = np.arange(K)[None, :] < n_acc[:, None]
    return encode_block_flat(hi, n_app, n_acc, payload_host[mask])


def decode_block(data: bytes):
    """Inverse of the encoders -> (lane_lo, hi, n_app, n_acc, rows)
    where rows is [N, Kmax, C] for the block's lane slice with noop
    rows already zero-filled."""
    magic = data[:4]
    if magic == MAGIC2:
        _m, n, c, dt, n_flat, lane_lo = _BLK2.unpack_from(data, 0)
        off = _BLK2.size
    elif magic == MAGIC:
        _m, n, c, dt, n_flat = _BLK.unpack_from(data, 0)
        lane_lo = 0
        off = _BLK.size
    else:
        raise ValueError("bad engine block magic")
    dtype = np.dtype(dt.rstrip(b"\x00").decode())
    hi = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    n_app = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    n_acc = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    flat = np.frombuffer(data, dtype, n_flat * c, off).reshape(n_flat, c)
    kmax = int(n_app.max()) if n else 0
    rows = np.zeros((n, kmax, c), dtype)
    if kmax:
        mask = np.arange(kmax)[None, :] < n_acc[:, None]
        rows[mask] = flat
    return lane_lo, hi, n_app, n_acc, rows


class _WalFileRetirer:
    """Duck-typed segment_writer for an engine WAL shard: instead of
    flushing per-server memtables to segments, rolled WAL files are kept
    until a checkpoint covers their step range, then unlinked — the
    engine's .npz checkpoint plays the segment role (the WAL-file
    deletion barrier of ra_log_segment_writer.erl:129-201)."""

    def __init__(self) -> None:
        self._files: list = []  # (hi_step, path)
        self._lock = threading.Lock()

    def accept_ranges(self, ranges: dict, wal_path: str) -> None:
        hi = max(r[1] for r in ranges.values())
        with self._lock:
            self._files.append((hi, wal_path))

    def retire(self, uids: list, wal_files: list) -> None:
        # recovered files: every record in them predates any future
        # checkpoint, so hi=0 (pruned by the first checkpoint taken)
        with self._lock:
            for path in wal_files:
                self._files.append((0, path))

    def mark_deleted(self, uid: str) -> None:  # pragma: no cover
        pass

    def prune(self, ckpt_step: int) -> None:
        with self._lock:
            keep = []
            for hi, path in self._files:
                if hi <= ckpt_step:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                else:
                    keep.append((hi, path))
            self._files = keep


class _WalShard:
    """One WAL shard: a contiguous lane slice [lo, hi) with its own
    file, writer thread and fsync, plus an encode worker that pulls the
    device-compacted aux of queued steps to the host, encodes the WAL
    block (CRC included) off the engine dispatch thread, and hands it to
    this shard's fan-in Wal — so step N+1's XLA dispatch overlaps step
    N's encode+write+fsync end to end."""

    def __init__(self, bridge, idx: int, lo: int, hi: int,
                 shard_dir: str, wal_kwargs: dict) -> None:
        self.idx = idx
        self.lo = lo
        self.hi = hi
        self.bridge = bridge  # ra-type: EngineDurability
        self.error: Optional[BaseException] = None
        self.retirer = _WalFileRetirer()
        self.wal = Wal(shard_dir, segment_writer=self.retirer,
                       **wal_kwargs)
        self.confirmed_step = 0
        self.confirm_upto = np.zeros((hi - lo,), np.int32)
        self._appended: dict = {}   # step -> hi np[N_s] (until confirmed)
        self._blocks: dict = {}     # step -> bytes      (until confirmed)
        self._bases: dict = {}      # step -> base np[N_s]
        self._jobs: collections.deque = collections.deque()
        self.unprocessed = 0
        self._resend_above: Optional[int] = None
        self._generation = self.wal.generation
        self._stop = False
        self.wal.register(UID, self._notify)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ra-engine-wal-s{idx}")
        self._thread.start()

    # -- WAL confirm path (called from this shard's WAL batch thread) ------

    def _notify(self, uid: str, lo: Optional[int], hi: int,
                term: int) -> None:
        cond = self.bridge._cond
        with cond:
            if lo is None:
                # out-of-sequence signal: resend everything above hi on
                # the encode worker (ra_log_wal.erl:457-481)
                self._resend_above = hi
                cond.notify_all()
                return
            if hi <= self.confirmed_step:
                return
            self.confirmed_step = hi
            # (lane, submit_index)-keyed durable hop: the shard's step
            # horizon advanced — ra_trace joins this against
            # engine.submit by step range (docs/INTERNALS.md §10)
            record("engine.confirm", shard=self.idx, step=hi)
            # commit_e2e phase stamp: a step is end-to-end durable when
            # EVERY shard's horizon covers it (the merged confirm rule
            # the commit quorum gates on) — pop matured submit stamps
            # at the moment the laggiest shard advances
            self.bridge._note_confirmed_steps()
            arr = self._appended.get(hi)
            if arr is not None:
                # exact durable tail as of step hi — then re-apply the
                # bases of still-unconfirmed steps: an unconfirmed
                # truncation means indexes above its base are occupied
                # by entries not yet on disk
                confirm = arr.copy()
                for s, base in self._bases.items():
                    if s > hi:
                        confirm = np.minimum(confirm, base)
                self.confirm_upto = confirm
            for s in [s for s in self._appended if s <= hi]:
                del self._appended[s]
                self._blocks.pop(s, None)
                self._bases.pop(s, None)
            cond.notify_all()

    # -- encode worker ------------------------------------------------------

    def _run(self) -> None:
        cond = self.bridge._cond
        while True:
            with cond:
                cond.wait_for(
                    lambda: self._stop or self._jobs
                    or self._resend_above is not None,
                    timeout=0.25)
                if self._stop:
                    return
                job = self._jobs.popleft() if self._jobs else None
            self._maybe_resend()
            if job is None:
                continue
            step, aux, t_enq = job
            # queue_wait phase stamp: how long the submitted step sat
            # in this shard's encode queue before a worker picked it up
            self.bridge.phases.note("queue_wait",
                                    time.monotonic() - t_enq)
            try:
                self._process(step, aux)
            except Exception as exc:  # noqa: BLE001 — surfaced to callers
                record("engine.crash", shard=self.idx,
                       error=repr(exc)[:200])
                RECORDER.dump("engine_shard_error",
                              what=repr(exc)[:200],
                              where=f"shard{self.idx}",
                              data_dir=self.bridge.dir)
                with cond:
                    self.error = exc
            finally:
                with cond:
                    self.unprocessed -= 1
                    cond.notify_all()

    def _process(self, step: int, aux: dict) -> None:
        lo, hi_l = self.lo, self.hi
        t_enc = time.monotonic()
        with trace.span("wal.encode", "wal", shard=self.idx, step=step):
            if aux.get("__mesh__"):
                # sharded-engine path (ISSUE 11): a worker thread must
                # NOT launch device computations — slicing a sharded
                # array compiles+enqueues a multi-device gather, and
                # concurrent enqueues from encode workers deadlock
                # against the dispatch thread's pjit.  The bridge
                # materializes the step's aux to host ONCE (pure d2h
                # transfers, safe off-thread); slicing happens in
                # numpy.
                host = self.bridge._host_aux(aux)
                hi = host["appended_hi"][lo:hi_l]
                n_app = host["n_app"][lo:hi_l]
                n_acc = host["n_acc"][lo:hi_l]
                full_csum = host["row_csum"]
                # csum: this shard's logical slice, kept for the
                # readback_bytes accounting below (the wire moved the
                # FULL cumsum once per step via _host_aux)
                csum = full_csum[max(0, lo - 1):hi_l]
                r0 = int(full_csum[lo - 1]) if lo else 0
                r1 = int(full_csum[hi_l - 1])
                flat = host["flat_rows"][r0:r1]
            else:
                # documented readback point: this worker runs one step
                # behind dispatch, so the device values are ready (or
                # the pull overlaps the next dispatch) — RA02's
                # allowlisted home
                hi = np.asarray(
                    aux["appended_hi"][lo:hi_l]).astype(np.int32)
                n_app = np.asarray(
                    aux["n_app"][lo:hi_l]).astype(np.int32)
                n_acc = np.asarray(
                    aux["n_acc"][lo:hi_l]).astype(np.int32)
                # only this slice's row-offset boundary values are
                # needed — pulling the full-N cumsum on every shard
                # would duplicate the transfer S times
                csum = np.asarray(aux["row_csum"][max(0, lo - 1):hi_l])
                r0 = int(csum[0]) if lo else 0
                r1 = int(csum[-1])
                flat = np.asarray(aux["flat_rows"][r0:r1])
            t_blk = time.monotonic()
            blk = encode_block_flat(hi, n_app, n_acc, flat, lane_lo=lo)
            # encode phase stamp (ISSUE 18): just the block encode+CRC,
            # the lane plane's contribution to encode_share_pct (the
            # classic plane's half lands in DurableLog._put_batch)
            self.bridge.phases.note("encode", time.monotonic() - t_blk)
        # wal_encode phase stamp: readback pull + encode + CRC for one
        # step's block on this shard (runs off the dispatch thread)
        self.bridge.phases.note("wal_encode", time.monotonic() - t_enc)
        n_s = hi_l - lo
        k = aux["flat_rows"].shape[0] // max(1, self.bridge.n_lanes)
        item = flat.dtype.itemsize * (flat.shape[-1] if flat.ndim > 1
                                      else 1)
        base = hi - n_app
        cond = self.bridge._cond
        with cond:
            ctr = self.bridge.counters
            ctr["readback_bytes"] += (hi.nbytes + n_app.nbytes +
                                      n_acc.nbytes + csum.nbytes +
                                      flat.nbytes)
            # what the pre-compaction full-ring readback moved for the
            # same step slice: the whole [N_s, K, C] host batch
            ctr["readback_bytes_full"] += (hi.nbytes + n_app.nbytes +
                                           n_acc.nbytes + n_s * k * item)
            ctr["encoded_blocks"] += 1
            ctr["encoded_bytes"] += len(blk)
            # transfer-ledger mirror (ISSUE 16): the WAL encode pull is
            # the third d2h budget line of a durable dispatch loop —
            # same bytes as readback_bytes, attributed per site so the
            # device plane's ledger is complete (host int increments
            # only; RA12: no device work on this worker thread)
            devicewatch.record_d2h(
                "wal_readback",
                hi.nbytes + n_app.nbytes + n_acc.nbytes +
                csum.nbytes + flat.nbytes)
            self._appended[step] = hi
            self._blocks[step] = blk
            self._bases[step] = base
            # an election truncation reuses indexes: the durable horizon
            # drops to the step's base until this block itself confirms
            self.confirm_upto = np.minimum(self.confirm_upto, base)
        # sync with any new WAL incarnation BEFORE submitting: a fresh
        # writer accepts any first step, so writing this block ahead of
        # the unconfirmed backlog would leave a step gap in the new file
        # if the WAL dies again before the backlog resends (recovery
        # also guards against the remaining race — _assemble_blocks
        # drops gapped pieces)
        self._maybe_resend()
        try:
            self.wal.write(UID, step, 1, blk)
        except WalDown:
            # block is recorded; the resend path replays it once the
            # supervisor restarts this shard's WAL
            pass

    def _maybe_resend(self) -> None:
        """After a WAL crash+restart (or an out-of-sequence signal),
        resend every unconfirmed block above the shard's durable horizon
        (the resend_from protocol, ra_log.erl:778-793)."""
        cond = self.bridge._cond
        resend_from = None
        with cond:
            if self._resend_above is not None:
                resend_from = self._resend_above
                self._resend_above = None
        if self.wal.generation != self._generation and self.wal.alive:
            self._generation = self.wal.generation
            with cond:
                resend_from = self.confirmed_step if resend_from is None \
                    else min(resend_from, self.confirmed_step)
        if resend_from is None:
            return
        with cond:
            pending = sorted((s, b) for s, b in self._blocks.items()
                             if s > resend_from)
        for s, b in pending:
            try:
                self.wal.write(UID, s, 1, b)
            except WalDown:
                return

    def stop(self) -> None:
        with self.bridge._cond:
            self._stop = True
            self.bridge._cond.notify_all()
        self._thread.join(timeout=5)


class EngineDurability:
    """Host-side bridge: owns the engine's sharded WAL plane (S lane
    shards, each with its own file/writer/fsync and encode worker) and
    the merged confirm feedback arrays."""

    def __init__(self, data_dir: str, n_lanes: int, *, sync_mode: int = 1,
                 write_strategy: str = "default", max_pending: int = 8,
                 wal_max_size: int = 256 * 1024 * 1024,
                 wal_shards: int = 1,
                 wal_batch_bytes: int = 4 * 1024 * 1024,
                 wal_batch_interval_ms: Optional[float] = None,
                 wal_supervise: bool = True) -> None:
        os.makedirs(data_dir, exist_ok=True)
        if not 1 <= wal_shards <= n_lanes:
            raise ValueError(
                f"wal_shards must be in [1, n_lanes]; got {wal_shards}")
        self.dir = data_dir
        self.n_lanes = n_lanes
        self.max_pending = max_pending
        self.wal_shards = wal_shards
        if wal_batch_interval_ms is None:
            # default: no wait.  Group commit still emerges under load
            # (the greedy drain batches every record queued behind the
            # backpressure window); an explicit interval only pays off
            # when the caller KNOWS records arrive faster than fsyncs
            # complete — on boxes with slow/serializing fsync a forced
            # wait just adds a per-step confirm-latency tax.
            wal_batch_interval_ms = 0.0
        self._cond = threading.Condition()
        #: serializes the once-per-step host materialization of mesh
        #: aux (see _host_aux) — NOT self._cond: a d2h transfer can
        #: take milliseconds and must never block the confirm path
        self._readback_lock = threading.Lock()
        self.counters: dict = {f: 0 for f in ENGINE_WAL_FIELDS}
        self.step_seq = 0
        # phase-resolved latency attribution (ISSUE 9): one accumulator
        # for the whole durable plane — the engine adopts it on attach,
        # every WAL shard feeds its fsync/confirm stamps into it, and
        # the bridge stamps queue/encode/e2e edges itself
        from ..telemetry import PhaseStats
        self.phases = PhaseStats()
        #: step -> monotonic submit stamp; popped when the MERGED
        #: confirm horizon covers the step (the commit_e2e phase)
        self._submit_ts: dict = {}
        wal_kwargs = dict(sync_mode=sync_mode,
                          write_strategy=write_strategy,
                          max_size=wal_max_size,
                          max_batch_bytes=wal_batch_bytes,
                          max_batch_interval_ms=wal_batch_interval_ms,
                          phase_stats=self.phases,
                          # every shard's post-mortem bundles land at
                          # the BRIDGE's data dir, not one per shard
                          blackbox_dir=data_dir)
        bounds = [round(i * n_lanes / wal_shards)
                  for i in range(wal_shards + 1)]
        self._shards: list = []
        own_dirs = set()
        for i in range(wal_shards):
            sdir = data_dir if wal_shards == 1 else \
                os.path.join(data_dir, f"shard{i:02d}")
            own_dirs.add(os.path.abspath(os.path.join(sdir, "wal")))
            self._shards.append(
                _WalShard(self, i, bounds[i], bounds[i + 1], sdir,
                          wal_kwargs))
        # foreign-layout recovery: wal dirs left by a run with a
        # different shard count are scanned read-only here and their
        # files retired at the first checkpoint — blocks self-describe
        # their lane slice, so a shard-count change needs no migration.
        # One table PER DIRECTORY: different shards reuse the same step
        # index for different lane slices, so merging them into one
        # idx-keyed table would trip the overwrite-dedup rule across
        # slices and silently drop whole shards.
        self._legacy_tables: list = []
        self._legacy_files: list = []
        for wdir in self._discover_wal_dirs(data_dir):
            if os.path.abspath(wdir) in own_dirs:
                continue
            tables: dict = {}
            for fname in sorted(os.listdir(wdir)):
                if not fname.endswith(".wal"):
                    continue
                path = os.path.join(wdir, fname)
                try:
                    scan_wal_file(path, tables)
                except Exception:
                    logging.getLogger("ra_tpu").warning(
                        "wal recovery: truncated/corrupt tail in %s",
                        path)
                self._legacy_files.append(path)
            self._legacy_tables.append(tables)
        # per-shard WAL supervisor (the ra_log_wal_sup role for the
        # sharded plane): a dead shard batch thread is restarted under
        # an intensity window, the shard worker detects the generation
        # bump and resends its unconfirmed blocks — the merged confirm
        # vector never advanced past them, so nothing reported committed
        # depends on the crashed incarnation (disabled by tests that
        # assert raw WalDown freeze behaviour)
        # post-mortem bundle sources: per-shard durable watermarks +
        # the durability config (last engine wins the shared names; a
        # closed bridge unhooks its own, see close())
        self._bb_config = {
            "data_dir": data_dir, "n_lanes": n_lanes,
            "wal_shards": wal_shards, "sync_mode": sync_mode,
            "write_strategy": write_strategy,
            "max_pending": max_pending,
            "wal_batch_bytes": wal_batch_bytes,
            "wal_batch_interval_ms": wal_batch_interval_ms,
            "wal_supervise": wal_supervise,
        }
        self._bb_watermarks = self._watermark_source
        self._bb_config_src = lambda: self._bb_config
        RECORDER.add_source("engine_wal_watermarks", self._bb_watermarks)
        RECORDER.add_source("engine_wal_config", self._bb_config_src)
        self._sup_stop = threading.Event()
        self._shard_restarts: collections.deque = collections.deque()
        self._sup_thread: Optional[threading.Thread] = None
        if wal_supervise:
            self._sup_thread = threading.Thread(
                target=self._supervise_shards, daemon=True,
                name="ra-engine-wal-sup")
            self._sup_thread.start()

    def _supervise_shards(self) -> None:
        max_r, period = SHARD_RESTART_INTENSITY
        log = logging.getLogger("ra_tpu")
        while not self._sup_stop.wait(0.02):
            for sh in self._shards:
                wal = sh.wal
                if wal._stop or wal.alive:
                    continue
                now = time.monotonic()
                while self._shard_restarts and \
                        now - self._shard_restarts[0] > period:
                    self._shard_restarts.popleft()
                if len(self._shard_restarts) >= max_r:
                    log.error("engine wal supervisor: restart intensity "
                              "exceeded (%d in %.0fs); backing off",
                              max_r, period)
                    record("sup.giveup", plane="engine_wal",
                           shard=sh.idx)
                    RECORDER.dump(
                        "engine_wal_supervisor_giveup",
                        what=f"shard restart intensity exceeded "
                             f"({max_r} in {period:.0f}s)",
                        where=f"shard{sh.idx}", data_dir=self.dir)
                    if self._sup_stop.wait(period):
                        return
                    continue
                self._shard_restarts.append(now)
                log.warning("engine wal supervisor: restarting dead "
                            "WAL shard %d", sh.idx)
                try:
                    wal.restart()
                    record("sup.restart", plane="engine_wal",
                           shard=sh.idx)
                except Exception:
                    log.exception("engine wal supervisor: restart of "
                                  "shard %d failed; will retry", sh.idx)
                    continue
                with self._cond:
                    # wake the shard worker: _maybe_resend sees the
                    # generation bump and replays unconfirmed blocks
                    self._cond.notify_all()

    @staticmethod
    def _discover_wal_dirs(data_dir: str) -> list:
        dirs = []
        top = os.path.join(data_dir, "wal")
        if os.path.isdir(top):
            dirs.append(top)
        try:
            names = sorted(os.listdir(data_dir))
        except OSError:
            names = []
        for name in names:
            w = os.path.join(data_dir, name, "wal")
            if name.startswith("shard") and os.path.isdir(w):
                dirs.append(w)
        return dirs

    # -- compat surface -----------------------------------------------------

    @property
    def wal(self) -> Wal:
        """The first shard's WAL — the whole plane when ``wal_shards=1``
        (the surface the single-shard tests drive kill/restart/flush
        through)."""
        return self._shards[0].wal

    @property
    def wals(self) -> list:
        return [sh.wal for sh in self._shards]

    @property
    def confirm_upto(self) -> np.ndarray:
        """Merged per-lane durable horizon across shards."""
        if len(self._shards) == 1:
            return self._shards[0].confirm_upto
        with self._cond:
            return np.concatenate(
                [sh.confirm_upto for sh in self._shards])

    @property
    def confirmed_step(self) -> int:
        return min(sh.confirmed_step for sh in self._shards)

    def seed(self, prev_hi: np.ndarray, step_seq: int) -> None:
        """Set the post-recovery baseline: everything up to ``prev_hi``
        is durable and recorded through ``step_seq``."""
        prev = prev_hi.astype(np.int32)
        with self._cond:
            self.step_seq = step_seq
            self._submit_ts.clear()  # replay steps are not e2e samples
            for sh in self._shards:
                sh.confirm_upto = prev[sh.lo:sh.hi].copy()
                sh.confirmed_step = step_seq

    # -- phase attribution / live tunables ---------------------------------

    def _note_confirmed_steps(self) -> None:
        """Pop submit stamps the MERGED confirm horizon now covers and
        record their commit_e2e samples (called from a shard's WAL
        notify path with the bridge cond held — it is an RLock)."""
        with self._cond:
            m = min(sh.confirmed_step for sh in self._shards)
            if not self._submit_ts:
                return
            now = time.monotonic()
            for s in [s for s in self._submit_ts if s <= m]:
                self.phases.note("commit_e2e",
                                 now - self._submit_ts.pop(s))
            # a dead shard freezes the merged horizon: stamps would
            # otherwise pile up for the rest of the process — bound the
            # table; dropped stamps just lose samples, never accounting
            while len(self._submit_ts) > 4096:
                self._submit_ts.pop(min(self._submit_ts))

    def pending_steps(self) -> int:
        """Dispatched-but-unconfirmed steps on the laggiest shard — the
        durability half of the ingress plane's bounded-queue accounting
        (ISSUE 10): ingress queue depth + this is the node's total
        uncommitted command backlog (IngressPlane.gauges reads it)."""
        return self.step_seq - self.confirmed_step

    def shard_layout(self) -> list:
        """``[[lo, hi], ...]`` lane slice per WAL shard — the bench
        tail's ``wal_shard_layout`` stamp (ISSUE 11 satellite): a
        multichip row must record whether its fsync parallelism was
        per-device (slices matching the mesh's lane sharding) or
        host-defaulted, or cross-round durable comparisons are
        apples-to-oranges."""
        return [[sh.lo, sh.hi] for sh in self._shards]

    def batch_interval_ms(self) -> float:
        """The live WAL group-commit wait budget (uniform across
        shards — the engine_pipeline overview stamps this, rule RA07)."""
        return float(self._shards[0].wal.max_batch_interval_ms)

    def set_batch_interval_ms(self, ms: float) -> None:
        """Autotuner hook: retarget every shard's group-commit wait
        budget.  The WAL batch threads read the interval per group, so
        the change lands at the next batch — no restart, no flush."""
        ms = max(0.0, float(ms))
        for sh in self._shards:
            sh.wal.max_batch_interval_ms = ms
        self._bb_config["wal_batch_interval_ms"] = ms

    # -- submit path (engine dispatch thread — must never host-sync) --------

    def _host_aux(self, aux: dict) -> dict:
        """Host materialization of one step's aux, ONCE per step across
        all shards (first worker converts, the rest reuse the memo).
        Under a mesh the conversion is pure device->host transfers —
        safe from a worker thread, unlike slicing, which would enqueue
        a multi-device computation concurrently with the dispatch
        thread (a runtime deadlock, observed on the forced-host CPU
        client).  The full compacted buffer therefore moves once per
        step instead of S sliced gathers."""
        host = aux.get("__host__")
        if host is not None:
            return host
        with self._readback_lock:
            host = aux.get("__host__")
            if host is None:
                host = {k: np.asarray(aux[k])
                        for k in self._BLOCK_KEYS}
                aux["__host__"] = host
        return host

    def submit(self, aux: dict) -> None:
        """Queue one step's device aux for off-thread encode + WAL write
        on every shard.  No host sync happens here: the shard workers
        pull the compacted readback when the device values are ready."""
        job = {key: aux[key] for key in self._BLOCK_KEYS}
        if _is_multidevice(job["appended_hi"]):
            job["__mesh__"] = True
        t_sub = time.monotonic()
        with self._cond:
            self.step_seq += 1
            step = self.step_seq
            self._submit_ts[step] = t_sub
            for sh in self._shards:
                sh._jobs.append((step, job, t_sub))
                sh.unprocessed += 1
            self._cond.notify_all()
        # host-side boundary event only (step counters — no device
        # value is touched on this thread, rule RA04): commands are
        # joined post-hoc by (lane, submit_index) against the
        # on-device step stamps (docs/INTERNALS.md §10)
        record("engine.submit", step_lo=step, step_hi=step, k=1)

    #: stacked-aux leaves a WAL record needs per inner step (the extra
    #: superstep watermarks — committed_lanes/applied_lanes — are host-
    #: pipelining aids, not durability data)
    _BLOCK_KEYS = ("appended_hi", "n_app", "n_acc", "row_csum",
                   "flat_rows")

    def submit_block(self, aux: dict, k: int) -> None:
        """Queue one fused superstep dispatch's STACKED aux (leading
        [K] axis per leaf, see lockstep._superstep) as ``k``
        consecutive per-inner-step encode jobs on every shard.  The
        leading-axis slices are taken here as device ops (async, no
        host readback — this runs on the engine dispatch thread), so
        each job carries exactly the single-step aux shape and the
        shard workers, WAL record format and confirm protocol are
        unchanged: one RTB block per inner step per shard, confirms
        advance per inner step as each block fsyncs."""
        mesh = _is_multidevice(aux["appended_hi"])
        subs = []
        for j in range(k):
            # leading-axis slices taken HERE, on the dispatch thread:
            # under a mesh these enqueue multi-device gathers, which
            # only the dispatch thread may do (see _host_aux)
            sub = {key: aux[key][j] for key in self._BLOCK_KEYS}
            if mesh:
                sub["__mesh__"] = True
            subs.append(sub)
        t_sub = time.monotonic()
        with self._cond:
            step_lo = self.step_seq + 1
            for sub in subs:
                self.step_seq += 1
                step = self.step_seq
                self._submit_ts[step] = t_sub
                for sh in self._shards:
                    sh._jobs.append((step, sub, t_sub))
                    sh.unprocessed += 1
            step_hi = self.step_seq
            self._cond.notify_all()
        record("engine.submit", step_lo=step_lo, step_hi=step_hi, k=k)

    def flush_all(self, timeout: float = 5.0) -> None:
        """Durability barrier on every shard: drains the encode workers
        first so steps still queued there are written, then flushes
        each shard's WAL."""
        self.drain_all(timeout)
        for sh in self._shards:
            sh.wal.flush(timeout)

    def _raise_shard_error(self) -> None:
        err = next((sh.error for sh in self._shards if sh.error), None)
        if err is not None:
            raise err

    def drain_all(self, timeout: float = 30.0) -> None:
        """Barrier: every submitted step is encoded and handed to its
        shard WAL (not necessarily fsynced — flush the shards for that).
        After this returns, every election truncation's base clamp is
        reflected in ``confirm_upto``."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: any(sh.error for sh in self._shards)
                or all(sh.unprocessed == 0 for sh in self._shards),
                timeout)
        self._raise_shard_error()
        if not ok:
            raise TimeoutError("WAL encode workers stalled")

    def backpressure(self, timeout: float = 30.0) -> None:
        """Bound the unconfirmed window: wait for WAL confirms when more
        than ``max_pending`` steps are in flight on the laggiest shard
        (the flow control a gen_batch_server gets from its bounded
        mailbox)."""

        def lag() -> int:
            return self.step_seq - min(sh.confirmed_step
                                       for sh in self._shards)

        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                # sliced wait: WAL thread death never notifies the cond
                self._cond.wait_for(
                    lambda: lag() < self.max_pending
                    or any(sh.error for sh in self._shards)
                    or any(not sh.wal.alive for sh in self._shards),
                    min(0.5, max(0.0, deadline - time.monotonic())))
                under = lag() < self.max_pending
            self._raise_shard_error()
            if under:
                return
            for sh in self._shards:
                if not sh.wal.alive:
                    raise WalDown(
                        f"engine WAL shard {sh.idx} died under "
                        "backpressure; call wal.restart() to resume")
            if time.monotonic() > deadline:
                raise TimeoutError("WAL confirms stalled")

    # -- observability ------------------------------------------------------

    def _watermark_source(self) -> dict:
        """Per-shard durable watermarks for post-mortem bundles: host
        ints/np arrays only (``confirm_upto`` lives on the host side of
        the confirm protocol — no device sync here)."""
        with self._cond:
            return {
                "step_seq": self.step_seq,
                "shards": [{
                    "shard": sh.idx,
                    "lanes": [sh.lo, sh.hi],
                    "confirmed_step": sh.confirmed_step,
                    "jobs_pending": len(sh._jobs),
                    "wal_alive": sh.wal.alive,
                    "confirm_upto_min": int(sh.confirm_upto.min())
                    if sh.confirm_upto.size else 0,
                    "confirm_upto_max": int(sh.confirm_upto.max())
                    if sh.confirm_upto.size else 0,
                } for sh in self._shards],
            }

    def wal_overview(self) -> dict:
        """ENGINE_WAL_FIELDS plus per-shard WAL stats (batch bytes,
        records per fsync, fsync latency p50/p99, confirm lag) — the
        key_metrics merge mirroring the RPC_FIELDS pattern."""
        with self._cond:
            eng = dict(self.counters)
            eng["confirm_lag_steps"] = self.step_seq - min(
                sh.confirmed_step for sh in self._shards)
            shards = []
            for sh in self._shards:
                st = sh.wal.stats()
                st["shard"] = sh.idx
                st["lanes"] = [sh.lo, sh.hi]
                st["confirm_lag_steps"] = \
                    self.step_seq - sh.confirmed_step
                # encode-queue backlog: steps dispatched but not yet
                # picked up by this shard's encode worker — with
                # Wal.stats' queue_depth this completes the per-shard
                # pipeline-depth picture the Observatory/ra_top render
                st["jobs_pending"] = len(sh._jobs)
                shards.append(st)
        return {"engine": eng, "shards": shards,
                "disk_faults": faults.disk_fault_counters()}

    # -- checkpoint / recovery ----------------------------------------------

    def checkpoint(self, engine, timeout: float = 30.0) -> str:
        deadline = time.monotonic() + timeout
        self.drain_all(timeout)
        while True:
            with self._cond:
                done = all(sh.confirmed_step >= self.step_seq
                           for sh in self._shards)
            if done:
                break
            for sh in self._shards:
                if not sh.wal.alive:
                    raise WalDown("checkpoint: WAL shard died; "
                                  "wal.restart() and retry")
                try:
                    sh.wal.flush(min(5.0, max(
                        0.1, deadline - time.monotonic())))
                except TimeoutError:
                    pass
            with self._cond:
                self._cond.wait_for(
                    lambda: all(sh.confirmed_step >= self.step_seq
                                for sh in self._shards),
                    min(0.5, max(0.0, deadline - time.monotonic())))
            self._raise_shard_error()
            if time.monotonic() > deadline:
                raise TimeoutError("checkpoint: WAL confirms stalled")
        path = os.path.join(self.dir, "ckpt.npz")
        engine.save(path)
        # the pytree schema rides the meta for post-mortem diagnostics:
        # a reopen under a different engine version can say WHICH field
        # set the archive carries before restore() decides (the archive
        # itself is schema-named since ISSUE 15 and is authoritative)
        from .lockstep import LaneState
        meta = {"step": self.step_seq, "wal_shards": self.wal_shards,
                "schema": list(LaneState._fields)}
        tmp = path + ".meta.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, "ckpt.meta.json"))
        # roll every shard's current file so its (now-covered) records
        # become prunable, then drop every covered file
        for sh in self._shards:
            sh.wal.rollover()
            sh.wal.flush()
            sh.retirer.prune(self.step_seq)
        self._prune_legacy()
        return path

    def _prune_legacy(self) -> None:
        files, self._legacy_files = self._legacy_files, []
        dirs = set()
        for path in files:
            try:
                os.unlink(path)
            except OSError:
                pass
            dirs.add(os.path.dirname(path))
        for d in dirs:
            parent = os.path.dirname(d)
            try:
                os.rmdir(d)
                if os.path.basename(parent).startswith("shard"):
                    os.rmdir(parent)
            except OSError:
                pass
        self._legacy_tables = []

    def recovered_pieces(self, base_step: int) -> dict:
        """step -> [(lane_lo, hi, n_app, n_acc, rows)] merged from every
        shard's recovered WAL tables plus foreign-layout leftovers."""
        pieces: dict = {}
        tabs = [sh.wal.recovered_table(UID) for sh in self._shards]
        tabs += [t.get(UID, {}) for t in self._legacy_tables]
        for tbl in tabs:
            for s, (_t, blk) in tbl.items():
                if s <= base_step:
                    continue
                pieces.setdefault(s, []).append(decode_block(blk))
        return pieces

    def close(self) -> None:
        RECORDER.remove_source("engine_wal_watermarks",
                               self._bb_watermarks)
        RECORDER.remove_source("engine_wal_config", self._bb_config_src)
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=5)
        try:
            self.drain_all(timeout=10.0)
        except Exception:  # noqa: BLE001 — a dead WAL must not block cleanup
            pass
        for sh in self._shards:
            try:
                sh.wal.flush()
            except (WalDown, TimeoutError):
                pass
        for sh in self._shards:
            sh.stop()
            sh.wal.close()


def _assemble_blocks(pieces: dict, n_lanes: int, ckpt_tail: np.ndarray):
    """Stitch per-slice step pieces into full-lane step blocks.

    Lanes with no piece at a step (their shard crashed before recording
    it, or a foreign layout covered other slices) carry their tail
    forward with ``n_app=0`` — nothing was durably recorded for them at
    that step, and the merged per-lane confirm rule guarantees nothing
    beyond their last record was ever reported committed.

    Contiguity guard (the engine twin of the classic log's recovery
    clamp): a piece whose append BASE exceeds a lane's carried tail
    records appends above a step gap — a post-restart write that beat
    the unconfirmed-backlog resend into the new WAL file before a
    second crash.  Those appends were never confirmable (the shard's
    confirm slice froze below the gap), so the lane skips the piece
    and carries its tail forward instead of replaying a holed log the
    engine could never converge on."""
    blocks = []
    cur_hi = ckpt_tail.astype(np.int32).copy()
    for s in sorted(pieces):
        ps = pieces[s]
        kmax = max(p[4].shape[1] for p in ps)
        c = ps[0][4].shape[2]
        hi = cur_hi.copy()
        n_app = np.zeros((n_lanes,), np.int32)
        n_acc = np.zeros((n_lanes,), np.int32)
        rows = np.zeros((n_lanes, kmax, c), ps[0][4].dtype)
        for lane_lo, phi, papp, pacc, prows in ps:
            sl = slice(lane_lo, lane_lo + phi.shape[0])
            ok = (phi - papp) <= cur_hi[sl]
            if not ok.all():
                logging.getLogger("ra_tpu").warning(
                    "engine recovery: step %d piece at lanes [%d,%d) "
                    "appends above a gap on %d lane(s); skipped",
                    s, lane_lo, lane_lo + phi.shape[0],
                    int((~ok).sum()))
            hi[sl] = np.where(ok, phi, hi[sl])
            n_app[sl] = np.where(ok, papp, n_app[sl])
            n_acc[sl] = np.where(ok, pacc, n_acc[sl])
            if prows.shape[1]:
                dst = rows[sl]
                dst[ok, :prows.shape[1]] = prows[ok]
        blocks.append((s, hi, n_app, n_acc, rows))
        cur_hi = hi
    return blocks


def _final_logs(blocks: list, ckpt_tail: np.ndarray):
    """Resolve election truncations across recovered step blocks into the
    surviving per-step entry counts.

    blocks: [(step, hi, n_app, n_acc, rows)] in step order.  Returns
    (surv_counts per block [N], trimmed_tail np[N], final_hi np[N]):
    ``surv_counts[b][i]`` entries of block b survive for lane i (always a
    prefix — truncation removes a suffix of earlier appends), and
    ``trimmed_tail`` is where the checkpoint state itself must be cut
    (a truncation can reach below the checkpoint when unconfirmed
    leader tail existed at checkpoint time)."""
    n = ckpt_tail.shape[0]
    if not blocks:
        return [], ckpt_tail.copy(), ckpt_tail.copy()
    bases = np.stack([hi - n_app for _s, hi, n_app, _a, _r in blocks])
    his = np.stack([hi for _s, hi, _n, _a, _r in blocks])
    # suffix-min of bases strictly after each block: entries above it die
    suffix = np.full((len(blocks) + 1, n), np.iinfo(np.int32).max,
                     np.int32)
    for b in range(len(blocks) - 1, -1, -1):
        suffix[b] = np.minimum(suffix[b + 1], bases[b])
    surv = []
    for b, (_s, hi, n_app, _n_acc, _rows) in enumerate(blocks):
        end = np.minimum(his[b], suffix[b + 1])
        surv.append(np.clip(end - bases[b], 0, n_app).astype(np.int32))
    trimmed_tail = np.minimum(ckpt_tail, suffix[0])
    final_hi = his[-1]
    return surv, trimmed_tail, final_hi


def open_engine(machine, data_dir: str, n_lanes: int, n_members: int = 3,
                *, sync_mode: int = 1, write_strategy: str = "default",
                max_pending: int = 8, wal_shards: int = 1,
                wal_batch_bytes: int = 4 * 1024 * 1024,
                wal_batch_interval_ms: Optional[float] = None,
                wal_supervise: bool = True,
                settle_limit: int = 10_000, **engine_kwargs):
    """Create-or-recover a durable LockstepEngine at ``data_dir``.

    Fresh directory: a new engine wired to ``wal_shards`` new WAL
    shards.  Existing data: restore the checkpoint, merge the surviving
    shard records (any layout), replay them through the jitted step
    (recomputing machine state with the same apply fold), and resume in
    durable mode.  Matches the recovery contract of SURVEY.md §3.4 at
    engine scale: recovery = checkpoint + WAL re-read, deduped by the
    overwrite rule, applied with effects suppressed."""
    import jax
    import jax.numpy as jnp

    from .lockstep import LockstepEngine

    os.makedirs(data_dir, exist_ok=True)
    ckpt = os.path.join(data_dir, "ckpt.npz")
    meta_path = os.path.join(data_dir, "ckpt.meta.json")
    base_step = 0
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            base_step = json.load(f).get("step", 0)

    # the bridge's shard Wals scan surviving files once on construction
    # (scan_wal_file dedups per-index overwrites); the merged piece
    # tables are the step-block source for replay.  No engine writes
    # happen until attach, so constructing it up front is safe.
    dur = EngineDurability(data_dir, n_lanes, sync_mode=sync_mode,
                           write_strategy=write_strategy,
                           max_pending=max_pending,
                           wal_shards=wal_shards,
                           wal_batch_bytes=wal_batch_bytes,
                           wal_batch_interval_ms=wal_batch_interval_ms,
                           wal_supervise=wal_supervise)
    pieces = dur.recovered_pieces(base_step)

    kmax = max((p[4].shape[1] for ps in pieces.values() for p in ps),
               default=0)
    if kmax:
        # the replay apply window must cover the widest recovered block,
        # or ring backpressure would silently clip replayed entries
        engine_kwargs = dict(engine_kwargs)
        engine_kwargs["apply_window"] = max(
            engine_kwargs.get("apply_window") or 0, kmax + 2)
        ring = engine_kwargs.get("ring_capacity", 1024)
        if ring < kmax + 2:
            # the ring-write dummy slot must stay clear of the widest
            # replayed block; reopening with a smaller geometry than
            # the writer would otherwise silently corrupt the replay
            raise ValueError(
                f"ring_capacity {ring} too small to replay recovered "
                f"blocks of width {kmax}; use >= {kmax + 2} (the "
                "engine that wrote this WAL had larger max_step_cmds)")

    eng = LockstepEngine(machine, n_lanes, n_members, **engine_kwargs)
    if os.path.exists(ckpt):
        eng.restore(ckpt)
        # transient failure masks do not survive a node restart: every
        # non-removed member recovers with the node (removed members
        # have voter=False too and stay out).  Revival is by SNAPSHOT
        # INSTALL from the lane leader, vectorized over all revived
        # members — a bare active-flag flip would leave a frozen
        # applied cursor that drags the lane-uniform apply window onto
        # recycled ring slots (silent divergence).
        st = eng.state
        revive = st.voter & ~st.active
        if bool(revive.any()):
            lead = st.leader_slot[:, None]                      # [N,1]
            snap = jnp.take_along_axis(st.applied, lead, axis=1)

            def from_leader(x):
                idx = lead.reshape((n_lanes, 1) + (1,) * (x.ndim - 2))
                idx = jnp.broadcast_to(idx, (n_lanes, 1) + x.shape[2:])
                lx = jnp.take_along_axis(x, idx, axis=1)
                rv = revive.reshape(revive.shape + (1,) * (x.ndim - 2))
                return jnp.where(rv, lx, x)

            st = st._replace(
                mac=jax.tree.map(from_leader, st.mac),
                applied=jnp.where(revive, snap, st.applied),
                commit=jnp.where(revive, snap, st.commit),
                last_index=jnp.where(revive, snap, st.last_index),
                last_written=jnp.where(revive, snap, st.last_written),
                match=jnp.where(revive, 0, st.match),
                next_index=jnp.where(revive, snap + 1, st.next_index))
        eng.state = st._replace(active=st.active | st.voter)

    lane = np.arange(n_lanes)
    st = eng.state
    leader = np.asarray(st.leader_slot)
    ckpt_tail = np.asarray(st.last_index)[lane, leader].astype(np.int32)

    blocks = _assemble_blocks(pieces, n_lanes, ckpt_tail)
    surv, trimmed_tail, final_hi = _final_logs(blocks, ckpt_tail)

    if (trimmed_tail < ckpt_tail).any():
        # a post-checkpoint election truncated into the checkpoint's
        # unconfirmed tail: cut the restored cursors (commit/applied are
        # always below the cut — commit never truncates)
        t = jnp.asarray(trimmed_tail)[:, None]
        st = eng.state
        eng.state = st._replace(
            last_index=jnp.minimum(st.last_index, t),
            last_written=jnp.minimum(st.last_written, t),
            match=jnp.minimum(st.match, t),
            next_index=jnp.minimum(st.next_index, t + 1))

    if blocks:
        kmax = kmax or 1
        C = eng.payload_width
        for (s, hi, n_app, n_acc, rows), keep in zip(blocks, surv):
            pad = np.zeros((n_lanes, kmax, C), rows.dtype)
            if rows.shape[1]:
                pad[:, :rows.shape[1]] = rows
            eng.step(keep, pad)
        # settle: drain the apply/commit pipeline until every lane's
        # recovered log is fully committed and applied on every live
        # member (recovery commits the whole surviving log: it is on
        # disk, i.e. replicated on every co-hosted member by definition)
        zero_n = np.zeros((n_lanes,), np.int32)
        zero_p = np.zeros((n_lanes, 1, C), eng.payload_dtype)
        for _ in range(settle_limit):
            stn = eng.state
            com = np.asarray(stn.commit)[lane, np.asarray(stn.leader_slot)]
            active = np.asarray(stn.active)
            app = np.where(active, np.asarray(stn.applied),
                           np.iinfo(np.int32).max).min(axis=1)
            if (com >= final_hi).all() and (app >= com).all():
                break
            eng.step(zero_n, zero_p)
        else:
            raise RuntimeError("recovery settle did not converge")

    st = eng.state
    leader = np.asarray(st.leader_slot)
    tail = np.asarray(st.last_index)[lane, leader].astype(np.int32)
    last_step = max(pieces) if pieces else base_step
    dur.seed(tail, last_step)
    eng.attach_durability(dur)
    if pieces or os.path.exists(ckpt):
        # an actual recovery happened (checkpoint restore and/or WAL
        # replay): stamp the join-able report next to any post-mortem
        # bundle the crash left (ISSUE 7 — crash + recovery are one
        # incident)
        stamp_recovery(
            {"plane": "engine", "base_step": base_step,
             "replayed_steps": len(pieces),
             "resumed_at_step": last_step,
             "wal_shards": wal_shards,
             "tail_min": int(tail.min()) if tail.size else 0,
             "tail_max": int(tail.max()) if tail.size else 0},
            data_dir=data_dir)
    return eng
