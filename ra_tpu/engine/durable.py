"""Durability bridge between the lockstep lane engine and the fan-in WAL.

This closes the loop the engine docstring describes: in durable mode a
step's accepted entries are pulled off-device (double-buffered — the aux
readback of step N overlaps the dispatch of step N+1), encoded as ONE
WAL record per step, and fed through :class:`ra_tpu.log.wal.Wal`.  The
WAL's fsync confirm comes back as the ``confirm_upto`` input of a later
step, so ``last_written`` — and therefore the commit quorum — advances
only over entries that are really on disk.  This is the engine-scale
version of the reference's written-event protocol: an entry only counts
toward the commit median after write(2)+fsync
(/root/reference/src/ra_log_wal.erl:753-800), and the batch unit is the
device step — the fan-in batching axis of SURVEY.md §2.4 (one WAL batch
= one XLA dispatch worth of appends for ALL co-hosted clusters).

Record format (one WAL payload per step, uid ``__engine__``):

  magic "RTB1"(4) | n_lanes:u32 | C:u32 | dtype:8s | n_flat:u32
  hi:    i32[N]   leader tail after the step (per lane)
  n_app: i32[N]   entries appended this step (accepted cmds + noop)
  n_acc: i32[N]   how many of those came from the host batch
  flat:  [n_flat, C] the accepted host rows, lane-major

``hi - n_app`` is the step's append base; a base below the running tail
records an election truncation (a deposed leader's unconfirmed suffix),
exactly the overwrite-invalidates-higher-indexes rule of WAL recovery
(/root/reference/src/ra_log_wal.erl:871-955) at step granularity.
Entries between ``n_acc`` and ``n_app`` are the term-opening noop
(all-zero payload, the machine-noop encoding).

Recovery (:func:`open_engine`) restores the latest checkpoint, scans the
surviving WAL files, resolves truncations into the final per-lane logs,
and replays them through the jitted step — machine state is recomputed
by the same apply fold that produced it.  A crash (kill -9) therefore
loses nothing that was ever reported committed: commits gate on
confirms, and confirmed records are on disk by definition.

Checkpointing (:meth:`EngineDurability.checkpoint`) quiesces the WAL,
snapshots the full lane state via ``engine.save`` (atomic .npz), and
prunes WAL files whose records the checkpoint covers — the
release_cursor/snapshot-truncation role of ra_snapshot.erl at the
engine scale.
"""
from __future__ import annotations

import collections
import json
import os
import struct
import threading
import time
from typing import Optional

import numpy as np

from ..log.wal import Wal, WalDown

UID = "__engine__"
MAGIC = b"RTB1"
_BLK = struct.Struct("<4sII8sI")


def encode_block(hi: np.ndarray, n_app: np.ndarray, n_acc: np.ndarray,
                 payload_host: np.ndarray) -> bytes:
    """Encode one step's append outcome as a single WAL payload."""
    N, K, C = payload_host.shape
    mask = np.arange(K)[None, :] < n_acc[:, None]
    flat = np.ascontiguousarray(payload_host[mask])
    dt = np.dtype(payload_host.dtype).str.encode().ljust(8, b"\x00")
    head = _BLK.pack(MAGIC, N, C, dt, flat.shape[0])
    return b"".join((head,
                     np.ascontiguousarray(hi, "<i4").tobytes(),
                     np.ascontiguousarray(n_app, "<i4").tobytes(),
                     np.ascontiguousarray(n_acc, "<i4").tobytes(),
                     flat.tobytes()))


def decode_block(data: bytes):
    """Inverse of :func:`encode_block` -> (hi, n_app, n_acc, rows) where
    rows is [N, Kmax, C] with noop rows already zero-filled."""
    magic, n, c, dt, n_flat = _BLK.unpack_from(data, 0)
    if magic != MAGIC:
        raise ValueError("bad engine block magic")
    dtype = np.dtype(dt.rstrip(b"\x00").decode())
    off = _BLK.size
    hi = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    n_app = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    n_acc = np.frombuffer(data, "<i4", n, off).astype(np.int32)
    off += 4 * n
    flat = np.frombuffer(data, dtype, n_flat * c, off).reshape(n_flat, c)
    kmax = int(n_app.max()) if n else 0
    rows = np.zeros((n, kmax, c), dtype)
    if kmax:
        mask = np.arange(kmax)[None, :] < n_acc[:, None]
        rows[mask] = flat
    return hi, n_app, n_acc, rows


class _WalFileRetirer:
    """Duck-typed segment_writer for the engine's Wal: instead of
    flushing per-server memtables to segments, rolled WAL files are kept
    until a checkpoint covers their step range, then unlinked — the
    engine's .npz checkpoint plays the segment role (the WAL-file
    deletion barrier of ra_log_segment_writer.erl:129-201)."""

    def __init__(self) -> None:
        self._files: list = []  # (hi_step, path)
        self._lock = threading.Lock()

    def accept_ranges(self, ranges: dict, wal_path: str) -> None:
        hi = max(r[1] for r in ranges.values())
        with self._lock:
            self._files.append((hi, wal_path))

    def retire(self, uids: list, wal_files: list) -> None:
        # recovered files: every record in them predates any future
        # checkpoint, so hi=0 (pruned by the first checkpoint taken)
        with self._lock:
            for path in wal_files:
                self._files.append((0, path))

    def mark_deleted(self, uid: str) -> None:  # pragma: no cover
        pass

    def prune(self, ckpt_step: int) -> None:
        with self._lock:
            keep = []
            for hi, path in self._files:
                if hi <= ckpt_step:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                else:
                    keep.append((hi, path))
            self._files = keep


class EngineDurability:
    """Host-side bridge: owns the engine's Wal, the inflight aux queue,
    and the confirm feedback arrays."""

    def __init__(self, data_dir: str, n_lanes: int, *, sync_mode: int = 1,
                 write_strategy: str = "default", max_pending: int = 8,
                 wal_max_size: int = 256 * 1024 * 1024) -> None:
        os.makedirs(data_dir, exist_ok=True)
        self.dir = data_dir
        self.n_lanes = n_lanes
        self.max_pending = max_pending
        self.retirer = _WalFileRetirer()
        self.wal = Wal(data_dir, sync_mode=sync_mode,
                       write_strategy=write_strategy,
                       max_size=wal_max_size, segment_writer=self.retirer)
        self.step_seq = 0
        self.confirmed_step = 0
        self.confirm_upto = np.zeros((n_lanes,), np.int32)
        self._prev_hi = np.zeros((n_lanes,), np.int32)
        self._appended: dict = {}     # step -> hi np[N] (until confirmed)
        self._blocks: dict = {}       # step -> bytes   (until confirmed)
        self._bases: dict = {}        # step -> base np[N] (until confirmed)
        self._inflight: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._wal_generation = self.wal.generation
        self._resend_above: Optional[int] = None
        self.wal.register(UID, self._notify)

    def seed(self, prev_hi: np.ndarray, step_seq: int) -> None:
        """Set the post-recovery baseline: everything up to ``prev_hi``
        is durable and recorded through ``step_seq``."""
        self._prev_hi = prev_hi.astype(np.int32).copy()
        self.confirm_upto = prev_hi.astype(np.int32).copy()
        self.step_seq = step_seq
        self.confirmed_step = step_seq

    # -- WAL confirm path (called from the WAL batch thread) ---------------

    def _notify(self, uid: str, lo: Optional[int], hi: int,
                term: int) -> None:
        with self._cond:
            if lo is None:
                # out-of-sequence signal: resend everything above hi on
                # the host thread (ra_log_wal.erl:457-481)
                self._resend_above = hi
                self._cond.notify_all()
                return
            if hi <= self.confirmed_step:
                return
            self.confirmed_step = hi
            arr = self._appended.get(hi)
            if arr is not None:
                # exact durable tail as of step hi — then re-apply the
                # bases of still-unconfirmed steps: an unconfirmed
                # truncation means indexes above its base are occupied
                # by entries not yet on disk
                confirm = arr.copy()
                for s, base in self._bases.items():
                    if s > hi:
                        confirm = np.minimum(confirm, base)
                self.confirm_upto = confirm
            for s in [s for s in self._appended if s <= hi]:
                del self._appended[s]
                self._blocks.pop(s, None)
                self._bases.pop(s, None)
            self._cond.notify_all()

    # -- submit path (engine host thread) ----------------------------------

    def submit(self, aux: dict, payload_host: np.ndarray) -> None:
        """Queue step aux for WAL encoding; drains older steps (their
        device values are ready by now — one step of overlap)."""
        self._maybe_resend()
        self._inflight.append((aux, payload_host))
        while len(self._inflight) > 1:
            self._drain_one()

    def drain_all(self) -> None:
        while self._inflight:
            self._drain_one()

    def _drain_one(self) -> None:
        aux, ph = self._inflight.popleft()
        hi = np.asarray(aux["appended_hi"]).astype(np.int32)
        n_acc = np.asarray(aux["n_acc"]).astype(np.int32)
        n_app = np.asarray(aux["n_app"]).astype(np.int32)
        base = hi - n_app
        blk = encode_block(hi, n_app, n_acc, ph)
        self._prev_hi = hi
        self.step_seq += 1
        with self._cond:
            self._appended[self.step_seq] = hi
            self._blocks[self.step_seq] = blk
            self._bases[self.step_seq] = base
            # an election truncation reuses indexes: the durable horizon
            # drops to the step's base until this block itself confirms
            self.confirm_upto = np.minimum(self.confirm_upto, base)
        self.wal.write(UID, self.step_seq, 1, blk)

    def _maybe_resend(self) -> None:
        """After a WAL crash+restart (or an out-of-sequence signal),
        resend every unconfirmed block above the WAL's durable horizon
        (the resend_from protocol, ra_log.erl:778-793)."""
        resend_from = None
        with self._cond:
            if self._resend_above is not None:
                resend_from = self._resend_above
                self._resend_above = None
        if self.wal.generation != self._wal_generation and self.wal.alive:
            self._wal_generation = self.wal.generation
            resend_from = self.confirmed_step
        if resend_from is None:
            return
        with self._cond:
            pending = sorted((s, b) for s, b in self._blocks.items()
                             if s > resend_from)
        for s, b in pending:
            self.wal.write(UID, s, 1, b)

    def backpressure(self, timeout: float = 30.0) -> None:
        """Bound the unconfirmed window: wait for WAL confirms when more
        than ``max_pending`` steps are in flight (the flow control a
        gen_batch_server gets from its bounded mailbox)."""
        self._maybe_resend()
        while self._inflight and \
                self.step_seq - self.confirmed_step >= self.max_pending:
            self._drain_one()
        if self.step_seq - self.confirmed_step < self.max_pending:
            return
        deadline = time.monotonic() + timeout
        while True:
            # sliced wait: WAL thread death never notifies the condition
            with self._cond:
                self._cond.wait_for(
                    lambda: self.step_seq - self.confirmed_step <
                    self.max_pending or self._resend_above is not None
                    or not self.wal.alive,
                    min(0.5, max(0.0, deadline - time.monotonic())))
                under = self.step_seq - self.confirmed_step < \
                    self.max_pending
            if not self.wal.alive:
                raise WalDown("engine WAL died under backpressure; call "
                              "wal.restart() to resume")
            self._maybe_resend()
            if under:
                return
            if time.monotonic() > deadline:
                raise TimeoutError("WAL confirms stalled")

    # -- checkpoint / recovery --------------------------------------------

    def checkpoint(self, engine, timeout: float = 30.0) -> str:
        while self._inflight:
            self._drain_one()
        deadline = time.monotonic() + timeout
        # wait in slices: WAL thread death never notifies the condition,
        # and an out-of-sequence signal needs a resend, not more waiting
        while True:
            self._maybe_resend()
            self.wal.flush()
            with self._cond:
                self._cond.wait_for(
                    lambda: self.confirmed_step >= self.step_seq
                    or self._resend_above is not None
                    or not self.wal.alive,
                    min(0.5, max(0.0, deadline - time.monotonic())))
                done = self.confirmed_step >= self.step_seq
            if done:
                break
            if not self.wal.alive:
                raise WalDown("checkpoint: WAL died; wal.restart() and "
                              "retry")
            if time.monotonic() > deadline:
                raise TimeoutError("checkpoint: WAL confirms stalled")
        path = os.path.join(self.dir, "ckpt.npz")
        engine.save(path)
        meta = {"step": self.step_seq}
        tmp = path + ".meta.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, "ckpt.meta.json"))
        # roll the current WAL file so its (now-covered) records become
        # prunable, then drop every covered file
        self.wal.rollover()
        self.wal.flush()
        self.retirer.prune(self.step_seq)
        return path

    def close(self) -> None:
        try:
            while self._inflight:
                self._drain_one()
            self.wal.flush()
        except (WalDown, TimeoutError):
            pass  # best-effort: a dead WAL must not block cleanup
        self.wal.close()


def _final_logs(blocks: list, ckpt_tail: np.ndarray):
    """Resolve election truncations across recovered step blocks into the
    surviving per-step entry counts.

    blocks: [(step, hi, n_app, n_acc, rows)] in step order.  Returns
    (surv_counts per block [N], trimmed_tail np[N], final_hi np[N]):
    ``surv_counts[b][i]`` entries of block b survive for lane i (always a
    prefix — truncation removes a suffix of earlier appends), and
    ``trimmed_tail`` is where the checkpoint state itself must be cut
    (a truncation can reach below the checkpoint when unconfirmed
    leader tail existed at checkpoint time)."""
    n = ckpt_tail.shape[0]
    if not blocks:
        return [], ckpt_tail.copy(), ckpt_tail.copy()
    bases = np.stack([hi - n_app for _s, hi, n_app, _a, _r in blocks])
    his = np.stack([hi for _s, hi, _n, _a, _r in blocks])
    # suffix-min of bases strictly after each block: entries above it die
    suffix = np.full((len(blocks) + 1, n), np.iinfo(np.int32).max,
                     np.int32)
    for b in range(len(blocks) - 1, -1, -1):
        suffix[b] = np.minimum(suffix[b + 1], bases[b])
    surv = []
    for b, (_s, hi, n_app, _n_acc, _rows) in enumerate(blocks):
        end = np.minimum(his[b], suffix[b + 1])
        surv.append(np.clip(end - bases[b], 0, n_app).astype(np.int32))
    trimmed_tail = np.minimum(ckpt_tail, suffix[0])
    final_hi = his[-1]
    return surv, trimmed_tail, final_hi


def open_engine(machine, data_dir: str, n_lanes: int, n_members: int = 3,
                *, sync_mode: int = 1, write_strategy: str = "default",
                max_pending: int = 8,
                settle_limit: int = 10_000, **engine_kwargs):
    """Create-or-recover a durable LockstepEngine at ``data_dir``.

    Fresh directory: a new engine wired to a new WAL.  Existing data:
    restore the checkpoint, replay surviving WAL records through the
    jitted step (recomputing machine state with the same apply fold),
    and resume in durable mode.  Matches the recovery contract of
    SURVEY.md §3.4 at engine scale: recovery = checkpoint + WAL re-read,
    deduped by the overwrite rule, applied with effects suppressed."""
    import jax
    import jax.numpy as jnp

    from .lockstep import LockstepEngine

    os.makedirs(data_dir, exist_ok=True)
    ckpt = os.path.join(data_dir, "ckpt.npz")
    meta_path = os.path.join(data_dir, "ckpt.meta.json")
    base_step = 0
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            base_step = json.load(f).get("step", 0)

    # the bridge's Wal scans surviving files once on construction
    # (scan_wal_file dedups per-index overwrites); its recovered table
    # is the step-block source for replay.  No engine writes happen
    # until attach, so constructing it up front is safe.
    dur = EngineDurability(data_dir, n_lanes, sync_mode=sync_mode,
                           write_strategy=write_strategy,
                           max_pending=max_pending)
    steps = {s: blk for s, (_t, blk)
             in dur.wal.recovered_table(UID).items() if s > base_step}

    blocks = []
    for s in sorted(steps):
        hi, n_app, n_acc, rows = decode_block(steps[s])
        blocks.append((s, hi, n_app, n_acc, rows))
    kmax = max((r.shape[1] for *_x, r in blocks), default=0)
    if kmax:
        # the replay apply window must cover the widest recovered block,
        # or ring backpressure would silently clip replayed entries
        engine_kwargs = dict(engine_kwargs)
        engine_kwargs["apply_window"] = max(
            engine_kwargs.get("apply_window") or 0, kmax + 2)
        ring = engine_kwargs.get("ring_capacity", 1024)
        if ring < kmax + 2:
            # the ring-write dummy slot must stay clear of the widest
            # replayed block; reopening with a smaller geometry than
            # the writer would otherwise silently corrupt the replay
            raise ValueError(
                f"ring_capacity {ring} too small to replay recovered "
                f"blocks of width {kmax}; use >= {kmax + 2} (the "
                "engine that wrote this WAL had larger max_step_cmds)")

    eng = LockstepEngine(machine, n_lanes, n_members, **engine_kwargs)
    if os.path.exists(ckpt):
        eng.restore(ckpt)
        # transient failure masks do not survive a node restart: every
        # non-removed member recovers with the node (removed members
        # have voter=False too and stay out).  Revival is by SNAPSHOT
        # INSTALL from the lane leader, vectorized over all revived
        # members — a bare active-flag flip would leave a frozen
        # applied cursor that drags the lane-uniform apply window onto
        # recycled ring slots (silent divergence).
        st = eng.state
        revive = st.voter & ~st.active
        if bool(revive.any()):
            lead = st.leader_slot[:, None]                      # [N,1]
            snap = jnp.take_along_axis(st.applied, lead, axis=1)

            def from_leader(x):
                idx = lead.reshape((n_lanes, 1) + (1,) * (x.ndim - 2))
                idx = jnp.broadcast_to(idx, (n_lanes, 1) + x.shape[2:])
                lx = jnp.take_along_axis(x, idx, axis=1)
                rv = revive.reshape(revive.shape + (1,) * (x.ndim - 2))
                return jnp.where(rv, lx, x)

            st = st._replace(
                mac=jax.tree.map(from_leader, st.mac),
                applied=jnp.where(revive, snap, st.applied),
                commit=jnp.where(revive, snap, st.commit),
                last_index=jnp.where(revive, snap, st.last_index),
                last_written=jnp.where(revive, snap, st.last_written),
                match=jnp.where(revive, 0, st.match),
                next_index=jnp.where(revive, snap + 1, st.next_index))
        eng.state = st._replace(active=st.active | st.voter)

    lane = np.arange(n_lanes)
    st = eng.state
    leader = np.asarray(st.leader_slot)
    ckpt_tail = np.asarray(st.last_index)[lane, leader].astype(np.int32)

    surv, trimmed_tail, final_hi = _final_logs(blocks, ckpt_tail)

    if (trimmed_tail < ckpt_tail).any():
        # a post-checkpoint election truncated into the checkpoint's
        # unconfirmed tail: cut the restored cursors (commit/applied are
        # always below the cut — commit never truncates)
        t = jnp.asarray(trimmed_tail)[:, None]
        st = eng.state
        eng.state = st._replace(
            last_index=jnp.minimum(st.last_index, t),
            last_written=jnp.minimum(st.last_written, t),
            match=jnp.minimum(st.match, t),
            next_index=jnp.minimum(st.next_index, t + 1))

    if blocks:
        kmax = kmax or 1
        C = eng.payload_width
        for (s, hi, n_app, n_acc, rows), keep in zip(blocks, surv):
            pad = np.zeros((n_lanes, kmax, C), rows.dtype)
            if rows.shape[1]:
                pad[:, :rows.shape[1]] = rows
            eng.step(keep, pad)
        # settle: drain the apply/commit pipeline until every lane's
        # recovered log is fully committed and applied on every live
        # member (recovery commits the whole surviving log: it is on
        # disk, i.e. replicated on every co-hosted member by definition)
        zero_n = np.zeros((n_lanes,), np.int32)
        zero_p = np.zeros((n_lanes, 1, C), eng.payload_dtype)
        for _ in range(settle_limit):
            stn = eng.state
            com = np.asarray(stn.commit)[lane, np.asarray(stn.leader_slot)]
            active = np.asarray(stn.active)
            app = np.where(active, np.asarray(stn.applied),
                           np.iinfo(np.int32).max).min(axis=1)
            if (com >= final_hi).all() and (app >= com).all():
                break
            eng.step(zero_n, zero_p)
        else:
            raise RuntimeError("recovery settle did not converge")

    st = eng.state
    leader = np.asarray(st.leader_slot)
    tail = np.asarray(st.last_index)[lane, leader].astype(np.int32)
    last_step = max(steps) if steps else base_step
    dur.seed(tail, last_step)
    eng.attach_durability(dur)
    return eng
