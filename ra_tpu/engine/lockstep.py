"""Lockstep multi-Raft lane engine — thousands of co-hosted clusters as one
XLA program.

This is the TPU-native inversion of the reference's process-per-server
design (SURVEY.md §7.1): instead of one gen_statem per member
(ra_server_proc.erl), *all* members of *all* co-hosted clusters live in SoA
device arrays with a leading lane axis, and one jitted ``step`` advances
every cluster simultaneously:

  1. leader append     — host-enqueued command batches land in a device
                         payload ring (the host→HBM entry ring; the
                         fan-in role of ra_log_wal.erl:193-214)
  2. replication       — followers adopt the leader tail, bounded by the
                         per-peer pipeline window (ra_server.hrl:7)
  3. write confirm     — last_written tracks the WAL fsync confirm; with
                         ``write_delay=1`` it lags one step, reproducing
                         the async written-event protocol (ra_log.erl:474+)
  4. reply fold + quorum — ops.quorum.update_match_next / evaluate_quorum
                         (ra_server.erl:418-454, 2941-2993)
  5. apply fold        — lax.scan over the committed window, vmapped over
                         (lane, member), calling the machine's jit_apply
                         (the ra_machine_xla contract; host machines use
                         the oracle path instead)

Rare/divergent transitions (member failure, election, membership change)
are host-initiated: the host failure detector marks members down and
requests elections via mask inputs; the vote round itself runs on-device
— candidate selection by best durable log, per-voter grant decisions,
and counted quorum via ops.quorum.election_quorum — so a minority
partition cannot seat a leader (ra_server.erl:986-1002, 2260-2319).
Divergent follower tails (a healed deposed leader's uncommitted
entries) are truncated by an every-step consistency clamp before the
quorum fold reads them (ra_server.erl:1032-1156), and replication is
governed by the pipeline_credit flow-control kernel
(ra_server.erl:1862-1918).

The lane axis is embarrassingly parallel: sharding it over a
jax.sharding.Mesh scales co-hosted clusters across chips with zero
cross-lane collectives (see ra_tpu.parallel.mesh).
"""
from __future__ import annotations

import collections
import functools
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import devicewatch, trace
from ..blackbox import record
from ..core.machine import JitMachine
from ..metrics import ENGINE_PIPELINE_FIELDS, TELEMETRY_FIELDS
from ..ops.exact import split16_matmul
from ..ops.quorum import (election_quorum, evaluate_quorum, pipeline_credit,
                          query_quorum, update_match_next)

Array = jax.Array


def _ring_write(ring: Array, payloads: Array, leader_last: Array,
                n_acc: Array, elect_ok: Array, *, impl: str) -> Array:
    """Append ``n_acc`` payload rows (entries leader_last+1..+n_acc at
    slots (idx-1) % R) plus, on a won election, the zero-payload
    term-opening noop — without a generic scatter.

    impl='gather': per-row put_along_axis with masked columns parked on
    a dummy slot one past the write range (needs R >= K+2).
    impl='onehot': one-hot matmul over the whole ring (MXU path)."""
    N, R, C = ring.shape
    K = payloads.shape[1]
    vals = jnp.concatenate(
        [payloads.astype(ring.dtype), jnp.zeros((N, 1, C), ring.dtype)],
        axis=1)                                              # [N,K+1,C]
    if impl == "onehot":
        r_idx = jnp.arange(R)[None, :]
        rel = (r_idx - leader_last[:, None]) % R             # [N,R]
        in_rng = (rel < n_acc[:, None]) | \
            ((rel == n_acc[:, None]) & elect_ok[:, None])
        # the noop slot (rel == n_acc) takes the zero column K
        col = jnp.where(rel == n_acc[:, None], K, rel)
        oh = (col[:, :, None] ==
              jnp.arange(K + 1)[None, None, :]).astype(jnp.float32)
        written = split16_matmul(oh, vals)                  # [N,R,C]
        return jnp.where(in_rng[..., None], written, ring)
    k_idx = jnp.arange(K + 1)
    dest = (leader_last[:, None] + k_idx[None, :]) % R       # [N,K+1]
    noop_col = k_idx[None, :] == n_acc[:, None]
    write_mask = (k_idx[None, :] < n_acc[:, None]) | \
        (noop_col & elect_ok[:, None])
    dummy = ((leader_last + K + 1) % R)[:, None]
    dest_s = jnp.where(write_mask, dest, dummy)
    vals = jnp.where(noop_col[..., None], jnp.zeros((), ring.dtype), vals)
    dest3 = jnp.broadcast_to(dest_s[..., None], vals.shape)
    old = jnp.take_along_axis(ring, dest3, axis=1)
    vals = jnp.where(write_mask[..., None], vals, old)
    return jnp.put_along_axis(ring, dest3, vals, axis=1, inplace=False)


def _ring_read_window(ring: Array, idx_lane: Array, *, impl: str) -> Array:
    """Read the per-lane entry window ``idx_lane`` (int32[N,A], entry
    indexes) from the ring: [N,A,C].  Slot mapping (idx-1) % R."""
    N, R, C = ring.shape
    slot = (idx_lane - 1) % R
    if impl == "onehot":
        oh = (slot[:, :, None] ==
              jnp.arange(R)[None, None, :]).astype(jnp.float32)
        return split16_matmul(oh, ring)
    return jnp.take_along_axis(
        ring, jnp.broadcast_to(slot[..., None], slot.shape + (C,)),
        axis=1)


class LaneTelemetry(NamedTuple):
    """Device-resident per-lane telemetry accumulators (ISSUE 6): the
    ``[lanes]``-shaped int32 pytree updated by every jitted step —
    which of 100k lanes is stuck, churning leaders or lagging commit,
    answerable without a host-syncing readback.  Field meanings are the
    registry's (metrics.TELEMETRY_FIELDS, parity pinned by tests);
    aggregation to histograms/top-K happens in :func:`_telemetry_summary`
    at sampling cadence, NOT per step.  All fields share int32[N] so the
    pytree donates, shards (lanes axis) and checkpoints exactly like
    the rest of LaneState — it rides inside it."""

    elections_requested: Array  # int32[N] host-requested vote rounds
    elections_won: Array        # int32[N] rounds that seated a leader
    leader_changes: Array       # int32[N] leader moved to another slot
    leader_age: Array           # int32[N] steps since last leader change
    commit_lag: Array           # int32[N] leader tail - leader commit
    apply_lag: Array            # int32[N] leader commit - apply frontier
    stall_steps: Array          # int32[N] consecutive no-progress rounds
                                #          with a nonempty commit backlog
    steps: Array                # int32[N] engine rounds observed


assert LaneTelemetry._fields == TELEMETRY_FIELDS  # registry parity


def _init_telemetry(n_lanes: int) -> LaneTelemetry:
    # one zeros() PER field: sharing a single array across the fields
    # would alias one device buffer 8 ways, and the donating superstep
    # path rejects a donated buffer appearing twice in an Execute()
    return LaneTelemetry(*(jnp.zeros((n_lanes,), jnp.int32)
                           for _ in LaneTelemetry._fields))


class LaneState(NamedTuple):
    """SoA state for N lanes × P member slots (ra_server_state() flattened —
    the per-lane scalars and per-lane×peer fields listed in SURVEY.md §7.1)."""

    term: Array           # int32[N]   shared current term (steady state)
    leader_slot: Array    # int32[N]   which slot leads the lane
    term_start: Array     # int32[N]   index of this term's noop (§5.4.2 gate)
    last_index: Array     # int32[N,P] per-member log tail
    last_written: Array   # int32[N,P] fsync-confirmed tail
    match: Array          # int32[N,P] leader's view (own slot = own written)
    next_index: Array     # int32[N,P] per-peer send cursor
    commit: Array         # int32[N,P] per-member commit index
    applied: Array        # int32[N,P] per-member last applied
    voter: Array          # bool[N,P]  voting members
    active: Array         # bool[N,P]  member exists and is up
    ring: Array           # int32/…[N,R,C] payload ring (device log window)
    ring_base: Array      # int32[N]   reclaim horizon (entries <= base may
                          #            be recycled; mapping is (idx-1) % R)
    total_committed: Array  # int32[N] cumulative committed entries per lane
    query_index: Array    # int32[N]   consistent-query counter
                          #            (ra_server.erl:3035-3071)
    peer_query: Array     # int32[N,P] per-member confirmed query index
                          #            (#heartbeat_reply, :3101-3170)
    query_agreed: Array   # int32[N]   majority-confirmed query index
    # -- vectorized read plane (ISSUE 20): leases + read-index state ------
    read_clock: Array     # int32[N]   monotone step clock (lease base)
    lease_until: Array    # int32[N]   leader lease expiry, read_clock units
    read_buf: Array       # [N,Kr,Cq]  pending read-query batch (device)
    read_n: Array         # int32[N]   pending read count (0 = slot free)
    read_ix: Array        # int32[N]   captured read index (commit at reg.)
    read_tok: Array       # int32[N]   captured heartbeat token (reg. round)
    read_reg: Array       # int32[N]   registration clock (timeout base)
    read_served: Array    # int32[N]   cumulative reads served
    read_shed: Array      # int32[N]   cumulative reads shed at arrival
    read_stale: Array     # int32[N]   cumulative stale-refusals (timeouts)
    read_leased: Array    # int32[N]   served-under-lease subset
    telem: Any            # LaneTelemetry pytree, int32[N] per field
    mac: Any              # machine state pytree, leading dims [N,P]


#: RA15 checkpoint schema registry (ISSUE 15): per-field restore
#: behaviour for archives written BEFORE the field existed.  Every
#: LaneState field MUST have an entry (the static gate pins parity
#: with ``LaneState._fields``), so adding a pytree field forces the
#: author to declare its forward-compat default here — and
#: :meth:`LockstepEngine.restore` fills it generically, so a
#: checkpoint format bump can never strand a durable dir again (the
#: PR 6 pre-telemetry ``restore()`` KeyError, closed for every future
#: field, not just ``telem``).
#:
#:   "require" — consensus-bearing state every archive has always
#:               carried; a missing leaf is a corrupt archive, refuse
#:   "zeros"   — derived/health state that restarts from zero
#:               (zeros_like the restoring engine's leaf)
#:   "init"    — keep the restoring engine's CURRENT value for the
#:               field (for fields whose zero is not the correct
#:               default, e.g. a future all-ones mask).  In the
#:               open_engine recovery path the engine is freshly
#:               constructed before restore(), so this IS the
#:               fresh-init value; a mid-run rollback keeps the live
#:               value — callers wanting a true re-init must restore
#:               into a fresh engine
CHECKPOINT_FIELD_DEFAULTS = {
    "term": "require",
    "leader_slot": "require",
    "term_start": "require",
    "last_index": "require",
    "last_written": "require",
    "match": "require",
    "next_index": "require",
    "commit": "require",
    "applied": "require",
    "voter": "require",
    "active": "require",
    "ring": "require",
    "ring_base": "require",
    "total_committed": "require",
    "query_index": "require",
    "peer_query": "require",
    "query_agreed": "require",
    # read plane (ISSUE 20): ALL "zeros" — a lease must never survive a
    # restart (the restarting process has no idea how long it was down,
    # so an archived lease could outlive the wall-clock grant), and a
    # pending read batch's client is gone; cumulative read counters are
    # health state like telem
    "read_clock": "zeros",
    "lease_until": "zeros",
    "read_buf": "zeros",
    "read_n": "zeros",
    "read_ix": "zeros",
    "read_tok": "zeros",
    "read_reg": "zeros",
    "read_served": "zeros",
    "read_shed": "zeros",
    "read_stale": "zeros",
    "read_leased": "zeros",
    "telem": "zeros",       # health counters: restart from zero
    "mac": "require",
}


def _init_state(n_lanes: int, n_members: int, ring_capacity: int,
                payload_width: int, mac_state: Any,
                payload_dtype=jnp.int32, read_window: int = 1,
                query_width: int = 1,
                query_dtype=jnp.int32) -> LaneState:
    N, P, R, C = n_lanes, n_members, ring_capacity, payload_width
    z = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
    return LaneState(
        term=jnp.ones((N,), jnp.int32),
        leader_slot=z(N),
        term_start=jnp.ones((N,), jnp.int32),
        last_index=z(N, P),
        last_written=z(N, P),
        match=z(N, P),
        next_index=jnp.ones((N, P), jnp.int32),
        commit=z(N, P),
        applied=z(N, P),
        voter=jnp.ones((N, P), bool),
        active=jnp.ones((N, P), bool),
        ring=jnp.zeros((N, R, C), payload_dtype),
        ring_base=z(N),
        total_committed=jnp.zeros((N,), jnp.int32),
        query_index=z(N),
        peer_query=z(N, P),
        query_agreed=z(N),
        read_clock=z(N),
        lease_until=z(N),
        read_buf=jnp.zeros((N, read_window, query_width), query_dtype),
        read_n=z(N),
        read_ix=z(N),
        read_tok=z(N),
        read_reg=z(N),
        read_served=z(N),
        read_shed=z(N),
        read_stale=z(N),
        read_leased=z(N),
        telem=_init_telemetry(N),
        mac=mac_state,
    )


def _step(state: LaneState, n_new: Array, payloads: Array,
          fail_mask: Array, elect_mask: Array, confirm_upto: Array,
          query_mask: Array, n_read: Array, read_q: Array, *,
          machine: JitMachine, ring_capacity: int, apply_window: int,
          pipeline_window: int, max_append_batch: int, write_delay: int,
          durable: bool = False, ring_io: str = "gather",
          lease_ttl: int = 8, read_timeout: int = 64,
          quorum_fn=evaluate_quorum):
    """One lockstep round for every lane.  Pure; jitted by the engine.

    Returns ``(new_state, aux)`` where aux carries the per-lane append
    outcome the host needs to form the step's WAL record in durable mode:
    ``appended_hi`` (the leader tail after this step) and ``n_acc`` (how
    many of the host batch were accepted — the rest were clipped by ring
    backpressure or a down leader).

    ``confirm_upto`` (int32[N]) is the durability horizon fed back from
    the fan-in WAL: with ``durable=True``, ``last_written`` only advances
    to it, so the commit quorum counts nothing that has not really been
    fsynced (the {written,..} notify protocol, ra_log_wal.erl:753-800);
    the ``write_delay`` emulation is bypassed."""
    N, P = state.last_index.shape
    R = ring_capacity
    lane = jnp.arange(N)

    # -- 0. failures, divergence repair, elections ------------------------
    active = state.active & ~fail_mask

    # divergence repair (the AER consistency-check outcome,
    # ra_server.erl:1032-1156): an active non-leader's tail can never
    # extend past its leader's log — entries beyond it are uncommitted
    # leftovers of a deposed leader and are truncated before anything
    # (quorum, apply) can read them.  Runs before the match fold so a
    # healed ex-leader's stale tail never enters the commit median.
    leader_arm0 = jax.nn.one_hot(state.leader_slot, P, dtype=jnp.bool_)
    cur_leader_last = jnp.take_along_axis(
        state.last_index, state.leader_slot[:, None], axis=-1)[:, 0]
    clamp = active & ~leader_arm0
    last_index0 = jnp.where(
        clamp, jnp.minimum(state.last_index, cur_leader_last[:, None]),
        state.last_index)
    last_written0 = jnp.minimum(state.last_written, last_index0)

    # election: the host requests one (elect_mask); the device runs the
    # vote round.  Candidate = active voter with the longest durable log
    # (the member a pre-vote round converges on, §5.4.1); each reachable
    # voter grants iff the candidate's log is up-to-date vs its own
    # (process_pre_vote/request_vote, ra_server.erl:2260-2319, 1211-1251);
    # the candidacy succeeds only on a counted quorum of grants
    # (election_quorum, ra_server.erl:986-1002).  A minority partition
    # therefore cannot elect: term and leader stay put.
    score = jnp.where(active & state.voter, last_written0, -1)
    cand = jnp.argmax(score, axis=-1).astype(jnp.int32)
    cand_written = jnp.take_along_axis(last_written0, cand[:, None],
                                       axis=-1)[:, 0]
    grants = active & state.voter & \
        (cand_written[:, None] >= last_written0)
    won = election_quorum(grants, state.voter)
    elect_ok = elect_mask & won

    leader_slot = jnp.where(elect_ok, cand, state.leader_slot)
    term = jnp.where(elect_ok, state.term + 1, state.term)
    leader_arm = jax.nn.one_hot(leader_slot, P, dtype=jnp.bool_)
    leader_last = jnp.take_along_axis(last_index0, leader_slot[:, None],
                                      axis=-1)[:, 0]
    leader_written = jnp.take_along_axis(last_written0,
                                         leader_slot[:, None], axis=-1)[:, 0]
    # new leader discards its own unwritten tail and opens its term at
    # written+1 (overwrite semantics; become-leader ra_server.erl:845-859)
    leader_last = jnp.where(elect_ok, leader_written, leader_last)
    term_start = jnp.where(elect_ok, leader_last + 1, state.term_start)
    # a won election appends the term-opening noop entry (payload 0)
    n_noop = jnp.where(elect_ok, 1, 0).astype(jnp.int32)

    # a lane whose leader is inactive cannot accept commands
    leader_up = jnp.take_along_axis(active, leader_slot[:, None],
                                    axis=-1)[:, 0]

    # -- 1. leader append into the ring (with backpressure) ---------------
    # ring headroom: entries not yet applied by every member must stay
    min_applied = jnp.min(jnp.where(active, state.applied,
                                    jnp.int32(2**30)), axis=-1)
    ring_base = jnp.maximum(state.ring_base, jnp.minimum(min_applied,
                                                         leader_last))
    used = leader_last - ring_base
    headroom = jnp.maximum(R - used - 1, 0)
    n_acc = jnp.minimum(jnp.where(leader_up, n_new, 0), headroom)
    n_acc = jnp.minimum(n_acc, payloads.shape[1])
    total_app = n_acc + jnp.where(leader_up, n_noop, 0)

    # entry index i lives at ring slot (i - 1) % R; ring_base only tracks
    # the reclaim horizon.  Write payloads at slots for indexes
    # leader_last+1 .. leader_last+n_acc, plus the term-opening noop
    # (zeros — the machine-noop encoding) on a won election.  A generic
    # scatter would serialize on TPU; see _ring_write for the two fast
    # lowerings.
    ring = _ring_write(state.ring, payloads, leader_last, n_acc,
                       elect_ok, impl=ring_io)
    new_leader_last = leader_last + total_app

    # -- 2. replication, governed by per-peer pipeline credit --------------
    # a won election resets peer cursors (initialise_peers,
    # ra_server.erl:845-859: next := last+1, match := 0)
    next0 = jnp.where(elect_ok[:, None], new_leader_last[:, None] + 1,
                      state.next_index)
    match0 = jnp.where(elect_ok[:, None],
                       jnp.where(leader_arm, leader_written[:, None], 0),
                       state.match)
    # flow control: entries shipped this round are bounded by the in-flight
    # window and the AER batch size (make_pipelined_rpc_effects,
    # ra_server.erl:1862-1918; limits ra_server.hrl:7-8)
    n_send, _needs = pipeline_credit(next0, match0, new_leader_last,
                                     jnp.zeros((N,), jnp.int32),
                                     jnp.zeros((N, P), jnp.int32),
                                     pipeline_window, max_append_batch)
    send_hi = next0 + n_send - 1
    # adopt only when entries actually ship (n_send > 0): a truncated
    # member's stale send cursor must not resurrect its old tail via
    # send_hi before the cursor itself is repaired below
    last_index = jnp.where(active & (n_send > 0),
                           jnp.maximum(last_index0, send_hi),
                           last_index0)
    last_index = jnp.where(leader_arm,
                           jnp.broadcast_to(new_leader_last[:, None], (N, P)),
                           last_index)
    # on a won election, follower tails cap at the NEW leader's log in the
    # same round — the step-start clamp ran against the old leader, and
    # without this a longer follower tail would enter the match fold below
    # as a phantom replica for one step (§5.4 safety)
    last_index = jnp.where(elect_ok[:, None] & active,
                           jnp.minimum(last_index,
                                       new_leader_last[:, None]),
                           last_index)

    # -- 3. write confirm (async WAL protocol) ----------------------------
    if durable:
        # real confirms: the host feeds back the fan-in WAL's durable
        # horizon; nothing beyond it enters the quorum median.  On a won
        # election the horizon is additionally capped at the new leader's
        # pre-noop written tail: the truncated suffix's indexes are being
        # REUSED by fresh entries, so a confirm that covered the old
        # suffix must not vouch for the replacements (the (index,term)
        # identity of the written-event protocol, ra_log.erl:474+)
        eff_confirm = jnp.where(elect_ok,
                                jnp.minimum(confirm_upto, leader_written),
                                confirm_upto)
        last_written = jnp.where(active,
                                 jnp.minimum(last_index,
                                             eff_confirm[:, None]),
                                 last_written0)
    elif write_delay == 0:
        last_written = jnp.where(active, last_index, last_written0)
    else:
        # confirms lag one step: this step confirms the *previous* tail
        last_written = jnp.where(active,
                                 jnp.minimum(last_index, last_index0),
                                 last_written0)
    last_written = jnp.minimum(last_written, last_index)

    # -- 4. reply fold + quorum -------------------------------------------
    match, _ = update_match_next(match0, next0,
                                 active, last_written, last_index + 1)
    # lockstep has perfect reply information, so the send cursor tracks the
    # follower tail directly — in particular it *decreases* after a
    # divergence truncation, reopening credit (the reference's next_index
    # decrement on failed AER, ra_server.erl:477-529)
    next_index = jnp.where(active, last_index + 1, next0)
    leader_commit0 = jnp.take_along_axis(state.commit, leader_slot[:, None],
                                         axis=-1)[:, 0]
    # NB: down members stay in the quorum denominator (their match just
    # freezes) — a leader that lost a majority must stop committing
    new_leader_commit = quorum_fn(leader_commit0, match,
                                  state.voter, term_start)
    # followers learn commit via the (lockstep) AER broadcast, bounded by
    # their own log (evaluate_commit_index_follower: min(last_index, CI))
    commit = jnp.minimum(new_leader_commit[:, None], last_index)
    commit = jnp.where(active, jnp.maximum(commit, state.commit),
                       state.commit)
    delta = (jnp.take_along_axis(commit, leader_slot[:, None], axis=-1)[:, 0]
             - leader_commit0)
    total_committed = state.total_committed + delta

    # -- 4a. lease grant/expiry + read-batch registration (ISSUE 20) ------
    # The leader lease is PURE per-lane arithmetic on the heartbeat
    # round the lockstep step already is: a leader whose lane holds a
    # counted quorum of active voters this round (the same grant
    # arithmetic the vote round uses) extends its lease to
    # read_clock + lease_ttl; a leader cut from its majority stops
    # extending and the lease expires lease_ttl rounds later; a won
    # election revokes it outright (the new leader earns its own).
    # Note the SoA model admits no split-brain within a lane —
    # leader_slot is lane-global, so a deposed leader cannot serve
    # anything; the lease here bounds serving under LOST quorum (the
    # partitioned-leader window before the host triggers an election),
    # which is exactly what the read oracle pins.
    read_clock = state.read_clock + 1
    lease_q = election_quorum(active & state.voter, state.voter)
    lease_until = jnp.where(elect_ok, 0, state.lease_until)
    lease_until = jnp.where(
        lease_q & leader_up,
        jnp.maximum(lease_until, read_clock + lease_ttl), lease_until)
    lease_ok = read_clock < lease_until

    # read registration: reads NEVER touch the ring (zero log appends).
    # A lane accepts an arriving batch only when its pending slot is
    # free (one in-flight batch per lane — the device-side backpressure
    # the ingress read lane leans on), its leader is up, and the
    # machine has a query kernel; everything else is shed at arrival
    # (counted, refused — never served stale).  The captured read index
    # is the leader commit AT registration: the linearization point
    # every write committed before the batch must be visible at
    # (consistent_query's registration, ra_server.erl:3035-3071).
    supports_read = machine.query_spec is not None
    Kr = state.read_buf.shape[1]
    if supports_read:
        acc_lane = (n_read > 0) & leader_up & (state.read_n == 0)
    else:
        acc_lane = jnp.zeros((N,), jnp.bool_)
    r_acc = jnp.where(acc_lane, jnp.minimum(n_read, Kr), 0)
    r_shed_now = n_read - r_acc
    read_buf = jnp.where(acc_lane[:, None, None], read_q, state.read_buf)
    read_ix = jnp.where(acc_lane, leader_commit0, state.read_ix)
    read_reg = jnp.where(acc_lane, read_clock, state.read_reg)
    read_n1 = jnp.where(acc_lane, r_acc, state.read_n)

    # -- 4b. consistent-query heartbeat quorum -----------------------------
    # The host registers reads by bumping the lane's query counter
    # (query_mask); every active member confirms the current counter in
    # the lockstep round (the #heartbeat_rpc/#heartbeat_reply exchange,
    # ra_server.erl:3082-3170 collapsed into one step); down voters'
    # stale confirmations hold the median back, so a leader cut off
    # from its majority can never certify a read.  A won election wipes
    # the confirmations of members that are NOT reachable this round
    # (active members re-ack immediately below): stale acks collected by
    # a deposed leader can never certify a read under the new one (the
    # new-leader pending_consistent_queries gate, :3174-3190).  A lane
    # accepting a read batch rides the same machinery: its registration
    # bumps the counter, and the batch's token is confirmed by the same
    # quorum fold (the read-index path when the lease is cold).
    query_index = state.query_index + \
        jnp.where(query_mask | acc_lane, 1, 0)
    read_tok = jnp.where(acc_lane, query_index, state.read_tok)
    peer_q0 = jnp.where(elect_ok[:, None], 0, state.peer_query)
    peer_query = jnp.where(active, query_index[:, None], peer_q0)
    query_agreed = query_quorum(peer_query, state.voter)

    # -- 5. apply fold over the committed window ---------------------------
    # The window is LANE-uniform: all active members of a lane share the
    # same apply frontier (failed members freeze; recover/add re-seed
    # from the leader's replica), so the committed entries are read from
    # the ring ONCE per lane with an along-axis gather — the generic
    # per-(lane,member) gather this replaces lowered to a serialized
    # scatter-read on TPU and dominated the whole step (~67ms at 10k
    # lanes; the along-axis form is ~0.02ms).  Per-member progress is
    # enforced by the `do` mask.
    applied0 = state.applied
    apply_to = jnp.minimum(commit, applied0 + apply_window)
    A = apply_window
    big = jnp.int32(2 ** 30)
    base = jnp.min(jnp.where(active, applied0, big), axis=-1)
    base = jnp.where(jnp.any(active, axis=-1), base, 0)      # [N]

    a_idx = jnp.arange(A)
    idx_lane = base[:, None] + 1 + a_idx[None, :]            # [N,A]
    cmds_lane = _ring_read_window(ring, idx_lane, impl=ring_io)  # [N,A,C]
    idx = idx_lane[:, None, :]                               # [N,1,A]
    do = (idx > applied0[..., None]) & (idx <= apply_to[..., None]) \
        & active[..., None]                                  # [N,P,A]
    idx = jnp.broadcast_to(idx, do.shape)

    if machine.supports_batch_apply:
        # one-shot masked window fold (machine-managed, order-preserving):
        # no scan depth
        cmds = jnp.broadcast_to(cmds_lane[:, None],
                                do.shape + cmds_lane.shape[-1:])
        meta = {"index": idx, "term": term[:, None, None]}
        mac = machine.jit_apply_batch(meta, cmds, do, state.mac)
        applied = jnp.where(
            active,
            jnp.maximum(applied0,
                        jnp.minimum(apply_to, (base + A)[:, None])),
            applied0)
    else:
        # Sequential machines: ONE lane-representative scan instead of a
        # per-member one.  Every active member of a lane applies the
        # same committed commands in the same order, so the per-member
        # scan did the machine fold P times over; instead the scan runs
        # on the representative state (the active member at the lane
        # apply frontier), records the trajectory, and each member's
        # final state is SELECTED from it at offset
        # (its own apply_to - base) via an exact one-hot matmul —
        # members that may not apply the full window (commit lag,
        # frozen failures) land on the right intermediate state.
        sel = jnp.argmax(active & (applied0 == base[:, None]),
                         axis=-1)                        # [N]

        def pick(x):
            idx = sel[:, None].reshape((N, 1) + (1,) * (x.ndim - 2))
            idx = jnp.broadcast_to(idx, (N, 1) + x.shape[2:])
            return jnp.take_along_axis(x, idx, axis=1)[:, 0]

        mac_lane = jax.tree.map(pick, state.mac)

        def body(mac0, xs):
            a, cmd_row = xs                              # [], [N,C]
            meta = {"index": base + 1 + a, "term": term}
            new_mac, _reply = machine.jit_apply(meta, cmd_row, mac0)
            return new_mac, new_mac

        _, traj = jax.lax.scan(body, mac_lane,
                               (a_idx, jnp.moveaxis(cmds_lane, 1, 0)))
        # trajectory offsets 0..A (0 = nothing applied this step)
        stacked = jax.tree.map(
            lambda init, tr: jnp.concatenate([init[None], tr], axis=0),
            mac_lane, traj)                              # [A+1, N, ...]
        off = jnp.clip(apply_to - base[:, None], 0, A)   # [N,P]
        oh = (off[..., None] ==
              jnp.arange(A + 1)[None, None, :]).astype(jnp.float32)

        def select(stk, old):
            # NB memory: the trajectory holds A+1 state snapshots per
            # lane (vs P replicas before) — an (A+1)/P multiplier on
            # apply-path peak memory, the price of the P-fold compute
            # cut.  Machines with very large per-lane state at large
            # apply windows should size ring/window accordingly.
            tail_shape = stk.shape[2:]
            S = 1
            for d in tail_shape:
                S *= d
            flat = jnp.moveaxis(stk, 0, 1).reshape(N, A + 1, S)
            if old.dtype in (jnp.int32, jnp.int16, jnp.int8,
                             jnp.uint8, jnp.uint16, jnp.bool_):
                # exact one-hot matmul (MXU path): <=32-bit ints
                # round-trip through the 16-bit split losslessly
                picked = split16_matmul(
                    oh, flat.astype(jnp.int32)).astype(old.dtype)
            else:
                # floats / 64-bit: gather (a matmul select would mix
                # unselected offsets — 0*Inf=NaN — and wider types
                # truncate); slower but exact and poison-free
                idx = off[..., None]
                idx3 = jnp.broadcast_to(idx, (N, P, S))
                picked = jnp.take_along_axis(
                    jnp.broadcast_to(flat[:, None], (N, P, A + 1, S)),
                    idx3[:, :, None, :], axis=2)[:, :, 0]
            picked = picked.reshape((N, P) + tail_shape)
            m = active.reshape(active.shape + (1,) * (picked.ndim - 2))
            return jnp.where(m, picked, old)

        mac = jax.tree.map(select, stacked, state.mac)
        applied = jnp.where(
            active,
            jnp.maximum(applied0,
                        jnp.minimum(apply_to, (base + A)[:, None])),
            applied0)

    # -- 5b. per-lane telemetry accumulators (device-resident, ISSUE 6) --
    # A handful of [N] vector ops next to the step's [N,P]/[N,R,C] work:
    # the observability plane rides the dispatch it observes, so no
    # extra dispatch, readback or host sync is ever needed to know which
    # lane is stuck.  Aggregation (histograms/top-K) happens at sampling
    # cadence in _telemetry_summary, not here.
    tel = state.telem
    one = jnp.int32(1)
    leader_commit_new = leader_commit0 + delta
    lane_applied = jnp.min(jnp.where(active, applied, big), axis=-1)
    lane_applied = jnp.where(jnp.any(active, axis=-1), lane_applied, 0)
    lead_changed = leader_slot != state.leader_slot
    backlog = new_leader_last > leader_commit_new
    telem = LaneTelemetry(
        elections_requested=tel.elections_requested +
        jnp.where(elect_mask, one, 0),
        elections_won=tel.elections_won + jnp.where(elect_ok, one, 0),
        leader_changes=tel.leader_changes +
        jnp.where(lead_changed, one, 0),
        # reset only when the leader actually MOVED: an incumbent
        # re-elected at a higher term is still a stable leader, and
        # leader_age must agree with leader_changes, not elections_won
        leader_age=jnp.where(lead_changed, 0, tel.leader_age + 1),
        commit_lag=new_leader_last - leader_commit_new,
        apply_lag=leader_commit_new - lane_applied,
        # a stall is a lane that HAS a commit backlog and made no commit
        # progress this round (a leader cut from its quorum, a wedged
        # confirm path); idle lanes (no backlog) never count
        stall_steps=jnp.where((delta > 0) | ~backlog, 0,
                              tel.stall_steps + 1),
        steps=tel.steps + 1)

    # -- 5c. read serve/refuse (the read-index confirm schedule) ----------
    # A pending batch serves the moment its lane can certify BOTH
    # authority and freshness, all as masked vector ops: authority is
    # the live lease OR the heartbeat quorum having confirmed the
    # batch's token (the read-index path — note it needs no fsync:
    # unlike the commit quorum, read certification gates on the apply
    # frontier, not last_written, so reads are never held back by the
    # fsync hold-back the write plane pays); freshness is the leader's
    # apply frontier having reached the captured read index.  Queries
    # evaluate against the leader replica via the machine's vectorized
    # query kernel — zero log appends, zero host syncs; the answers
    # ride the step aux and drain off the existing async readbacks.
    # A batch that cannot certify within read_timeout rounds is REFUSED
    # (stale-refusal counter) — a partitioned leader's lease reads can
    # never outlive the lease: once lease_until passes and the quorum
    # is gone, can_serve stays False until the batch expires.
    lead_applied = jnp.take_along_axis(applied, leader_slot[:, None],
                                       axis=-1)[:, 0]
    authority = lease_ok | (query_agreed >= read_tok)
    can_serve = (read_n1 > 0) & leader_up & authority & \
        (lead_applied >= read_ix)
    expired = (read_n1 > 0) & ~can_serve & \
        (read_clock - read_reg >= read_timeout)
    if supports_read:
        def _pick_lead(x):
            sidx = leader_slot[:, None].reshape(
                (N, 1) + (1,) * (x.ndim - 2))
            sidx = jnp.broadcast_to(sidx, (N, 1) + x.shape[2:])
            return jnp.take_along_axis(x, sidx, axis=1)[:, 0]
        replies = machine.jit_query(read_buf,
                                    jax.tree.map(_pick_lead, mac))
        replies = jnp.where(can_serve[:, None, None], replies, 0)
    else:
        replies = jnp.zeros((N, Kr, 1), jnp.int32)
    read_done = jnp.where(can_serve, read_n1, 0)
    stale_now = jnp.where(expired, read_n1, 0)
    read_served = state.read_served + read_done
    read_shed_tot = state.read_shed + r_shed_now
    read_stale_tot = state.read_stale + stale_now
    read_leased = state.read_leased + \
        jnp.where(can_serve & lease_ok, read_n1, 0)

    new_state = LaneState(term=term, leader_slot=leader_slot,
                          term_start=term_start, last_index=last_index,
                          last_written=last_written, match=match,
                          next_index=next_index, commit=commit,
                          applied=applied, voter=state.voter, active=active,
                          ring=ring, ring_base=ring_base,
                          total_committed=total_committed,
                          query_index=query_index, peer_query=peer_query,
                          query_agreed=query_agreed,
                          read_clock=read_clock, lease_until=lease_until,
                          read_buf=read_buf,
                          read_n=jnp.where(can_serve | expired, 0,
                                           read_n1),
                          read_ix=read_ix, read_tok=read_tok,
                          read_reg=read_reg, read_served=read_served,
                          read_shed=read_shed_tot,
                          read_stale=read_stale_tot,
                          read_leased=read_leased, telem=telem, mac=mac)
    aux = {"appended_hi": new_leader_last, "n_acc": n_acc,
           "n_app": total_app,
           # read-plane aux: per-step serve/refuse outcomes plus the
           # cumulative per-lane watermarks the driver's async
           # readbacks drain (the read twin of committed_lanes)
           "read_done": read_done, "read_shed": r_shed_now,
           "read_stale": stale_now,
           "read_watermark": jnp.where(can_serve, lead_applied, -1),
           "read_replies": replies,
           "read_served_lanes": read_served,
           "read_shed_lanes": read_shed_tot,
           "read_stale_lanes": read_stale_tot}
    if durable:
        # -- 6. on-device payload compaction for the WAL readback ---------
        # The WAL record stores only the ACCEPTED host rows (lane-major,
        # n_acc per lane); reading back the full [N,K,C] batch and
        # masking on the host moves every rejected/empty slot over the
        # host link first.  Instead a prefix-sum gather compacts the
        # accepted rows into a dense [N*K, C] buffer on device: output
        # row j's source lane is a length-preserving repeat of the lane
        # ids by their accept counts (jnp.repeat lowers to a cumsum +
        # gather — measured 3x cheaper than the searchsorted form and
        # 6x cheaper than a scatter on CPU), so the host pulls exactly
        # rows [0, csum[-1]) — the copy shrinks by the rejection/
        # occupancy factor.
        K = payloads.shape[1]
        C = payloads.shape[2]
        csum = jnp.cumsum(n_acc).astype(jnp.int32)           # [N]
        j = jnp.arange(N * K, dtype=jnp.int32)
        src_lane = jnp.repeat(jnp.arange(N, dtype=jnp.int32), n_acc,
                              total_repeat_length=N * K)
        row_base = csum[src_lane] - n_acc[src_lane]          # [N*K]
        k_off = jnp.clip(j - row_base, 0, max(K - 1, 0))
        flat_src = src_lane * K + k_off
        flat = jnp.take(payloads.reshape(N * K, C).astype(ring.dtype),
                        flat_src, axis=0)
        valid = j < (csum[-1] if N else jnp.int32(0))
        aux["flat_rows"] = jnp.where(valid[:, None], flat, 0)
        aux["row_csum"] = csum
    return new_state, aux


def _superstep(state: LaneState, n_new_blk: Array, payloads_blk: Array,
               fail_mask: Array, elect_blk: Array, confirm_upto: Array,
               query_blk: Array, n_read_blk: Array, read_q_blk: Array,
               **step_kwargs):
    """K lockstep rounds fused into ONE XLA dispatch via ``lax.scan``
    (the tentpole of ISSUE 5).  The scan consumes a device-staged
    ``[K, ...]`` schedule — per-inner-step command counts, payload
    blocks and elect/query masks — while the failure mask and the
    durability confirm horizon are dispatch-constant: failures are
    host-detected between dispatches, and the per-shard WAL confirm
    watermark is sampled ONCE per dispatch, so within a superstep
    confirms can only lag real fsyncs, never lead them (the
    write_delay/confirm contract of step 3 is preserved verbatim —
    the inner step body IS `_step`).

    Returns ``(new_state, aux)`` with every aux leaf stacked along a
    leading ``[K]`` axis (one entry per inner step), so the durable
    readback contract is unchanged: each inner step still yields the
    exact per-step WAL record inputs.  Two extra per-inner-step
    watermarks ride along for host pipelining: ``committed_lanes``
    (cumulative committed per lane — the on-device latency stamp the
    bench derives observed-commit steps from) and ``applied_lanes``
    (the lane apply frontier over active members)."""
    big = jnp.int32(2 ** 30)

    def body(st, xs):
        n_new, payloads, elect, query, n_read, read_q = xs
        new_st, aux = _step(st, n_new, payloads, fail_mask, elect,
                            confirm_upto, query, n_read, read_q,
                            **step_kwargs)
        aux["committed_lanes"] = new_st.total_committed
        applied = jnp.min(jnp.where(new_st.active, new_st.applied, big),
                          axis=-1)
        aux["applied_lanes"] = jnp.where(
            jnp.any(new_st.active, axis=-1), applied, 0)
        return new_st, aux

    return jax.lax.scan(body, state,
                        (n_new_blk, payloads_blk, elect_blk, query_blk,
                         n_read_blk, read_q_blk))


def _telemetry_summary(telem: LaneTelemetry, total_committed: Array,
                       reads: tuple, *,
                       top_k: int, hist_buckets: int,
                       stall_threshold: int) -> dict:
    """Aggregate the per-lane telemetry pytree ON DEVICE into a
    fixed-size snapshot: scalar rollups, a log2-bucket commit-lag
    histogram, and a ``lax.top_k`` offender summary.  Output size is
    O(top_k + hist_buckets) regardless of lane count — the readback the
    async sampler starts is a few hundred bytes, not [lanes].  Under a
    sharded mesh the jit lowers the reductions/top_k to cross-device
    collectives, so one call covers every device's lane slice."""
    f32 = jnp.float32
    lag = telem.commit_lag
    stalled = telem.stall_steps >= stall_threshold
    # offender score: any stalled lane outranks any merely-laggy lane;
    # both components clipped so the packed int32 score cannot overflow
    score = (jnp.clip(telem.stall_steps, 0, (1 << 15) - 1) * (1 << 15)
             + jnp.clip(lag + telem.apply_lag, 0, (1 << 15) - 1))
    _top_score, top_idx = jax.lax.top_k(score, top_k)
    # log2 bucketing: bucket b holds lanes with lag in [2^(b-1), 2^b)
    # (bucket 0 = lag 0); the last bucket absorbs the tail
    bucket = jnp.clip(
        jnp.ceil(jnp.log2(jnp.maximum(lag, 0).astype(f32) + 1.0))
        .astype(jnp.int32), 0, hist_buckets - 1)
    hist = jnp.sum(
        (bucket[:, None] == jnp.arange(hist_buckets)[None, :])
        .astype(jnp.int32), axis=0)
    return {
        "steps": jnp.max(telem.steps),
        "elections_requested": jnp.sum(
            telem.elections_requested.astype(f32)),
        "elections_won": jnp.sum(telem.elections_won.astype(f32)),
        "leader_changes": jnp.sum(telem.leader_changes.astype(f32)),
        "stalled_lanes": jnp.sum(stalled.astype(jnp.int32)),
        "commit_lag_max": jnp.max(lag),
        "commit_lag_mean": jnp.mean(lag.astype(f32)),
        "apply_lag_max": jnp.max(telem.apply_lag),
        "apply_lag_mean": jnp.mean(telem.apply_lag.astype(f32)),
        "leader_age_min": jnp.min(telem.leader_age),
        "commit_lag_hist": hist,
        "top_lanes": top_idx,
        "top_commit_lag": jnp.take(lag, top_idx),
        "top_apply_lag": jnp.take(telem.apply_lag, top_idx),
        "top_stall_steps": jnp.take(telem.stall_steps, top_idx),
        # float32: the node-wide sum can exceed int32; the Observatory
        # ring differentiates this into per-window commit rates
        "committed_total": jnp.sum(total_committed.astype(f32)),
        # read-plane rollups (ISSUE 20): cumulative like committed_total
        # — the ring differentiates them into reads/s and refusal rates,
        # and leased/served is the lease-coverage ratio ra_top renders
        "read_served_total": jnp.sum(reads[0].astype(f32)),
        "read_shed_total": jnp.sum(reads[1].astype(f32)),
        "read_stale_total": jnp.sum(reads[2].astype(f32)),
        "read_leased_total": jnp.sum(reads[3].astype(f32)),
    }


#: shared jitted telemetry-summary fns, keyed by aggregation geometry
#: (pure in (telem, total_committed) given the static config)
_SUMMARY_JIT_CACHE: dict = {}


def telemetry_summary_fn(top_k: int = 8, hist_buckets: int = 16,
                         stall_threshold: int = 8):
    key = (top_k, hist_buckets, stall_threshold)
    fn = _SUMMARY_JIT_CACHE.get(key)
    if fn is None:
        # recompile-sentinel wrap (ISSUE 16): the proxy lives in the
        # cache next to the jitted fn, so samplers sharing a geometry
        # share one compile count — a retrace of the summary path is
        # as much a steady-state bug as one of the step path
        fn = devicewatch.wrap_jit(jax.jit(functools.partial(
            _telemetry_summary, top_k=top_k, hist_buckets=hist_buckets,
            stall_threshold=stall_threshold)), "summary")
        _SUMMARY_JIT_CACHE[key] = fn
    return fn


#: shared jitted step fns (see _compile_step)
_STEP_JIT_CACHE: dict = {}


class LockstepEngine:
    """Host API around the jitted lockstep step function."""

    def __init__(self, machine: JitMachine, n_lanes: int, n_members: int = 3,
                 *, ring_capacity: int = 1024, max_step_cmds: int = 64,
                 apply_window: Optional[int] = None,
                 pipeline_window: int = 4096, max_append_batch: int = 128,
                 write_delay: int = 0, ring_io: str = "auto",
                 donate: bool = False, quorum_impl: str = "xla",
                 superstep_donate: Optional[bool] = None,
                 max_step_reads: int = 16, lease_ttl: int = 8,
                 read_timeout: int = 0) -> None:
        # donate=False by default ON THE SINGLE-STEP PATH: buffer
        # donation costs ~35ms/step on tunneled PJRT backends (a
        # per-step sync), vs ~0.05ms/step without — XLA's allocator
        # handles the transient double buffering fine at these state
        # sizes.  Flip on for memory-constrained local deployments.
        #
        # The SUPERSTEP path defaults donation ON (superstep_donate
        # None -> True): any per-dispatch donation overhead amortizes
        # over the K fused rounds, while donating saves the full-state
        # double buffer per dispatch.  Re-measured for ISSUE 5 (CPU,
        # jax 0.4.37 — donation is real there, the donated input is
        # invalidated; 512 lanes x 5, K=8, 32 cmds/step, 3x2s reps):
        # median 4.91M cmds/s donated vs 4.71M not, parity exact — a
        # wash to slightly positive, so the memory win decides.  See
        # docs/INTERNALS.md §8 for the dataflow.
        self.machine = machine
        self.n_lanes = n_lanes
        self.n_members = n_members
        if ring_capacity < max_step_cmds + 3:
            # the put-along ring write parks masked columns one slot past
            # the write range (payload + noop + recovery-replay widths)
            raise ValueError("ring_capacity must be >= max_step_cmds + 3")
        self.ring_capacity = ring_capacity
        self.max_step_cmds = max_step_cmds
        self.apply_window = apply_window or (max_step_cmds + 2)
        dtype, shape = machine.command_spec
        self.payload_width = int(np.prod(shape)) if shape else 1
        self.payload_dtype = jnp.dtype(dtype)
        # read-plane geometry (ISSUE 20): Kr pending-read slots per lane
        # ride LaneState; a machine without a query kernel still carries
        # the (minimal [N,1,1]) read fields so the step signature and
        # checkpoint schema stay uniform, but every read is refused
        self.reads_enabled = machine.query_spec is not None
        self.read_window = max(1, int(max_step_reads)) \
            if self.reads_enabled else 1
        if self.reads_enabled:
            qdtype, qshape = machine.query_spec
            self.query_width = int(np.prod(qshape)) if qshape else 1
            self.query_dtype = jnp.dtype(qdtype)
            _rd, rshape = machine.query_reply_spec
            self.query_reply_width = int(np.prod(rshape)) if rshape else 1
        else:
            self.query_width = 1
            self.query_dtype = jnp.int32
            self.query_reply_width = 1
        self.lease_ttl = int(lease_ttl)
        self.read_timeout = int(read_timeout) if read_timeout \
            else 8 * self.lease_ttl
        mac = machine.jit_init(n_lanes)
        # broadcast machine state over member slots: [N,...] -> [N,P,...]
        mac = jax.tree.map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[:, None], (n_lanes, n_members) +
                jnp.asarray(x).shape[1:]),
            mac)
        self.state = _init_state(n_lanes, n_members, ring_capacity,
                                 self.payload_width, mac,
                                 self.payload_dtype, self.read_window,
                                 self.query_width, self.query_dtype)
        from ..ops.pallas_quorum import make_evaluate_quorum
        if ring_io == "auto":
            # MXU one-hot IO on TPU backends; along-axis gather (fast and
            # exact) on CPU and friends
            ring_io = "onehot" if jax.default_backend() in ("tpu", "axon") \
                else "gather"
        self.ring_io = ring_io
        self._step_kwargs = dict(machine=machine,
                                 ring_capacity=ring_capacity,
                                 apply_window=self.apply_window,
                                 pipeline_window=pipeline_window,
                                 max_append_batch=max_append_batch,
                                 write_delay=write_delay, ring_io=ring_io,
                                 lease_ttl=self.lease_ttl,
                                 read_timeout=self.read_timeout,
                                 quorum_fn=make_evaluate_quorum(quorum_impl))
        self._quorum_impl = quorum_impl
        self._donate = donate
        self._superstep_donate = superstep_donate \
            if superstep_donate is not None else True
        self._dur = None  # ra-type: ra_tpu.engine.durable.EngineDurability
        self._driver = None
        self._telemetry = None  # ra-type: ra_tpu.telemetry.TelemetrySampler
        self._ingress = None    # attached IngressPlane (ISSUE 10)
        self._mesh = None       # device mesh, set by shard_engine_state
                                # (ISSUE 11: drivers/ingress read it to
                                # stage blocks pre-partitioned)
        # phase-resolved latency attribution (ISSUE 9): host-side
        # monotonic stamps at the dispatch/staging edges land here; a
        # durability bridge brings its own accumulator (shared with the
        # WAL shards) and attach_durability adopts it
        from ..telemetry import PhaseStats
        self.phases = PhaseStats()
        #: host-side dispatch-pipeline bookkeeping (ENGINE_PIPELINE_FIELDS)
        self.pipeline_counters = {f: 0 for f in ENGINE_PIPELINE_FIELDS}
        self._superstep_k_last = 0
        self._compile_step(durable=False)
        self._zero_fail = jnp.zeros((n_lanes, n_members), bool)
        self._zero_elect = jnp.zeros((n_lanes,), bool)
        self._zero_confirm = jnp.zeros((n_lanes,), jnp.int32)
        self._zero_nread = jnp.zeros((n_lanes,), jnp.int32)
        self._zero_readq = jnp.zeros(
            (n_lanes, self.read_window, self.query_width),
            self.query_dtype)
        self._fail_host = np.zeros((n_lanes, n_members), bool)

    def _build_jit(self, fn, durable: bool, donate: bool, tag: str):
        # share the jitted step across same-config engines: jax.jit
        # caches by function identity, so a per-instance partial forces
        # a full recompile for every engine construction (a fuzz seed,
        # a test case, a bench child).  Sound for machines whose config
        # is all scalars — jit_apply is pure in (meta, cmd, state)
        # given that config (the JitMachine contract), so same-config
        # instances are interchangeable; others keep per-instance jits.
        m = self.machine
        attrs = [(k, v) for k, v in sorted(m.__dict__.items())
                 if not k.startswith("_")]
        partial = functools.partial(fn, durable=durable,
                                    **self._step_kwargs)
        if all(isinstance(v, (int, float, str, bool)) for _k, v in attrs):
            key = (type(m), tuple(attrs), tag, durable, donate,
                   self._quorum_impl,
                   tuple(sorted((k, v)
                                for k, v in self._step_kwargs.items()
                                if k not in ("machine", "quorum_fn"))))
            jitted = _STEP_JIT_CACHE.get(key)
            if jitted is None:
                # recompile-sentinel wrap (ISSUE 16): the sentinel
                # proxy is stored IN the cache next to the jitted fn,
                # so same-config engines share one compile count and a
                # cache hit costs no extra wrapping.  The proxy itself
                # is never traced (it wraps the jit OUTPUT) — RA13's
                # static guarantee is untouched; this is its runtime
                # mirror.
                jitted = devicewatch.wrap_jit(
                    jax.jit(partial,
                            donate_argnums=(0,) if donate else ()),
                    tag)
                _STEP_JIT_CACHE[key] = jitted
            return jitted
        return devicewatch.wrap_jit(
            jax.jit(partial, donate_argnums=(0,) if donate else ()), tag)

    def _compile_step(self, durable: bool) -> None:
        self._step = self._build_jit(_step, durable, self._donate, "step")
        self._sstep = self._build_jit(_superstep, durable,
                                      self._superstep_donate, "superstep")

    def attach_durability(self, dur) -> None:
        """Switch the engine into durable mode: ``dur`` (an
        engine-durability bridge, see ra_tpu.engine.durable) supplies the
        per-lane WAL-confirm horizon before each step and receives each
        step's append outcome after dispatch."""
        self._dur = dur
        # one attribution plane per engine: the bridge's accumulator is
        # already wired into its WAL shards, so the engine adopts it —
        # staging/dispatch stamps and fsync/confirm stamps merge
        self.phases = dur.phases
        self._compile_step(durable=True)

    # -- driving -----------------------------------------------------------

    def _host_mask(self, mask):
        """Coerce a HOST-side mask (election/query requests originate on
        the host failure detector) and record whether any lane is set —
        the host-side bookkeeping that lets the hot step path skip the
        post-dispatch ``np.asarray(elect_mask).any()`` device sync the
        old code paid on every masked step (ISSUE 5 satellite).  Callers
        must pass host data (numpy/list); a device array here would
        reintroduce the sync it exists to remove."""
        arr = np.asarray(mask)  # ra02-ok: host data by contract (docstring) — a device array here would reintroduce the sync this helper removes
        return jnp.asarray(arr), bool(arr.any())

    def step(self, n_new, payloads, elect_mask=None,
             query_mask=None, n_read=None, read_q=None):
        """Advance every lane one round.  n_new: int32[N]; payloads:
        [N, K, C] with K <= max_step_cmds.  In durable mode the step's
        accepted entries are compacted on device, read back off-thread
        by the WAL shards, and commits gate on the fsync confirm — host
        or device payloads both work (no host-side copy is taken).
        Masks are host data (see _host_mask).  ``n_read``/``read_q``
        (int32[N], [N, Kr, Cq]) register consistent-read batches on the
        lease/read-index plane (ISSUE 20).  Returns the step aux (device
        arrays) so read callers can drain serve outcomes."""
        fail = (jnp.asarray(self._fail_host)
                if self._fail_host.any() else self._zero_fail)
        elect_any = False
        if elect_mask is None:
            elect = self._zero_elect
        else:
            elect, elect_any = self._host_mask(elect_mask)
        query = self._zero_elect if query_mask is None \
            else jnp.asarray(query_mask)
        nr = self._zero_nread if n_read is None else jnp.asarray(n_read)
        rq = self._zero_readq if read_q is None else jnp.asarray(read_q)
        self.pipeline_counters["dispatches"] += 1
        self.pipeline_counters["inner_steps"] += 1
        if self._dur is None:
            with trace.span("engine.step", "engine"):
                self.state, aux = self._step(self.state,
                                             jnp.asarray(n_new),
                                             jnp.asarray(payloads), fail,
                                             elect, self._zero_confirm,
                                             query, nr, rq)
            if self._telemetry is not None:
                self._telemetry.tick(1)
            return aux
        with trace.span("engine.backpressure", "engine"):
            self._dur.backpressure()
        confirm = jnp.asarray(self._dur.confirm_upto)
        with trace.span("engine.step", "engine", durable=True):
            self.state, aux = self._step(self.state, jnp.asarray(n_new),
                                         jnp.asarray(payloads), fail, elect,
                                         confirm, query, nr, rq)
        with trace.span("engine.wal_submit", "engine"):
            # no host payload copy here: the WAL shards read back the
            # device-compacted flat rows off-thread (see durable.py)
            self._dur.submit(aux)
        if elect_any:
            # elections truncate+reuse indexes: drain now so the next
            # dispatch reads a confirm horizon clamped at the new base
            # (elect_any is host bookkeeping — no device readback here)
            self._dur.drain_all()
        if self._telemetry is not None:
            # after dispatch, never blocking: the sampler only starts
            # async device work/readbacks on this path (rule RA04)
            self._telemetry.tick(1)
        return aux

    def superstep(self, n_new_blk, payloads_blk, elect_blk=None,
                  query_blk=None, n_read_blk=None,
                  read_q_blk=None) -> dict:
        """Advance every lane K rounds in ONE XLA dispatch (the fused
        `lax.scan` path, ISSUE 5).  Inputs carry a leading inner-step
        axis: ``n_new_blk`` int32[K, N]; ``payloads_blk`` [K, N, Kc, C];
        optional elect/query schedules bool[K, N] (host data) for
        mid-superstep elections/reads.  The failure mask and — in
        durable mode — the WAL confirm horizon are sampled once per
        dispatch: within the superstep confirms only lag real fsyncs.

        Returns the stacked per-inner-step aux (device arrays, one [K]
        leading axis per leaf): ``committed_lanes`` [K, N] is the
        cumulative committed watermark after each inner step — start an
        async readback of it to observe commit progress without ever
        blocking the dispatch pipeline (what DispatchAheadDriver and
        the bench's step-stamped latency mode do)."""
        k = int(n_new_blk.shape[0]) if hasattr(n_new_blk, "shape") \
            else len(n_new_blk)
        fail = (jnp.asarray(self._fail_host)
                if self._fail_host.any() else self._zero_fail)
        elect_any = False
        if elect_blk is None:
            elect = jnp.broadcast_to(self._zero_elect,
                                     (k, self.n_lanes))
        else:
            elect, elect_any = self._host_mask(elect_blk)
        query = jnp.broadcast_to(self._zero_elect, (k, self.n_lanes)) \
            if query_blk is None else jnp.asarray(query_blk)
        nr = jnp.broadcast_to(self._zero_nread, (k, self.n_lanes)) \
            if n_read_blk is None else jnp.asarray(n_read_blk)
        rq = jnp.broadcast_to(self._zero_readq,
                              (k,) + self._zero_readq.shape) \
            if read_q_blk is None else jnp.asarray(read_q_blk)
        self.pipeline_counters["dispatches"] += 1
        self.pipeline_counters["superstep_dispatches"] += 1
        self.pipeline_counters["inner_steps"] += k
        self._superstep_k_last = k
        if self._dur is None:
            with trace.span("engine.superstep", "engine", k=k):
                self.state, aux = self._sstep(
                    self.state, jnp.asarray(n_new_blk),
                    jnp.asarray(payloads_blk), fail, elect,
                    self._zero_confirm, query, nr, rq)
            if self._telemetry is not None:
                self._telemetry.tick(k)
            return aux
        with trace.span("engine.backpressure", "engine"):
            self._dur.backpressure()
        # confirm horizon sampled ONCE per dispatch — the scan's
        # (constant) confirm schedule; write_delay semantics preserved:
        # confirms may only lag, never lead fsync
        confirm = jnp.asarray(self._dur.confirm_upto)
        with trace.span("engine.superstep", "engine", durable=True, k=k):
            self.state, aux = self._sstep(
                self.state, jnp.asarray(n_new_blk),
                jnp.asarray(payloads_blk), fail, elect, confirm, query,
                nr, rq)
        with trace.span("engine.wal_submit", "engine", k=k):
            self._dur.submit_block(aux, k)
        if elect_any:
            self._dur.drain_all()
        if self._telemetry is not None:
            self._telemetry.tick(k)
        return aux

    def checkpoint(self) -> str:
        """Durable mode: quiesce the WAL, snapshot the full lane state,
        and prune WAL files the snapshot covers (the release_cursor /
        snapshot-truncation role).  Returns the checkpoint path."""
        if self._dur is None:
            raise RuntimeError("checkpoint() requires durable mode")
        return self._dur.checkpoint(self)

    def close(self) -> None:
        """Flush and close the durability bridge (no-op when volatile)."""
        if self._dur is not None:
            self._dur.close()

    def uniform_step(self, cmds_per_lane: int, payload_value=1) -> None:
        """Bench helper: every lane's leader receives the same number of
        commands this round."""
        N, K, C = self.n_lanes, self.max_step_cmds, self.payload_width
        n_new = jnp.full((N,), min(cmds_per_lane, K), jnp.int32)
        payloads = jnp.full((N, K, C), payload_value, self.payload_dtype)
        self.step(n_new, payloads)

    def uniform_superstep(self, k: int, cmds_per_lane: int,
                          payload_value=1) -> dict:
        """Bench/soak helper: one fused dispatch of ``k`` rounds, every
        lane's leader receiving the same command count each round."""
        N, K, C = self.n_lanes, self.max_step_cmds, self.payload_width
        n_new = jnp.full((k, N), min(cmds_per_lane, K), jnp.int32)
        payloads = jnp.full((k, N, K, C), payload_value,
                            self.payload_dtype)
        return self.superstep(n_new, payloads)

    def uniform_read_block(self, k: int, reads_per_lane: int,
                           query_value=0):
        """Bench/soak helper: build a ``(n_read_blk, read_q_blk)``
        superstep read schedule registering one uniform batch of
        ``reads_per_lane`` queries per lane at inner step 0 (one batch
        per lane is in flight at a time — see step 4a — so scheduling
        at later inner steps would only shed)."""
        N, Kr, Cq = self.n_lanes, self.read_window, self.query_width
        r = min(int(reads_per_lane), Kr)
        n_read = jnp.zeros((k, N), jnp.int32).at[0].set(r)
        read_q = jnp.broadcast_to(
            jnp.full((N, Kr, Cq), query_value, self.query_dtype),
            (k, N, Kr, Cq))
        return n_read, read_q

    # -- failure injection / elections ------------------------------------

    def fail_member(self, lane: int, slot: int) -> None:
        # host-initiated transitions are RARE and exactly what a
        # post-mortem wants: flight events here, never per step
        record("engine.fail", lane=int(lane), slot=int(slot))
        self._fail_host[lane, slot] = True

    def recover_member(self, lane: int, slot: int) -> None:
        """Re-activate a member via *snapshot install* from the lane
        leader (the escalation the reference takes when a follower falls
        behind the log truncation horizon, ra_server.erl:1962-1981):
        machine state and cursors are copied from the leader's replica.
        A failed member's apply frontier freezes while it is down (the
        apply fold reads a lane-uniform window), so rejoin is always by
        snapshot rather than ring replay.

        Recovering the lane's CURRENT leader slot is refused: the install
        would seed the leader from its own stale applied frontier,
        truncating its durable tail — including entries the rest of the
        lane committed while it was down (a §5.4 violation).  Revive the
        other members first, ``trigger_election`` (the longest durable
        log wins, as a restarting reference leader would), then recover
        the deposed slot from the new leader."""
        if int(self.state.leader_slot[lane]) == slot:
            raise ValueError(
                f"slot {slot} is lane {lane}'s leader; recover the other "
                "members, trigger_election, then recover this slot")
        record("engine.recover", lane=int(lane), slot=int(slot))
        self._fail_host[lane, slot] = False
        self.state = self._snapshot_install(lane, slot)

    def recover_members(self, lanes, slots) -> None:
        """Vectorized :meth:`recover_member`: revive MANY (lane, slot)
        pairs in one state update (one masked snapshot-install over the
        whole fleet instead of ~6 device ops per member).  The
        multichip chaos phase heals thousands of members per round at
        the 64k-lane ladder rung — per-member eager updates there cost
        seconds of dispatch latency per heal (ISSUE 11).  Same contract
        as the scalar form: recovering a lane's CURRENT leader slot is
        refused, install seeds from the leader's APPLIED frontier."""
        lanes = np.atleast_1d(np.asarray(lanes)).astype(np.int64)
        slots = np.atleast_1d(np.asarray(slots)).astype(np.int64)
        if not len(lanes):
            return
        leads = np.asarray(self.state.leader_slot)[lanes]
        if (leads == slots).any():
            bad = lanes[leads == slots]
            raise ValueError(
                f"lanes {bad[:8].tolist()}: slot is the lane's leader; "
                "recover the other members, trigger_election, then "
                "recover this slot")
        record("engine.recover", lanes=lanes[:64].tolist(),
               n=int(len(lanes)))
        self._fail_host[lanes, slots] = False
        rv_host = np.zeros((self.n_lanes, self.n_members), bool)
        rv_host[lanes, slots] = True
        rv = jnp.asarray(rv_host)
        st = self.state
        lead = st.leader_slot[:, None]                        # [N,1]
        snap = jnp.take_along_axis(st.applied, lead, axis=1)  # [N,1]

        def from_leader(x):
            idx = lead.reshape((self.n_lanes, 1) + (1,) * (x.ndim - 2))
            idx = jnp.broadcast_to(idx, (self.n_lanes, 1) + x.shape[2:])
            lx = jnp.take_along_axis(x, idx, axis=1)
            m = rv.reshape(rv.shape + (1,) * (x.ndim - 2))
            return jnp.where(m, lx, x)

        self.state = st._replace(
            mac=jax.tree.map(from_leader, st.mac),
            applied=jnp.where(rv, snap, st.applied),
            commit=jnp.where(rv, snap, st.commit),
            last_index=jnp.where(rv, snap, st.last_index),
            last_written=jnp.where(rv, snap, st.last_written),
            active=st.active | rv)

    def _snapshot_install(self, lane: int, slot: int) -> LaneState:
        """Seed a (re)joining member from the lane leader at the leader's
        APPLIED index — the snapshot covers exactly the state the copied
        machine state reflects (snapshot idx <= commit, ra_snapshot
        semantics).  Seeding at the leader's written tail instead would
        hand the member a claim to entries it does not hold — a deposed
        minority leader's uncommitted suffix could then enter the match
        median as a phantom replica."""
        st = self.state
        leader = int(st.leader_slot[lane])
        snap_idx = st.applied[lane, leader]
        return st._replace(
            mac=jax.tree.map(
                lambda x: x.at[lane, slot].set(x[lane, leader]), st.mac),
            applied=st.applied.at[lane, slot].set(snap_idx),
            commit=st.commit.at[lane, slot].set(snap_idx),
            last_index=st.last_index.at[lane, slot].set(snap_idx),
            last_written=st.last_written.at[lane, slot].set(snap_idx),
            active=st.active.at[lane, slot].set(True))

    # -- membership (per-lane add/remove/promote, SURVEY §2.1 membership) --
    # NB durable mode: membership and recover_member are host-side state
    # edits outside the WAL block stream, so they are durable only from
    # the next checkpoint() on — call checkpoint() after changing
    # membership (the reference logs '$ra_join'/'$ra_leave' as commands;
    # the engine trades that for checkpoint-granularity durability).

    def add_member(self, lane: int, slot: int,
                   voter: bool = False) -> None:
        """Bring a member slot into a lane's cluster.  Joins as nonvoter
        by default (the reference's join→catch-up→promote flow,
        ra_server.erl:3218-3293): the new member is seeded from the
        leader's replica (snapshot install) and only counts toward
        quorum once promoted."""
        record("engine.member", op="add", lane=int(lane),
               slot=int(slot), voter=bool(voter))
        st = self._snapshot_install(lane, slot)
        self.state = st._replace(
            voter=st.voter.at[lane, slot].set(bool(voter)))
        self._fail_host[lane, slot] = False

    def promote_member(self, lane: int, slot: int) -> None:
        """Nonvoter -> voter once caught up ('$ra_join' promotion)."""
        record("engine.member", op="promote", lane=int(lane),
               slot=int(slot))
        self.state = self.state._replace(
            voter=self.state.voter.at[lane, slot].set(True))

    def remove_member(self, lane: int, slot: int) -> None:
        """Drop a member from a lane's cluster: it leaves the quorum
        denominator immediately ('$ra_leave').  Removing the lane's
        current leader is refused — transfer leadership first (trigger an
        election for the lane), as the reference does when the leader is
        asked to leave; silently deactivating the leader slot would stall
        the lane forever with no error."""
        if int(self.state.leader_slot[lane]) == slot:
            raise ValueError(
                f"slot {slot} is lane {lane}'s leader; "
                "trigger_election first")
        record("engine.member", op="remove", lane=int(lane),
               slot=int(slot))
        st = self.state
        self.state = st._replace(
            active=st.active.at[lane, slot].set(False),
            voter=st.voter.at[lane, slot].set(False))

    def trigger_election(self, lanes) -> None:
        mask = np.zeros((self.n_lanes,), bool)
        mask[np.asarray(lanes)] = True
        record("engine.elect",
               lanes=np.atleast_1d(np.asarray(lanes)).tolist()[:64])
        N, K, C = self.n_lanes, self.max_step_cmds, self.payload_width
        self.step(jnp.zeros((N,), jnp.int32),
                  jnp.zeros((N, K, C), self.payload_dtype),
                  elect_mask=mask)

    # -- consistent (linearizable) reads -----------------------------------

    def consistent_read(self, lanes, fn=None, timeout_steps: int = 256):
        """Linearizable read of the given lanes' machine state — the
        engine-path ra:consistent_query (ra_server.erl:3032-3190).

        Registers a query token (bumps the lanes' query counters), then
        drives empty rounds until (a) a majority of voters have
        confirmed the token — certifying this leader's authority after
        registration — and (b) the leader has applied at least its
        commit index as of registration.  Together these guarantee the
        returned state reflects every write that completed before this
        call, including across elections (a new leader must re-collect
        confirmations and commit its noop first).

        Returns the per-lane leader machine state (a pytree with one
        leading lane axis), or ``fn(state_pytree)`` if given.  Raises
        TimeoutError when no quorum certifies within ``timeout_steps``
        rounds (e.g. the lanes' leaders lost their majority)."""
        lanes = np.atleast_1d(np.asarray(lanes))
        qm = np.zeros((self.n_lanes,), bool)
        qm[lanes] = True
        zero_n = np.zeros((self.n_lanes,), np.int32)
        # full payload width: reuses the executable the normal step
        # loop already compiled (a narrower shape would retrace)
        zero_p = np.zeros((self.n_lanes, self.max_step_cmds,
                           self.payload_width), self.payload_dtype)
        self.step(zero_n, zero_p, query_mask=qm)
        st = self.state
        token = np.asarray(st.query_index)[lanes]
        lead = np.asarray(st.leader_slot)[lanes]
        commit_reg = np.asarray(st.commit)[lanes, lead]
        for _ in range(timeout_steps):
            st = self.state
            agreed = np.asarray(st.query_agreed)[lanes]
            lead = np.asarray(st.leader_slot)[lanes]
            applied = np.asarray(st.applied)[lanes, lead]
            if (agreed >= token).all() and (applied >= commit_reg).all():
                mac = jax.tree.map(
                    lambda x: np.asarray(x)[lanes, lead], st.mac)
                return fn(mac) if fn is not None else mac
            self.step(zero_n, zero_p)
        raise TimeoutError(
            "consistent_read: no heartbeat quorum within "
            f"{timeout_steps} rounds (leader lost its majority?)")

    def read_lanes(self, lanes, queries, timeout_steps: int = 256):
        """Consistent reads through the VECTORIZED read plane (ISSUE 20)
        — the lease/read-index twin of :meth:`consistent_read`, serving
        from the jitted step with zero log appends.

        Registers ONE encoded query per given lane in a single
        zero-command step, then drives empty rounds until every batch
        settles.  ``queries``: [len(lanes), Cq] encoded rows (see the
        machine's ``encode_query``).  Returns ``(replies, watermark,
        ok)`` — np arrays aligned with ``lanes``: per-lane decoded-width
        reply rows, the apply watermark each read was served at, and
        ``ok`` False where the lane REFUSED the read (stale-refusal:
        lease expired / quorum lost / timeout) rather than serve it
        stale.  Raises TimeoutError if any batch neither serves nor
        refuses within ``timeout_steps`` rounds."""
        if not self.reads_enabled:
            raise ValueError("machine has no query kernel "
                             "(query_spec is None)")
        lanes = np.atleast_1d(np.asarray(lanes))
        n = len(lanes)
        q = np.asarray(queries).reshape(n, -1)
        nr = np.zeros((self.n_lanes,), np.int32)
        nr[lanes] = 1
        rq = np.zeros((self.n_lanes, self.read_window, self.query_width),
                      self.query_dtype)
        rq[lanes, 0] = q
        zero_n = np.zeros((self.n_lanes,), np.int32)
        zero_p = np.zeros((self.n_lanes, self.max_step_cmds,
                           self.payload_width), self.payload_dtype)
        Wq = self.query_reply_width
        replies = np.zeros((n, Wq), np.int32)
        wm = np.full((n,), -1, np.int32)
        ok = np.zeros((n,), bool)
        settled = np.zeros((n,), bool)
        aux = self.step(zero_n, zero_p, n_read=nr, read_q=rq)
        for _ in range(timeout_steps):
            done = np.asarray(aux["read_done"])[lanes] > 0
            # refused at arrival (leader down / slot busy) or by
            # timeout — either way the batch settles with ok=False
            stale = (np.asarray(aux["read_stale"])[lanes] > 0) | \
                (np.asarray(aux["read_shed"])[lanes] > 0)
            fresh = done & ~settled
            if fresh.any():
                rep = np.asarray(aux["read_replies"])[lanes[fresh], 0]
                replies[fresh] = rep.reshape(fresh.sum(), -1)
                wm[fresh] = np.asarray(
                    aux["read_watermark"])[lanes[fresh]]
                ok[fresh] = True
            settled |= done | stale
            if settled.all():
                return replies, wm, ok
            aux = self.step(zero_n, zero_p)
        raise TimeoutError(
            f"read_lanes: {int((~settled).sum())} batches neither "
            f"served nor refused within {timeout_steps} rounds")

    # -- checkpoint / resume (device-state snapshot, SURVEY §5) ------------

    def save(self, path: str) -> None:
        """Write the full lane state to one .npz (atomic replace): the
        lockstep analogue of the checkpoint/snapshot subsystem — all
        clusters' Raft cursors + machine states in one device pull.

        Archive keys are SCHEMA-NAMED since ISSUE 15
        (``<field>:<leaf-index>`` per LaneState field) so restore can
        resolve fields by name and default the ones an old archive
        predates — the forward-compat contract
        ``CHECKPOINT_FIELD_DEFAULTS`` declares and rule RA15 pins."""
        import os

        arrays = {}
        for name in LaneState._fields:
            leaves = jax.tree.flatten(getattr(self.state, name))[0]
            for j, x in enumerate(leaves):
                arrays[f"{name}:{j}"] = np.asarray(x)
        meta = {"n_lanes": self.n_lanes, "n_members": self.n_members,
                "ring_capacity": self.ring_capacity,
                "schema": list(LaneState._fields)}
        tmp = path + ".partial"
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(
                repr(meta).encode(), dtype=np.uint8), **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def restore(self, path: str) -> None:
        """Load a .npz written by :meth:`save` into this engine.  Engine
        geometry (lanes/members/ring) must match construction — the
        snapshot is state, not config.

        Forward compat (ISSUE 15, generalizing the PR 6 pre-telemetry
        fix): fields the archive predates restore through their
        ``CHECKPOINT_FIELD_DEFAULTS`` entry — ``"zeros"`` zero-fills
        (health counters), ``"init"`` keeps the restoring engine's
        current value (the fresh-init value in the open_engine
        recovery path), ``"require"`` refuses (consensus state every
        archive has always carried).  A durable dir is never stranded behind a
        pytree format bump.  Archives from a NEWER schema (unknown
        field names) are refused — silently dropping consensus state
        is not a degrade this layer may choose.  Positional pre-ISSUE-
        15 archives (``a<i>`` keys, with or without the telemetry
        plane) still restore."""
        with np.load(path) as z:
            names = [k for k in z.files if k != "__meta__"]
            if not any(":" in k for k in names):
                self._restore_positional(z)
                return
            by_field: dict = {}
            for k in names:
                by_field.setdefault(k.split(":", 1)[0], []).append(k)
            unknown = sorted(set(by_field) - set(LaneState._fields))
            if unknown:
                raise ValueError(
                    f"checkpoint carries unknown schema fields "
                    f"{unknown[:6]} (written by a newer engine?); "
                    "refusing to drop state")
            loaded = []
            for name in LaneState._fields:
                cur = getattr(self.state, name)
                leaves, treedef = jax.tree.flatten(cur)
                if not leaves:
                    # a zero-leaf field (e.g. a stateless machine's
                    # empty mac pytree) writes no archive keys — there
                    # is nothing to load OR default; keep the
                    # structure as-is (a 'require' mode must not
                    # refuse a checkpoint the same engine just wrote)
                    loaded.append(cur)
                    continue
                if name not in by_field:
                    mode = CHECKPOINT_FIELD_DEFAULTS.get(name,
                                                         "require")
                    if mode == "require":
                        raise ValueError(
                            f"checkpoint is missing required field "
                            f"{name!r}")
                    new = [jnp.zeros_like(x) for x in leaves] \
                        if mode == "zeros" else list(leaves)
                elif len(by_field[name]) != len(leaves):
                    raise ValueError(
                        f"checkpoint leaf count mismatch for "
                        f"{name!r}: archive has "
                        f"{len(by_field[name])}, engine needs "
                        f"{len(leaves)}")
                else:
                    new = []
                    for j, x in enumerate(leaves):
                        got = jnp.asarray(z[f"{name}:{j}"])
                        if x.shape != got.shape:
                            raise ValueError(
                                f"checkpoint geometry mismatch: "
                                f"{got.shape} != {x.shape}")
                        new.append(got)
                loaded.append(jax.tree.unflatten(treedef, new))
            self.state = LaneState(*loaded)

    def _restore_positional(self, z) -> None:
        """Legacy archive format: index-flattened ``a<i>`` keys.
        Archives written before the telemetry plane existed (LaneState
        without ``telem``) restore with zero-filled telemetry — the
        original PR 6 special case, kept verbatim for old dirs."""
        flat, treedef = jax.tree.flatten(self.state)
        n = len(flat)
        n_arch = sum(1 for k in z.files if k != "__meta__")
        n_tel = len(LaneTelemetry._fields)
        tel_at = len(jax.tree.flatten(
            tuple(self.state[:LaneState._fields.index("telem")]))[0])
        legacy = n_arch == n - n_tel
        if not legacy and n_arch != n:
            raise ValueError(
                f"checkpoint leaf count mismatch: archive has "
                f"{n_arch} arrays, engine state needs {n}")
        loaded, j = [], 0
        for i in range(n):
            if legacy and tel_at <= i < tel_at + n_tel:
                loaded.append(jnp.zeros_like(flat[i]))
                continue
            got = jnp.asarray(z[f"a{j}"])
            j += 1
            if flat[i].shape != got.shape:
                raise ValueError(
                    f"checkpoint geometry mismatch: {got.shape} "
                    f"!= {flat[i].shape}")
            loaded.append(got)
        self.state = jax.tree.unflatten(treedef, loaded)

    # -- readback ----------------------------------------------------------

    def mesh_shape(self) -> str:
        """``"<members>x<lanes>"`` device-mesh stamp (``""`` when
        unsharded) — rides the engine_pipeline overview so multichip
        bench tails/ring windows always carry the mesh the rates were
        measured on (ISSUE 11 satellite)."""
        if self._mesh is None:
            return ""
        shape = dict(self._mesh.shape)
        return f"{shape.get('members', 1)}x{shape.get('lanes', 1)}"

    def committed_total(self) -> int:
        # per-lane counters are int32 (wrap needs 2^31 commits in ONE lane —
        # unreachable in practice); the node-wide sum can exceed 2^31, so
        # sum on host in int64
        return int(np.asarray(self.state.total_committed)
                   .astype(np.int64).sum())

    def committed_per_lane(self) -> np.ndarray:
        return np.asarray(self.state.total_committed)

    def committed_lanes_async(self):
        """Non-blocking commit readback: returns a fresh device array of
        per-lane cumulative committed counts with a host copy already in
        flight.  Poll ``.is_ready()``; convert with ``np.asarray`` once
        ready.  The copy (`+ 0`) decouples the readback from buffer
        donation, so the next ``step`` can be dispatched immediately —
        this is the async host<->device overlap latency mode is built on
        (the applied-notification edge of ra_bench.erl:153-190 without a
        device barrier)."""
        tc = self.state.total_committed + 0
        try:
            tc.copy_to_host_async()
        except AttributeError:  # pragma: no cover — older jax arrays
            pass
        # transfer ledger (ISSUE 16): one d2h copy starts here —
        # counted at copy START, so an awaited handle is never counted
        # twice (.nbytes is host metadata, no sync)
        devicewatch.record_d2h("lanes_async", tc.nbytes)
        return tc

    def machine_states(self) -> Any:
        return jax.tree.map(np.asarray, self.state.mac)

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)

    def overview(self, lane: int = 0) -> dict:
        s = self.state
        out = {
            "term": int(s.term[lane]),
            "leader_slot": int(s.leader_slot[lane]),
            "last_index": np.asarray(s.last_index[lane]).tolist(),
            "last_written": np.asarray(s.last_written[lane]).tolist(),
            "commit": np.asarray(s.commit[lane]).tolist(),
            "applied": np.asarray(s.applied[lane]).tolist(),
            "active": np.asarray(s.active[lane]).tolist(),
            "total_committed": int(s.total_committed[lane]),
        }
        # dispatch-pipeline stamp (ISSUE 5): last fused K, the attached
        # driver's stage-ahead depth + live in-flight count, and the
        # host-side pipeline counters
        out["pipeline"] = {
            "superstep_k": self._superstep_k_last,
            # the autotuner-tunable knobs ride the overview (RA07: no
            # silent knob turns — knob value next to the rates it moves)
            "cmds_per_step": self.max_step_cmds,
            "mesh_shape": self.mesh_shape(),
            "wal_max_batch_interval_ms": (
                self._dur.batch_interval_ms()
                if self._dur is not None else -1.0),
            "dispatch_ahead": (self._driver.max_in_flight
                               if self._driver is not None else 0),
            "dispatches_in_flight": (self._driver.in_flight()
                                     if self._driver is not None else 0),
            **self.pipeline_counters,
        }
        if self.reads_enabled:
            # read-plane health (ISSUE 20): cumulative serve/refuse
            # ledger + lease coverage (the ra_top read panel's source)
            i64 = np.int64
            served = int(np.asarray(s.read_served).astype(i64).sum())
            leased = int(np.asarray(s.read_leased).astype(i64).sum())
            out["reads"] = {
                "served_total": served,
                "shed_total": int(
                    np.asarray(s.read_shed).astype(i64).sum()),
                "stale_refusals": int(
                    np.asarray(s.read_stale).astype(i64).sum()),
                "leased_total": leased,
                "lease_coverage_pct": (100.0 * leased / served)
                if served else 0.0,
                "pending_lanes": int((np.asarray(s.read_n) > 0).sum()),
                "lease_ttl": self.lease_ttl,
                "read_timeout": self.read_timeout,
                "read_window": self.read_window,
            }
        if self._dur is not None:
            # durability-plane health (ENGINE_WAL_FIELDS + per-shard
            # WAL_FIELDS/stats), the key_metrics merge of PR 2's
            # RPC_FIELDS pattern
            out["wal"] = self._dur.wal_overview()
        if self._ingress is not None:
            # the session tier's flow gauges ride the engine overview
            # (queue depth next to the pipeline it feeds, ISSUE 10)
            out["ingress"] = self._ingress.gauges()
        return out


class DispatchAheadDriver:
    """Dispatch-ahead host pipeline for the superstep path (ISSUE 5).

    Double-buffered staging: :meth:`submit` starts the host->device
    transfer (``device_put``) of THIS block, then dispatches the
    PREVIOUSLY staged block — so the host-side encode + H2D copy of
    block i+1 overlaps the device execution of dispatch i.  No
    ``block_until_ready`` anywhere in the loop: the in-flight cap is
    enforced with asynchronous commit readbacks (one per dispatch, of
    the superstep's last inner-step committed watermark), and only when
    more than ``max_in_flight`` dispatches are unobserved does the
    driver await the OLDEST readback — the window-boundary sync, the
    single blocking point (counted in ``window_syncs``; lint rule RA04
    polices the bench loops this feeds).

    ``shardings`` (optional, from
    :func:`ra_tpu.parallel.mesh.superstep_block_shardings`) places the
    staged ``n_new``/``payloads`` blocks on a device mesh so a sharded
    engine's fused dispatch consumes them without a resharding copy.
    Elect schedules are NOT staged: they are host data by the
    `_host_mask` contract (the any-election bookkeeping runs on the
    host), so the driver hands them to :meth:`LockstepEngine.superstep`
    untouched.
    """

    def __init__(self, engine: "LockstepEngine", max_in_flight: int = 2,
                 shardings: Optional[dict] = None) -> None:
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self.engine = engine
        self.max_in_flight = max_in_flight
        self.shardings = shardings or {}
        self._staged = None
        self._handles: collections.deque = collections.deque()
        self.last_committed: Optional[np.ndarray] = None
        #: newest OBSERVED cumulative read watermarks (np.int32[N]) —
        #: the read twin of last_committed, advanced at the same
        #: window-boundary pops; the ingress read lane settles its
        #: in-flight blocks against these (ISSUE 20)
        self.last_read_served: Optional[np.ndarray] = None
        self.last_read_shed: Optional[np.ndarray] = None
        self.last_read_stale: Optional[np.ndarray] = None
        #: observed read aux (served replies + watermarks, np arrays)
        #: in dispatch order, drained by IngressPlane read harvest —
        #: bounded so a driver with no read consumer (bench loops that
        #: only need the served counters) cannot grow host memory
        self.read_obs: collections.deque = collections.deque(maxlen=64)
        engine._driver = self

    def in_flight(self) -> int:
        return len(self._handles)

    def _stage(self, n_new_blk, payloads_blk, elect_blk=None,
               read_blk=None) -> None:
        put = jax.device_put
        t0 = time.monotonic()
        n = put(np.asarray(n_new_blk, np.int32),  # ra02-ok: host block -> staging encode (async H2D; no device readback)
                self.shardings.get("n_new"))
        p = put(np.asarray(payloads_blk), self.shardings.get("payloads"))  # ra02-ok: host block -> staging encode (async H2D; no device readback)
        nbytes, nev = n.nbytes + p.nbytes, 2
        if read_blk is not None:
            rn = put(np.asarray(read_blk[0], np.int32),  # ra02-ok: host read block -> staging encode (async H2D; no device readback)
                     self.shardings.get("n_read"))
            rq = put(np.asarray(read_blk[1]), self.shardings.get("read_q"))  # ra02-ok: host read block -> staging encode (async H2D; no device readback)
            nbytes += rn.nbytes + rq.nbytes
            nev += 2
            read_blk = (rn, rq)
        # host_staging phase stamp: the host-side encode + H2D submit
        # cost of this block (device_put is async, so this is the edge
        # the host pays, not the wire time — rule RA04: no sync here)
        self.engine.phases.note("host_staging", time.monotonic() - t0)
        self.engine.pipeline_counters["blocks_staged"] += 1
        # transfer ledger (ISSUE 16): the steady-state loop's h2d
        # budget is exactly these staged blocks per submit —
        # measured here so the "fixed per-window transfer budget" is a
        # number, not an RA04 lint promise (.nbytes = host metadata)
        devicewatch.record_h2d("driver_stage", nbytes, events=nev)
        self._staged = (n, p, elect_blk, read_blk)

    def submit(self, n_new_blk, payloads_blk, elect_blk=None,
               read_blk=None):
        """Stage this block (async H2D), dispatch the previous one.
        ``read_blk``: optional ``(n_read_blk [K,N], read_q_blk
        [K,N,Kr,Cq])`` read schedule riding the same dispatch.
        Returns the previous dispatch's async committed-watermark
        handle, or None on the first call (nothing dispatched yet)."""
        prev = self._staged
        self._stage(n_new_blk, payloads_blk, elect_blk, read_blk)
        return self._dispatch(prev) if prev is not None else None

    def _dispatch(self, blk):
        t_sub = time.monotonic()
        read_blk = blk[3]
        aux = self.engine.superstep(
            blk[0], blk[1], elect_blk=blk[2],
            n_read_blk=None if read_blk is None else read_blk[0],
            read_q_blk=None if read_blk is None else read_blk[1])
        # the `+ 0` copy decouples the readback from buffer donation by
        # the next dispatch (same contract as committed_lanes_async)
        h = aux["committed_lanes"][-1] + 0
        try:
            h.copy_to_host_async()
        except AttributeError:  # pragma: no cover — older jax arrays
            pass
        # transfer ledger (ISSUE 16): one watermark readback per
        # dispatch, counted at copy start (the window-boundary pop
        # below observes the SAME copy — never double-counted)
        devicewatch.record_d2h("driver_watermark", h.nbytes)
        robs = None
        if self.engine.reads_enabled:
            # read answers drain off the same async-readback rhythm as
            # the committed watermark: copies START here (no sync), and
            # are OBSERVED at the window-boundary pops below (ISSUE 20
            # — no new host sync points for the read plane).  The
            # cumulative [N] outcome counters ride EVERY dispatch (a
            # batch registered in dispatch i may serve or expire during
            # a read-less dispatch i+k — settlement must still see it);
            # the full reply tensors ride only read-carrying dispatches
            robs = {"read_served_lanes": aux["read_served_lanes"][-1] + 0,
                    "read_shed_lanes": aux["read_shed_lanes"][-1] + 0,
                    "read_stale_lanes": aux["read_stale_lanes"][-1] + 0}
            if read_blk is not None:
                robs.update({k: aux[k] + 0 for k in
                             ("read_done", "read_replies",
                              "read_watermark")})
            rb = 0
            for v in robs.values():
                try:
                    v.copy_to_host_async()
                except AttributeError:  # pragma: no cover
                    pass
                rb += v.nbytes
            devicewatch.record_d2h("driver_read", rb, events=len(robs))
        self._handles.append((t_sub, h, robs))
        while len(self._handles) > self.max_in_flight:
            # window boundary: await the OLDEST dispatch's watermark.
            # Only a harvest that actually had to WAIT counts as a
            # window_sync — a ready readback popped in passing is the
            # pipeline working, not blocking (the counter backs the
            # "window_syncs << dispatches" health rule, so it must
            # distinguish the two)
            t0, oldest, orobs = self._handles.popleft()
            try:
                waited = not oldest.is_ready()
            except AttributeError:  # pragma: no cover — older jax arrays
                waited = True
            if waited:
                self.engine.pipeline_counters["window_syncs"] += 1
            self.last_committed = np.asarray(oldest)  # ra02-ok: the in-flight cap's window-boundary readback — the driver's single documented sync point (window_syncs)
            # device_dispatch phase stamp: submit -> the dispatch's
            # committed watermark observed on the host, read at the
            # pops the in-flight cap already performs (PR 5's async
            # watermark readbacks — no NEW sync point is introduced)
            self.engine.phases.note("device_dispatch",
                                    time.monotonic() - t0)
            self._observe_reads(t0, orobs)
        return h

    def _observe_reads(self, t_sub, robs) -> None:
        """Convert a popped dispatch's read-aux copies to host data —
        called only at the pops the in-flight cap already performs (the
        copies were started at dispatch; observing them here adds no
        new sync point beyond the committed-watermark one)."""
        if robs is None:
            return
        obs = {k: np.asarray(v) for k, v in robs.items()}  # ra02-ok: window-boundary read observation — same pop as last_committed, copies started async at dispatch
        self.last_read_served = obs["read_served_lanes"]
        self.last_read_shed = obs["read_shed_lanes"]
        self.last_read_stale = obs["read_stale_lanes"]
        self.read_obs.append(obs)
        # read_e2e phase stamp: read-block submit -> serve outcome
        # observed on the host (the continuous signal behind the
        # read_p99_ms SLO objective) — stamped only for dispatches
        # that actually served reads, so write-only dispatches on a
        # reads-enabled engine don't dilute the read latency signal
        if "read_done" in obs and obs["read_done"].any():
            self.engine.phases.note("read_e2e",
                                    time.monotonic() - t_sub)

    def drain(self) -> Optional[np.ndarray]:
        """Dispatch any staged block and await every in-flight
        readback; returns the newest observed per-lane committed
        watermark (np.int32[N])."""
        if self._staged is not None:
            blk, self._staged = self._staged, None
            self._dispatch(blk)
        while self._handles:
            t0, h, robs = self._handles.popleft()
            self.last_committed = np.asarray(h)
            self.engine.phases.note("device_dispatch",
                                    time.monotonic() - t0)
            self._observe_reads(t0, robs)
        return self.last_committed
