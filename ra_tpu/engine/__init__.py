from .lockstep import LaneState, LockstepEngine
from .durable import EngineDurability, open_engine

__all__ = ["LaneState", "LockstepEngine", "EngineDurability", "open_engine"]
