from .lockstep import LaneState, LockstepEngine
