from .lockstep import (CHECKPOINT_FIELD_DEFAULTS, DispatchAheadDriver,
                       LaneState, LockstepEngine)
from .durable import EngineDurability, open_engine

__all__ = ["CHECKPOINT_FIELD_DEFAULTS", "DispatchAheadDriver",
           "LaneState", "LockstepEngine", "EngineDurability",
           "open_engine"]
