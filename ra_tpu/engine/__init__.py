from .lockstep import DispatchAheadDriver, LaneState, LockstepEngine
from .durable import EngineDurability, open_engine

__all__ = ["DispatchAheadDriver", "LaneState", "LockstepEngine",
           "EngineDurability", "open_engine"]
