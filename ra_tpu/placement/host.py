"""One lane-engine host: a durable engine + ingress plane + wire
listener under a single engine id (the data-plane unit the placement
table assigns lane ranges to, ISSUE 17).

Failure model — **kill-9, not shutdown**: :meth:`LaneEngineHost.kill9`
kills every WAL shard abruptly (queued-but-unfsynced writes are lost,
exactly what SIGKILL loses), stops the shard supervisor (a kill-9'd
process has no supervisor), and abandons the engine WITHOUT flush or
checkpoint.  Because commits gate on the fsync confirm and ACK
watermarks fan out only on commit, everything a client was ever ACKed
is on disk — the never-acked tail is the only loss, and that loss is
Raft-legal (docs/PLACEMENT.md).

Recovery model — **adoption, not restart**: the survivor host opens
the victim's durable directory through the standard recovery path
(:func:`ra_tpu.engine.durable.open_engine`: checkpoint restore + RTB2
WAL-shard merge of ANY layout + replay, gated at the fsynced
watermark) and serves the recovered lane space through a fresh ingress
plane + wire listener of its own.  The new listener re-seeds its
per-lane dedup-slot cursors from the recovered machine's ``seq``
watermarks (WireListener._recovered_lane_next) — the "ingress dedup
watermarks re-seeded from recovered machine state" leg of the
exactly-once contract; the client side claims its old slots through
:meth:`ra_tpu.wire.server.WireListener.loopback_rehome`.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..blackbox import record
from ..wire.framing import data_stride
from ..wire.server import WireListener


class LaneEngineHost:
    """One engine id's serving stack.  ``machine_factory`` builds the
    lane machine (one per engine incarnation — recovered adoptions
    build their own); geometry kwargs mirror the wire soak's."""

    def __init__(self, engine_id: str, data_dir: str, *,
                 machine_factory, lanes: int = 64,
                 ring_capacity: int = 512, max_step_cmds: int = 16,
                 wal_shards: int = 2, superstep_k: int = 4,
                 max_conns: int = 256, ring_records: int = 32,
                 port: Optional[int] = None) -> None:
        from ..engine.durable import open_engine
        from ..ingress import IngressPlane
        self.engine_id = engine_id
        self.data_dir = data_dir
        self.lanes = int(lanes)
        self._geometry = dict(ring_capacity=ring_capacity,
                              max_step_cmds=max_step_cmds,
                              wal_shards=wal_shards,
                              superstep_k=superstep_k,
                              max_conns=max_conns,
                              ring_records=ring_records)
        self._machine_factory = machine_factory
        self.engine = open_engine(
            machine_factory(), data_dir, self.lanes,
            wal_shards=wal_shards, ring_capacity=ring_capacity,
            max_step_cmds=max_step_cmds, donate=False)
        self.plane = IngressPlane(self.engine, superstep_k=superstep_k,
                                  window_s=0.001, soft_credit=1 << 20,
                                  hard_credit=1 << 20)
        # port=None keeps the in-process loopback shape (the classic
        # soaks); port=0 binds an ephemeral TCP listener so a geo
        # child process serves real wire clients (ISSUE 19)
        self._port = port
        self.listener = WireListener(
            self.plane, port=port, max_conns=max_conns,
            ring_bytes=ring_records * data_stride(
                self.engine.payload_width))
        self._alive = True
        #: victim engine id -> (engine, plane, listener) restored into
        #: this host's lane space by adopt()
        self.adopted: dict = {}

    # -- liveness ------------------------------------------------------

    def alive(self) -> bool:
        """The supervisor's heartbeat probe."""
        return self._alive

    def kill9(self) -> None:
        """Abrupt whole-host death (the engine_kill nemesis op).  The
        WAL loses queued-but-unfsynced writes, the engine keeps no
        flush/checkpoint ceremony, and this host never serves again —
        a survivor adopts its durable directory instead."""
        if not self._alive:
            return
        self._alive = False
        dur = getattr(self.engine, "_dur", None)
        if dur is not None:
            # a kill-9'd process has no shard supervisor either: stop
            # it FIRST or it would resurrect the shards we kill
            dur._sup_stop.set()
            for wal in dur.wals:
                wal.kill()

    def close(self) -> None:
        """Graceful teardown (test/soak cleanup — NOT the failure
        path).  A kill-9'd host only releases its adopted stacks and
        host-side listener state; its own engine died with kill9()."""
        for eng, _plane, lst in self.adopted.values():
            lst.close()
            eng.close()
        self.adopted.clear()
        self.listener.close()
        if self._alive:
            self._alive = False
            self.engine.close()

    # -- serving -------------------------------------------------------

    def cycle(self) -> None:
        """One pump of every serving stack this host owns (its own
        lane space + every adopted one)."""
        if not self._alive:
            return
        self.listener.sweep()
        self.plane.pump(force=True)
        for _eng, plane, lst in self.adopted.values():
            lst.sweep()
            plane.pump(force=True)

    def settle(self, timeout: float = 30.0) -> None:
        if not self._alive:
            return
        self.plane.settle(timeout=timeout)
        for _eng, plane, _lst in self.adopted.values():
            plane.settle(timeout=timeout)

    # -- adoption (lane-range migration as recovery) -------------------

    def adopt(self, victim_id: str, victim_dir: str, *,
              wal_shards: Optional[int] = None,
              trace_ctx: Optional[str] = None) -> WireListener:
        """Restore ``victim_dir``'s durable lane state into this
        host's lane space and serve it: standard engine recovery
        (checkpoint + WAL merge at ANY shard layout + replay to the
        fsynced watermark) behind a fresh plane + listener.  Returns
        the adopted listener — the new home re-homed sessions bind to.

        The adopted ingress plane is constructed exactly like the
        victim's (same lane count, default directory seed), so the
        deterministic key→lane hashing re-places every re-homed
        session on the lane its recovered machine state lives in."""
        from ..engine.durable import open_engine
        from ..ingress import IngressPlane
        if victim_id in self.adopted:
            # a re-delivered failover (retrying supervisor) adopts once
            return self.adopted[victim_id][2]
        g = self._geometry
        eng = open_engine(
            self._machine_factory(), victim_dir, self.lanes,
            wal_shards=wal_shards if wal_shards is not None
            else g["wal_shards"],
            ring_capacity=g["ring_capacity"],
            max_step_cmds=g["max_step_cmds"], donate=False)
        plane = IngressPlane(eng, superstep_k=g["superstep_k"],
                             window_s=0.001, soft_credit=1 << 20,
                             hard_credit=1 << 20)
        # a TCP-serving host (geo child) gives the adopted stack its
        # own ephemeral TCP listener — the address a REHOME hint's
        # resolver hands back to re-homed wire clients
        lst = WireListener(
            plane, port=0 if self._port is not None else None,
            max_conns=g["max_conns"],
            ring_bytes=g["ring_records"] * data_stride(
                eng.payload_width))
        self.adopted[victim_id] = (eng, plane, lst)
        st = eng.state
        lane = np.arange(self.lanes)
        tail = np.asarray(st.last_index)[
            lane, np.asarray(st.leader_slot)]
        record("placement.adopt", trace=trace_ctx, victim=victim_id,
               survivor=self.engine_id,
               recovered_tail_max=int(tail.max(initial=0)),
               wal_dirs=len([d for d in os.listdir(victim_dir)
                             if d.startswith(("wal", "shard"))]))
        return lst

    def adopted_listener(self, victim_id: str) -> WireListener:
        return self.adopted[victim_id][2]

    def adopted_engine(self, victim_id: str):
        return self.adopted[victim_id][0]
