"""The geo-distributed survival soak (ISSUE 19 acceptance).

Everything PR 17 did in one process, stretched across REAL processes
and a CD-Raft latency geometry:

* the parent ("ctl") hosts one placement-table member, the
  :class:`~ra_tpu.placement.supervisor.EngineSupervisor` (probing over
  the reliable RPC tier via :class:`~ra_tpu.placement.fabric
  .RpcEngineProbe`), and the wire clients;
* a control child ("far") hosts the other two table members behind an
  80-150 ms latency-domain matrix — every control commit pays at least
  one cross-domain round trip for quorum (the CD-Raft shape);
* two engine children each run a :class:`~ra_tpu.placement.host
  .LaneEngineHost` serving a REAL TCP wire listener, fronted by a
  :class:`~ra_tpu.placement.fabric.HostAgent` (the host_* control
  verbs over reliable RPC); the engine tier is local — the delay
  matrix does not touch it.

One run (:func:`run_geo_soak`):

1. live wire traffic against both engine children;
2. a **delay-only episode**: the parent's matrix temporarily stretches
   the control→engine domain crossing by the same 80-150 ms — probes
   slow down but keep completing (RTT reads as age), and the run
   asserts ZERO migrations and zero down verdicts: geography is not
   death;
3. **SIGKILL** of one engine child mid-traffic: probes go silent, the
   verdict ladder escalates through the hysteresis window, the
   supervisor commits ``engine_down`` + generation-gated ``migrate``
   through the cross-domain table, the survivor adopts the victim's
   durable directory over ``host_adopt``, the committed placement is
   pushed to the survivor's serving cache (``host_placement``), the
   victim's wire client re-homes over ``host_rehome`` +
   :meth:`WireClient.rehome_to` (old dedup slots claimed, unacked
   window replayed);
4. the exactly-once oracle closes over BOTH engines' machine state
   read back over ``host_lane_sums``: zero lost-acked, zero
   double-applied.

The JSON tail stamps ``geo_failover_recovery_s`` (SIGKILL → first
commit on the new home) and ``geo_false_migrations`` (must be 0) for
tools/bench_diff.py.  ``tools/soak.py --geo SEED [SEED...]`` drives it
standalone; this module is also its own child-process entrypoint
(``python -m ra_tpu.placement.geo --child ...``).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Callable, Optional

import numpy as np

from ..blackbox import record
from ..trace import new_trace_ctx

#: the latency geometry: control followers are far, engines are local
_DELAY_MS = (80.0, 150.0)


def _geo_members() -> dict:
    return {"ctl": ["ctl"], "far": ["gf1", "gf2"],
            "eng": ["n_engA", "n_engB"]}


def _geo_plan(local: str, seed: int, *, eng_delay: bool = False):
    """The latency-domain FaultPlan one process of the geo topology
    installs: geography as a named-domain matrix, compiled onto the
    per-(peer, class, direction) fault streams (docs/INTERNALS.md
    §20).  ``eng_delay`` adds the control→engine crossing — the
    delay-only episode's knob."""
    from ..transport.rpc import FaultPlan
    matrix: dict = {("ctl", "far"): {"delay_ms": _DELAY_MS}}
    if eng_delay:
        matrix[("ctl", "eng")] = {"delay_ms": _DELAY_MS}
    return FaultPlan(seed=seed, domains={
        "local": local, "members": _geo_members(), "matrix": matrix})


def _tune_detector(router) -> None:
    """Transport-level detector thresholds that tolerate the matrix:
    a 150 ms one-way stretch must never flap a peer suspect (reliable
    RPC refuses suspect peers — flapping would starve the commit
    path)."""
    router.suspect_after = 2.0
    router.down_after = 6.0
    router.detector_hysteresis = 0.5


def _await(what: str, timeout_s: float, fn: Callable[[], bool], *,
           tick: Optional[Callable[[], None]] = None,
           sleep_s: float = 0.01) -> int:
    """Deadline-bounded progress wait (the one retry shape RA16
    allows): polls ``fn`` — optionally driving ``tick`` between polls
    — and emits the registered give-up event on exhaustion."""
    deadline = time.monotonic() + timeout_s
    attempts = 0
    while time.monotonic() < deadline:
        attempts += 1
        if tick is not None:
            tick()
        if fn():
            return attempts
        time.sleep(sleep_s)
    record("placement.giveup", what=what, attempts=attempts)
    raise TimeoutError(f"geo soak: {what} not reached in {timeout_s}s")


def _machine_slots(sessions: int, lanes: int) -> int:
    """Dedup-slot budget per lane (parent and children must agree —
    the machine is built in the child, the client asserts against it
    in the parent)."""
    return 4 * max(1, sessions // lanes) + 64


def _write_ready(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # atomic: the parent never reads a torn file


# ----------------------------------------------------------------------
# child entrypoints (one OS process each)
# ----------------------------------------------------------------------


def _engine_child(args) -> None:
    """One lane-engine host process: TcpRouter + RaNode + LaneEngineHost
    (real TCP wire listener) + HostAgent, pumping until stopped,
    killed, or the run deadline."""
    from ..node import RaNode
    from ..transport.tcp import TcpRouter
    from ..wire.dedup import DedupCounterMachine
    from .fabric import HostAgent
    from .host import LaneEngineHost
    eid = args.eid
    router = TcpRouter(("127.0.0.1", 0),
                       {"ctl": (args.parent_host, args.parent_port)})
    router.set_fault_plan(_geo_plan("eng", args.seed))
    _tune_detector(router)
    node = RaNode(f"n_{eid}", router=router)
    slots = _machine_slots(args.sessions, args.lanes)
    host = LaneEngineHost(
        eid, args.data_dir,
        machine_factory=lambda: DedupCounterMachine(slots=slots),
        lanes=args.lanes, wal_shards=args.wal_shards, max_conns=16,
        port=0)
    agent = HostAgent(host, node, placement_rid=f"{eid}/lanes")
    _write_ready(args.ready, {
        "router": list(router.listen_addr),
        "wire": list(host.listener.address),
        "node": node.name, "pid": os.getpid()})
    deadline = time.monotonic() + args.max_run_s
    n = 0
    while time.monotonic() < deadline and not agent.stopped.is_set():
        agent.pump()
        host.cycle()
        n += 1
        if n % 64 == 0:
            # drive the async committed-watermark readbacks so ACK
            # watermarks stay live between the parent's waves
            try:
                host.settle(timeout=2.0)
            except (TimeoutError, RuntimeError):
                pass
        time.sleep(0.002)
    if not agent.stopped.is_set():
        record("placement.giveup", what="geo_engine_child_deadline",
               attempts=n)
    host.close()
    node.stop()
    router.stop()


def _control_child(args) -> None:
    """The far latency domain: one TcpRouter hosting BOTH remote
    placement-table nodes (gf1, gf2) — local to each other, 80-150 ms
    from the parent's domain.  The table members themselves are
    started REMOTELY by the parent over the control plane
    (start_cluster's config-snapshot path)."""
    import threading
    from ..node import RaNode
    from ..transport.tcp import TcpRouter
    from . import table as _table  # registers the machine spec  # noqa: F401
    router = TcpRouter(("127.0.0.1", 0),
                       {"ctl": (args.parent_host, args.parent_port)})
    router.set_fault_plan(_geo_plan("far", args.seed))
    _tune_detector(router)
    stop = threading.Event()
    nodes = [RaNode("gf1", router=router), RaNode("gf2", router=router)]
    nodes[0].control_ops["geo_stop"] = \
        lambda a: (stop.set(), "stopping")[1]
    _write_ready(args.ready, {
        "router": list(router.listen_addr),
        "node": "gf1,gf2", "pid": os.getpid()})
    deadline = time.monotonic() + args.max_run_s
    waited = 0
    while time.monotonic() < deadline and not stop.is_set():
        time.sleep(0.05)
        waited += 1
    if not stop.is_set():
        record("placement.giveup", what="geo_control_child_deadline",
               attempts=waited)
    for n in nodes:
        n.stop()
    router.stop()


def _spawn_child(role: str, ready: str, parent_addr: tuple,
                 seed: int, max_run_s: float, **kw) -> subprocess.Popen:
    argv = [sys.executable, "-m", "ra_tpu.placement.geo",
            "--child", role, "--ready", ready,
            "--parent-host", parent_addr[0],
            "--parent-port", str(parent_addr[1]),
            "--seed", str(seed), "--max-run-s", str(max_run_s)]
    for k, v in kw.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(argv, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)


def _read_ready(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


# ----------------------------------------------------------------------
# the parent orchestration
# ----------------------------------------------------------------------


def run_geo_soak(seed: int, *, sessions: int = 24, lanes: int = 16,
                 waves: int = 5, wave_ops: int = 300,
                 wal_shards: int = 2,
                 delay_episode_s: float = 2.5,
                 data_dir: Optional[str] = None,
                 max_run_s: float = 300.0,
                 recovery_bar: Optional[float] = None) -> dict:
    """One geo run; returns a bench_diff-comparable tail row.  See the
    module docstring for the scenario."""
    from ..api import process_command, start_cluster
    from ..core.types import ErrorResult, ServerId
    from ..node import RaNode
    from ..transport.tcp import TcpRouter
    from ..wire.client import WireClient
    from .fabric import (RpcEngineProbe, push_placement, remote_adopt,
                         remote_lane_sums, remote_rehome)
    from .supervisor import EngineSupervisor
    from .table import placement_spec
    rng = np.random.default_rng(seed)
    tmp = None
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="geo-soak-")
        data_dir = tmp.name
    dirs = {e: os.path.join(data_dir, e) for e in ("engA", "engB")}
    base_plan = _geo_plan("ctl", seed)
    router = TcpRouter(("127.0.0.1", 0), {})
    router.set_fault_plan(base_plan)
    _tune_detector(router)
    ctl = RaNode("ctl", router=router)
    procs: dict = {}
    clients: dict = {}
    row: dict = {}
    try:
        # -- topology: one far control child + two engine children ---
        ready = {r: os.path.join(data_dir, f"{r}.ready")
                 for r in ("far", "engA", "engB")}
        procs["far"] = _spawn_child("control", ready["far"],
                                    router.listen_addr, seed, max_run_s)
        for eid in ("engA", "engB"):
            procs[eid] = _spawn_child(
                "engine", ready[eid], router.listen_addr, seed,
                max_run_s, eid=eid, data_dir=dirs[eid], lanes=lanes,
                sessions=sessions, wal_shards=wal_shards)
        _await("geo_children_ready", 120.0,
               lambda: all(os.path.exists(p) for p in ready.values()),
               sleep_s=0.05)
        info = {r: _read_ready(p) for r, p in ready.items()}
        for n in ("gf1", "gf2"):
            router.address_book[n] = tuple(info["far"]["router"])
        for eid in ("engA", "engB"):
            router.address_book[f"n_{eid}"] = \
                tuple(info[eid]["router"])
        node_of = {eid: f"n_{eid}" for eid in ("engA", "engB")}
        wire_of = {eid: tuple(info[eid]["wire"])
                   for eid in ("engA", "engB")}

        # -- control plane: the table quorum spans the delay matrix --
        sids = [ServerId("pt1", "ctl"), ServerId("pt2", "gf1"),
                ServerId("pt3", "gf2")]
        start_cluster("geo_pt", placement_spec(), sids, router=router,
                      election_timeout_ms=800, tick_interval_ms=200)
        sup = EngineSupervisor(
            sids[0], router, suspect_after=0.75, down_after=2.5,
            hysteresis=0.5, commit_timeout=10.0)
        probes = {}
        for eid in ("engA", "engB"):
            p = RpcEngineProbe(router, node_of[eid], eid, timeout=1.5,
                               min_interval=0.05)
            sup.watch(eid, p)
            p.bind(sup)
            probes[eid] = p
        adopted_addr: dict = {}

        def _on_migrate(victim, survivor, placements, trace_ctx):
            adopted_addr[victim] = remote_adopt(
                router, node_of[survivor], victim, dirs[victim],
                survivor=survivor, rid=f"{victim}/lanes",
                timeout=90.0, trace_ctx=trace_ctx)
        sup.on_migrate = _on_migrate
        for cmd in (("register_engine", "engA"),
                    ("register_engine", "engB"),
                    ("assign", "engA/lanes", "engA", 0, lanes),
                    ("assign", "engB/lanes", "engB", 0, lanes)):
            res = sup._commit(lambda c=cmd: process_command(
                sids[0], c, router, timeout=15.0), what="geo_setup")
            assert not isinstance(res, ErrorResult)
        state0 = sup.table_state()
        for eid in ("engA", "engB"):
            push_placement(router, node_of[eid], state0, timeout=15.0)

        # -- live wire traffic over real TCP -------------------------
        mslots = _machine_slots(sessions, lanes)
        for eid in ("engA", "engB"):
            c = WireClient(wire_of[eid], f"geo{seed}/{eid}",
                           n_sessions=sessions, tenants=2,
                           timeout=20.0)
            assert int(np.max(c.slots)) < mslots, "dedup slot overflow"
            clients[eid] = c
        victim, survivor = "engA", "engB"
        killed = False
        handled: set = set()  # engines whose down verdict was acted on

        def _live() -> list:
            return [e for e in ("engA", "engB")
                    if not (e == victim and killed
                            and e not in handled)]

        def _failover(eid: str) -> None:
            surv = "engB" if eid == "engA" else "engA"
            ctx = new_trace_ctx("geo-failover")
            record("placement.refuse", trace=ctx, engine=eid,
                   unplaced=int(_unplaced(eid)))
            sup.failover(eid, surv, trace_ctx=ctx)  # on_migrate adopts
            # cache-invalidation-on-commit: the survivor's serving view
            # learns the committed move BEFORE the client is re-pointed
            # — its placement mask then routes the re-homed sessions
            # instead of REHOME-refusing them
            push_placement(router, node_of[surv], sup.table_state(),
                           timeout=15.0)
            durable = remote_rehome(router, node_of[surv], eid,
                                    clients[eid], timeout=60.0,
                                    trace_ctx=ctx)
            clients[eid].rehome_to(adopted_addr[eid], durable)

        def _pump() -> None:
            # the nemesis reaction lives HERE: a down verdict — never a
            # mere delay — is the one migration trigger, so the delay
            # episode's zero-migration assert is a real property
            for eid in sup.tick():
                if eid not in handled:
                    handled.add(eid)
                    _failover(eid)
            for e in _live():
                try:
                    clients[e].flush()
                    clients[e].poll()
                except OSError:
                    pass

        def _unplaced(e: str) -> int:
            c = clients[e]
            return sum(1 for s in c.op_state if s != 2)

        def _undrained(e: str) -> int:
            # placed is a SWEEP verdict; the oracle reads committed
            # machine state, so drain until every RANKED op is acked
            # (acks ride the committed watermark — fsync-gated).
            # DUP-placed replays never rank: their delta is already in
            # the recovered state, nothing of theirs is in flight.
            c = clients[e]
            ranked_unacked = sum(
                1 for i in range(len(c.op_state))
                if c.op_rank[i] >= 0 and not c._acked(i))
            return _unplaced(e) + ranked_unacked

        def _wave() -> None:
            for e in _live():
                c = clients[e]
                for _ in range(wave_ops):
                    c.enqueue(int(rng.integers(1, 8)),
                              sess=int(rng.integers(0, sessions)))
            _await("geo_wave_placed", 60.0,
                   lambda: all(_unplaced(e) == 0 for e in _live()),
                   tick=_pump)

        t0 = time.perf_counter()
        _wave()  # warm both serving paths end to end

        # -- episode 1: delay is not death ---------------------------
        downs0 = sup.counters["downs"]
        mig0 = sup.counters["migrations"]
        router.set_fault_plan(_geo_plan("ctl", seed, eng_delay=True))
        ep_end = time.monotonic() + delay_episode_s
        _wave()
        _await("geo_delay_episode", delay_episode_s + 30.0,
               lambda: time.monotonic() >= ep_end, tick=_pump)
        router.set_fault_plan(base_plan)
        false_migrations = sup.counters["migrations"] - mig0
        assert sup.counters["downs"] == downs0, \
            "delay-only episode produced a down verdict"
        assert false_migrations == 0, \
            "delay-only episode migrated an engine (geography as death)"

        # -- episode 2: SIGKILL one engine host ----------------------
        for w in range(waves):
            if w == waves // 2 and not killed:
                os.kill(info[victim]["pid"], signal.SIGKILL)
                t_kill = time.perf_counter()
                killed = True
                wm = int(clients[victim].watermark.sum())
                _await("geo_detect_and_migrate", 60.0,
                       lambda: victim in handled, tick=_pump)
                _await("geo_recovery_commit", 90.0,
                       lambda: int(clients[victim].watermark.sum())
                       > wm, tick=_pump)
                recovery_s = time.perf_counter() - t_kill
            _wave()
        assert killed and victim in handled, "kill wave never ran"
        _await("geo_drain", 120.0,
               lambda: all(_undrained(e) == 0
                           for e in ("engA", "engB")), tick=_pump)
        elapsed = time.perf_counter() - t0

        # -- the exactly-once oracle over both engines ---------------
        got = {
            victim: remote_lane_sums(router, node_of[survivor],
                                     victim, timeout=30.0),
            survivor: remote_lane_sums(router, node_of[survivor],
                                       survivor, timeout=30.0),
        }
        lost = double = 0
        for eid in ("engA", "engB"):
            expected = _expected_lane_sums(clients[eid], lanes,
                                           f"geo{seed}/{eid}")
            lost += int(np.maximum(expected - got[eid], 0).sum())
            double += int(np.maximum(got[eid] - expected, 0).sum())
            np.testing.assert_array_equal(got[eid], expected)
        assert sup.counters["downs"] - downs0 == 1
        assert sup.counters["migrations"] >= 1
        if recovery_bar is not None:
            assert recovery_s <= recovery_bar, \
                f"recovery {recovery_s:.3f}s > bar {recovery_bar}s"
        row = {
            "value": recovery_s,
            "geo_failover_recovery_s": recovery_s,
            "geo_false_migrations": int(false_migrations),
            "geo_lost_acked": lost,
            "geo_double_applied": double,
            "seed": seed, "sessions": 2 * sessions, "lanes": lanes,
            "ops": int(sum(len(clients[e].op_state)
                           for e in clients)),
            "migrations": int(sup.counters["migrations"]),
            "stale_probe_drops":
                int(sup.counters["stale_probe_drops"]),
            "rehome_follows":
                int(sum(clients[e].rehome_follows for e in clients)),
            "probe_replies":
                {e: int(probes[e].replies) for e in probes},
            "detector": {k: int(sup.counters[k]) for k in
                         ("heartbeats", "suspects", "downs",
                          "recoveries")},
            "domain_matrix": base_plan.overview().get("domain_matrix"),
            "elapsed_s": elapsed, "wal_shards": wal_shards,
            "host": _host_envelope(),
        }
        return row
    finally:
        _teardown(router, ctl, procs, clients, node_of={
            "engA": "n_engA", "engB": "n_engB"})
        if tmp is not None:
            tmp.cleanup()


def _expected_lane_sums(client, lanes: int, key: str) -> np.ndarray:
    """The oracle's truth, reconstructed parent-side: the key→lane
    hashing is deterministic per (seed, key), so a shadow directory
    re-derives exactly the lane placement the engine child's
    directory handed the client's sessions."""
    from ..ingress.sessions import SessionDirectory
    d = SessionDirectory(lanes, seed=0)
    h = d.connect_bulk(client.n_sessions, key=f"wire/{key}",
                       tenants=client.tenants)
    lane = d.lane[h]
    out = np.zeros(lanes, np.int64)
    for i in range(len(client.op_state)):  # control-plane scale
        out[lane[client.op_sess[i]]] += int(client.op_pay[i])
    return out


def _teardown(router, ctl, procs: dict, clients: dict,
              node_of: dict) -> None:
    from ..transport.rpc import reliable_node_call
    for c in clients.values():
        try:
            c.close()
        except OSError:
            pass
    for eid, node in node_of.items():
        try:
            reliable_node_call(router, node, "host_stop", {},
                               timeout=2.0)
        except (RuntimeError, TimeoutError):
            pass
    try:
        reliable_node_call(router, "gf1", "geo_stop", {}, timeout=2.0)
    except (RuntimeError, TimeoutError):
        pass
    for p in procs.values():
        try:
            p.terminate()
            p.wait(timeout=10.0)
        except (subprocess.TimeoutExpired, OSError):
            p.kill()
    ctl.stop()
    router.stop()


def _host_envelope() -> dict:
    from ..utils import host_envelope
    return host_envelope()


def geo_main(seeds, **kw) -> list:
    """tools/soak.py --geo: one run per seed, JSON tail per run."""
    rows = []
    for seed in seeds:
        res = run_geo_soak(int(seed), **kw)
        print(f"geo seed={seed}: "
              f"recovery={res['geo_failover_recovery_s']:.2f}s "
              f"false_migrations={res['geo_false_migrations']} "
              f"lost_acked={res['geo_lost_acked']} "
              f"migrations={res['migrations']}")
        print(json.dumps(res))
        rows.append(res)
    return rows


def _child_main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser(prog="ra_tpu.placement.geo")
    ap.add_argument("--child", required=True,
                    choices=("engine", "control"))
    ap.add_argument("--ready", required=True)
    ap.add_argument("--parent-host", required=True)
    ap.add_argument("--parent-port", type=int, required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-run-s", type=float, default=300.0)
    ap.add_argument("--eid", default="")
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--lanes", type=int, default=16)
    ap.add_argument("--sessions", type=int, default=24)
    ap.add_argument("--wal-shards", type=int, default=2)
    args = ap.parse_args(argv)
    if args.child == "engine":
        _engine_child(args)
    else:
        _control_child(args)


if __name__ == "__main__":
    _child_main()
