"""The engine supervisor tier — the classic control plane watching
lane engines (ISSUE 17 tentpole part 2).

An :class:`EngineSupervisor` heartbeats every registered lane engine
and escalates silence through the aten-style verdict ladder the TCP
detector uses (up → suspect → down), with one addition the transport
detector also gained in this PR: a **hysteresis window**.  A down
verdict requires the engine to be BOTH silent beyond ``down_after``
AND continuously suspect for ``hysteresis`` seconds — so a latency
spike (a slow fsync, a CD-Raft cross-domain delay injected by the
transport FaultPlan's delay matrix) rides out the window and recovers,
while a kill-9 stays silent and escalates.  test_placement.py pins the
distinction: a pure-delay FaultPlan never triggers a migration.

On confirmed death the supervisor COMMITS the re-placement through the
placement table (:mod:`ra_tpu.placement.table`) — never a local
mutation: the table's generation gate makes redelivered/retried
migrations idempotent, and a supervisor that dies mid-failover leaves
a table any successor can read and finish from.  Every commit loop in
this module is deadline-bounded and emits ``placement.giveup`` on
exhaustion — the contract lint rule RA16 enforces over this whole
package: no silent infinite retry in the control plane.

The supervisor is **tick-driven** (call :meth:`tick` from the serving
loop): deterministic under test, no thread of its own, and the soak
drives it at whatever cadence the scenario needs.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Optional

from ..blackbox import record
from ..metrics import PLACEMENT_FIELDS
from .table import owned_ranges

_INF = float("inf")


class PlacementError(RuntimeError):
    """A bounded placement commit loop gave up (deadline exhausted)."""


class EngineSupervisor:
    """Monitors lane engines; commits re-placements on confirmed death.

    ``table_sid``/``router`` name any member of the placement-table
    cluster (leader redirects are the commit path's business).
    ``probes`` maps engine id → zero-arg callable returning truthy
    while the engine is alive — the in-process heartbeat.  Across
    hosts the callable wraps a reliable-RPC ping and returns **None**
    ("asynchronous: the completion arrives via :meth:`probe_reply`"),
    so a slow round trip never blocks the tick and RTT reads as age
    (:mod:`ra_tpu.placement.fabric`).  ``fault_plan`` (a
    transport FaultPlan) is consulted per heartbeat on the ``ping``
    frame class honoring BOTH drop and delay: a dropped probe is
    silence, a delayed probe arrives late (``delay_s`` added to the
    observed age) — which is exactly what lets the hysteresis pin
    distinguish delay from death."""

    def __init__(self, table_sid, router, *,
                 probes: Optional[dict] = None,
                 suspect_after: float = 1.0, down_after: float = 2.0,
                 hysteresis: float = 0.5,
                 fault_plan=None,
                 on_migrate: Optional[Callable] = None,
                 commit_timeout: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.table_sid = table_sid
        self.router = router
        self.suspect_after = float(suspect_after)
        self.down_after = float(down_after)
        self.hysteresis = float(hysteresis)
        self.fault_plan = fault_plan
        self.on_migrate = on_migrate
        self.commit_timeout = float(commit_timeout)
        self._clock = clock
        self.counters = {f: 0 for f in PLACEMENT_FIELDS}
        self._probe: dict[str, Callable] = {}
        self._gen: dict[str, int] = {}         # watched slot generation
        self._last_heard: dict[str, float] = {}
        self._arrive: dict[str, float] = {}    # in-flight probe reply
        self._verdict: dict[str, str] = {}
        self._suspect_since: dict[str, float] = {}
        self._migrated: set = set()
        for eid, probe in (probes or {}).items():
            self.watch(eid, probe)

    # -- registration --------------------------------------------------

    def watch(self, eid: str, probe: Callable[[], Any],
              generation: int = 1) -> None:
        """(Re)register an engine slot.  Re-watching with a HIGHER
        generation is a re-provision: the old incumbent's in-flight
        probe replies become stale (see :meth:`probe_reply`) and the
        detector state resets for the new incumbent."""
        now = self._clock()
        self._probe[eid] = probe
        self._gen[eid] = int(generation)
        self._last_heard[eid] = now
        self._arrive[eid] = _INF
        self._verdict[eid] = "up"
        self._suspect_since.pop(eid, None)

    def generation(self, eid: str) -> int:
        """The watched slot's current generation — async probes capture
        this when the probe is issued and hand it back to
        :meth:`probe_reply` with the reply."""
        return self._gen.get(eid, 0)

    def verdict(self, eid: str) -> str:
        return self._verdict.get(eid, "unknown")

    def last_heard_age(self, eid: str,
                       now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        return now - self._last_heard.get(eid, now)

    # -- asynchronous probe completion ---------------------------------

    def probe_reply(self, eid: str, *, heard_at: Optional[float] = None,
                    generation: Optional[int] = None) -> bool:
        """Complete a probe whose reply arrived OUTSIDE the tick (the
        cross-host path: a reliable-RPC ping finishing on its own
        thread).  ``heard_at`` is the time the probe was ISSUED — a
        completed round trip proves the engine was alive at send time,
        so cross-domain RTT reads as age and the hysteresis window
        absorbs it (CD-Raft: delay is not death).

        ``generation`` is the slot generation captured when the probe
        was issued.  A reply from a SUPERSEDED generation — the slot
        was re-provisioned while the probe was in flight — is
        discarded: counting it would reset the NEW incumbent's suspect
        streak with evidence about a different engine (the ISSUE 19
        bug-hardening pin).  Returns True when the reply counted."""
        if eid not in self._probe:
            return False
        if generation is not None and generation != self._gen.get(eid):
            self.counters["stale_probe_drops"] += 1
            record("placement.stale_probe", peer=eid,
                   reply_generation=generation,
                   generation=self._gen.get(eid, 0))
            return False
        heard = self._clock() if heard_at is None else heard_at
        if heard > self._last_heard.get(eid, -_INF):
            self._last_heard[eid] = heard
            self.counters["heartbeats"] += 1
        return True

    # -- the detector tick ---------------------------------------------

    def tick(self, now: Optional[float] = None) -> list:
        """One heartbeat round over every watched engine; returns the
        engine ids newly confirmed DOWN this tick (the caller decides
        whether to failover them — the nemesis heal path forces it)."""
        now = self._clock() if now is None else now
        newly_down: list = []
        for eid, probe in self._probe.items():
            if self._verdict[eid] == "down":
                continue
            # a previous probe's delayed reply landing now counts as
            # heard AT ITS ARRIVAL TIME (not probe time): delay shows
            # up as age, which is what the hysteresis must absorb
            if self._arrive[eid] <= now:
                self._last_heard[eid] = self._arrive[eid]
                self._arrive[eid] = _INF
                self.counters["heartbeats"] += 1
            res: Any = False
            try:
                res = probe()
            except Exception:
                res = False
            if res is None:
                # asynchronous probe: it issued (or has in flight) a
                # real RPC whose completion lands via probe_reply() —
                # the silence ladder below still judges what has
                # actually been heard
                alive = False
            else:
                alive = bool(res)
            if alive:
                delay_s = 0.0
                deliver = True
                if self.fault_plan is not None:
                    d = self.fault_plan.decide(eid, "ping", "send")
                    deliver = d.action != "drop"
                    delay_s = d.delay_s
                if deliver:
                    if delay_s <= 0.0:
                        self._last_heard[eid] = now
                        self.counters["heartbeats"] += 1
                    else:
                        self._arrive[eid] = min(self._arrive[eid],
                                                now + delay_s)
            silent = now - self._last_heard[eid]
            verdict = self._verdict[eid]
            if silent <= self.suspect_after:
                if verdict == "suspect":
                    self._verdict[eid] = "up"
                    self._suspect_since.pop(eid, None)
                    self.counters["recoveries"] += 1
                continue
            if verdict == "up":
                self._verdict[eid] = "suspect"
                self._suspect_since[eid] = now
                self.counters["suspects"] += 1
                record("detector.suspect", peer=eid, age=silent)
                continue
            if silent > self.down_after and \
                    now - self._suspect_since.get(eid, now) >= \
                    self.hysteresis:
                self._verdict[eid] = "down"
                self.counters["downs"] += 1
                record("detector.down", peer=eid, age=silent)
                newly_down.append(eid)
        return newly_down

    # -- re-placement --------------------------------------------------

    def table_state(self) -> dict:
        """A committed read of the placement table."""
        from ..api import consistent_query
        res = self._commit(lambda: consistent_query(
            self.table_sid, lambda s: s, router=self.router,
            timeout=self.commit_timeout), what="table_read")
        return res.reply

    def failover(self, victim: str, survivor: str,
                 trace_ctx: Optional[str] = None) -> list:
        """Commit the victim's death + one migrate per owned range,
        all through the table (each command generation-gated, each
        commit loop deadline-bounded).  Returns the committed
        ``(rid, survivor, new_generation)`` placements; invokes
        ``on_migrate(victim, survivor, placements, trace_ctx)`` so the
        host tier performs the actual adoption + re-home."""
        from ..api import process_command
        state = self.table_state()
        eng = state["engines"].get(victim)
        if eng is not None and eng["status"] != "down":
            self._commit(lambda: process_command(
                self.table_sid,
                ("engine_down", victim, eng["generation"]),
                self.router, timeout=self.commit_timeout,
                trace_ctx=trace_ctx), what="engine_down")
        placements: list = []
        for rid, ent in owned_ranges(state, victim):
            new_gen = ent["generation"] + 1
            res = self._commit(lambda: process_command(
                self.table_sid,
                ("migrate", rid, victim, survivor, new_gen),
                self.router, timeout=self.commit_timeout,
                trace_ctx=trace_ctx), what=f"migrate/{rid}")
            _tag, _rid, home, gen = res.reply
            record("placement.migrate", trace=trace_ctx, rid=rid,
                   victim=victim, survivor=home, generation=gen)
            self.counters["migrations"] += 1
            placements.append((rid, home, gen))
        self._migrated.add(victim)
        if self.on_migrate is not None and placements:
            self.on_migrate(victim, survivor, placements, trace_ctx)
        return placements

    def _commit(self, attempt: Callable[[], Any], *,
                what: str) -> Any:
        """The ONE retry shape this package allows (rule RA16): a
        deadline-bounded loop that emits a registered give-up event
        when exhausted."""
        deadline = self._clock() + self.commit_timeout * 3
        attempts = 0
        last: Any = None
        while self._clock() < deadline:
            attempts += 1
            try:
                res = attempt()
            except (TimeoutError, RuntimeError) as exc:
                last = exc
                self.counters["migrate_retries"] += 1
                continue
            from ..core.types import ErrorResult
            if isinstance(res, ErrorResult):
                last = res
                self.counters["migrate_retries"] += 1
                continue
            return res
        self.counters["giveups"] += 1
        record("placement.giveup", what=what, attempts=attempts)
        raise PlacementError(
            f"placement commit {what} gave up after {attempts} "
            f"attempts: {last!r}")
