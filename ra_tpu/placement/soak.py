"""The placement-failover soak (ISSUE 17 acceptance).

One run = :func:`run_failover_soak`: two lane-engine hosts (each a
durable engine + ingress plane + wire listener) serve live loopback
wire traffic under a classic 3-member control cluster running the
replicated PlacementTable; an :class:`~ra_tpu.placement.supervisor
.EngineSupervisor` heartbeats both.  Mid-traffic the nemesis kill-9's
one host (WAL shards die abruptly — queued-but-unfsynced writes lost),
the detector escalates up → suspect → down through its hysteresis
window, the supervisor COMMITS the re-placement through the table
(generation-gated), the survivor adopts the victim's durable directory
through standard engine recovery (checkpoint + RTB2 WAL merge + replay
at the fsynced watermark), and every victim session re-homes onto the
adopted listener — epoch bumped, old dedup slots claimed, committed
watermarks re-seeded, unacked ops replayed at-least-once.

The run closes on the exactly-once oracle over the UNION of both
engines' machine state: every op's delta applied exactly once
somewhere, zero acked-but-lost, zero double-applied.  The tail stamps
``failover_recovery_s`` (kill → first commit on the new home) and
``failover_lost_acked`` (must be 0) for tools/bench_diff.py.

``tools/soak.py --failover SEED [SEED...]`` drives it standalone;
tests/test_placement.py runs one CPU-scaled seed in tier 1.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

import numpy as np

from ..blackbox import record
from ..trace import new_trace_ctx
from ..wire.dedup import DedupCounterMachine
from .host import LaneEngineHost
from .supervisor import EngineSupervisor
from .table import placement_spec


def run_failover_soak(seed: int, *, conns: int = 16,
                      sessions_per_conn: int = 2, lanes: int = 32,
                      waves: int = 8, wave_ops: int = 1200,
                      kill_wave: int = 3, wal_shards: int = 2,
                      data_dir: Optional[str] = None,
                      disk_faults: bool = False,
                      suspect_after: float = 0.05,
                      down_after: float = 0.12,
                      hysteresis: float = 0.05,
                      fault_plan=None,
                      recovery_bar: Optional[float] = None) -> dict:
    """One failover run; returns a bench_diff-comparable tail row.
    See the module docstring for the scenario."""
    from ..api import process_command
    from ..core.types import ErrorResult, ServerId
    from ..node import LocalRouter, RaNode
    from ..wire.client import LoopbackFleet
    rng = np.random.default_rng(seed)
    spc = int(sessions_per_conn)
    sessions = conns * spc
    slots = 4 * max(1, sessions // lanes) + 64
    factory = lambda: DedupCounterMachine(slots=slots)  # noqa: E731
    tmp = None
    if data_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="failover-soak-")
        data_dir = tmp.name
    dirs = {"engA": os.path.join(data_dir, "engA"),
            "engB": os.path.join(data_dir, "engB")}
    disk_plan = None
    if disk_faults:
        from ..log import faults
        disk_plan = faults.DiskFaultPlan(
            seed=seed, by_class={"wal": faults.DiskFaultSpec(
                fsync_eio=0.05, short_write=0.02, limit=4)})
    router = LocalRouter()
    nodes = [RaNode(f"pn{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"pt{i}", f"pn{i}") for i in (1, 2, 3)]
    hosts: dict = {}
    fleets: dict = {}
    try:
        # -- control plane: the replicated placement table -----------
        from ..api import start_cluster
        start_cluster("placement", placement_spec(), sids,
                      router=router)
        # -- data plane: two lane-engine hosts + their fleets --------
        for eid in ("engA", "engB"):
            hosts[eid] = LaneEngineHost(
                eid, dirs[eid], machine_factory=factory, lanes=lanes,
                wal_shards=wal_shards, max_conns=conns + 8)
            fleets[eid] = LoopbackFleet(
                hosts[eid].listener, conns, sessions_per_conn=spc,
                key=f"fl/{eid}", tenants=4, seed=seed,
                max_ops=waves * wave_ops + wave_ops + 1024)
            assert int(fleets[eid].slots.max()) < slots, \
                "dedup slot overflow"
        sup = EngineSupervisor(
            sids[0], router,
            probes={eid: hosts[eid].alive for eid in hosts},
            suspect_after=suspect_after, down_after=down_after,
            hysteresis=hysteresis, fault_plan=fault_plan)
        sup.on_migrate = _adopt_and_rehome(hosts, fleets, dirs, sup)
        for cmd in (("register_engine", "engA"),
                    ("register_engine", "engB"),
                    ("assign", "engA/lanes", "engA", 0, lanes),
                    ("assign", "engB/lanes", "engB", 0, lanes)):
            res = sup._commit(lambda c=cmd: process_command(
                sids[0], c, router, timeout=10.0), what="setup")
            assert not isinstance(res, ErrorResult)
        nem = _nemesis(router, nodes, seed)

        def _cycle() -> None:
            # send everything first, THEN pump every host (an adopted
            # stack is pumped by its survivor), THEN harvest credits
            for eid in ("engA", "engB"):
                fleets[eid].send_queued()
            for eid in ("engA", "engB"):
                hosts[eid].cycle()
            for eid in ("engA", "engB"):
                fleets[eid].collect()

        # warm the fused executables outside the measured window
        for eid in ("engA", "engB"):
            fleets[eid].new_ops(np.arange(sessions) % sessions,
                                np.zeros(sessions, np.int32))
        _cycle()
        for eid in ("engA", "engB"):
            hosts[eid].settle()
        _cycle()
        if disk_plan is not None:
            nem.run([("disk_faults", disk_plan)])

        victim, survivor = "engA", "engB"
        ctx: Optional[str] = None
        t_kill = recovery_s = -1.0
        killed = migrated = False
        t0 = time.perf_counter()
        for w in range(waves):
            for eid in ("engA", "engB"):
                if eid == victim and killed and not migrated:
                    continue  # old home dead, new home not bound yet
                sess = rng.integers(0, sessions, wave_ops)
                fleets[eid].new_ops(sess, rng.integers(1, 8, wave_ops)
                                    .astype(np.int32))
            for _ in range(3):
                _cycle()
            sup.tick()
            if w and w != kill_wave:
                # wave-boundary settle: drive the async committed-
                # watermark readbacks so ACK watermarks stay live (the
                # kill wave skips it — the kill must land on a rich
                # in-flight window)
                for eid in ("engA", "engB"):
                    hosts[eid].settle(timeout=60.0)
                _cycle()
            if w == kill_wave and not killed:
                # mid-traffic kill-9: unfsynced WAL tail is lost, the
                # never-acked loss the fsynced-watermark gate makes
                # Raft-legal
                nem.run([("engine_kill", hosts[victim])])
                t_kill = time.perf_counter()
                killed = True
                # detection: heartbeats go silent, the verdict ladder
                # climbs through the hysteresis window
                det_deadline = time.monotonic() + 30.0
                while sup.verdict(victim) != "down":
                    sup.tick()
                    _cycle()
                    time.sleep(0.005)
                    if time.monotonic() > det_deadline:
                        raise TimeoutError("detector never confirmed "
                                           "the kill-9'd engine down")
                ctx = new_trace_ctx("failover")
                # the client-visible refusal: the old home is gone,
                # in-flight commands park until the table re-homes them
                record("placement.refuse", trace=ctx, engine=victim,
                       unplaced=int(fleets[victim].unplaced_count()))
                nem.run([("placement_failover", sup, victim, survivor,
                          ctx)])
                migrated = True
                if disk_plan is not None:
                    nem.run([("disk_heal",)])
                # first commit on the new home closes the recovery
                # window (acks fan out only on commit + fsync; the
                # settle drives the async committed-watermark readback
                # so the first ack is observed promptly)
                wm = int(fleets[victim].watermark.sum())
                rec_deadline = time.monotonic() + 60.0
                while int(fleets[victim].watermark.sum()) <= wm:
                    _cycle()
                    hosts[survivor].settle(timeout=60.0)
                    _cycle()
                    if time.monotonic() > rec_deadline:
                        raise TimeoutError("no commit on the new home")
                recovery_s = time.perf_counter() - t_kill
        assert killed and migrated, "kill wave never ran"
        # drain: at-least-once means every op retries until placed
        deadline = time.monotonic() + 120.0
        while any(fleets[eid].unplaced_count() for eid in fleets):
            _cycle()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"failover drain: "
                    f"{[fleets[e].unplaced_count() for e in fleets]} "
                    "ops unplaced")
        for eid in ("engA", "engB"):
            hosts[eid].settle(timeout=120.0)
        for _ in range(3):
            _cycle()
        elapsed = time.perf_counter() - t0
        # -- the exactly-once oracle over the UNION of both engines --
        lane_ids = np.arange(lanes)
        got = {
            victim: np.asarray(hosts[survivor].adopted_engine(victim)
                               .consistent_read(lane_ids)["value"])
            .astype(np.int64),
            survivor: np.asarray(hosts[survivor].engine
                                 .consistent_read(lane_ids)["value"])
            .astype(np.int64),
        }
        lost = double = 0
        for eid in ("engA", "engB"):
            expected = fleets[eid].expected_lane_sums(lanes)
            lost += int(np.maximum(expected - got[eid], 0).sum())
            double += int(np.maximum(got[eid] - expected, 0).sum())
        row = {
            "value": recovery_s,
            "failover_recovery_s": recovery_s,
            "failover_lost_acked": lost,
            "failover_double_applied": double,
            "seed": seed, "conns": 2 * conns,
            "sessions": 2 * sessions, "lanes": lanes,
            "ops": int(sum(fleets[e].n_ops for e in fleets)),
            "rehomed_sessions": int(sup.counters["rehomed_sessions"]),
            "migrations": int(sup.counters["migrations"]),
            "detector": {k: int(sup.counters[k]) for k in
                         ("heartbeats", "suspects", "downs",
                          "recoveries")},
            "elapsed_s": elapsed, "wal_shards": wal_shards,
            "disk_faults_injected":
                dict(disk_plan.counters) if disk_plan else {},
            "host": _host_envelope(),
        }
        for eid in ("engA", "engB"):
            expected = fleets[eid].expected_lane_sums(lanes)
            np.testing.assert_array_equal(got[eid], expected)
            fl = fleets[eid]
            ranked = fl.op_rank[:fl.n_ops] >= 0
            acked = fl.acked_mask()
            assert acked[ranked].all(), \
                f"{eid}: {int((~acked[ranked]).sum())} ranked ops " \
                "never acked"
        assert sup.counters["downs"] == 1
        assert sup.counters["migrations"] >= 1
        if recovery_bar is not None:
            assert recovery_s <= recovery_bar, \
                f"recovery {recovery_s:.3f}s > bar {recovery_bar}s"
        return row
    finally:
        for h in hosts.values():
            h.close()
        for n in nodes:
            n.stop()
        if disk_plan is not None:
            from ..log import faults
            faults.clear_plan()
        if tmp is not None:
            tmp.cleanup()


def _adopt_and_rehome(hosts: dict, fleets: dict, dirs: dict, sup):
    """The supervisor's on_migrate hook: survivor adopts the victim's
    durable directory, then the victim's fleet re-homes onto the
    adopted listener (old slots claimed, epochs bumped, unacked ops
    replayed)."""
    def hook(victim: str, survivor: str, placements: list,
             trace_ctx) -> None:
        lst = hosts[survivor].adopt(victim, dirs[victim],
                                    trace_ctx=trace_ctx)
        fleets[victim].rehome(lst, trace_ctx=trace_ctx)
        sup.counters["adopts"] += 1
        sup.counters["rehomed_sessions"] += fleets[victim].n_sessions
    return hook


def _nemesis(router, nodes, seed: int):
    """The scripted fault interpreter when the test harness is on the
    path (repo checkouts), else a minimal stand-in with the same two
    placement ops — the soak runs identically either way."""
    try:
        from tests.nemesis import Nemesis
        return Nemesis(router, nodes, seed=seed)
    except ImportError:
        class _Mini:
            def run(self, schedule):
                for step in schedule:
                    op, args = step[0], step[1:]
                    record("nemesis.op", op=op,
                           args=repr(args)[:120] if args else "")
                    getattr(self, f"_op_{op}")(*args)

            def _op_engine_kill(self, host):
                host.kill9()

            def _op_placement_failover(self, sup, victim, survivor,
                                       trace_ctx=None):
                sup.failover(victim, survivor, trace_ctx=trace_ctx)

            def _op_disk_faults(self, plan):
                from ..log import faults
                faults.install_plan(plan)

            def _op_disk_heal(self):
                from ..log import faults
                faults.clear_plan()
        return _Mini()


def _host_envelope() -> dict:
    from ..utils import host_envelope
    return host_envelope()


def failover_main(seeds, **kw) -> list:
    """tools/soak.py --failover: one run per seed, JSON tail per run."""
    rows = []
    for seed in seeds:
        res = run_failover_soak(int(seed), **kw)
        print(f"failover seed={seed}: "
              f"recovery={res['failover_recovery_s'] * 1e3:.1f}ms "
              f"lost_acked={res['failover_lost_acked']} "
              f"migrations={res['migrations']}")
        print(json.dumps(res))
        rows.append(res)
    return rows
