"""The replicated PlacementTable machine — lane-range → engine
assignments on a classic control cluster (ISSUE 17, ROADMAP item 2's
hierarchical-consensus shape: classic clusters as control plane over
lane engines as data plane).

The table is the ONLY authority on who serves which lane range.  It is
mutated exclusively by committed commands, so every mutation inherits
the classic plane's guarantees: a leader kill-9 mid-migration leaves
the table either pre- or post-move (a migration is one command — there
is no half-moved state to observe), and a re-delivered migration is a
no-op because each assignment carries a **generation** number that
only ever moves forward (the cross-plane twin of the session epoch).

Everything downstream — the SessionDirectory's lane placements, the
wire listener's session bindings, a client's notion of "home" — is a
CACHE of this table (:class:`PlacementCache`), valid only at the
generation it was read at; docs/PLACEMENT.md states the invalidation
rules.

State shape (plain dicts/tuples: picklable, snapshot-friendly,
deepcopy-cheap at control-plane scale — tens of ranges, not millions)::

    {"engines": {eid: {"status": "up"|"down", "generation": int}},
     "ranges":  {rid: {"engine": eid, "generation": int,
                       "lo": int, "hi": int}},
     "rev": int}

``rev`` bumps on every effective mutation — the cheap "did anything
move" probe caches poll.
"""
from __future__ import annotations

from typing import Any

from ..core.machine import ApplyMeta, Machine
from ..machines import machine_spec, register_machine

MACHINE_NAME = "placement_table"


def _copy(state: dict) -> dict:
    """Two-level copy-on-write: apply never mutates the input state in
    place (queries may hold references to old snapshots)."""
    return {
        "engines": {k: dict(v) for k, v in state["engines"].items()},
        "ranges": {k: dict(v) for k, v in state["ranges"].items()},
        "rev": state["rev"],
    }


class PlacementTableMachine(Machine):
    """Commands (tuples, picklable — they travel the control plane):

    * ``("register_engine", eid)`` — add an engine as up at generation
      1; idempotent (re-registration of a known engine is a no-op).
    * ``("assign", rid, eid, lo, hi)`` — create the lane range ``[lo,
      hi)`` on ``eid`` at generation 1.  Idempotent when identical;
      re-assigning an EXISTING range to a different engine is refused
      (that is what ``migrate`` is for — assignment churn must carry a
      generation).
    * ``("engine_down", eid, expect_gen)`` — mark an engine down, gated
      on its current generation (a stale supervisor's verdict against
      an engine that already re-registered is a no-op).
    * ``("migrate", rid, from_eid, to_eid, new_gen)`` — move a range,
      applied ONLY when the range is still on ``from_eid`` at a
      generation below ``new_gen``.  The reply always carries the
      post-apply assignment, so a re-delivered migrate (cumulative-ack
      redelivery, a retrying supervisor) observes the move it already
      made instead of applying it twice.

    Every reply is ``("placed", rid_or_eid, engine, generation)`` /
    ``("engines", ...)`` style plain data — safe to ship over any
    transport.
    """

    def init(self, config: dict) -> dict:
        return {"engines": {}, "ranges": {}, "rev": 0}

    def apply(self, meta: ApplyMeta, command: Any, state: dict):
        op = command[0]
        if op == "register_engine":
            _, eid = command
            if eid in state["engines"]:
                ent = state["engines"][eid]
                return state, ("engine", eid, ent["status"],
                               ent["generation"])
            state = _copy(state)
            state["engines"][eid] = {"status": "up", "generation": 1}
            state["rev"] += 1
            return state, ("engine", eid, "up", 1)
        if op == "assign":
            _, rid, eid, lo, hi = command
            cur = state["ranges"].get(rid)
            if cur is not None:
                # identical re-assign is a no-op; anything else must
                # be a migrate (generation-gated) — refuse with the
                # current placement so the caller can see why
                ok = cur["engine"] == eid and cur["lo"] == lo and \
                    cur["hi"] == hi
                return state, (("placed" if ok else "refused"), rid,
                               cur["engine"], cur["generation"])
            state = _copy(state)
            state["ranges"][rid] = {"engine": eid, "generation": 1,
                                    "lo": int(lo), "hi": int(hi)}
            state["rev"] += 1
            return state, ("placed", rid, eid, 1)
        if op == "engine_down":
            _, eid, expect_gen = command
            ent = state["engines"].get(eid)
            if ent is None:
                return state, ("refused", eid, None, 0)
            if ent["status"] == "down" or \
                    ent["generation"] != expect_gen:
                return state, ("engine", eid, ent["status"],
                               ent["generation"])
            state = _copy(state)
            ent = state["engines"][eid]
            ent["status"] = "down"
            state["rev"] += 1
            return state, ("engine", eid, "down", ent["generation"])
        if op == "migrate":
            _, rid, from_eid, to_eid, new_gen = command
            cur = state["ranges"].get(rid)
            if cur is None:
                return state, ("refused", rid, None, 0)
            if cur["engine"] == from_eid and \
                    cur["generation"] < new_gen:
                state = _copy(state)
                ent = state["ranges"][rid]
                ent["engine"] = to_eid
                ent["generation"] = int(new_gen)
                state["rev"] += 1
                cur = ent
            # already-moved (or stale) migrate: reply the placement
            # that stands — the redelivery-idempotence contract
            return state, ("placed", rid, cur["engine"],
                           cur["generation"])
        raise ValueError(f"placement_table: unknown command {op!r}")

    def overview(self, state: dict) -> dict:
        return {"rev": state["rev"],
                "engines": len(state["engines"]),
                "ranges": len(state["ranges"])}


def placement_spec() -> tuple:
    """The picklable machine spec cross-node starts ship."""
    return machine_spec(MACHINE_NAME)


def owned_ranges(state: dict, eid: str) -> list:
    """[(rid, entry)] of every range currently homed on ``eid``."""
    return sorted((rid, dict(ent))
                  for rid, ent in state["ranges"].items()
                  if ent["engine"] == eid)


class PlacementCache:
    """A client-side cache of the replicated table — the role the
    SessionDirectory (and every other placement consumer) plays after
    ISSUE 17: placements are only ever LEARNED from committed table
    state, never invented locally, and a cached entry is valid exactly
    while its generation matches the table's.

    ``refresh(state)`` swallows a table snapshot (from consistent/
    local query); ``lookup``/``lane_owner`` answer from the cache;
    ``stale_against(state)`` reports whether a newer revision exists
    (the cheap poll the re-home path uses)."""

    def __init__(self) -> None:
        self.rev = -1
        self._ranges: dict = {}

    def refresh(self, state: dict) -> bool:
        """Adopt a table snapshot; returns True when it superseded the
        cached revision (monotone: an older snapshot never rolls the
        cache back — stale reads from a lagging follower are harmless)."""
        if state["rev"] <= self.rev:
            return False
        self.rev = state["rev"]
        self._ranges = {rid: dict(ent)
                        for rid, ent in state["ranges"].items()}
        return True

    def invalidate(self) -> None:
        self.rev = -1
        self._ranges = {}

    def lookup(self, rid: str):
        """(engine, generation) or None."""
        ent = self._ranges.get(rid)
        return None if ent is None else (ent["engine"],
                                         ent["generation"])

    def lane_owner(self, lane: int):
        """The engine id homing ``lane``, or None when no cached range
        covers it."""
        for ent in self._ranges.values():
            if ent["lo"] <= lane < ent["hi"]:
                return ent["engine"]
        return None

    def ranges(self) -> dict:
        """The cached ``rid -> entry`` view (entries are copies — a
        serving listener derives its lane masks from these, ISSUE 19)."""
        return {rid: dict(ent) for rid, ent in self._ranges.items()}

    def stale_against(self, state: dict) -> bool:
        return state["rev"] > self.rev


register_machine(MACHINE_NAME, lambda **kw: PlacementTableMachine())
