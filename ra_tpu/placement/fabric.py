"""The cross-host placement fabric (ISSUE 19): the supervisor's
probe/commit path and the host tier's adoption verbs running over the
reliable control-plane RPC layer (:mod:`ra_tpu.transport.rpc`) between
real processes.

Two halves:

* :class:`RpcEngineProbe` — the supervisor side.  A zero-arg probe
  callable (the :meth:`EngineSupervisor.watch` contract) that issues a
  ``host_status`` reliable RPC on its own daemon thread and returns
  **None** ("asynchronous") immediately, so a cross-domain round trip
  never blocks the detector tick.  A completed round trip lands via
  :meth:`EngineSupervisor.probe_reply` stamped with the probe's ISSUE
  time — cross-domain RTT reads as age, which the hysteresis window
  absorbs (CD-Raft: delay is not death) — and with the slot generation
  captured at issue, so a reply straggling in after the slot was
  re-provisioned is discarded instead of resetting the new incumbent's
  suspect streak.

* :class:`HostAgent` — the engine-host side.  Registers the host
  verbs (``host_status``/``host_adopt``/``host_lane_sums``/
  ``host_address``/``host_stop``) on a :class:`~ra_tpu.node.RaNode`'s
  pluggable ``control_ops``, so they ride the SAME reliable-RPC
  control plane as the builtin lifecycle ops: retry/backoff/deadline
  on the caller, receiver-side request dedup — a duplicated or
  reordered ``host_adopt`` adopts once (and the placement table's
  generation gate makes the matching ``migrate`` commit idempotent
  end to end).  Control ops execute on the node's control threads;
  verbs that mutate the serving stack are bridged onto the host's
  main serving loop through a queue + event handshake
  (:meth:`HostAgent.pump`), because an engine/plane/listener is
  single-threaded by construction.

Every RPC call site in this module carries an explicit ``timeout=``
— the deadline discipline rule RA16 enforces across this package.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from ..blackbox import record
from ..transport.rpc import reliable_node_call
from .table import PlacementCache

__all__ = ["RpcEngineProbe", "HostAgent", "remote_adopt",
           "remote_lane_sums", "remote_rehome", "push_placement"]


class RpcEngineProbe:
    """An asynchronous cross-host heartbeat for one engine slot.

    Calling the instance (what :meth:`EngineSupervisor.tick` does)
    starts at most ONE in-flight ``host_status`` RPC — paced by
    ``min_interval`` — and returns ``None`` immediately; the reply
    completes via ``sup.probe_reply(eid, heard_at=<issue time>,
    generation=<captured at issue>)``.  :meth:`bind` attaches the
    supervisor after :meth:`~EngineSupervisor.watch` registered the
    slot (the probe needs the supervisor for the generation capture
    and the reply path)."""

    def __init__(self, router, node: str, eid: str, *,
                 timeout: float = 2.0, min_interval: float = 0.05,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.router = router
        self.node = node
        self.eid = eid
        self.timeout = float(timeout)
        self.min_interval = float(min_interval)
        self._clock = clock
        self.sup = None
        self._in_flight = False
        self._last_issue = -float("inf")
        self._lock = threading.Lock()
        self.replies = 0
        self.failures = 0

    def bind(self, sup) -> None:
        self.sup = sup

    def __call__(self) -> None:
        now = self._clock()
        with self._lock:
            if self._in_flight or \
                    now - self._last_issue < self.min_interval:
                return None
            self._in_flight = True
            self._last_issue = now
        gen = self.sup.generation(self.eid) if self.sup is not None \
            else None
        threading.Thread(target=self._probe_once, args=(now, gen),
                         daemon=True,
                         name=f"rpc-probe-{self.eid}").start()
        return None

    def _probe_once(self, issued_at: float, generation) -> None:
        try:
            res = reliable_node_call(self.router, self.node,
                                     "host_status", {"eid": self.eid},
                                     timeout=self.timeout)
            alive = bool(res.get("alive")) if isinstance(res, dict) \
                else False
            if alive and self.sup is not None:
                # heard AT ISSUE TIME: a completed round trip proves
                # the engine was alive when the probe left, so the
                # cross-domain RTT shows up as age — never as a fresher
                # heartbeat than the evidence supports
                self.sup.probe_reply(self.eid, heard_at=issued_at,
                                     generation=generation)
                self.replies += 1
        except (RuntimeError, TimeoutError):
            # unreachable/timed out/remote error: silence IS the
            # signal — the supervisor's verdict ladder judges it
            self.failures += 1
        finally:
            with self._lock:
                self._in_flight = False


class HostAgent:
    """Serves one :class:`~ra_tpu.placement.host.LaneEngineHost` over
    the node control plane.  Construct it in the host's process with
    the host and its RaNode; call :meth:`pump` from the host's serving
    loop every cycle (it executes the loop-bridged verbs)."""

    #: bound every loop-bridged verb waits for the serving loop
    BRIDGE_TIMEOUT_S = 60.0

    def __init__(self, host, node, *, generation: int = 1,
                 placement_rid: Optional[str] = None) -> None:
        self.host = host
        self.node = node
        self.generation = int(generation)
        self.stopped = threading.Event()
        self._actions: queue.Queue = queue.Queue()
        #: the serving-path placement view (ISSUE 19): the control
        #: plane PUSHES committed table state here (``host_placement``)
        #: and every listener this host serves derives its lane mask
        #: from it — revision-monotone, fail-open while empty
        self.cache = PlacementCache()
        self.placement_rid = placement_rid
        if placement_rid is not None:
            host.listener.bind_placement(self.cache, {host.engine_id},
                                         rids={placement_rid})
        node.control_ops.update({
            "host_status": self._op_status,
            "host_address": self._op_address,
            "host_adopt": self._op_adopt,
            "host_rehome": self._op_rehome,
            "host_placement": self._op_placement,
            "host_lane_sums": self._op_lane_sums,
            "host_stop": self._op_stop,
        })

    # -- the serving-loop bridge ---------------------------------------

    def pump(self) -> int:
        """Execute queued loop-bridged verbs (call from the serving
        loop).  Returns the number executed."""
        done = 0
        while True:
            try:
                fn, box, ev = self._actions.get_nowait()
            except queue.Empty:
                return done
            try:
                box["res"] = fn()
            except Exception as exc:  # noqa: BLE001 — travels to caller
                box["exc"] = exc
            ev.set()
            done += 1

    def _run_on_loop(self, fn: Callable[[], Any]) -> Any:
        box: dict = {}
        ev = threading.Event()
        self._actions.put((fn, box, ev))
        if not ev.wait(self.BRIDGE_TIMEOUT_S):
            raise TimeoutError("host serving loop did not pump the "
                               "bridged control verb within deadline")
        if "exc" in box:
            raise box["exc"]
        return box["res"]

    # -- control verbs (executed on node control threads) --------------

    def _op_status(self, args: dict) -> dict:
        # answered IMMEDIATELY (no loop bridge): alive is a plain bool
        # read, and the probe path must stay cheap and non-blocking
        return {"eid": self.host.engine_id,
                "alive": bool(self.host.alive()),
                "generation": self.generation}

    def _op_address(self, args: dict) -> dict:
        eid = args.get("engine", self.host.engine_id)
        if eid == self.host.engine_id:
            addr = self.host.listener.address
        else:
            addr = self.host.adopted_listener(eid).address
        return {"engine": eid,
                "address": list(addr) if addr is not None else None}

    def _op_adopt(self, args: dict) -> dict:
        victim = args["victim"]

        def do():
            lst = self.host.adopt(victim, args["victim_dir"],
                                  trace_ctx=args.get("trace_ctx"))
            rid = args.get("rid")
            if rid is not None:
                # the adopted range's post-migration home is THIS
                # host's engine id; while the pushed cache is still
                # empty/stale the mask fails open
                lst.bind_placement(self.cache, {self.host.engine_id},
                                   rids={rid})
            return lst.address
        addr = self._run_on_loop(do)
        record("placement.adopt_rpc", victim=victim,
               survivor=self.host.engine_id,
               address=str(addr) if addr else "loopback")
        return {"victim": victim, "survivor": self.host.engine_id,
                "address": list(addr) if addr is not None else None}

    def _op_rehome(self, args: dict) -> dict:
        """Pre-claim a re-homed wire client's session block on the
        ADOPTED listener (WireListener.claim_sessions): old dedup
        slots claimed verbatim, committed watermarks seeded at the
        client's acked counts.  Returns the recovered durable op-id
        watermarks the client re-bases against."""
        victim = args["victim"]

        def do():
            lst = self.host.adopted_listener(victim)
            dur = lst.claim_sessions(
                args["key"], int(args["n_sessions"]),
                slots=np.asarray(args["slots"], np.int64),
                committed=np.asarray(args["committed"], np.int64),
                tenants=int(args.get("tenants", 1)),
                trace_ctx=args.get("trace_ctx"))
            return dur.tolist()
        return {"victim": victim, "durable": self._run_on_loop(do)}

    def _op_placement(self, args: dict) -> dict:
        """Adopt a committed placement-table snapshot (the cache-
        invalidation-on-commit push): revision-monotone, so a stale
        push from a lagging control member is a no-op."""
        state = args["state"]

        def do():
            changed = self.cache.refresh(state)
            return {"rev": int(self.cache.rev),
                    "changed": bool(changed)}
        return self._run_on_loop(do)

    def _op_lane_sums(self, args: dict) -> dict:
        eid = args.get("engine", self.host.engine_id)

        def do():
            eng = self.host.engine if eid == self.host.engine_id \
                else self.host.adopted_engine(eid)
            lanes = np.arange(self.host.lanes)
            vals = np.asarray(eng.consistent_read(lanes)["value"])
            return vals.astype(np.int64).tolist()
        return {"engine": eid, "sums": self._run_on_loop(do)}

    def _op_stop(self, args: dict) -> str:
        self.stopped.set()
        return "stopping"


# -- supervisor-side helpers over the fabric ---------------------------


def remote_adopt(router, node: str, victim: str, victim_dir: str, *,
                 survivor: str, rid: Optional[str] = None,
                 timeout: float = 30.0,
                 trace_ctx: Optional[str] = None):
    """Commit an adoption on a remote survivor host; returns the
    adopted listener's ``(host, port)`` (or None for loopback).
    Rides reliable RPC end to end: a redelivered call re-adopts
    nothing (LaneEngineHost.adopt is idempotent per victim) and the
    receiver's request dedup absorbs duplicated attempts."""
    res = reliable_node_call(router, node, "host_adopt",
                             {"victim": victim,
                              "victim_dir": victim_dir,
                              "survivor": survivor, "rid": rid,
                              "trace_ctx": trace_ctx},
                             timeout=timeout, trace_ctx=trace_ctx)
    addr = res.get("address") if isinstance(res, dict) else None
    return tuple(addr) if addr is not None else None


def remote_rehome(router, node: str, victim: str, client, *,
                  timeout: float = 30.0,
                  trace_ctx: Optional[str] = None):
    """Pre-claim ``client``'s session block on the survivor's adopted
    listener, then return the durable op-id watermarks for
    :meth:`WireClient.rehome_to`."""
    res = reliable_node_call(
        router, node, "host_rehome",
        {"victim": victim, "key": client.key,
         "n_sessions": client.n_sessions,
         "slots": np.asarray(client.slots, np.int64).tolist(),
         "committed": client.watermark.tolist(),
         "tenants": client.tenants, "trace_ctx": trace_ctx},
        timeout=timeout, trace_ctx=trace_ctx)
    return np.asarray(res["durable"], np.int64)


def push_placement(router, node: str, state: dict, *,
                   timeout: float = 10.0) -> dict:
    """Push a committed table snapshot to one host's serving-path
    cache (the cache-invalidation-on-commit fan-out)."""
    return reliable_node_call(router, node, "host_placement",
                              {"state": state}, timeout=timeout)


def remote_lane_sums(router, node: str, engine: str, *,
                     timeout: float = 30.0) -> np.ndarray:
    """The exactly-once oracle's cross-process read: the per-lane
    machine sums an engine host serves for ``engine`` (its own id or
    an adopted victim's)."""
    res = reliable_node_call(router, node, "host_lane_sums",
                             {"engine": engine}, timeout=timeout)
    return np.asarray(res["sums"], np.int64)
