"""Placement failover: the classic control plane re-homing lane
engines (ISSUE 17).

Three tiers:

* :mod:`~ra_tpu.placement.table` — the replicated PlacementTable
  machine (lane-range → engine + generation), the single authority on
  placement; everything else is a cache of it.
* :mod:`~ra_tpu.placement.supervisor` — the detector + re-placement
  committer: heartbeats engines, escalates up→suspect→down with
  hysteresis, commits generation-gated migrations through the table.
* :mod:`~ra_tpu.placement.host` — one engine id's serving stack
  (durable engine + ingress plane + wire listener), with kill-9 and
  adoption (recover a victim's durable directory and serve it).

:mod:`~ra_tpu.placement.soak` wires all three under live wire traffic
with a mid-traffic kill-9 and checks the exactly-once oracle over the
union of both engines' state.  See docs/PLACEMENT.md.

ISSUE 19 stretches the same tiers across REAL processes:
:mod:`~ra_tpu.placement.fabric` carries the probe/adopt/re-home paths
over the reliable control-plane RPC tier (host_* verbs), and
:mod:`~ra_tpu.placement.geo` is the geo-distributed survival soak —
latency-domain matrices, SIGKILL of an engine host, and the
exactly-once oracle read back over RPC.
"""
from .table import (MACHINE_NAME, PlacementCache, PlacementTableMachine,
                    owned_ranges, placement_spec)
from .supervisor import EngineSupervisor, PlacementError
from .host import LaneEngineHost
from .fabric import (HostAgent, RpcEngineProbe, push_placement,
                     remote_adopt, remote_lane_sums, remote_rehome)
from .soak import run_failover_soak
from .geo import run_geo_soak

__all__ = [
    "MACHINE_NAME",
    "PlacementTableMachine",
    "PlacementCache",
    "placement_spec",
    "owned_ranges",
    "EngineSupervisor",
    "PlacementError",
    "LaneEngineHost",
    "HostAgent",
    "RpcEngineProbe",
    "remote_adopt",
    "remote_rehome",
    "remote_lane_sums",
    "push_placement",
    "run_failover_soak",
    "run_geo_soak",
]
