"""Aggregation window: per-session submissions → dense superstep blocks
(ISSUE 10).

The lane engine eats ``[K, lanes, cmds_per_step, C]`` superstep blocks
(one fused XLA dispatch, ISSUE 5); clients produce ragged per-session
dribbles.  This module is the node-wide batching tier between them —
the role ra_log_wal plays for the reference's thousands of co-hosted
clusters (PAPER.md §0), and the canonical batching-before-consensus
throughput lever (arxiv 1605.05619) — implemented as a per-lane staging
ring in host numpy:

* :meth:`CoalesceWindow.offer` scatters an admitted batch into per-lane
  ring positions — within-batch per-lane ranks come from one stable
  argsort, the scatter is one fancy-indexed store.  Rows that would
  overflow a lane's ring are NOT placed (returned to the caller's shed
  ladder: bounded queues shed, they never grow).
* :meth:`CoalesceWindow.pop_block` gathers the front ``K*cmds_per_step``
  window of every lane into the dense block shape in three vectorized
  ops (gather, reshape, transpose) and advances the ring heads.

Both are the **block-build hot path**: they run for every ingress wave
at up-to-millions-of-rows rates, so lint rule RA08 statically forbids
per-session Python loops and dict allocation inside them (an
``# ra08-ok: <why>`` line comment allowlists a deliberate exception).
Why host-side pre-jit at all (docs/INTERNALS.md §12): ragged fan-in is
data-dependent control flow — exactly what jit cannot trace — while a
dense block is what the device consumes without host syncs; the
boundary between "ragged world" and "dense world" therefore sits in
host numpy, once, per window.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np


def batch_rank(keys: np.ndarray) -> np.ndarray:
    """Within-batch occurrence rank per key (vectorized): for
    ``[7, 3, 7, 7, 3]`` returns ``[0, 0, 1, 2, 1]``.  One stable
    argsort + a run-length subtraction — the primitive both the
    coalescer scatter and the credit ladder's multiplicity accounting
    are built on (no per-session loop)."""
    keys = np.asarray(keys)
    n = len(keys)
    if n == 0:
        return np.zeros(0, np.int64)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    new_run = np.empty(n, bool)
    new_run[0] = True
    new_run[1:] = sk[1:] != sk[:-1]
    run_starts = np.flatnonzero(new_run)
    run_ids = np.cumsum(new_run) - 1
    rank_sorted = np.arange(n, dtype=np.int64) - run_starts[run_ids]
    rank = np.empty(n, np.int64)
    rank[order] = rank_sorted
    return rank


class CoalesceWindow:
    """Per-lane staging rings + the dense block builder.

    ``capacity`` bounds each lane's queued-but-undispatched rows (the
    bounded-queue half of the backpressure story); a block drains up to
    ``superstep_k * cmds_per_step`` rows per lane.  ``ready`` triggers
    on fill (``fill_frac`` of one full block node-wide) or cadence
    (``window_s`` since the last pop) — the batching-window shape of
    the reference WAL's gen_batch_server."""

    def __init__(self, n_lanes: int, cmds_per_step: int,
                 payload_width: int, *, superstep_k: int = 8,
                 capacity: Optional[int] = None, window_s: float = 0.002,
                 fill_frac: float = 0.5,
                 payload_dtype=np.int32,
                 track_seqnos: bool = False) -> None:
        self.n_lanes = int(n_lanes)
        self.cmds_per_step = int(cmds_per_step)
        self.payload_width = int(payload_width)
        self.superstep_k = int(superstep_k)
        width = self.superstep_k * self.cmds_per_step
        self.capacity = int(capacity) if capacity else 2 * width
        if self.capacity < width:
            raise ValueError(
                f"capacity {self.capacity} < one block window {width}")
        self.window_s = float(window_s)
        #: node-wide fill (rows) that triggers an eager pop: a fraction
        #: of one FULL block across every lane
        self.fill_trigger = max(1, int(fill_frac * width * self.n_lanes))
        self.buf = np.zeros((self.n_lanes, self.capacity,
                             self.payload_width), payload_dtype)
        #: session handle per staged row (credit release + audit joins)
        self.hbuf = np.full((self.n_lanes, self.capacity), -1, np.int64)
        #: optional per-row seqno ring (the READ lane's reply
        #: correlation ids, ISSUE 20) — opt-in: the write lane's seqno
        #: bookkeeping lives in the dedup directory, and an
        #: unconditional int64 ring would double this class's memory
        self.sbuf = np.zeros((self.n_lanes, self.capacity), np.int64) \
            if track_seqnos else None
        #: seqno matrix [N, K*Kc] of the LAST pop_block (None when
        #: seqno tracking is off) — read immediately after the pop
        self.last_pop_seqnos: Optional[np.ndarray] = None
        self.head = np.zeros(self.n_lanes, np.int64)
        self.fill = np.zeros(self.n_lanes, np.int64)
        self._staged_rows = 0
        self._last_pop = time.monotonic()

    # -- hot path (rule RA08: no per-session loops, no dict allocation) ----

    def offer(self, lanes: np.ndarray, payloads: np.ndarray,
              handles: np.ndarray, seqnos=None) -> np.ndarray:
        """Scatter an admitted batch into the per-lane rings.  Returns
        the PLACED mask; unplaced rows overflowed their lane's bounded
        ring and must be shed/deferred by the caller (their seqnos are
        not marked, so a later resend is still fresh)."""
        lanes = np.asarray(lanes, np.int64)
        rank = batch_rank(lanes)
        rel = self.fill[lanes] + rank
        placed = rel < self.capacity
        lp = lanes[placed]
        slot = (self.head[lp] + rel[placed]) % self.capacity
        self.buf[lp, slot] = payloads[placed]
        self.hbuf[lp, slot] = np.asarray(handles, np.int64)[placed]
        if self.sbuf is not None and seqnos is not None:
            self.sbuf[lp, slot] = np.asarray(seqnos, np.int64)[placed]
        np.add.at(self.fill, lp, 1)
        self._staged_rows += int(len(lp))
        return placed

    def pop_block(self):
        """Drain up to one superstep block: returns ``(n_new, payloads,
        handles, take)`` with ``n_new`` int32[K, N], ``payloads``
        [K, N, cmds_per_step, C] (dense; rows past ``n_new`` are stale
        ring bytes the engine never reads), ``handles`` int64[N, K*Kc]
        (valid through ``take[lane]`` rows per lane — the credit-release
        join), ``take`` int64[N]."""
        k, kc = self.superstep_k, self.cmds_per_step
        width = k * kc
        take = np.minimum(self.fill, width)
        idx = (self.head[:, None] + np.arange(width)[None, :]) \
            % self.capacity
        payloads = np.take_along_axis(self.buf, idx[..., None], axis=1)
        handles = np.take_along_axis(self.hbuf, idx, axis=1)
        if self.sbuf is not None:
            self.last_pop_seqnos = np.take_along_axis(self.sbuf, idx, axis=1)
        n_new = np.clip(take[None, :] - (np.arange(k) * kc)[:, None],
                        0, kc).astype(np.int32)
        payloads = payloads.reshape(self.n_lanes, k, kc,
                                    self.payload_width)
        payloads = payloads.transpose(1, 0, 2, 3)
        self.head = (self.head + take) % self.capacity
        self.fill = self.fill - take
        self._staged_rows -= int(take.sum())
        self._last_pop = time.monotonic()
        return n_new, payloads, handles, take

    # -- control plane -----------------------------------------------------

    def ready(self, now: Optional[float] = None) -> bool:
        """Fill trigger OR cadence trigger (with anything staged)."""
        if self._staged_rows <= 0:
            return False
        if self._staged_rows >= self.fill_trigger:
            return True
        now = time.monotonic() if now is None else now
        return (now - self._last_pop) >= self.window_s

    def queue_rows(self) -> int:
        return int(self._staged_rows)

    def overview(self) -> dict:
        return {
            "queue_rows": int(self._staged_rows),
            "capacity_rows": self.capacity * self.n_lanes,
            "fill_max": int(self.fill.max()) if self.n_lanes else 0,
            "superstep_k": self.superstep_k,
            "cmds_per_step": self.cmds_per_step,
            "fill_trigger": self.fill_trigger,
            "window_s": self.window_s,
        }
