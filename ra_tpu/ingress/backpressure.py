"""Graduated backpressure: per-session credit, per-tenant admission,
and the SLO-driven shed/defer/reject ladder (ISSUE 10).

FifoClient speaks a three-step protocol per session — ``ok`` → ``slow``
(soft limit) → ``StopSending`` (hard limit), mirrored from
ra_fifo_client.erl — but that only protects one mailbox.  This module
generalizes the ladder to ALL machines and a million sessions at once:

* **per-session credit** — each session holds at most ``hard_credit``
  commands in flight (staged + dispatched, un-committed); past
  ``soft_credit`` the row is admitted but stamped ``SLOW`` so the
  client eases off.  Credit is released at BLOCK granularity when the
  engine's committed watermark covers the block (no per-command host
  work — one vectorized ``np.add.at`` per retired block).
* **per-tenant admission + fairness counters** — tenants' in-flight
  totals are tracked; once the ladder escalates, tenants over their
  quota get ``DEFER`` first, so one noisy tenant cannot starve the
  rest (``tenant_used`` is the fairness evidence, exported via
  INGRESS_FIELDS).
* **the graduated ladder** — driven by PR 8 SloEngine verdicts on the
  commit-latency objective: level 0 (open) admits to the configured
  credits; a ``breach`` verdict tightens to level 1 (credits halved —
  tighten BEFORE queues grow, the whole point of latency-driven
  admission); an ``alert`` escalates to level 2 (tenant fairness
  enforced: over-quota tenants deferred).  Level 3 is the coalescer's
  own overflow shed (bounded rings drop, they never grow).  Recovery
  de-escalates one level per clean window (hysteresis: no flapping).

Every level transition emits a registered ``ingress.level`` flight-
recorder event (RA06); per-row outcomes are counters, never events —
the emit path must not ride a million-row batch.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..blackbox import record
from .coalesce import batch_rank

#: per-row admission statuses (np.int8), shared across the ingress plane
OK, SLOW, DEFER, REJECT, DUP, SHED = 0, 1, 2, 3, 4, 5

STATUS_NAMES = ("ok", "slow", "defer", "reject", "dup", "shed")

#: ladder levels (index = level)
LEVEL_NAMES = ("open", "tight", "fair", "shed")


class CreditLadder:
    """Vectorized credit + admission over a SessionDirectory's handle
    space.  The ladder level is set by :meth:`on_slo` from SloEngine
    verdicts; :meth:`admit` stamps per-row statuses and takes credit;
    :meth:`release` returns it when blocks commit."""

    def __init__(self, directory, *, soft_credit: int = 64,
                 hard_credit: int = 256,
                 tenant_quota: int = 65536) -> None:
        if soft_credit > hard_credit:
            raise ValueError("soft_credit must be <= hard_credit")
        self.directory = directory
        self.soft_credit = int(soft_credit)
        self.hard_credit = int(hard_credit)
        #: per-tenant in-flight cap enforced at level >= 2
        self.tenant_quota = int(tenant_quota)
        self.level = 0
        self._clean_windows = 0
        self.used = np.zeros(directory.capacity, np.int64)
        self.tenant_used = np.zeros(16, np.int64)

    def _ensure(self) -> None:
        cap = self.directory.capacity
        if len(self.used) < cap:
            grown = np.zeros(cap, np.int64)
            grown[:len(self.used)] = self.used
            self.used = grown
        nt = self.directory.n_tenants
        if len(self.tenant_used) < nt:
            grown = np.zeros(max(nt, 2 * len(self.tenant_used)), np.int64)
            grown[:len(self.tenant_used)] = self.tenant_used
            self.tenant_used = grown

    # -- effective limits by ladder level ----------------------------------

    def effective_limits(self) -> tuple:
        """(soft, hard) scaled by the ladder level: each escalation
        halves both — tighten credits before queues grow."""
        shift = min(self.level, 2)
        return (max(1, self.soft_credit >> shift),
                max(1, self.hard_credit >> shift))

    # -- admission (vectorized; one sweep per batch) -----------------------

    def admit(self, handles: np.ndarray) -> np.ndarray:
        """Per-row status (OK/SLOW/DEFER/REJECT) for a batch of fresh
        rows; takes credit for the admitted ones.  Within-batch
        multiplicity counts: a session pushing 300 rows in one wave
        hits its hard credit inside the wave, not a wave late."""
        self._ensure()
        handles = np.asarray(handles, np.int64)
        n = len(handles)
        status = np.zeros(n, np.int8)
        if n == 0:
            return status
        soft, hard = self.effective_limits()
        used_here = self.used[handles] + batch_rank(handles)
        status[used_here >= soft] = SLOW
        if self.level >= 2:
            t = self.directory.tenant[handles]
            t_here = self.tenant_used[t] + batch_rank(t)
            over = t_here >= self.tenant_quota
            status = np.where(over & (status <= SLOW),
                              np.int8(DEFER), status)
        status[used_here >= hard] = REJECT
        adm = status <= SLOW
        np.add.at(self.used, handles[adm], 1)
        np.add.at(self.tenant_used, self.directory.tenant[handles[adm]], 1)
        return status

    def release(self, handles: np.ndarray) -> int:
        """Return credit for committed (or shed) rows — one vectorized
        scatter per retired block."""
        handles = np.asarray(handles, np.int64)
        if len(handles) == 0:
            return 0
        self._ensure()
        tenants = self.directory.tenant[handles]
        np.add.at(self.used, handles, -1)
        np.add.at(self.tenant_used, tenants, -1)
        # double-release cannot happen by construction (each placed row
        # is released exactly once); clamp anyway so an accounting bug
        # degrades to loose credit, not a permanently wedged session.
        # Clamp only the TOUCHED rows — a full-array pass here would
        # sweep the whole million-session directory per retired block
        np.maximum.at(self.used, handles, 0)
        np.maximum.at(self.tenant_used, tenants, 0)
        return int(len(handles))

    # -- the SLO-driven ladder ---------------------------------------------

    def on_slo(self, verdicts: dict) -> int:
        """Escalate/decay from an SloEngine result (the ``evaluate()``
        dict or its ``objectives`` sub-dict): extracts the commit-
        latency verdict and delegates to :meth:`on_verdict`."""
        objs = verdicts.get("objectives", verdicts) or {}
        return self.on_verdict(
            (objs.get("commit_p99_ms") or {}).get("verdict"))

    def on_verdict(self, v: Optional[str]) -> int:
        """Escalate/decay the ladder from one commit-latency verdict
        string (what ``SloEngine.verdict("commit_p99_ms")`` returns):
        breach → level 1, alert → level 2; ``ok`` decays one level per
        TWO clean windows (hysteresis); ``no_data``/None holds.
        Returns the (possibly new) level; transitions are recorded."""
        if v == "alert":
            target, self._clean_windows = 2, 0
        elif v == "breach":
            target, self._clean_windows = max(self.level, 1), 0
        elif v == "ok":
            self._clean_windows += 1
            target = self.level - 1 if self._clean_windows >= 2 else \
                self.level
            if target != self.level:
                self._clean_windows = 0
        else:  # no_data / objective absent: hold
            target = self.level
        target = int(np.clip(target, 0, 2))
        if target != self.level:
            record("ingress.level", old=LEVEL_NAMES[self.level],
                   new=LEVEL_NAMES[target], verdict=v or "none")
            self.level = target
        return self.level

    def overview(self) -> dict:
        self._ensure()
        soft, hard = self.effective_limits()
        nt = self.directory.n_tenants
        return {
            "level": self.level,
            "level_name": LEVEL_NAMES[self.level],
            "soft_credit": soft,
            "hard_credit": hard,
            "tenant_quota": self.tenant_quota,
            "credit_in_use": int(self.used.sum()),
            "tenant_used_max": int(self.tenant_used[:nt].max())
            if nt else 0,
        }
