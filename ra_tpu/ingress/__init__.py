"""Ingress plane: a million client sessions fanning into the lane
engine (ISSUE 10, ROADMAP item 2).

``IngressPlane`` composes the three tiers this package provides —

* :class:`~ra_tpu.ingress.sessions.SessionDirectory`: external id →
  (tenant, lane, shard) deterministic placement, reconnect-stable
  epochs, vectorized per-session seqno dedup (at-most-once end-to-end);
* :class:`~ra_tpu.ingress.coalesce.CoalesceWindow`: per-lane staging
  rings coalescing concurrent submissions into the dense
  ``[K, lanes, cmds_per_step, C]`` superstep blocks the engine eats
  (host-side pre-jit; lint rule RA08 keeps its block-build path free of
  per-session Python work);
* :class:`~ra_tpu.ingress.backpressure.CreditLadder`: per-session
  credit, per-tenant fairness, and the SLO-driven shed/defer/reject
  ladder (FifoClient's ok→slow→StopSending protocol generalized to all
  machines)

— and drives them against a ``LockstepEngine`` through the PR 5
``DispatchAheadDriver``, releasing session credit at block granularity
as the driver's async committed-watermark readbacks land (no
per-command host work anywhere past admission).

Quickstart::

    eng = LockstepEngine(CounterMachine(), 10_000, 3)
    plane = IngressPlane(eng, superstep_k=4)
    handles = plane.connect_bulk(1_000_000, tenants=16, key="fleet")
    status = plane.submit(handles[:4096], seqnos, payloads)
    plane.pump()          # dispatch a block when the window triggers
"""
from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

from ..blackbox import record
from ..engine.lockstep import DispatchAheadDriver
from ..metrics import INGRESS_FIELDS
from .backpressure import (DEFER, DUP, LEVEL_NAMES, OK, REJECT, SHED, SLOW,
                           STATUS_NAMES, CreditLadder)
from .coalesce import CoalesceWindow, batch_rank
from .sessions import SessionDirectory, default_directory

__all__ = [
    "IngressPlane", "SessionDirectory", "CoalesceWindow", "CreditLadder",
    "OK", "SLOW", "DEFER", "REJECT", "DUP", "SHED", "STATUS_NAMES",
    "LEVEL_NAMES", "batch_rank", "default_directory",
]


class IngressPlane:
    """The session tier over one lane engine: dedup → admission →
    coalesce → fused dispatch, with block-granularity credit release."""

    def __init__(self, engine, *, directory: Optional[SessionDirectory]
                 = None, superstep_k: int = 8,
                 max_in_flight: int = 2, window_s: float = 0.002,
                 fill_frac: float = 0.5, capacity: Optional[int] = None,
                 soft_credit: int = 64, hard_credit: int = 256,
                 tenant_quota: int = 65536, slo=None,
                 shardings: Optional[dict] = None) -> None:
        self.engine = engine
        self.directory = directory or default_directory(engine)
        if self.directory.n_lanes != engine.n_lanes:
            raise ValueError("directory/engine lane count mismatch")
        self.window = CoalesceWindow(
            engine.n_lanes, engine.max_step_cmds, engine.payload_width,
            superstep_k=superstep_k, capacity=capacity,
            window_s=window_s, fill_frac=fill_frac,
            payload_dtype=np.dtype(engine.payload_dtype))
        self.ladder = CreditLadder(self.directory,
                                   soft_credit=soft_credit,
                                   hard_credit=hard_credit,
                                   tenant_quota=tenant_quota)
        if shardings is None and getattr(engine, "_mesh", None) is not None:
            # mesh-native composition (ISSUE 11): a sharded engine's
            # plane stages its coalesced blocks pre-partitioned against
            # the mesh, so the fused dispatch consumes them with zero
            # resharding copies (shard_engine_state stamped the mesh)
            from ..parallel.mesh import superstep_block_shardings
            shardings = superstep_block_shardings(engine._mesh)
        self.driver = DispatchAheadDriver(engine,
                                          max_in_flight=max_in_flight,
                                          shardings=shardings)
        #: optional SloEngine whose commit-latency verdicts drive the
        #: ladder (polled at pump time — host dict work only)
        self.slo = slo
        #: optional block-retire hook (the wire plane's ack fan-out,
        #: ISSUE 12): called with the released handle array whenever a
        #: block's committed watermark lands — i.e. off the driver's
        #: EXISTING async readbacks, never a new host sync
        self.on_block_committed = None
        self.counters = {f: 0 for f in INGRESS_FIELDS}
        #: in-flight blocks awaiting commit: (per-lane cumulative
        #: dispatched-row target, handle matrix [N, width], take [N])
        self._inflight: deque = deque()
        self._dispatched_rows = np.zeros(engine.n_lanes, np.int64)
        # commit baseline: election noops also advance total_committed,
        # so the release join is >=, never ==, and credit may release a
        # hair early around an election — flow control, not correctness
        self._base_committed = \
            np.asarray(engine.state.total_committed).astype(np.int64)
        self._shedding = False
        engine._ingress = self

    # -- sessions ----------------------------------------------------------

    def connect(self, external_id: str) -> int:
        """Resolve/create a named session; reconnects bump the epoch
        (recorded — reconnects are rare control-plane events)."""
        h, reconnected = self.directory.connect(external_id)
        if reconnected:
            self.counters["reconnects"] += 1
            record("ingress.connect", id=external_id, handle=int(h),
                   epoch=int(self.directory.epoch[h]))
        return h

    def connect_bulk(self, n: int, *, key: str = "bulk",
                     tenants: int = 1) -> np.ndarray:
        """Connect a synthetic fleet (one event for the whole fleet —
        the per-session path must not emit a million records)."""
        known = key in self.directory._bulk
        h = self.directory.connect_bulk(n, key=key, tenants=tenants)
        if known:
            self.counters["reconnects"] += n
        record("ingress.connect", bulk=key, n=int(n),
               reconnect=bool(known))
        return h

    # -- submission --------------------------------------------------------

    def submit(self, handles, seqnos, payloads) -> np.ndarray:
        """One ingress wave: per-row status (OK/SLOW/DEFER/REJECT/DUP/
        SHED, np.int8).  Dedup → admission → coalesce, all vectorized;
        only PLACED rows advance the at-most-once watermark, so a
        deferred/rejected/shed command's resend (same seqno) is fresh."""
        handles = np.asarray(handles, np.int64)
        seqnos = np.asarray(seqnos, np.int64)
        payloads = np.asarray(payloads)
        if payloads.ndim == 1:
            payloads = payloads[:, None]
        n = len(handles)
        c = self.counters
        c["submitted"] += n
        fresh = self.directory.fresh(handles, seqnos)
        status = np.full(n, DUP, np.int8)
        idx_fresh = np.flatnonzero(fresh)
        c["dup_dropped"] += n - len(idx_fresh)
        if not len(idx_fresh):
            return status
        fh = handles[idx_fresh]
        adm = self.ladder.admit(fh)
        status[idx_fresh] = adm
        ok = adm <= SLOW
        idx_ok = idx_fresh[ok]
        if len(idx_ok):
            placed = self.window.offer(self.directory.lane[handles[idx_ok]],
                                       payloads[idx_ok],
                                       handles[idx_ok])
            if not placed.all():
                # ring overflow: shed (bounded queues drop, they never
                # grow) — credit returned, seqno NOT marked, so the
                # client's resend survives the episode
                idx_shed = idx_ok[~placed]
                status[idx_shed] = SHED
                self.ladder.release(handles[idx_shed])
                c["shed_rows"] += len(idx_shed)
                if not self._shedding:
                    self._shedding = True
                    record("ingress.shed", rows=int(len(idx_shed)),
                           queue_rows=self.window.queue_rows(),
                           level=LEVEL_NAMES[self.ladder.level])
            else:
                self._shedding = False
            idx_placed = idx_ok[placed]
            self.directory.mark(handles[idx_placed], seqnos[idx_placed])
            c["accepted"] += len(idx_placed)
        c["slow_signals"] += int((adm == SLOW).sum())
        c["deferred"] += int((adm == DEFER).sum())
        c["rejected"] += int((adm == REJECT).sum())
        if len(idx_fresh) < n:
            # a within-wave twin of a row that was NOT placed must not
            # read as DUP ("already accepted — stop resending"): it
            # inherits its first occurrence's verdict instead.  One
            # stable lexsort groups equal (handle, seqno) runs; the run
            # head is the row fresh() kept (or a true watermark dup,
            # whose head status is already DUP)
            order = np.lexsort((seqnos, handles))
            sh, ss = handles[order], seqnos[order]
            new_run = np.empty(n, bool)
            new_run[0] = True
            new_run[1:] = (sh[1:] != sh[:-1]) | (ss[1:] != ss[:-1])
            run_ids = np.cumsum(new_run) - 1
            st_sorted = status[order]
            head_st = st_sorted[np.flatnonzero(new_run)][run_ids]
            # head placed -> the twin IS a duplicate of an accepted row;
            # head refused -> the twin shares the refusal (resendable)
            prop = np.where(head_st <= SLOW, np.int8(DUP), head_st)
            upd = ~new_run & (st_sorted == DUP)
            status[order[upd]] = prop[upd]
        return status

    def submit_auto(self, handles, payloads) -> np.ndarray:
        """Demo/test convenience: mint the next per-session seqnos
        server-side (a well-behaved resend-free client)."""
        handles = np.asarray(handles, np.int64)
        return self.submit(handles, self.directory.next_seqnos(handles),
                           payloads)

    # -- dispatch ----------------------------------------------------------

    def pump(self, now: Optional[float] = None,
             force: bool = False) -> bool:
        """Harvest committed blocks (credit release), poll the SLO
        ladder, and dispatch one superstep block if the window
        triggered (or ``force``).  Host dict/numpy work only — the
        dispatch itself is the driver's async staged submit."""
        self._harvest()
        if self.slo is not None:
            # memoized with evaluate(): a per-pump poll is a dict hit
            self.ladder.on_verdict(self.slo.verdict("commit_p99_ms"))
        if not force and not self.window.ready(now):
            return False
        if self.window.queue_rows() <= 0:
            return False
        n_new, payloads, handles, take = self.window.pop_block()
        self.driver.submit(n_new, payloads)
        self._dispatched_rows += take
        self._inflight.append((self._dispatched_rows.copy(), handles,
                               take))
        self.counters["blocks_built"] += 1
        self.counters["block_rows"] += int(take.sum())
        self._harvest()
        return True

    def _committed_rows(self) -> Optional[np.ndarray]:
        lc = self.driver.last_committed
        if lc is None:
            return None
        return np.asarray(lc, np.int64) - self._base_committed

    def _harvest(self) -> None:
        """Release credit for blocks the engine's committed watermark
        now covers (block granularity: one vectorized release per
        retired block, driven by the driver's EXISTING async watermark
        readbacks — no new host syncs)."""
        done = self._committed_rows()
        if done is None:
            return
        while self._inflight:
            target, handles, take = self._inflight[0]
            if not (done >= target).all():
                break
            self._inflight.popleft()
            width = handles.shape[1]
            valid = np.arange(width)[None, :] < take[:, None]
            released = self.ladder.release(handles[valid])
            self.counters["credits_released"] += released
            if self.on_block_committed is not None:
                self.on_block_committed(handles[valid])

    def settle(self, timeout: float = 30.0) -> None:
        """Flush everything: drain the window, dispatch, and drive
        empty supersteps until the committed watermark covers every
        dispatched row (write-delay / durable-confirm settling), then
        release all remaining credit.  A barrier — never on the hot
        path."""
        while self.window.queue_rows() > 0:
            self.pump(force=True)
        self.driver.drain()
        k = self.window.superstep_k
        n, kc, c = (self.engine.n_lanes, self.engine.max_step_cmds,
                    self.engine.payload_width)
        zero_n = np.zeros((k, n), np.int32)
        zero_p = np.zeros((k, n, kc, c),
                          np.dtype(self.engine.payload_dtype))
        deadline = time.monotonic() + timeout
        while self._inflight:
            # same block shapes as the pump path: reuses the compiled
            # fused executable rather than retracing a new geometry
            self.driver.submit(zero_n, zero_p)
            self.driver.drain()
            self._harvest()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"ingress settle: {len(self._inflight)} blocks "
                    "still uncommitted")

    # -- observability -----------------------------------------------------

    def gauges(self, credit_in_use: Optional[int] = None) -> dict:
        out = {
            "sessions": int(self.directory.n_sessions),
            "tenants": self.directory.n_tenants,
            "queue_rows": self.window.queue_rows(),
            "inflight_blocks": len(self._inflight),
            "level": self.ladder.level,
            # O(sessions) sum: overview() passes the ladder's value in
            # so one snapshot does the full-array reduction ONCE
            "credit_in_use": int(self.ladder.used.sum())
            if credit_in_use is None else credit_in_use,
        }
        dur = getattr(self.engine, "_dur", None)
        if dur is not None:
            # the durability half of the backlog: ingress queue depth
            # + unconfirmed steps = the node's uncommitted total
            out["wal_pending_steps"] = dur.pending_steps()
        return out

    def overview(self) -> dict:
        """The Observatory ``ingress`` source: INGRESS_FIELDS counters
        + flow gauges, one flat numeric namespace (ring keys
        ``ingress_<field>``)."""
        lad = self.ladder.overview()
        return {**self.counters,
                **self.gauges(credit_in_use=lad["credit_in_use"]),
                "ladder": lad,
                "window": self.window.overview()}

    def attach(self, observatory) -> "IngressPlane":
        """Register this plane as the Observatory's ``ingress`` source
        (``Observatory.for_engine`` wires it automatically when the
        engine carries an attached plane)."""
        observatory.add_source("ingress", self.overview)
        return self

    def bench_row(self, elapsed_s: float) -> dict:
        """A bench/soak tail row carrying the ingress regression keys
        tools/bench_diff.py compares (``ingress_cmds_per_s`` higher-is-
        better, ``ingress_shed_rate`` lower-is-better), plus the
        device-plane stamp (ISSUE 16): the ingress pump is one of the
        four steady-state dispatch loops, so its tail carries
        ``n_compiles``/``compile_time_s``/``transfer_bytes``/
        ``peak_live_bytes`` like the engine bench tails."""
        from .. import devicewatch
        c = self.counters
        accepted = c["accepted"]
        submitted = max(1, c["submitted"])
        return {
            "value": accepted / max(elapsed_s, 1e-9),
            "ingress_cmds_per_s": accepted / max(elapsed_s, 1e-9),
            "ingress_shed_rate": c["shed_rows"] / submitted,
            "ingress_accepted": accepted,
            "ingress_submitted": c["submitted"],
            "ingress_dup_dropped": c["dup_dropped"],
            "elapsed_s": elapsed_s,
            **devicewatch.bench_tail_keys(commands=accepted),
        }
