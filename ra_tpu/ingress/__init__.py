"""Ingress plane: a million client sessions fanning into the lane
engine (ISSUE 10, ROADMAP item 2).

``IngressPlane`` composes the three tiers this package provides —

* :class:`~ra_tpu.ingress.sessions.SessionDirectory`: external id →
  (tenant, lane, shard) deterministic placement, reconnect-stable
  epochs, vectorized per-session seqno dedup (at-most-once end-to-end);
* :class:`~ra_tpu.ingress.coalesce.CoalesceWindow`: per-lane staging
  rings coalescing concurrent submissions into the dense
  ``[K, lanes, cmds_per_step, C]`` superstep blocks the engine eats
  (host-side pre-jit; lint rule RA08 keeps its block-build path free of
  per-session Python work);
* :class:`~ra_tpu.ingress.backpressure.CreditLadder`: per-session
  credit, per-tenant fairness, and the SLO-driven shed/defer/reject
  ladder (FifoClient's ok→slow→StopSending protocol generalized to all
  machines)

— and drives them against a ``LockstepEngine`` through the PR 5
``DispatchAheadDriver``, releasing session credit at block granularity
as the driver's async committed-watermark readbacks land (no
per-command host work anywhere past admission).

Quickstart::

    eng = LockstepEngine(CounterMachine(), 10_000, 3)
    plane = IngressPlane(eng, superstep_k=4)
    handles = plane.connect_bulk(1_000_000, tenants=16, key="fleet")
    status = plane.submit(handles[:4096], seqnos, payloads)
    plane.pump()          # dispatch a block when the window triggers
"""
from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

from ..blackbox import record
from ..engine.lockstep import DispatchAheadDriver
from ..metrics import INGRESS_FIELDS, READ_FIELDS
from .backpressure import (DEFER, DUP, LEVEL_NAMES, OK, REJECT, SHED, SLOW,
                           STATUS_NAMES, CreditLadder)
from .coalesce import CoalesceWindow, batch_rank
from .sessions import SessionDirectory, default_directory

__all__ = [
    "IngressPlane", "SessionDirectory", "CoalesceWindow", "CreditLadder",
    "OK", "SLOW", "DEFER", "REJECT", "DUP", "SHED", "STATUS_NAMES",
    "LEVEL_NAMES", "batch_rank", "default_directory",
]


class IngressPlane:
    """The session tier over one lane engine: dedup → admission →
    coalesce → fused dispatch, with block-granularity credit release."""

    def __init__(self, engine, *, directory: Optional[SessionDirectory]
                 = None, superstep_k: int = 8,
                 max_in_flight: int = 2, window_s: float = 0.002,
                 fill_frac: float = 0.5, capacity: Optional[int] = None,
                 soft_credit: int = 64, hard_credit: int = 256,
                 tenant_quota: int = 65536, slo=None,
                 shardings: Optional[dict] = None) -> None:
        self.engine = engine
        self.directory = directory or default_directory(engine)
        if self.directory.n_lanes != engine.n_lanes:
            raise ValueError("directory/engine lane count mismatch")
        self.window = CoalesceWindow(
            engine.n_lanes, engine.max_step_cmds, engine.payload_width,
            superstep_k=superstep_k, capacity=capacity,
            window_s=window_s, fill_frac=fill_frac,
            payload_dtype=np.dtype(engine.payload_dtype))
        self.ladder = CreditLadder(self.directory,
                                   soft_credit=soft_credit,
                                   hard_credit=hard_credit,
                                   tenant_quota=tenant_quota)
        if shardings is None and getattr(engine, "_mesh", None) is not None:
            # mesh-native composition (ISSUE 11): a sharded engine's
            # plane stages its coalesced blocks pre-partitioned against
            # the mesh, so the fused dispatch consumes them with zero
            # resharding copies (shard_engine_state stamped the mesh)
            from ..parallel.mesh import superstep_block_shardings
            shardings = superstep_block_shardings(engine._mesh)
        self.driver = DispatchAheadDriver(engine,
                                          max_in_flight=max_in_flight,
                                          shardings=shardings)
        #: optional SloEngine whose commit-latency verdicts drive the
        #: ladder (polled at pump time — host dict work only)
        self.slo = slo
        #: optional block-retire hook (the wire plane's ack fan-out,
        #: ISSUE 12): called with the released handle array whenever a
        #: block's committed watermark lands — i.e. off the driver's
        #: EXISTING async readbacks, never a new host sync
        self.on_block_committed = None
        self.counters = {f: 0 for f in INGRESS_FIELDS}
        #: in-flight blocks awaiting commit: (per-lane cumulative
        #: dispatched-row target, handle matrix [N, width], take [N])
        self._inflight: deque = deque()
        self._dispatched_rows = np.zeros(engine.n_lanes, np.int64)
        # commit baseline: election noops also advance total_committed,
        # so the release join is >=, never ==, and credit may release a
        # hair early around an election — flow control, not correctness
        self._base_committed = \
            np.asarray(engine.state.total_committed).astype(np.int64)
        self._shedding = False
        # -- vectorized read lane (ISSUE 20) ---------------------------
        # A second, read-side CoalesceWindow stages consistent reads
        # into ``(n_read [K,N], read_q [K,N,Kr,Cq])`` blocks that RIDE
        # the write dispatches (superstep_k=1: the engine holds at most
        # ONE in-flight read batch per lane, so a block is exactly one
        # window of Kr rows per lane, registered at inner step 0 to
        # maximize confirm rounds within the dispatch).  Reads consume
        # the same session credit as writes but shed FIRST: any
        # tightened ladder level refuses whole read waves at admission
        # (overload sheds reads before it delays writes).
        self.reads_enabled = bool(getattr(engine, "reads_enabled", False))
        self.read_counters = {f: 0 for f in READ_FIELDS}
        #: reply fan-out hook (the wire plane's READ_REPLY path):
        #: called with (handles, seqnos, statuses, watermarks, payloads)
        #: row vectors as read batches settle — off the driver's
        #: EXISTING async read-aux readbacks, never a new host sync
        self.on_reads_done = None
        #: the single in-flight read block awaiting settlement:
        #: (handles [N,Kr], seqnos [N,Kr], take [N], pend bool[N])
        self._read_pending = None
        self._read_shedding = False
        self._read_stale_flag = False
        n = engine.n_lanes
        self._zero_wn = np.zeros((superstep_k, n), np.int32)
        self._zero_wp = np.zeros(
            (superstep_k, n, engine.max_step_cmds, engine.payload_width),
            np.dtype(engine.payload_dtype))
        if self.reads_enabled:
            kr, cq = engine.read_window, engine.query_width
            qdt = np.dtype(engine.query_dtype)
            self.read_window = CoalesceWindow(
                n, kr, cq, superstep_k=1, capacity=4 * kr,
                window_s=window_s, fill_frac=fill_frac,
                payload_dtype=qdt, track_seqnos=True)
            #: zero read block attached while a block is PENDING so the
            #: reply tensors (read_done/read_replies/read_watermark)
            #: keep riding every dispatch until the batch serves or
            #: expires — settlement never waits on a new read arriving
            self._zero_read_blk = (
                np.zeros((superstep_k, n), np.int32),
                np.zeros((superstep_k, n, kr, cq), qdt))
            # settlement joins on the engine's CUMULATIVE per-lane
            # outcome counters (served/shed/stale deltas per observed
            # dispatch) — baselines from current state, like
            # _base_committed above
            s = engine.state
            self._read_served_base = \
                np.asarray(s.read_served).astype(np.int64)
            self._read_shed_base = \
                np.asarray(s.read_shed).astype(np.int64)
            self._read_stale_base = \
                np.asarray(s.read_stale).astype(np.int64)
        else:
            self.read_window = None
            self._zero_read_blk = None
        engine._ingress = self

    # -- sessions ----------------------------------------------------------

    def connect(self, external_id: str) -> int:
        """Resolve/create a named session; reconnects bump the epoch
        (recorded — reconnects are rare control-plane events)."""
        h, reconnected = self.directory.connect(external_id)
        if reconnected:
            self.counters["reconnects"] += 1
            record("ingress.connect", id=external_id, handle=int(h),
                   epoch=int(self.directory.epoch[h]))
        return h

    def connect_bulk(self, n: int, *, key: str = "bulk",
                     tenants: int = 1) -> np.ndarray:
        """Connect a synthetic fleet (one event for the whole fleet —
        the per-session path must not emit a million records)."""
        known = key in self.directory._bulk
        h = self.directory.connect_bulk(n, key=key, tenants=tenants)
        if known:
            self.counters["reconnects"] += n
        record("ingress.connect", bulk=key, n=int(n),
               reconnect=bool(known))
        return h

    # -- submission --------------------------------------------------------

    def submit(self, handles, seqnos, payloads) -> np.ndarray:
        """One ingress wave: per-row status (OK/SLOW/DEFER/REJECT/DUP/
        SHED, np.int8).  Dedup → admission → coalesce, all vectorized;
        only PLACED rows advance the at-most-once watermark, so a
        deferred/rejected/shed command's resend (same seqno) is fresh."""
        handles = np.asarray(handles, np.int64)
        seqnos = np.asarray(seqnos, np.int64)
        payloads = np.asarray(payloads)
        if payloads.ndim == 1:
            payloads = payloads[:, None]
        n = len(handles)
        c = self.counters
        c["submitted"] += n
        fresh = self.directory.fresh(handles, seqnos)
        status = np.full(n, DUP, np.int8)
        idx_fresh = np.flatnonzero(fresh)
        c["dup_dropped"] += n - len(idx_fresh)
        if not len(idx_fresh):
            return status
        fh = handles[idx_fresh]
        adm = self.ladder.admit(fh)
        status[idx_fresh] = adm
        ok = adm <= SLOW
        idx_ok = idx_fresh[ok]
        if len(idx_ok):
            placed = self.window.offer(self.directory.lane[handles[idx_ok]],
                                       payloads[idx_ok],
                                       handles[idx_ok])
            if not placed.all():
                # ring overflow: shed (bounded queues drop, they never
                # grow) — credit returned, seqno NOT marked, so the
                # client's resend survives the episode
                idx_shed = idx_ok[~placed]
                status[idx_shed] = SHED
                self.ladder.release(handles[idx_shed])
                c["shed_rows"] += len(idx_shed)
                if not self._shedding:
                    self._shedding = True
                    record("ingress.shed", rows=int(len(idx_shed)),
                           queue_rows=self.window.queue_rows(),
                           level=LEVEL_NAMES[self.ladder.level])
            else:
                self._shedding = False
            idx_placed = idx_ok[placed]
            self.directory.mark(handles[idx_placed], seqnos[idx_placed])
            c["accepted"] += len(idx_placed)
        c["slow_signals"] += int((adm == SLOW).sum())
        c["deferred"] += int((adm == DEFER).sum())
        c["rejected"] += int((adm == REJECT).sum())
        if len(idx_fresh) < n:
            # a within-wave twin of a row that was NOT placed must not
            # read as DUP ("already accepted — stop resending"): it
            # inherits its first occurrence's verdict instead.  One
            # stable lexsort groups equal (handle, seqno) runs; the run
            # head is the row fresh() kept (or a true watermark dup,
            # whose head status is already DUP)
            order = np.lexsort((seqnos, handles))
            sh, ss = handles[order], seqnos[order]
            new_run = np.empty(n, bool)
            new_run[0] = True
            new_run[1:] = (sh[1:] != sh[:-1]) | (ss[1:] != ss[:-1])
            run_ids = np.cumsum(new_run) - 1
            st_sorted = status[order]
            head_st = st_sorted[np.flatnonzero(new_run)][run_ids]
            # head placed -> the twin IS a duplicate of an accepted row;
            # head refused -> the twin shares the refusal (resendable)
            prop = np.where(head_st <= SLOW, np.int8(DUP), head_st)
            upd = ~new_run & (st_sorted == DUP)
            status[order[upd]] = prop[upd]
        return status

    def submit_auto(self, handles, payloads) -> np.ndarray:
        """Demo/test convenience: mint the next per-session seqnos
        server-side (a well-behaved resend-free client)."""
        handles = np.asarray(handles, np.int64)
        return self.submit(handles, self.directory.next_seqnos(handles),
                           payloads)

    def submit_reads(self, handles, seqnos, queries) -> np.ndarray:
        """One consistent-read wave: per-row status (OK/SLOW/REJECT/
        SHED, np.int8), vectorized end to end (rule RA08 gates this
        path like the write coalescer's).

        Reads are idempotent, so there is NO dedup watermark: ``seqnos``
        are pure reply-correlation ids, and a shed read's resend is
        always fresh.  Credit bias (the ISSUE 20 overload story): any
        tightened ladder level sheds the whole read wave at admission —
        reads shed BEFORE writes are delayed, and a shed read costs no
        credit."""
        handles = np.asarray(handles, np.int64)
        seqnos = np.asarray(seqnos, np.int64)
        queries = np.asarray(queries)
        if queries.ndim == 1:
            queries = queries[:, None]
        n = len(handles)
        rc = self.read_counters
        rc["submitted"] += n
        status = np.full(n, SHED, np.int8)
        if not self.reads_enabled or n == 0:
            rc["shed"] += n
            return status
        if self.ladder.level > 0:
            rc["shed"] += n
            if not self._read_shedding:
                self._read_shedding = True
                record("read.shed", rows=int(n),
                       level=LEVEL_NAMES[self.ladder.level])
            return status
        self._read_shedding = False
        adm = self.ladder.admit(handles)
        status[:] = adm
        ok = adm <= SLOW
        idx_ok = np.flatnonzero(ok)
        rc["rejected"] += int(n - len(idx_ok))
        if len(idx_ok):
            placed = self.read_window.offer(
                self.directory.lane[handles[idx_ok]], queries[idx_ok],
                handles[idx_ok], seqnos=seqnos[idx_ok])
            if not placed.all():
                idx_shed = idx_ok[~placed]
                status[idx_shed] = SHED
                self.ladder.release(handles[idx_shed])
                rc["shed"] += len(idx_shed)
            rc["accepted"] += int(placed.sum())
        return status

    # -- dispatch ----------------------------------------------------------

    def pump(self, now: Optional[float] = None,
             force: bool = False) -> bool:
        """Harvest committed blocks (credit release), poll the SLO
        ladder, and dispatch one superstep block if the window
        triggered (or ``force``).  Host dict/numpy work only — the
        dispatch itself is the driver's async staged submit.

        Reads ride the same dispatch (ISSUE 20): a staged read block —
        or the zero block that keeps a PENDING batch's reply tensors
        flowing — is attached to whatever write block goes out.  With
        no write work at all, read work still dispatches against a
        cached zero write block (same geometry, same compiled
        executable — no retrace)."""
        self._harvest()
        if self.slo is not None:
            # memoized with evaluate(): a per-pump poll is a dict hit
            self.ladder.on_verdict(self.slo.verdict("commit_p99_ms"))
        write_ready = (force or self.window.ready(now)) and \
            self.window.queue_rows() > 0
        read_ready = self.reads_enabled and (
            self._read_pending is not None
            or self.read_window.queue_rows() > 0)
        if not write_ready and not read_ready:
            return False
        read_blk = self._pop_read_block()
        if write_ready:
            n_new, payloads, handles, take = self.window.pop_block()
            self.driver.submit(n_new, payloads, read_blk=read_blk)
            self._dispatched_rows += take
            self._inflight.append((self._dispatched_rows.copy(), handles,
                                   take))
            self.counters["blocks_built"] += 1
            self.counters["block_rows"] += int(take.sum())
        else:
            # reads-only dispatch: zero write rows, no write
            # bookkeeping — the read plane serves with zero log appends
            self.driver.submit(self._zero_wn, self._zero_wp,
                               read_blk=read_blk)
        self._harvest()
        return True

    def _committed_rows(self) -> Optional[np.ndarray]:
        lc = self.driver.last_committed
        if lc is None:
            return None
        return np.asarray(lc, np.int64) - self._base_committed

    def _pop_read_block(self):
        """The read half of a dispatch: ``None`` (reads off / nothing
        to do), the cached ZERO block (a batch is pending — keeps the
        reply tensors riding every dispatch until it settles), or one
        popped read window (at most Kr rows per lane, registered at
        inner step 0)."""
        if not self.reads_enabled:
            return None
        if self._read_pending is not None:
            return self._zero_read_blk
        if self.read_window.queue_rows() <= 0:
            return None
        n_r, read_q, handles, take = self.read_window.pop_block()
        seqnos = self.read_window.last_pop_seqnos
        nr_blk, rq_blk = (np.zeros_like(self._zero_read_blk[0]),
                          np.zeros_like(self._zero_read_blk[1]))
        nr_blk[0] = n_r[0]
        rq_blk[0] = read_q[0]
        self._read_pending = (handles, seqnos.copy(), take.copy(),
                              take > 0)
        self.read_counters["blocks_built"] += 1
        self.read_counters["block_rows"] += int(take.sum())
        return (nr_blk, rq_blk)

    def _harvest_reads(self) -> None:
        """Settle the in-flight read block against the driver's
        observed read aux (drained in dispatch order).  Because the
        engine accepts a lane's batch whole-or-nothing and registers at
        most one batch per lane, each pending lane settles as exactly
        one of served (OK + replies at a certified watermark), arrival-
        shed (SHED: leader down / slot busy at registration), or
        stale-expired (REJECT: the device refused rather than serve
        past lease/quorum cover) — joined on the cumulative per-lane
        outcome deltas, replies from the per-dispatch tensors."""
        robs = self.driver.read_obs
        while robs:  # ra08-ok: per-OBSERVED-DISPATCH drain (<= in-flight cap entries), not per-session work
            obs = robs.popleft()
            served_c = np.asarray(obs["read_served_lanes"], np.int64)
            shed_c = np.asarray(obs["read_shed_lanes"], np.int64)
            stale_c = np.asarray(obs["read_stale_lanes"], np.int64)
            blk = self._read_pending
            if blk is not None:
                handles, seqnos, take, pend = blk
                done = obs.get("read_done")
                if done is not None:
                    done = np.asarray(done)
                    served = (done.sum(axis=0) > 0) & pend
                    if served.any():
                        k_idx = np.argmax(done > 0, axis=0)
                        lane_ix = np.arange(done.shape[1])
                        replies = np.asarray(
                            obs["read_replies"])[k_idx, lane_ix]
                        wms = np.asarray(
                            obs["read_watermark"])[k_idx, lane_ix]
                        self._emit_read_replies(blk, served, OK, wms,
                                                replies)
                        pend = pend & ~served
                shed = ((shed_c - self._read_shed_base) > 0) & pend
                if shed.any():
                    self._emit_read_replies(blk, shed, SHED, None, None)
                    pend = pend & ~shed
                stale = ((stale_c - self._read_stale_base) > 0) & pend
                if stale.any():
                    self._emit_read_replies(blk, stale, REJECT, None,
                                            None)
                    pend = pend & ~stale
                self._read_pending = None if not pend.any() else \
                    (handles, seqnos, take, pend)
            self._read_served_base = served_c
            self._read_shed_base = shed_c
            self._read_stale_base = stale_c

    def _emit_read_replies(self, blk, mask, status, wms, replies) -> None:
        """Fan one settlement outcome out to reply rows: release read
        credit, bump counters, and fire ``on_reads_done`` (the wire
        plane's READ_REPLY path) — one vectorized gather per outcome,
        rule RA08-gated like the coalescer."""
        handles, seqnos, take, _pend = blk
        kr = handles.shape[1]
        valid = (np.arange(kr)[None, :] < take[:, None]) & mask[:, None]
        h = handles[valid]
        nrows = len(h)
        if not nrows:
            return
        s = seqnos[valid]
        st = np.full(nrows, status, np.int8)
        if wms is None:
            wm_rows = np.full(nrows, -1, np.int32)
        else:
            wm_rows = np.broadcast_to(
                np.asarray(wms, np.int32)[:, None],
                valid.shape)[valid]
        if replies is None:
            pay = np.zeros((nrows, self.engine.query_reply_width),
                           np.int32)
        else:
            pay = np.asarray(replies, np.int32)[valid]
        self.ladder.release(h)
        rc = self.read_counters
        if status == OK:
            rc["served"] += nrows
            self._read_stale_flag = False
        elif status == SHED:
            rc["shed"] += nrows
        else:
            rc["stale_refused"] += nrows
            if not self._read_stale_flag:
                self._read_stale_flag = True
                record("read.stale", rows=nrows)
        if self.on_reads_done is not None:
            self.on_reads_done(h, s, st, wm_rows, pay)
            rc["replies_sent"] += nrows

    def _harvest(self) -> None:
        """Release credit for blocks the engine's committed watermark
        now covers (block granularity: one vectorized release per
        retired block, driven by the driver's EXISTING async watermark
        readbacks — no new host syncs)."""
        if self.reads_enabled:
            self._harvest_reads()
        done = self._committed_rows()
        if done is None:
            return
        while self._inflight:
            target, handles, take = self._inflight[0]
            if not (done >= target).all():
                break
            self._inflight.popleft()
            width = handles.shape[1]
            valid = np.arange(width)[None, :] < take[:, None]
            released = self.ladder.release(handles[valid])
            self.counters["credits_released"] += released
            if self.on_block_committed is not None:
                self.on_block_committed(handles[valid])

    def settle(self, timeout: float = 30.0) -> None:
        """Flush everything: drain the window, dispatch, and drive
        empty supersteps until the committed watermark covers every
        dispatched row (write-delay / durable-confirm settling), then
        release all remaining credit.  A barrier — never on the hot
        path."""
        while self.window.queue_rows() > 0:
            self.pump(force=True)
        self.driver.drain()
        self._harvest()
        deadline = time.monotonic() + timeout
        while self._inflight or (self.reads_enabled and (
                self._read_pending is not None
                or self.read_window.queue_rows() > 0)):
            # same block shapes as the pump path: reuses the compiled
            # fused executable rather than retracing a new geometry.
            # Pending reads ride along until they serve or the device
            # read_timeout expires them — settlement always terminates
            self.driver.submit(self._zero_wn, self._zero_wp,
                               read_blk=self._pop_read_block())
            self.driver.drain()
            self._harvest()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"ingress settle: {len(self._inflight)} blocks "
                    "still uncommitted")

    # -- observability -----------------------------------------------------

    def gauges(self, credit_in_use: Optional[int] = None) -> dict:
        out = {
            "sessions": int(self.directory.n_sessions),
            "tenants": self.directory.n_tenants,
            "queue_rows": self.window.queue_rows(),
            "inflight_blocks": len(self._inflight),
            "level": self.ladder.level,
            # O(sessions) sum: overview() passes the ladder's value in
            # so one snapshot does the full-array reduction ONCE
            "credit_in_use": int(self.ladder.used.sum())
            if credit_in_use is None else credit_in_use,
        }
        dur = getattr(self.engine, "_dur", None)
        if dur is not None:
            # the durability half of the backlog: ingress queue depth
            # + unconfirmed steps = the node's uncommitted total
            out["wal_pending_steps"] = dur.pending_steps()
        return out

    def overview(self) -> dict:
        """The Observatory ``ingress`` source: INGRESS_FIELDS counters
        + flow gauges, one flat numeric namespace (ring keys
        ``ingress_<field>``)."""
        lad = self.ladder.overview()
        return {**self.counters,
                **self.gauges(credit_in_use=lad["credit_in_use"]),
                "ladder": lad,
                "window": self.window.overview()}

    def read_overview(self) -> dict:
        """The Observatory ``read`` source: READ_FIELDS counters + read
        flow gauges (flat ring keys ``read_<field>``).  ``lease_served``
        is filled from the device's cumulative served-under-lease
        counter at snapshot time (the observability pull path — the hot
        path never syncs for it); ``lease_coverage_pct`` is the
        served-under-lease share, the ra_top read panel's headline."""
        out = dict(self.read_counters)
        if self.reads_enabled:
            leased = int(np.asarray(
                self.engine.state.read_leased).astype(np.int64).sum())
            out["lease_served"] = leased
            served_dev = int(np.asarray(
                self.engine.state.read_served).astype(np.int64).sum())
            out["lease_coverage_pct"] = \
                100.0 * leased / max(1, served_dev)
            out["queue_rows"] = self.read_window.queue_rows()
            out["pending_lanes"] = 0 if self._read_pending is None \
                else int(self._read_pending[3].sum())
        return out

    def attach(self, observatory) -> "IngressPlane":
        """Register this plane as the Observatory's ``ingress`` (and,
        reads enabled, ``read``) source (``Observatory.for_engine``
        wires it automatically when the engine carries an attached
        plane)."""
        observatory.add_source("ingress", self.overview)
        if self.reads_enabled:
            observatory.add_source("read", self.read_overview)
        return self

    def bench_row(self, elapsed_s: float) -> dict:
        """A bench/soak tail row carrying the ingress regression keys
        tools/bench_diff.py compares (``ingress_cmds_per_s`` higher-is-
        better, ``ingress_shed_rate`` lower-is-better), plus the
        device-plane stamp (ISSUE 16): the ingress pump is one of the
        four steady-state dispatch loops, so its tail carries
        ``n_compiles``/``compile_time_s``/``transfer_bytes``/
        ``peak_live_bytes`` like the engine bench tails."""
        from .. import devicewatch
        c = self.counters
        accepted = c["accepted"]
        submitted = max(1, c["submitted"])
        row = {
            "value": accepted / max(elapsed_s, 1e-9),
            "ingress_cmds_per_s": accepted / max(elapsed_s, 1e-9),
            "ingress_shed_rate": c["shed_rows"] / submitted,
            "ingress_accepted": accepted,
            "ingress_submitted": c["submitted"],
            "ingress_dup_dropped": c["dup_dropped"],
            "elapsed_s": elapsed_s,
            **devicewatch.bench_tail_keys(commands=accepted),
        }
        if self.reads_enabled:
            # read-frontier regression keys (ISSUE 20, higher-better
            # read_cmds_per_s joined by the read_p99_ms phase key the
            # SLO engine stamps)
            rc = self.read_counters
            row["read_cmds_per_s"] = rc["served"] / max(elapsed_s, 1e-9)
            row["read_served"] = rc["served"]
            row["read_shed_rate"] = rc["shed"] / max(1, rc["submitted"])
            row["read_stale_refused"] = rc["stale_refused"]
        return row
