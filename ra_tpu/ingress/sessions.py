"""Session directory: a million external clients mapped onto the lane
plane (ISSUE 10).

The reference's heritage is MQTT-scale fan-in — thousands of clusters
sharing node-wide batching infrastructure (PAPER.md §0).  Here the
session tier sits ABOVE the lane data plane (the hierarchical
composition of Fast Raft, arxiv 2506.17793): an external client id maps
deterministically to a ``(tenant, lane, shard)`` placement, reconnects
land on the same lane under a bumped session *epoch*, and a per-session
seqno watermark makes resends at-most-once end-to-end — the dedup the
classic FifoClient does per mailbox, vectorized over a million rows.

Scale forces the layout: a Python object per session would be ~1GB of
heap and a per-command attribute chase.  Sessions are therefore rows in
flat numpy arrays (``lane``/``tenant``/``epoch``/``last_seqno``),
addressed by an integer *handle*; every per-command operation
(:meth:`SessionDirectory.fresh`, :meth:`mark`) is one vectorized sweep
over the submitted batch, never a per-session loop.  String external
ids resolve to handles on the (rare) connect path only; bulk fleets use
:meth:`connect_bulk`, which synthesizes placements with a vectorized
splitmix64 so a million sessions connect in milliseconds.

Dedup contract (the at-most-once invariant, pinned by tests): a
``(session, seqno)`` pair enters the engine at most once, ever —
within a batch by first-occurrence uniqueness, across batches/reconnects
by the monotone ``last_seqno`` watermark, which only advances for rows
the coalescer actually PLACED (``mark``), so an admission-rejected or
shed command's seqno survives for a later resend.  Clients submit
seqnos in order (the FifoClient protocol); trace ids are minted as
``<external_id>/<seqno>`` — stable across resends, so a retried command
records under ONE id (the PR 7 contract).
"""
from __future__ import annotations

import zlib

import numpy as np

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer — the deterministic placement
    hash (stable across processes and PYTHONHASHSEED, unlike hash())."""
    with np.errstate(over="ignore"):  # uint64 wraparound is the point
        z = (x + np.uint64(0x9E3779B97F4A7C15)) & _M64
        z = ((z ^ (z >> np.uint64(30))) *
             np.uint64(0xBF58476D1CE4E5B9)) & _M64
        z = ((z ^ (z >> np.uint64(27))) *
             np.uint64(0x94D049BB133111EB)) & _M64
        return z ^ (z >> np.uint64(31))


class SessionDirectory:
    """External client ids → (tenant, lane, shard) with vectorized
    per-session seqno dedup.  One instance per ingress plane."""

    def __init__(self, n_lanes: int, *, n_shards: int = 1, seed: int = 0,
                 capacity: int = 4096) -> None:
        if n_lanes < 1:
            raise ValueError("n_lanes must be >= 1")
        self.n_lanes = int(n_lanes)
        self.n_shards = max(1, int(n_shards))
        self.seed = int(seed)
        self.n_sessions = 0
        self._ids: dict[str, int] = {}       # named sessions only
        self._bulk: dict[str, tuple] = {}    # bulk key -> (base, n)
        self._tenant_ids: dict[str, int] = {}
        cap = max(16, int(capacity))
        self.lane = np.zeros(cap, np.int32)
        self.tenant = np.zeros(cap, np.int32)
        self.epoch = np.zeros(cap, np.int32)
        #: highest seqno PLACED into the engine path per session — the
        #: at-most-once watermark (advanced by mark(), never by fresh())
        self.last_seqno = np.zeros(cap, np.int64)

    # -- capacity ----------------------------------------------------------

    @property
    def capacity(self) -> int:
        return len(self.lane)

    def _ensure(self, n: int) -> None:
        cap = len(self.lane)
        if n <= cap:
            return
        new = max(n, cap * 2)
        for name in ("lane", "tenant", "epoch", "last_seqno"):
            arr = getattr(self, name)
            grown = np.zeros(new, arr.dtype)
            grown[:cap] = arr
            setattr(self, name, grown)

    # -- placement ---------------------------------------------------------

    def _hash_id(self, external_id: str) -> int:
        return int(_mix64(np.uint64(
            (zlib.crc32(external_id.encode()) ^ (self.seed & 0xFFFFFFFF))
            & 0xFFFFFFFF)))

    def place(self, external_id: str) -> tuple:
        """Deterministic ``(tenant, lane, shard)`` for an external id —
        stable across reconnects and processes.  Tenant is the id's
        ``<tenant>/<client>`` prefix (or ``"default"``)."""
        tenant, sep, _rest = external_id.partition("/")
        if not sep:
            tenant = "default"
        lane = self._hash_id(external_id) % self.n_lanes
        return tenant, lane, self.shard_of(lane)

    def lanes_of(self, handles) -> np.ndarray:
        """Vectorized handle → lane gather (the serving-path placement
        check reads this per sweep batch, ISSUE 19)."""
        return self.lane[np.asarray(handles, np.int64)]

    def shard_of(self, lane) -> np.ndarray:
        """Lane → WAL/engine shard bucket (contiguous lane slices, the
        EngineDurability layout)."""
        return (np.asarray(lane, np.int64) * self.n_shards
                // self.n_lanes).astype(np.int32)

    def _tenant_id(self, tenant: str) -> int:
        tid = self._tenant_ids.get(tenant)
        if tid is None:
            tid = len(self._tenant_ids)
            self._tenant_ids[tenant] = tid
        return tid

    @property
    def n_tenants(self) -> int:
        return max(1, len(self._tenant_ids))

    # -- connect -----------------------------------------------------------

    def connect(self, external_id: str) -> tuple:
        """Resolve (or create) the session for an external id.  Returns
        ``(handle, reconnected)``; a reconnect bumps the session epoch
        but keeps placement AND the dedup watermark — resends of
        in-flight commands from before the drop hit the same at-most-
        once gate (the reconnect contract the tests pin)."""
        h = self._ids.get(external_id)
        if h is not None:
            self.epoch[h] += 1
            return h, True
        tenant, lane, _shard = self.place(external_id)
        h = self.n_sessions
        self._ensure(h + 1)
        self.n_sessions = h + 1
        self.lane[h] = lane
        self.tenant[h] = self._tenant_id(tenant)
        self.epoch[h] = 1
        self._ids[external_id] = h
        return h, False

    def connect_bulk(self, n: int, *, key: str = "bulk",
                     tenants: int = 1) -> np.ndarray:
        """Connect ``n`` synthetic sessions (the simulation-scale path):
        placement is a vectorized splitmix64 over ``(seed, key, i)``,
        tenants assigned round-robin over ``tenants`` synthetic tenant
        names.  Calling again with the same key returns the SAME
        handles with every epoch bumped (a fleet-wide reconnect)."""
        got = self._bulk.get(key)
        if got is not None:
            base, m = got
            if m != n:
                raise ValueError(f"bulk key {key!r} has {m} sessions")
            h = np.arange(base, base + n, dtype=np.int64)
            self.epoch[h] += 1
            return h
        base = self.n_sessions
        self._ensure(base + n)
        self.n_sessions = base + n
        h = np.arange(base, base + n, dtype=np.int64)
        mix = _mix64(np.uint64(zlib.crc32(f"{self.seed}:{key}".encode()))
                     + h.astype(np.uint64))
        self.lane[h] = (mix % np.uint64(self.n_lanes)).astype(np.int32)
        # round-robin over the REGISTERED bulk tenant ids: with named
        # tenants already in the table, raw modulo values would alias
        # them and charge the fleet to an innocent tenant's quota
        tids = np.array([self._tenant_id(f"bulk-{t}")
                         for t in range(max(1, tenants))], np.int32)
        self.tenant[h] = tids[h % max(1, tenants)]
        self.epoch[h] = 1
        self._bulk[key] = (base, n)
        return h

    # -- seqno dedup (vectorized; the at-most-once gate) -------------------

    def fresh(self, handles: np.ndarray, seqnos: np.ndarray) -> np.ndarray:
        """Boolean mask of rows never seen before: seqno above the
        session's placed watermark AND first occurrence of its
        ``(handle, seqno)`` pair within this batch.  Pure — the
        watermark only advances via :meth:`mark` for rows that were
        actually placed, so a rejected/shed row's resend stays fresh."""
        handles = np.asarray(handles, np.int64)
        seqnos = np.asarray(seqnos, np.int64)
        fresh = seqnos > self.last_seqno[handles]
        if len(handles) > 1:
            # first-occurrence uniqueness on the FULL (handle, seqno)
            # pair: a resend duplicated WITHIN one batch must not pass
            # the watermark check twice.  Lexsort + neighbor compare —
            # a packed single-key form would truncate one component
            # and silently DUP two distinct rows that collide
            n = len(handles)
            order = np.lexsort((seqnos, handles))
            sh, ss = handles[order], seqnos[order]
            dup_sorted = np.zeros(n, bool)
            dup_sorted[1:] = (sh[1:] == sh[:-1]) & (ss[1:] == ss[:-1])
            mask = np.empty(n, bool)
            mask[order] = ~dup_sorted
            fresh &= mask
        return fresh

    def mark(self, handles: np.ndarray, seqnos: np.ndarray) -> None:
        """Advance the placed watermark for rows the coalescer accepted
        (call with the PLACED subset only)."""
        np.maximum.at(self.last_seqno, np.asarray(handles, np.int64),
                      np.asarray(seqnos, np.int64))

    def next_seqnos(self, handles: np.ndarray) -> np.ndarray:
        """Convenience for tests/demos: mint the next seqnos a well-
        behaved client would send (watermark + within-batch rank + 1).
        Real clients own their seqno counters (the FifoClient model)."""
        from .coalesce import batch_rank
        handles = np.asarray(handles, np.int64)
        return self.last_seqno[handles] + batch_rank(handles) + 1

    def trace_ctx(self, external_id: str, seqno: int) -> str:
        """Deterministic ingress trace id (the PR 7 contract): stable
        across resends, so a retried command's duplicate records under
        the same id — mirrors FifoClient._trace_ctx."""
        return f"{external_id}/{seqno}"

    def overview(self) -> dict:
        return {
            "sessions": int(self.n_sessions),
            "tenants": len(self._tenant_ids),
            "named_sessions": len(self._ids),
            "n_lanes": self.n_lanes,
            "n_shards": self.n_shards,
        }


def default_directory(engine, **kw) -> SessionDirectory:
    """Directory sized for an engine: lanes from the engine, shard
    count from its durability bridge when attached."""
    dur = getattr(engine, "_dur", None)
    n_shards = getattr(dur, "wal_shards", 1) if dur is not None else 1
    kw.setdefault("n_shards", n_shards)
    return SessionDirectory(engine.n_lanes, **kw)
