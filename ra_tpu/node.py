"""Node runtime: the effect-executing shell around pure cores.

The reference runs one gen_statem per member (ra_server_proc.erl) under a
per-system supervision tree (ra_system_sup.erl:25-43).  The TPU-native
inversion keeps *control flow on the host, state in cores*: a RaNode is a
single event-loop thread cooperatively scheduling all member shells it
hosts — the natural collector that forms device batches for the lane
engine, and the 'node' unit for the classic (oracle) deployment.

Responsibilities mirrored from ra_server_proc.erl:
* effect execution (send_rpc, vote fan-out, replies, timers, machine
  effects — handle_effect :1317-1566)
* election timers with randomized durations (:1638-1657)
* periodic tick (ra_server:tick + machine tick)
* snapshot send tasks (:1446-1488) — chunked InstallSnapshotRpc casts
* monitors/down routing (simplified; full failure detector in transport)
* registration in the node directory + leaderboard updates

Transport is pluggable: LocalRouter routes in-process between RaNodes
(the ct_slave-style multi-node tests run this way); ra_tpu.transport.tcp
carries the same six message families across OS processes.
"""
from __future__ import annotations

import logging
import random
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Optional

from .blackbox import RECORDER, record
from .core.server import RaServer
from .core.types import (
    AuxCommandEvent,
    AuxEffect,
    CancelElectionTimeout,
    Checkpoint,
    CommandEvent,
    CommandsEvent,
    ConsistentQueryEvent,
    Demonitor,
    ElectionTimeout,
    ErrorResult,
    GarbageCollection,
    InstallSnapshotRpc,
    LogReadEffect,
    ModCall,
    Monitor,
    NODE_SCOPE,
    NodeControlEvent,
    Notify,
    Priority,
    PromoteCheckpoint,
    RaftState,
    RecordLeader,
    ReleaseCursor,
    SNAPSHOT_TUNABLE_KEYS,
    Reply,
    ReplyMode,
    SendMsg,
    SendRpc,
    SendSnapshot,
    SendVoteRequests,
    ServerConfig,
    ServerId,
    StartElectionTimeout,
    TickEvent,
    TimerEffect,
    UserCommand,
)
from .log.memory import MemoryLog
from .log.wal import WalDown

logger = logging.getLogger("ra_tpu")

#: multipliers applied to election_timeout_ms per timeout kind
#: (ra_server_proc.erl:1638-1657: really_short/short/medium/long)
_TIMEOUT_KINDS = {
    "really_short": (0.05, 0.15),
    "short": (0.3, 0.6),
    "medium": (1.0, 1.6),
    "long": (2.0, 3.2),
}

#: low-priority commands buffered before a {commands, ...} flush — the
#: reference's ?FLUSH_COMMANDS_SIZE (ra_server.hrl:11) default; the
#: per-server ``ServerConfig.command_flush_size`` knob overrides it
#: (ISSUE 13: the batch-native append path amortizes one lock + one
#: WAL fan-in submit over the whole flush, so deeper flushes are
#: strictly cheaper until the AER frame bounds bite)
FLUSH_COMMANDS_SIZE = 16


class Future:
    """Reply slot handed to blocking client calls."""

    __slots__ = ("_event", "value")

    def __init__(self) -> None:
        self._event = threading.Event()
        self.value: Any = None

    def set(self, value: Any) -> None:
        self.value = value
        self._event.set()

    def wait(self, timeout: Optional[float]) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("ra: command timed out")
        return self.value


class LocalRouter:
    """In-process transport fabric: ServerId.node -> RaNode."""

    def __init__(self) -> None:
        self.nodes: dict[str, "RaNode"] = {}
        self.lock = threading.Lock()
        # (src_node, dst_node) pairs currently blocked (nemesis partitions)
        self.blocked: set = set()

    def register(self, node: "RaNode") -> None:
        with self.lock:
            self.nodes[node.name] = node

    def unregister(self, node: "RaNode") -> None:
        with self.lock:
            self.nodes.pop(node.name, None)

    def send(self, src_node: str, to: ServerId, msg: Any) -> bool:
        """Nonblocking send; returns False when dropped (the noconnect/
        nosuspend semantics of ra_server_proc:send_rpc :1317-1341)."""
        if (src_node, to.node) in self.blocked:
            return False
        node = self.nodes.get(to.node)
        if node is None:
            return False
        return node.deliver(to, msg)

    def block(self, a: str, b: str) -> None:
        self.blocked.add((a, b))
        self.blocked.add((b, a))

    def heal(self) -> None:
        self.blocked.clear()

    def remote_call(self, target: ServerId, make_event) -> Optional["Future"]:
        """Cross-host client call; the in-process router has no remote
        reach (TcpRouter overrides)."""
        return None

    def reply_remote(self, handle: tuple, msg: Any) -> None:
        """Route a reply for a remote call handle (TcpRouter overrides)."""
        return None

    def notify_remote(self, handle: tuple, correlations: Any) -> None:
        """Route an applied-notification for a remote-notify handle
        (TcpRouter overrides)."""
        return None


#: default in-process fabric (tests may build private ones)
DEFAULT_ROUTER = LocalRouter()


class ServerShell:
    """Per-member shell state owned by a RaNode."""

    def __init__(self, server: RaServer, node: "RaNode") -> None:
        self.server = server
        self.node = node
        self.inbox: deque = deque()
        self.low_queue: deque = deque()  # low-priority commands awaiting flush
        # pids the machine/aux asked to monitor, by component
        # (ra_monitors.erl per-component multiplexing)
        self.machine_monitors: set = set()
        self.aux_monitors: set = set()
        #: machine {timer, Name, T} effects: name -> (deadline, msg)
        #: (ra_server_proc.erl:1549-1550; expiry appends a '{timeout,
        #: Name}' command on the leader, :556-560)
        self.machine_timers: dict = {}
        self.election_deadline: Optional[float] = None
        self.tick_deadline: float = time.monotonic() + \
            server.cfg.tick_interval_ms / 1000.0
        #: per-shell flush depth (ServerConfig.command_flush_size,
        #: falling back to the reference's 16) — cached here so the
        #: poll loop pays one attribute read, not a config chain
        self.flush_size = getattr(server.cfg, "command_flush_size", 0) \
            or FLUSH_COMMANDS_SIZE
        self.stopped = False

    @property
    def sid(self) -> ServerId:
        return self.server.id


class RaNode:
    """One 'node': hosts many cluster members on one event-loop thread."""

    def __init__(self, name: str, router: Optional[LocalRouter] = None,
                 log_factory: Optional[Callable] = None,
                 system: Any = None) -> None:
        self.name = name
        self.router = router or DEFAULT_ROUTER
        #: owning RaSystem (optional): enables control-plane recovery of
        #: members from the on-disk directory (recover_config role)
        self.system = system
        if log_factory is None and system is not None:
            log_factory = system.log_factory
        self.log_factory = log_factory or (lambda cfg: MemoryLog())
        from .metrics import Counters, Leaderboard
        self.counters = Counters()
        self.leaderboard_tab = Leaderboard()
        self.shells: dict[str, ServerShell] = {}   # by server name
        self.directory: dict[str, ServerConfig] = {}  # uid -> config
        self.leaderboard: dict[str, tuple] = {}    # cluster -> (leader, members)
        self._crash_times: dict[str, list] = {}    # supervised restarts
        #: pluggable control verbs (ISSUE 19): op name -> fn(args) ->
        #: result, consulted before the unknown-op fallback.  The
        #: cross-host placement fabric registers its engine-host verbs
        #: (host_status/host_adopt/...) here so they ride the SAME
        #: reliable-RPC control plane as the builtin lifecycle ops —
        #: dedup cache, deadline propagation and all.
        self.control_ops: dict[str, Callable] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"ra-node-{name}")
        self.router.register(self)
        self._thread.start()

    # -- lifecycle ----------------------------------------------------------

    def start_server(self, config: ServerConfig) -> ServerId:
        """Init + recover a member on this node (ra:start_server)."""
        assert config.server_id.node == self.name
        log = self.log_factory(config)
        server = RaServer(config, log)
        server.recover()
        shell = ServerShell(server, self)
        self.counters.new(config.uid)
        with self._lock:
            self.shells[config.server_id.name] = shell
            self.directory[config.uid] = config
        # new servers get an election timeout so a fresh cluster elects
        self._arm_election(shell, "medium")
        # co-hosted siblings learn the member is back: a leader that saw
        # the kill's DownEvent resumes replication (the up edge the
        # transport detector provides for cross-node peers — without it
        # a restarted behind-the-tail follower wedges, ISSUE 13)
        self._notify_up(config.server_id)
        self._wake.set()
        return config.server_id

    def stop_server(self, name: str) -> None:
        # NB: the log is NOT closed here — it is owned by its factory/system
        # and survives server restarts (storage identity vs process
        # identity, ra_log_wal.erl:44-51)
        with self._lock:
            shell = self.shells.pop(name, None)
        if shell is not None:
            shell.stopped = True
            # clean stop: persist the lazy apply watermark so recovery
            # dedups every effect the subscriber already saw (a kill
            # keeps the crash semantics — see kill_server)
            try:
                shell.server.flush_applied_watermark()
            except Exception:  # noqa: BLE001 — a closed log must not block stop
                logger.exception("ra_tpu node %s: apply-watermark flush "
                                 "on stop of %s failed", self.name, name)

    #: supervised-restart intensity: allow this many crashes within the
    #: period before giving up (the ra_server_sup transient strategy —
    #: intensity 2, period 5s; ra_server_sup.erl)
    RESTART_INTENSITY = 2
    RESTART_PERIOD_S = 5.0

    def _maybe_restart(self, sid: ServerId) -> bool:
        """Supervised restart of a crashed member over its surviving log
        (storage identity outlives the process, ra_log_wal.erl:44-51).
        Returns False once the crash intensity is exceeded — the member
        stays down and peers get the DOWN signal, exactly like an OTP
        supervisor giving up on a child."""
        now = time.monotonic()
        times = self._crash_times.setdefault(sid.name, [])
        times[:] = [t for t in times if now - t < self.RESTART_PERIOD_S]
        times.append(now)
        if len(times) > self.RESTART_INTENSITY:
            logger.error(
                "ra_tpu node %s: server %s exceeded restart intensity "
                "(%d in %.0fs); giving up", self.name, sid,
                self.RESTART_INTENSITY, self.RESTART_PERIOD_S)
            record("sup.giveup", plane="server", node=self.name,
                   server=str(sid))
            RECORDER.dump(
                "server_restart_giveup",
                what=f"server crash intensity exceeded "
                     f"({self.RESTART_INTENSITY} in "
                     f"{self.RESTART_PERIOD_S:.0f}s)",
                where=str(sid),
                data_dir=getattr(self.system, "data_dir", None))
            return False
        cfg = self._config_for(sid.name)
        if cfg is None:
            return False
        # only restart over a log with DURABLE identity: a fresh
        # in-memory log forgets term/voted_for, and a restarted member
        # could then double-vote in a term it already voted in (the
        # amnesia hazard forget_server documents)
        probe = self.log_factory(cfg)
        if not getattr(probe, "durable", False):
            logger.warning(
                "ra_tpu node %s: not auto-restarting %s — its log "
                "factory has no durable identity", self.name, sid)
            return False
        try:
            self.start_server(cfg)
        except Exception:
            logger.exception("ra_tpu node %s: restart of %s failed",
                             self.name, sid)
            return False
        logger.warning("ra_tpu node %s: server %s restarted after crash",
                       self.name, sid)
        record("sup.restart", plane="server", node=self.name,
               server=str(sid))
        return True

    def _config_for(self, name: str):
        with self._lock:
            cfg = None
            for c in self.directory.values():
                if c.server_id.name == name:
                    cfg = c
            return cfg

    #: config keys a restart may modify — the reference's
    #: ?MUTABLE_CONFIG_KEYS whitelist (ra_server_sup_sup.erl:12-20);
    #: identity/consensus-bearing keys (uid, members, machine,
    #: election timeout) are immutable across restarts
    MUTABLE_CONFIG_KEYS = frozenset({
        "cluster_name", "broadcast_time_ms", "tick_interval_ms",
        "install_snap_rpc_timeout_ms", "await_condition_timeout_ms",
        "max_pipeline_count", "friendly_name",
    })

    def _merge_mutable(self, cfg: ServerConfig,
                       mutable: Optional[dict]) -> ServerConfig:
        if not mutable:
            return cfg
        from dataclasses import replace as _dc_replace
        accepted = {k: v for k, v in mutable.items()
                    if k in self.MUTABLE_CONFIG_KEYS}
        dropped = set(mutable) - set(accepted)
        if dropped:
            logger.warning("ra_tpu node %s: restart config keys %s are "
                           "not mutable; ignored", self.name,
                           sorted(dropped))
        return _dc_replace(cfg, **accepted) if accepted else cfg

    def restart_server(self, name: str,
                       mutable: Optional[dict] = None) -> ServerId:
        """Restart from the persisted log (ra:restart_server, §3.4).
        ``mutable`` merges whitelisted config keys into the recovered
        config (config_modification_at_restart, ra_server_sup_sup.erl:
        80-103).  Falls back to the system directory's persisted
        snapshot when the in-memory config is gone (node process
        restarted) — the same recover_config path the control plane
        takes."""
        cfg = self._config_for(name)
        if cfg is None:
            snap = self._disk_snapshot_for(name)
            if snap is None:
                raise RuntimeError(f"restart_server: unknown server "
                                   f"{name} (not_found)")
            cfg = self._config_from_snapshot(snap)
        cfg = self._merge_mutable(cfg, mutable)
        self.stop_server(name)
        return self.start_server(cfg)

    def kill_server(self, name: str) -> None:
        """Abrupt stop without log close (crash simulation)."""
        with self._lock:
            shell = self.shells.pop(name, None)
        if shell is not None:
            shell.stopped = True
            self._notify_down(shell.sid)

    def forget_server(self, name: str) -> None:
        """Drop a member's config from the node directory so
        restart_server can no longer recreate it — the node-side half of
        force_delete (a deleted member resurrected over an empty log
        would rejoin with amnesia under its old identity and could vote
        unsafely)."""
        with self._lock:
            for uid, c in list(self.directory.items()):
                if c.server_id.name == name:
                    del self.directory[uid]

    def _notify_down(self, dead: ServerId) -> None:
        """Local process-monitor role (ra_monitors): co-hosted members
        learn immediately that a sibling died — followers of a dead leader
        arm a really_short election (ra_server_proc.erl:760-788)."""
        from .core.types import DownEvent
        for other in list(self.shells.values()):
            if not other.stopped:
                other.inbox.append(DownEvent(dead))
        self._wake.set()

    def _notify_up(self, sid: ServerId) -> None:
        """The restart twin of _notify_down: co-hosted siblings (most
        importantly a leader that marked this peer DISCONNECTED at the
        kill's DownEvent) resume treating it as reachable."""
        from .core.types import UpEvent
        for other in list(self.shells.values()):
            if not other.stopped and other.sid != sid:
                other.inbox.append(UpEvent(sid))

    def process_down(self, pid: Any, reason: Any = "normal") -> None:
        """Report death of a machine-monitored external process.  Members
        monitoring ``pid`` get a ``("down", pid, reason)`` builtin command
        (ra_server:handle_down machine branch).  In practice only the
        current leader holds machine monitors — followers filter machine
        Monitor effects and a demoted leader clears its set — so exactly
        one member appends the command."""
        for shell in list(self.shells.values()):
            if shell.stopped:
                continue
            if pid in shell.machine_monitors:
                shell.machine_monitors.discard(pid)
                shell.inbox.append(CommandEvent(
                    UserCommand(("down", pid, reason)), from_=None))
            if pid in shell.aux_monitors:
                # aux branch of handle_down (ra_server.erl): the aux
                # handler sees the down directly, no log entry.  Routed
                # through the inbox so the (unsynchronized) RaServer is
                # only ever touched by the event-loop thread.
                shell.aux_monitors.discard(pid)
                shell.inbox.append(AuxCommandEvent(("down", pid, reason)))
        self._wake.set()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=5)
        # clean node shutdown: persist every live server's lazy apply
        # watermark (the event loop is joined, nothing applies anymore)
        for shell in list(self.shells.values()):
            try:
                shell.server.flush_applied_watermark()
            except Exception:  # noqa: BLE001 — a closed log must not block stop
                logger.exception("ra_tpu node %s: apply-watermark flush "
                                 "on node stop failed", self.name)
        self.router.unregister(self)

    # -- ingress ------------------------------------------------------------

    def deliver(self, to: ServerId, msg: Any) -> bool:
        if to.name == NODE_SCOPE:
            # node-lifecycle RPC (ra_server_sup_sup's rpc:call target):
            # runs on its own thread — start/restart recover logs and
            # must never block the transport's recv loop
            if not isinstance(msg, NodeControlEvent):
                return False
            threading.Thread(target=self._handle_control, args=(msg,),
                             daemon=True,
                             name=f"ra-node-ctrl-{self.name}").start()
            return True
        shell = self.shells.get(to.name)
        if shell is None or shell.stopped:
            return False
        shell.inbox.append(msg)
        if not self._wake.is_set():  # see submit_command
            self._wake.set()
        return True

    # -- control plane (cross-node lifecycle, ra_server_sup_sup.erl:42-130)

    def _handle_control(self, event: NodeControlEvent) -> None:
        from .core.types import ErrorResult
        op, args = event.op, dict(event.args)
        try:
            if op == "ping":
                result: Any = ("pong", self.name)
            elif op == "start_server":
                result = self._control_start(args)
            elif op == "restart_server":
                result = self._control_restart(args)
            elif op == "stop_server":
                self.stop_server(args["name"])
                result = "ok"
            elif op == "force_delete_server":
                result = self._control_force_delete(args)
            elif op == "classic_stats":
                # read-only batching-health probe (ISSUE 13): lets a
                # bench/ops client collect the leader's CLASSIC_FIELDS
                # from a remote worker process over the control plane
                result = self.classic_stats()
            elif op in self.control_ops:
                result = self.control_ops[op](args)
            else:
                result = ErrorResult(f"unknown_control_op:{op}", None)
        except Exception as exc:  # noqa: BLE001 — errors travel to caller
            logger.exception("ra_tpu node %s: control op %s failed",
                             self.name, op)
            result = ErrorResult(f"control_failed: {exc!r}"[:400], None)
        to = event.from_
        if to is None:
            return
        if isinstance(to, Future):
            to.set(result)
        elif isinstance(to, tuple) and to and to[0] == "rcall":
            self.router.reply_remote(to, result)
        elif callable(to):
            to(result)

    def _control_start(self, args: dict) -> Any:
        """start_server_rpc (ra_server_sup_sup.erl:56-77): build the
        member from a picklable config snapshot + machine spec.  A name
        that is RUNNING is already_started; a name with existing durable
        (or node-directory) state is not_new — recreating it under a
        fresh uid would orphan its log and rejoin it with amnesia (the
        double-vote hazard forget_server documents); the caller wants
        restart_server."""
        from .core.types import ErrorResult
        cfg = self._config_from_snapshot(args["config"])
        name = cfg.server_id.name
        shell = self.shells.get(name)
        if shell is not None and not shell.stopped:
            return ErrorResult("already_started", None)
        if self._config_for(name) is not None or \
                (self.system is not None and
                 self.system.directory.where_is(name) is not None):
            return ErrorResult("not_new", None)
        return self.start_server(cfg)

    def _control_restart(self, args: dict) -> Any:
        """restart_server_rpc: prefer the in-memory config; fall back to
        the system directory's persisted snapshot (recover_config,
        ra_server_sup_sup.erl:80-103)."""
        from .core.types import ErrorResult
        try:
            return self.restart_server(args["name"],
                                       mutable=args.get("mutable"))
        except RuntimeError:
            return ErrorResult("not_found", None)

    def _control_force_delete(self, args: dict) -> Any:
        name = args["name"]
        shell = self.shells.get(name)
        uid = shell.server.cfg.uid if shell is not None else None
        if uid is None and self.system is not None:
            uid = self.system.directory.where_is(name)
        self.kill_server(name)
        self.forget_server(name)
        self.wipe_member_footprint(uid, self.system)
        return "ok"

    @staticmethod
    def wipe_member_footprint(uid, system) -> None:
        """The force-delete footprint wipe shared by the control plane
        and the api layer: durable data via ``system`` when present
        (delete_server_data also drops the uid-scoped machine_ets side
        tables), else the side tables alone — a deleted member must
        leave nothing behind either way."""
        if uid is None:
            return
        if system is not None:
            system.delete_server_data(uid)
        else:
            from . import machine_ets
            machine_ets.drop_scope(uid)

    def _disk_snapshot_for(self, name: str) -> Optional[dict]:
        if self.system is None:
            return None
        directory = self.system.directory
        uid = directory.where_is(name)
        if uid is None:
            return None
        snap = dict(directory.config_of(uid) or {})
        if not snap:
            return None
        snap.setdefault("uid", uid)
        return snap

    def _config_from_snapshot(self, snap: dict) -> ServerConfig:
        from .core.types import Membership
        from .machines import resolve_machine
        machine = resolve_machine(snap["machine_spec"])
        return ServerConfig(
            server_id=ServerId(*snap["server_id"]),
            uid=snap["uid"],
            cluster_name=snap["cluster_name"],
            initial_members=tuple(ServerId(*m)
                                  for m in snap["initial_members"]),
            machine=machine,
            election_timeout_ms=snap.get("election_timeout_ms", 100),
            tick_interval_ms=snap.get("tick_interval_ms", 100),
            broadcast_time_ms=snap.get("broadcast_time_ms", 50),
            membership=Membership(snap.get("membership", "voter")),
            system_name=snap.get("system_name", "default"),
            **{k: snap[k] for k in SNAPSHOT_TUNABLE_KEYS
               if k in snap},
        )

    def submit(self, name: str, event: Any) -> bool:
        shell = self.shells.get(name)
        if shell is None or shell.stopped:
            return False
        shell.inbox.append(event)
        self._wake.set()
        return True

    def submit_command(self, name: str, command: Any, from_: Any,
                       priority: Priority = Priority.NORMAL) -> bool:
        """Normal commands go straight in; low-priority commands buffer and
        flush as {commands, Batch} (ra_server_proc.erl:458-513)."""
        shell = self.shells.get(name)
        if shell is None or shell.stopped:
            return False
        if priority == Priority.LOW:
            # client threads only append; batches are formed exclusively by
            # the event-loop thread (_poll_shell) so the deque is never
            # iterated concurrently with appends
            shell.low_queue.append(command)
        else:
            shell.inbox.append(CommandEvent(command, from_=from_))
        # set-when-clear guard: at pipelined rates the flag is almost
        # always already set (the loop only clears it when idle), and
        # Event.set() takes a lock this path should not pay per command
        if not self._wake.is_set():
            self._wake.set()
        return True

    def submit_commands(self, name: str, commands: list,
                        priority: Priority = Priority.LOW) -> bool:
        """Burst submit: one queue extend + one wake check for the whole
        batch instead of a per-command submit_command round."""
        shell = self.shells.get(name)
        if shell is None or shell.stopped:
            return False
        if priority == Priority.LOW:
            shell.low_queue.extend(commands)
        else:
            shell.inbox.extend(CommandEvent(c, from_=None)
                               for c in commands)
        if not self._wake.is_set():
            self._wake.set()
        return True

    # -- event loop ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop:
            busy = False
            now = time.monotonic()
            for shell in list(self.shells.values()):
                if shell.stopped:
                    continue
                try:
                    busy |= self._poll_shell(shell, now)
                except WalDown:
                    # infra fault, not a server fault: park the core in
                    # await_condition(wal_down) and keep the shell alive —
                    # the system's WAL supervisor restarts the WAL and the
                    # log surfaces a WalUpEvent to resume
                    # (ra_server.erl:538-554)
                    logger.warning(
                        "ra_tpu node %s: wal down; server %s parked",
                        self.name, shell.sid)
                    self._execute(shell, shell.server.enter_wal_down())
                    busy = True
                except Exception as exc:
                    logger.exception("ra_tpu node %s: server %s crashed",
                                     self.name, shell.sid)
                    # unhandled server crash: a black-box trigger — the
                    # bundle captures what the whole node was doing at
                    # the moment this core died
                    record("srv.crash", node=self.name,
                           server=str(shell.sid), error=repr(exc)[:200])
                    RECORDER.dump(
                        "server_crash", what=repr(exc)[:200],
                        where=str(shell.sid),
                        data_dir=getattr(self.system, "data_dir", None))
                    shell.stopped = True
                    # remove so clients get fast noproc instead of
                    # blocking on a dead inbox / stale leader state
                    with self._lock:
                        self.shells.pop(shell.sid.name, None)
                    # peers always learn about the dead incarnation
                    # (monitors fire even when a supervisor restarts
                    # the child, ra_server_proc.erl:760-788)
                    self._notify_down(shell.sid)
                    if self._maybe_restart(shell.sid):
                        busy = True
            if not busy:
                self._wake.wait(timeout=0.005)
                self._wake.clear()

    def _poll_shell(self, shell: ServerShell, now: float) -> bool:
        busy = False
        # async WAL confirms arrive independently of inbox traffic; route
        # through _handle so terminal states are honored
        for evt in shell.server.log.take_events():
            self._handle(shell, evt)
            busy = True
            if shell.stopped:
                return busy
        # timers
        if shell.election_deadline is not None and \
                now >= shell.election_deadline:
            shell.election_deadline = None
            self._handle(shell, ElectionTimeout())
            busy = True
        # machine timers: on expiry the LEADER routes a '{timeout, Name}'
        # command through consensus so every replica's machine sees it
        # (ra_server_proc.erl:556-560); non-leaders drop the expiry — the
        # lane leader owns machine time
        if shell.machine_timers:
            due = [n for n, (dl, _m) in shell.machine_timers.items()
                   if now >= dl]
            for name in due:
                _dl, msg = shell.machine_timers.pop(name)
                if shell.server.raft_state == RaftState.LEADER:
                    data = msg if msg is not None else ("timeout", name)
                    self._handle(shell, CommandEvent(
                        UserCommand(data, reply_mode=ReplyMode.NOREPLY)))
                    busy = True
        if now >= shell.tick_deadline:
            shell.tick_deadline = now + \
                shell.server.cfg.tick_interval_ms / 1000.0
            self._handle(shell, TickEvent())
            busy = True
        # flush low-priority commands in batches of FLUSH_COMMANDS_SIZE
        # (ra_server_proc.erl:458-513); only this thread removes items.
        # The reference drains the whole backlog 16 at a time via a
        # flush_commands self-message loop interleaved with the mailbox
        # — mirror that by forming several batches per poll (bounded so
        # RPC/confirm traffic still interleaves); one batch per poll
        # under-drains deep pipelines (measured 1.4x classic-bench
        # throughput moving 1 -> 16 batches per poll)
        batches = 0
        while shell.low_queue and batches < 16:
            n = min(len(shell.low_queue), shell.flush_size)
            batch = tuple(shell.low_queue.popleft() for _ in range(n))
            shell.inbox.append(CommandsEvent(batch))
            batches += 1
        # messages (bounded batch per poll to stay fair)
        for _ in range(256):
            if not shell.inbox:
                break
            self._handle(shell, shell.inbox.popleft())
            busy = True
        return busy

    def _handle(self, shell: ServerShell, event: Any) -> None:
        server = shell.server
        c = self.counters
        key = server.cfg.uid
        c.incr(key, "msgs_processed")
        if isinstance(event, CommandEvent):
            c.incr(key, "commands")
        elif isinstance(event, CommandsEvent):
            c.incr(key, "command_flushes")
            c.incr(key, "commands", len(event.commands))
        elif isinstance(event, ConsistentQueryEvent):
            c.incr(key, "consistent_queries")
        else:
            from .core.types import (AppendEntriesReply, AppendEntriesRpc,
                                     AuxCommandEvent)
            if isinstance(event, AppendEntriesRpc):
                c.incr(key, "aer_received_follower")
                if not event.entries:
                    c.incr(key, "aer_received_follower_empty")
            elif isinstance(event, AppendEntriesReply):
                c.incr(key, "aer_replies_success" if event.success
                       else "aer_replies_fail")
            elif isinstance(event, AuxCommandEvent):
                c.incr(key, "aux_commands")
        state_before = server.raft_state
        effects = server.handle(event)
        state_after = server.raft_state
        if state_after != state_before:
            if state_before == RaftState.LEADER:
                # machine monitors are a leader responsibility; the new
                # leader re-establishes them via state_enter(leader), and a
                # stale set here would make this ex-leader relay duplicate
                # ('down', ...) commands
                shell.machine_monitors.clear()
            if state_after == RaftState.PRE_VOTE:
                c.incr(key, "pre_vote_elections")
            elif state_after == RaftState.CANDIDATE:
                c.incr(key, "elections")
            # NB: no snapshot_installed increment here — that field is
            # LOG_FIELDS, owned and counted by the log facade on actual
            # container install; an incr against this SERVER_FIELDS
            # group was silently dropped before telemetry_dropped
            # existed and would now (correctly) flag the mismatch
        self._execute(shell, effects)
        # drain WAL confirms produced by this event
        for evt in server.log.take_events():
            self._execute(shell, server.handle(evt))
        if server.raft_state in (RaftState.STOP,
                                 RaftState.DELETE_AND_TERMINATE):
            # terminal states: leave the cluster / cluster deleted
            # (ra_server_proc terminating_leader/_follower)
            shell.stopped = True
            with self._lock:
                self.shells.pop(shell.sid.name, None)

    # -- effect executor (ra_server_proc:handle_effect :1317-1566) ----------

    def _execute(self, shell: ServerShell, effects: list) -> None:
        server = shell.server
        for eff in effects:
            if isinstance(eff, SendRpc):
                self.counters.incr(server.cfg.uid, "rpcs_sent")
                self.counters.incr(server.cfg.uid, "msgs_sent")
                ok = self.router.send(self.name, eff.to, eff.msg)
                if not ok:
                    # dropped send: pipeline catch-up recovers; counted
                    # like the reference (ra.hrl:329-330)
                    self.counters.incr(server.cfg.uid, "dropped_sends")
            elif isinstance(eff, SendVoteRequests):
                n = len(eff.requests)
                self.counters.incr(server.cfg.uid, "rpcs_sent", n)
                self.counters.incr(server.cfg.uid, "msgs_sent", n)
                for to, msg in eff.requests:
                    self.router.send(self.name, to, msg)
            elif isinstance(eff, Reply):
                # member-replier replies execute ONLY on the named
                # member; everyone else (including the leader) skips —
                # can_execute_locally (ra_server_proc.erl)
                rep = getattr(eff, "replier", "leader")
                if rep != "leader" and not (
                        isinstance(rep, tuple) and len(rep) == 2 and
                        rep[0] == "member" and rep[1] == server.id):
                    continue
                if isinstance(eff.to, Future):
                    eff.to.set(eff.msg)
                elif isinstance(eff.to, tuple) and eff.to and \
                        eff.to[0] == "rcall":
                    self.router.reply_remote(eff.to, eff.msg)
                elif callable(eff.to):
                    eff.to(eff.msg)
            elif isinstance(eff, Notify):
                if isinstance(eff.to, Future):
                    eff.to.set(eff.correlations)
                elif isinstance(eff.to, tuple) and eff.to and \
                        eff.to[0] == "rnotify":
                    self.router.notify_remote(eff.to, eff.correlations)
                elif callable(eff.to):
                    eff.to(eff.correlations)
            elif isinstance(eff, StartElectionTimeout):
                self._arm_election(shell, eff.kind)
            elif isinstance(eff, CancelElectionTimeout):
                shell.election_deadline = None
            elif isinstance(eff, (ReleaseCursor, Checkpoint,
                                  PromoteCheckpoint)):
                if isinstance(eff, ReleaseCursor):
                    self.counters.incr(server.cfg.uid, "release_cursors")
                elif isinstance(eff, Checkpoint):
                    self.counters.incr(server.cfg.uid, "checkpoints")
                self._execute(shell, server.handle_machine_effect(eff))
            elif isinstance(eff, SendSnapshot):
                self._send_snapshot(shell, eff)
            elif isinstance(eff, RecordLeader):
                self.leaderboard[eff.cluster_name] = (eff.leader, eff.members)
                self.leaderboard_tab.record(eff.cluster_name, eff.leader,
                                            eff.members)
            elif isinstance(eff, SendMsg):
                self.counters.incr(server.cfg.uid, "send_msg_effects_sent")
                if isinstance(eff.to, Future):
                    eff.to.set(eff.msg)
                elif callable(eff.to):
                    eff.to(eff.msg)
                elif isinstance(eff.to, ServerId):
                    self.counters.incr(server.cfg.uid, "msgs_sent")
                    self.router.send(self.name, eff.to, eff.msg)
            elif isinstance(eff, ModCall):
                try:
                    eff.fn(*eff.args)
                except Exception:
                    logger.exception("mod_call effect failed")
            elif isinstance(eff, LogReadEffect):
                # bare form runs on every member; {local, Node} targets
                # one node (ra_server_proc.erl:1369-1397)
                if eff.local is None or eff.local == self.name:
                    entries = server.log.sparse_read(eff.indexes)
                    try:
                        follow_up = eff.fn(entries)
                        # a fn may return follow-up EFFECTS (reference
                        # recursion); anything non-iterable is treated
                        # as no effects, not a crash
                        follow_up = list(follow_up) if \
                            isinstance(follow_up, (list, tuple)) else []
                    except Exception:
                        logger.exception("log effect failed")
                        follow_up = []
                    if follow_up:
                        self._execute(shell, follow_up)
            elif isinstance(eff, AuxEffect):
                self._execute(shell, server.handle_aux("eval", eff.msg))
            elif isinstance(eff, Monitor):
                # per-component multiplexing (ra_monitors.erl:34-56):
                # machine monitors feed the machine a {down,..} command,
                # aux monitors feed handle_aux; node/peer monitoring is
                # subsumed by the transport failure detector
                if eff.kind == "process":
                    if eff.component == "machine":
                        shell.machine_monitors.add(eff.target)
                    elif eff.component == "aux":
                        shell.aux_monitors.add(eff.target)
            elif isinstance(eff, Demonitor):
                if eff.kind == "process":
                    if eff.component == "machine":
                        shell.machine_monitors.discard(eff.target)
                    elif eff.component == "aux":
                        shell.aux_monitors.discard(eff.target)
            elif isinstance(eff, GarbageCollection):
                self.counters.incr(server.cfg.uid, "forced_gcs")
            elif isinstance(eff, TimerEffect):
                # {timer, Name, T}: arm/cancel a named machine timer
                # (ra_server_proc.erl:1549-1550); ms=None cancels.
                # MACHINE CONTRACT: timers are local to this replica and
                # an expiry is routed through consensus only while it is
                # the leader (_poll_shell) — an expiry on a non-leader is
                # discarded, so a machine that must keep machine-time
                # alive across failover re-arms its timers in
                # state_enter(leader) (exactly the reference's posture:
                # the timeout command is leader-routed, ra_server_proc
                # .erl:556-560, and a deposed leader's pending timers
                # die with its leadership)
                if eff.ms is None:
                    shell.machine_timers.pop(eff.name, None)
                else:
                    shell.machine_timers[eff.name] = (
                        time.monotonic() + eff.ms / 1000.0, eff.msg)
            # unknown machine effects are ignored (forward compat)

    def _arm_election(self, shell: ServerShell, kind: str) -> None:
        lo, hi = _TIMEOUT_KINDS.get(kind, _TIMEOUT_KINDS["medium"])
        dur = shell.server.cfg.election_timeout_ms / 1000.0
        shell.election_deadline = time.monotonic() + random.uniform(
            lo * dur, hi * dur)

    def _send_snapshot(self, shell: ServerShell, eff: SendSnapshot) -> None:
        """Chunked snapshot send (spawned in ra, :1446-1488; inline here —
        memory-log snapshots are small; the durable log grows a thread)."""
        server = shell.server
        snap = server.log.snapshot()
        if snap is None:
            return
        self.counters.incr(server.cfg.uid, "snapshots_sent")
        meta, data = snap
        leader_id, term = eff.id_term
        # chunk boundaries come from the machine's snapshot module
        # (begin_read/read_chunk role, ra_snapshot.erl:129-143)
        chunks = list(server.log.snapshot_module.chunks(
            data, server.cfg.snapshot_chunk_size)) or [b""]
        for i, piece in enumerate(chunks):
            flag = "last" if i == len(chunks) - 1 else "next"
            self.counters.incr(server.cfg.uid, "msgs_sent")
            self.router.send(self.name, eff.to,
                             InstallSnapshotRpc(term=term,
                                                leader_id=leader_id,
                                                meta=meta,
                                                chunk_number=i + 1,
                                                chunk_flag=flag,
                                                data=piece,
                                                chunk_crc=zlib.crc32(piece),
                                                token=eff.token))

    # -- introspection -------------------------------------------------------

    def classic_stats(self) -> dict:
        """Replication-batching health across this node's members — the
        CLASSIC_FIELDS snapshot (ISSUE 13): AER batches sent, total
        entries they carried, entries/batch p50/p99/mean from the
        cores' bounded reservoirs.  ``records_per_fsync`` (the
        group-commit fan-in half of the pair) lives in ``Wal.stats()``
        — the embedding bench/Observatory stamps both side by side."""
        batches = 0
        entries = 0
        sizes: list = []
        for shell in list(self.shells.values()):
            srv = shell.server
            batches += srv.stats.get("aer_batches_sent", 0)
            entries += srv.stats.get("aer_batch_entries", 0)
            # the event-loop thread appends concurrently (maxlen'd, so
            # a full deque mutates on every append): copy into a FRESH
            # list with retries rather than crash a stats probe
            # mid-traffic (a partial extend must not duplicate)
            got: list = []
            for _ in range(4):
                try:
                    got = list(srv._aer_batch_sizes)
                    break
                except RuntimeError:
                    got = []
            sizes.extend(got)
        sizes.sort()
        n = len(sizes)
        # encode share (ISSUE 18): co-hosted members fan into ONE wal
        # carrying the system-wide phase accumulator — the first shell
        # that reaches it answers for the node
        enc_pct = -1.0
        for shell in list(self.shells.values()):
            ph = getattr(getattr(shell.server.log, "wal", None),
                         "phases", None)
            if ph is not None:
                enc_pct = ph.encode_share_pct()
                break
        return {
            "aer_batches_sent": batches,
            "aer_batch_entries": entries,
            "entries_per_batch_mean":
                round(entries / batches, 2) if batches else -1.0,
            "entries_per_batch_p50": sizes[n // 2] if n else -1,
            "entries_per_batch_p99":
                sizes[min(n - 1, int(n * 0.99))] if n else -1,
            "encode_share_pct": enc_pct,
        }

    def overview(self) -> dict:
        return {
            "name": self.name,
            "servers": {n: s.server.overview()
                        for n, s in self.shells.items()},
            "leaderboard": dict(self.leaderboard),
        }
