"""The command codec — one schema'd, versioned binary image from socket
to segment (ROADMAP item 4, ISSUE 18).

A command is encoded ONCE — at the wire client for remote traffic, or at
leader append for local traffic — and the resulting *payload image* is
relayed as raw bytes through every later hop: the TCP compact forms
(``__cmds2__`` / ``__aer__``), the WAL batch-run records, segment files,
follower append, apply, and recovery all carry the same byte layout and
never re-pickle.  Pickle survives only as a *tagged, versioned fallback
record type* for arbitrary-object machines (``encode_fallback``), and as
decode-only legacy branches so WAL/segment dirs written before this
format still recover.

Record types (first byte is the tag; pickle protocol >= 2 streams always
start with 0x80, so tags 0x01-0x03 are collision-free):

  0x02  USER v1 — fixed-layout UserCommand record::

          <B tag><B version><B reply_mode><B flags>
          <I data_len><H corr_len><H notify_len><H from_len><H reply_from_len>
          data | correlation | notify_to | from_ | reply_from

        flags bit0: the data section is raw bytes (no value-codec kind
        byte — the dominant shape on the bench path).  All other
        sections (and non-bytes data) use the value mini-codec below.

  0x03  FALLBACK v1 — ``<B tag><B version>`` + pickle of the
        handle-stripped command.  The ONLY sanctioned object-encode on a
        hot path (lint rule RA10's codec family points here).

  0x01  legacy fast-tuple frame (pre-codec durable image) — decode only.
  0x80+ legacy raw pickle — decode only.

Value mini-codec (one kind byte + body); anything unrepresentable
falls to a per-field pickle (kind 5), and a section that would overflow
its u16 length field demotes the whole record to FALLBACK:

  0 None · 1 i64 · 2 bytes · 3 utf-8 str · 4 tuple (u8 count,
  u32-length-prefixed elements, recursive) · 5 field pickle ·
  6 all-int tuple (u8 count, count x i64 — the (cid, seq) correlation
  fast path)
"""
from __future__ import annotations

import pickle
import struct
from typing import Any, Optional

from .core.types import ReplyMode, UserCommand, strip_local_handles

#: bump whenever the byte layout of any record type changes; the golden
#: corpus pin in tests/test_codec.py fails if layout moves without it
CODEC_VERSION = 1

TAG_LEGACY_FAST = 0x01  # pre-codec fast-tuple frame (decode only)
TAG_USER = 0x02
TAG_FALLBACK = 0x03

_TAG_USER_B = bytes([TAG_USER])
_TAG_FALLBACK_B = bytes([TAG_FALLBACK])

#: tag, version, reply_mode, flags, data_len, corr/notify/from_/reply_from
_USER_HDR = struct.Struct("<BBBBIHHHH")
_USER_HDR_SIZE = _USER_HDR.size  # 16

_F_DATA_RAW = 0x01  # data section is raw bytes, no kind byte

#: ReplyMode <-> u8 wire codes.  Codes are part of the v1 layout — append
#: only, never renumber (the golden corpus pins them).
_RM_CODE = {
    ReplyMode.AFTER_LOG_APPEND: 0,
    ReplyMode.AWAIT_CONSENSUS: 1,
    ReplyMode.NOTIFY: 2,
    ReplyMode.NOREPLY: 3,
}
_RM_FROM_CODE = {v: k for k, v in _RM_CODE.items()}

_K_NONE = b"\x00"
_K_INT = 1
_K_BYTES = b"\x02"
_K_STR = b"\x03"
_K_TUPLE = 4
_K_PICKLE = b"\x05"
_K_ITUP = 6

_S_INT = struct.Struct("<Bq")
_S_ITUP2 = struct.Struct("<BBqq")   # kind, count=2, a, b
_S_Q2 = struct.Struct("<qq")
_S_U32 = struct.Struct("<I")
_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1

_dumps = pickle.dumps
_loads = pickle.loads
_PROTO = pickle.HIGHEST_PROTOCOL

#: UserCommand assembly through the slot descriptors — the frozen
#: dataclass __init__ funnels every field through object.__setattr__
#: plus the default-argument machinery (~0.8us); the descriptors set
#: the same slots in ~0.4us.  This is the decode-side twin of the
#: ingress-side slots=True decision on UserCommand itself (ISSUE 13).
_UC_NEW = UserCommand.__new__
_UC_SET_DATA = UserCommand.data.__set__
_UC_SET_RM = UserCommand.reply_mode.__set__
_UC_SET_CORR = UserCommand.correlation.__set__
_UC_SET_NOTIFY = UserCommand.notify_to.__set__
_UC_SET_FROM = UserCommand.from_.__set__
_UC_SET_RFROM = UserCommand.reply_from.__set__
_UC_SET_TRACE = UserCommand.trace.__set__


def build_user(data: Any, reply_mode: Any, correlation: Any,
               notify_to: Any, from_: Any, reply_from: Any,
               trace: Any = None) -> UserCommand:
    """A UserCommand built via the slot descriptors — ~2x cheaper than
    the frozen-dataclass constructor; used on the decode hot path where
    one instance is minted per command per member."""
    c = _UC_NEW(UserCommand)
    _UC_SET_DATA(c, data)
    _UC_SET_RM(c, reply_mode)
    _UC_SET_CORR(c, correlation)
    _UC_SET_NOTIFY(c, notify_to)
    _UC_SET_FROM(c, from_)
    _UC_SET_RFROM(c, reply_from)
    _UC_SET_TRACE(c, trace)
    return c

#: value-keyed memo for hot tuple sections.  The wire client mints ONE
#: notify handle per batch and stamps it into every command's image, so
#: encode sees the same tuple object thousands of times and decode sees
#: the same section bytes — both sides collapse the recursive walk to a
#: dict hit.  Tuples are immutable, so caching by value is safe; bounded
#: and cleared on overflow so a churn of distinct handles can't leak.
_TUP_CACHE_MAX = 512
_tup_enc_cache: dict = {}
_tup_dec_cache: dict = {}


class CodecError(ValueError):
    """A payload image is malformed (truncated, bit-flipped, or from a
    codec version this build does not know)."""


# ---------------------------------------------------------------------------
# value mini-codec
# ---------------------------------------------------------------------------

def _enc_tuple(v: tuple) -> bytes:
    if len(v) == 2:
        a, b = v
        if type(a) is int and type(b) is int \
                and _I64_MIN <= a <= _I64_MAX \
                and _I64_MIN <= b <= _I64_MAX:
            return _S_ITUP2.pack(_K_ITUP, 2, a, b)
    try:
        cached = _tup_enc_cache.get(v)
    except TypeError:           # unhashable element somewhere inside
        cached = None
        cacheable = False
    else:
        cacheable = True
        if cached is not None:
            return cached
    if len(v) > 255:
        out = _K_PICKLE + _dumps(v, protocol=_PROTO)  # ra10-ok: kind-5 FIELD pickle INSIDE a versioned record (oversized tuple)
    elif v and all(type(e) is int and _I64_MIN <= e <= _I64_MAX
                   for e in v):
        out = struct.pack("<BB%dq" % len(v), _K_ITUP, len(v), *v)
    else:
        parts = [struct.pack("<BB", _K_TUPLE, len(v))]
        for e in v:
            eb = _enc_val(e)
            parts.append(_S_U32.pack(len(eb)))
            parts.append(eb)
        out = b"".join(parts)
    if cacheable:
        if len(_tup_enc_cache) >= _TUP_CACHE_MAX:
            _tup_enc_cache.clear()
        _tup_enc_cache[v] = out
    return out


def _enc_val(v: Any) -> bytes:
    if v is None:
        return _K_NONE
    t = type(v)
    if t is int:
        if _I64_MIN <= v <= _I64_MAX:
            return _S_INT.pack(_K_INT, v)
        return _K_PICKLE + _dumps(v, protocol=_PROTO)  # ra10-ok: kind-5 FIELD pickle INSIDE a versioned record (bignum)
    if t is bytes:
        return _K_BYTES + v
    if t is str:
        return _K_STR + v.encode("utf-8")
    if t is tuple:
        return _enc_tuple(v)
    return _K_PICKLE + _dumps(v, protocol=_PROTO)  # ra10-ok: kind-5 FIELD pickle INSIDE a versioned record (generic value)


def _dec_val(b: bytes) -> Any:
    kind = b[0]
    if kind == 0:
        if len(b) != 1:
            raise ValueError("oversized None section")
        return None
    if kind == _K_INT:
        return _S_INT.unpack(b)[1]
    if kind == 0x02:
        return b[1:]
    if kind == 0x03:
        return b[1:].decode("utf-8")
    if kind == _K_ITUP:
        n = b[1]
        if len(b) != 2 + 8 * n:
            raise ValueError("oversized int-tuple section")
        if n == 2:
            return _S_Q2.unpack_from(b, 2)
        return struct.unpack_from("<%dq" % n, b, 2) if n else ()
    if kind == _K_TUPLE:
        cached = _tup_dec_cache.get(b)
        if cached is not None:
            return cached
        n = b[1]
        out = []
        off = 2
        for _ in range(n):
            (elen,) = _S_U32.unpack_from(b, off)
            off += 4
            if off + elen > len(b):
                raise ValueError("truncated tuple element")
            out.append(_dec_val(b[off:off + elen]))
            off += elen
        if off != len(b):
            raise ValueError("trailing bytes in tuple section")
        val = tuple(out)
        try:
            if len(_tup_dec_cache) >= _TUP_CACHE_MAX:
                _tup_dec_cache.clear()
            _tup_dec_cache[b] = val
        except TypeError:
            pass
        return val
    if kind == 0x05:
        return _loads(b[1:])
    raise ValueError("unknown value kind %d" % kind)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def _handle(v: Any) -> Any:
    """Process-local reply handles (futures/callables) never leave the
    process; remote (str/int/tuple) handles survive — a failed-over
    leader owes those notifications (see types.strip_local_handles)."""
    return v if isinstance(v, (str, int, tuple)) else None


def encode_user(data: Any, reply_mode: ReplyMode, correlation: Any,
                notify_to: Any, from_: Any, reply_from: Any,
                ) -> Optional[bytes]:
    """USER v1 image of the given command fields, or None when the shape
    does not fit the fixed layout (caller demotes to encode_fallback)."""
    rm = _RM_CODE.get(reply_mode)
    if rm is None:
        return None
    if type(data) is bytes:
        flags = _F_DATA_RAW
        db = data
    else:
        flags = 0
        db = _enc_val(data)
    # sections, common shapes inlined: correlation is None or a small
    # tuple ((cid, seq) on the wire path); notify_to is ONE handle tuple
    # per batch (the value-keyed cache hit); from_/reply_from are None
    # on virtually every hot-path command
    if correlation is None:
        cb = _K_NONE
    elif type(correlation) is tuple:
        cb = _enc_tuple(correlation)
    else:
        cb = _enc_val(correlation)
    if notify_to is None:
        nb = _K_NONE
    elif type(notify_to) is tuple:
        try:
            nb = _tup_enc_cache[notify_to]
        except (KeyError, TypeError):
            nb = _enc_tuple(notify_to)
    else:
        h = _handle(notify_to)
        nb = _K_NONE if h is None else _enc_val(h)
    if from_ is None:
        fb = _K_NONE
    else:
        h = _handle(from_)
        fb = _K_NONE if h is None else _enc_val(h)
    rb = _K_NONE if reply_from is None else _enc_val(reply_from)
    ld = len(db)
    lc = len(cb)
    ln = len(nb)
    lf = len(fb)
    lr = len(rb)
    if ld > 0xFFFFFFFF or lc > 0xFFFF or ln > 0xFFFF or lf > 0xFFFF \
            or lr > 0xFFFF:
        return None
    return b"".join((_USER_HDR.pack(TAG_USER, CODEC_VERSION, rm, flags,
                                    ld, lc, ln, lf, lr),
                     db, cb, nb, fb, rb))


def encode_fallback(obj: Any) -> bytes:
    """Tagged, versioned pickle record — the sanctioned escape hatch for
    arbitrary-object commands (noop/membership/cluster ops, machines
    with unpicklable-into-v1 shapes)."""
    return _TAG_FALLBACK_B + bytes([CODEC_VERSION]) \
        + _dumps(strip_local_handles(obj), protocol=_PROTO)  # ra10-ok: the codec's own tagged fallback record type — every hot-path object-encode is funneled through here by design


def encode_command(cmd: Any) -> bytes:
    """Durable/wire image of a log command: USER v1 when it fits the
    fixed layout, tagged fallback otherwise."""
    if type(cmd) is UserCommand:
        img = encode_user(cmd.data, cmd.reply_mode, cmd.correlation,
                          cmd.notify_to, cmd.from_, cmd.reply_from)
        if img is not None:
            return img
    return encode_fallback(cmd)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def decode_user_parts(payload: bytes) -> tuple:
    """(data, reply_mode, correlation, notify_to, from_, reply_from) of a
    USER record — the wire receiver uses this to attach a trace context
    in the same construction instead of rebuilding the dataclass."""
    tag, ver, rm, flags, dlen, clen, nlen, flen, rlen = \
        _USER_HDR.unpack_from(payload, 0)
    if ver > CODEC_VERSION:
        raise ValueError("USER record v%d from a newer codec" % ver)
    if _USER_HDR_SIZE + dlen + clen + nlen + flen + rlen != len(payload):
        raise ValueError("USER record length mismatch")
    reply_mode = _RM_FROM_CODE.get(rm)
    if reply_mode is None:
        raise ValueError("unknown reply_mode code %d" % rm)
    end = _USER_HDR_SIZE + dlen
    db = payload[_USER_HDR_SIZE:end]
    data = db if flags & _F_DATA_RAW else _dec_val(db)
    # sections unrolled, dominant shapes first: correlation is the
    # 18-byte (cid, seq) int-pair or None; notify_to is one handle tuple
    # per batch (dict hit on the section bytes); from_/reply_from None
    if clen == 18 and payload[end] == _K_ITUP and payload[end + 1] == 2:
        corr = _S_Q2.unpack_from(payload, end + 2)
        end += 18
    elif clen == 1 and payload[end] == 0:
        corr = None
        end += 1
    else:
        nxt = end + clen
        corr = _dec_val(payload[end:nxt])
        end = nxt
    if nlen == 1 and payload[end] == 0:
        notify = None
        end += 1
    else:
        nxt = end + nlen
        sect = payload[end:nxt]
        end = nxt
        notify = _tup_dec_cache.get(sect)
        if notify is None:
            notify = _dec_val(sect)
    if flen == 1 and payload[end] == 0:
        from_ = None
        end += 1
    else:
        nxt = end + flen
        from_ = _dec_val(payload[end:nxt])
        end = nxt
    if rlen == 1 and payload[end] == 0:
        reply_from = None
    else:
        reply_from = _dec_val(payload[end:end + rlen])
    return (data, reply_mode, corr, notify, from_, reply_from)


def decode_command(payload: bytes) -> Any:
    """Decode any payload image this repo has ever written: USER v1,
    tagged fallback, the pre-codec 0x01 fast-tuple frame, and raw-pickle
    images (the versioned read path that keeps r06 dirs recovering).
    Malformed images raise CodecError."""
    try:
        tag = payload[0]
        if tag == TAG_USER:
            return build_user(*decode_user_parts(payload))
        if tag == TAG_FALLBACK:
            if payload[1] > CODEC_VERSION:
                raise ValueError(
                    "FALLBACK record v%d from a newer codec" % payload[1])
            return _loads(payload[2:])
        if tag == TAG_LEGACY_FAST:
            fields = _loads(payload[1:])
            data, rm, corr, from_, notify = fields[:5]
            # frames written before the reply_from field carry five
            reply_from = fields[5] if len(fields) > 5 else None
            return UserCommand(data, ReplyMode(rm), corr, notify, from_,
                               reply_from)
        if tag >= 0x80:
            return _loads(payload)
        raise ValueError("unknown record tag 0x%02x" % tag)
    except CodecError:
        raise
    except Exception as exc:  # struct/pickle/unicode/index errors
        raise CodecError("corrupt payload image: %s" % (exc,)) from exc
