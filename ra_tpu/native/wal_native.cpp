// Native hot path for the fan-in write-ahead log.
//
// The reference's WAL hot loop (batch encode + write(2) + fsync,
// /root/reference/src/ra_log_wal.erl:488-560,753-800) runs on the BEAM's
// native runtime; this library is the equivalent layer for ra-tpu: the
// Python WAL thread hands a fully-encoded batch buffer to wal_write_batch,
// which performs the write + durability syscall with the GIL released
// (ctypes releases it for the call).  Record checksums use zlib.crc32 on
// the Python side — same polynomial, no FFI overhead per record.
//
// Build: g++ -O3 -shared -fPIC -o libra_wal.so wal_native.cpp
//
// Exposed (C ABI):
//   int      ra_wal_open(const char *path, int truncate);
//   long     ra_wal_write_batch(int fd, const uint8_t *buf, size_t len,
//                               int sync_mode);  // 0=none 1=fdatasync 2=fsync
//   int      ra_wal_close(int fd);
//   long     ra_pwrite(int fd, const uint8_t *buf, size_t len, long off);
//   long     ra_pread(int fd, uint8_t *buf, size_t len, long off);

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

extern "C" {

int ra_wal_open(const char *path, int truncate) {
  int flags = O_CREAT | O_RDWR | O_APPEND;
  if (truncate) flags |= O_TRUNC;
  return open(path, flags, 0644);
}

// o_sync variant: the file descriptor itself is synchronous, so write(2)
// returns only after the data is durable — the reference's `o_sync`
// write strategy (ra_log_wal.erl:66-96) where no separate fsync happens.
int ra_wal_open_sync(const char *path, int truncate) {
  int flags = O_CREAT | O_RDWR | O_APPEND | O_SYNC;
  if (truncate) flags |= O_TRUNC;
  return open(path, flags, 0644);
}

// standalone durability syscall for the `sync_after_notify` strategy
// (write -> notify -> sync): 1=fdatasync 2=fsync
int ra_wal_sync(int fd, int mode) {
  int r = 0;
  if (mode == 1) r = fdatasync(fd);
  else if (mode == 2) r = fsync(fd);
  return r == 0 ? 0 : -errno;
}

long ra_wal_write_batch(int fd, const uint8_t *buf, size_t len,
                        int sync_mode) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = write(fd, buf + done, len - done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    done += (size_t)n;
  }
  if (sync_mode == 1) {
    if (fdatasync(fd) != 0) return -(long)errno;
  } else if (sync_mode == 2) {
    if (fsync(fd) != 0) return -(long)errno;
  }
  return (long)done;
}

int ra_wal_close(int fd) { return close(fd); }

long ra_pwrite(int fd, const uint8_t *buf, size_t len, long off) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, buf + done, len - done, off + (long)done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    done += (size_t)n;
  }
  return (long)done;
}

long ra_pread(int fd, uint8_t *buf, size_t len, long off) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, buf + done, len - done, off + (long)done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    if (n == 0) break;
    done += (size_t)n;
  }
  return (long)done;
}

}  // extern "C"
