// Native hot path for the fan-in write-ahead log.
//
// The reference's WAL hot loop (batch encode + write(2) + fsync + checksum,
// /root/reference/src/ra_log_wal.erl:488-560,753-800) runs on the BEAM's
// native runtime; this library is the equivalent layer for ra-tpu: the
// Python WAL thread hands a fully-encoded batch buffer to wal_write_batch,
// which performs the write + durability syscall with the GIL released
// (ctypes releases it for the call), and crc32 of record payloads is
// computed here with a slice-by-8 table instead of per-byte Python work.
//
// Build: g++ -O3 -shared -fPIC -o libra_wal.so wal_native.cpp
//
// Exposed (C ABI):
//   int      ra_wal_open(const char *path, int truncate);
//   long     ra_wal_write_batch(int fd, const uint8_t *buf, size_t len,
//                               int sync_mode);  // 0=none 1=fdatasync 2=fsync
//   int      ra_wal_close(int fd);
//   uint32_t ra_crc32(uint32_t seed, const uint8_t *buf, size_t len);
//   long     ra_pwrite(int fd, const uint8_t *buf, size_t len, long off);
//   long     ra_pread(int fd, uint8_t *buf, size_t len, long off);

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

extern "C" {

static uint32_t crc_table[8][256];
static int crc_ready = 0;

static void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      crc_table[s][i] =
          crc_table[0][crc_table[s - 1][i] & 0xFF] ^ (crc_table[s - 1][i] >> 8);
  crc_ready = 1;
}

uint32_t ra_crc32(uint32_t seed, const uint8_t *buf, size_t len) {
  if (!crc_ready) crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  while (len >= 8) {
    c ^= (uint32_t)buf[0] | ((uint32_t)buf[1] << 8) | ((uint32_t)buf[2] << 16) |
         ((uint32_t)buf[3] << 24);
    uint32_t hi = (uint32_t)buf[4] | ((uint32_t)buf[5] << 8) |
                  ((uint32_t)buf[6] << 16) | ((uint32_t)buf[7] << 24);
    c = crc_table[7][c & 0xFF] ^ crc_table[6][(c >> 8) & 0xFF] ^
        crc_table[5][(c >> 16) & 0xFF] ^ crc_table[4][c >> 24] ^
        crc_table[3][hi & 0xFF] ^ crc_table[2][(hi >> 8) & 0xFF] ^
        crc_table[1][(hi >> 16) & 0xFF] ^ crc_table[0][hi >> 24];
    buf += 8;
    len -= 8;
  }
  while (len--) c = crc_table[0][(c ^ *buf++) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

int ra_wal_open(const char *path, int truncate) {
  int flags = O_CREAT | O_RDWR | O_APPEND;
  if (truncate) flags |= O_TRUNC;
  return open(path, flags, 0644);
}

// o_sync variant: the file descriptor itself is synchronous, so write(2)
// returns only after the data is durable — the reference's `o_sync`
// write strategy (ra_log_wal.erl:66-96) where no separate fsync happens.
int ra_wal_open_sync(const char *path, int truncate) {
  int flags = O_CREAT | O_RDWR | O_APPEND | O_SYNC;
  if (truncate) flags |= O_TRUNC;
  return open(path, flags, 0644);
}

// standalone durability syscall for the `sync_after_notify` strategy
// (write -> notify -> sync): 1=fdatasync 2=fsync
int ra_wal_sync(int fd, int mode) {
  int r = 0;
  if (mode == 1) r = fdatasync(fd);
  else if (mode == 2) r = fsync(fd);
  return r == 0 ? 0 : -errno;
}

long ra_wal_write_batch(int fd, const uint8_t *buf, size_t len,
                        int sync_mode) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = write(fd, buf + done, len - done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    done += (size_t)n;
  }
  if (sync_mode == 1) {
    if (fdatasync(fd) != 0) return -(long)errno;
  } else if (sync_mode == 2) {
    if (fsync(fd) != 0) return -(long)errno;
  }
  return (long)done;
}

int ra_wal_close(int fd) { return close(fd); }

long ra_pwrite(int fd, const uint8_t *buf, size_t len, long off) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, buf + done, len - done, off + (long)done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    done += (size_t)n;
  }
  return (long)done;
}

long ra_pread(int fd, uint8_t *buf, size_t len, long off) {
  size_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, buf + done, len - done, off + (long)done);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -(long)errno;
    }
    if (n == 0) break;
    done += (size_t)n;
  }
  return (long)done;
}

}  // extern "C"
