"""ctypes bindings for the native WAL/IO library, with pure-Python fallback.

The .so is built on first import with g++ (cached next to the source);
environments without a toolchain fall back to os-level Python I/O with
zlib.crc32 — same semantics, lower throughput.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import zlib

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "wal_native.cpp")
_SO = os.path.join(_HERE, "libra_wal.so")

_lib = None


def _build() -> bool:
    try:
        subprocess.run(["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
                       check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO) or os.path.getmtime(_SO) < \
            os.path.getmtime(_SRC):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
        lib.ra_wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ra_wal_open.restype = ctypes.c_int
        lib.ra_wal_open_sync.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ra_wal_open_sync.restype = ctypes.c_int
        lib.ra_wal_sync.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ra_wal_sync.restype = ctypes.c_int
        lib.ra_wal_write_batch.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                           ctypes.c_size_t, ctypes.c_int]
        lib.ra_wal_write_batch.restype = ctypes.c_long
        lib.ra_wal_close.argtypes = [ctypes.c_int]
        lib.ra_pwrite.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                  ctypes.c_size_t, ctypes.c_long]
        lib.ra_pwrite.restype = ctypes.c_long
        lib.ra_pread.argtypes = [ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_char),
                                 ctypes.c_size_t, ctypes.c_long]
        lib.ra_pread.restype = ctypes.c_long
        _lib = lib
    except OSError:
        _lib = None
    return _lib


class NativeIO:
    """Thin facade over the native lib (or the Python fallback).

    Records io operation counts/bytes (the ra_file_handle role,
    ra_file_handle.erl:26-40).  Plain int adds — approximate under
    concurrency, like any sampled io metric; reads via :meth:`stats`."""

    def __init__(self) -> None:
        self.lib = _load()
        self.native = self.lib is not None
        self._stats = {"reads": 0, "read_bytes": 0, "writes": 0,
                       "write_bytes": 0, "syncs": 0, "opens": 0}

    def stats(self) -> dict:
        return dict(self._stats)

    def random_open(self, path: str, truncate: bool = False) -> int:
        """Open for positioned I/O (pwrite/pread).  MUST NOT use O_APPEND:
        Linux pwrite ignores the offset on O_APPEND fds."""
        flags = os.O_CREAT | os.O_RDWR
        if truncate:
            flags |= os.O_TRUNC
        self._stats["opens"] += 1
        return os.open(path, flags, 0o644)

    # sync_mode: 0=none, 1=fdatasync, 2=fsync
    def wal_open(self, path: str, truncate: bool = False,
                 o_sync: bool = False) -> int:
        """o_sync opens the fd with O_SYNC: every write(2) is durable on
        return (the reference's `o_sync` write strategy)."""
        if self.native:
            fn = self.lib.ra_wal_open_sync if o_sync else \
                self.lib.ra_wal_open
            fd = fn(path.encode(), 1 if truncate else 0)
        else:
            flags = os.O_CREAT | os.O_RDWR | os.O_APPEND
            if o_sync:
                flags |= os.O_SYNC
            if truncate:
                flags |= os.O_TRUNC
            fd = os.open(path, flags, 0o644)
        if fd < 0:
            raise OSError(f"wal_open failed for {path}: {fd}")
        self._stats["opens"] += 1
        return fd

    def sync(self, fd: int, mode: int = 1) -> None:
        """Standalone durability syscall (sync_after_notify strategy)."""
        if mode == 0:
            return
        self._stats["syncs"] += 1
        if self.native:
            r = self.lib.ra_wal_sync(fd, mode)
            if r < 0:
                raise OSError(f"wal sync failed: errno {-r}")
            return
        if mode == 1:
            try:
                os.fdatasync(fd)
            except AttributeError:
                os.fsync(fd)
        else:
            os.fsync(fd)

    def write_batch(self, fd: int, buf: bytes, sync_mode: int = 1) -> int:
        self._stats["writes"] += 1
        self._stats["write_bytes"] += len(buf)
        if sync_mode:
            self._stats["syncs"] += 1
        if self.native:
            n = self.lib.ra_wal_write_batch(fd, buf, len(buf), sync_mode)
            if n < 0:
                raise OSError(f"wal write failed: errno {-n}")
            return n
        os.write(fd, buf)
        if sync_mode == 1:
            try:
                os.fdatasync(fd)
            except AttributeError:
                os.fsync(fd)
        elif sync_mode == 2:
            os.fsync(fd)
        return len(buf)

    def pwrite(self, fd: int, buf: bytes, off: int) -> int:
        self._stats["writes"] += 1
        self._stats["write_bytes"] += len(buf)
        if self.native:
            n = self.lib.ra_pwrite(fd, buf, len(buf), off)
            if n < 0:
                raise OSError(f"pwrite failed: errno {-n}")
            return n
        return os.pwrite(fd, buf, off)

    def pread(self, fd: int, length: int, off: int) -> bytes:
        self._stats["reads"] += 1
        self._stats["read_bytes"] += length
        if self.native:
            buf = ctypes.create_string_buffer(length)
            n = self.lib.ra_pread(fd, buf, length, off)
            if n < 0:
                raise OSError(f"pread failed: errno {-n}")
            return buf.raw[:n]
        return os.pread(fd, length, off)

    def crc32(self, data: bytes, seed: int = 0) -> int:
        # zlib.crc32 is the same polynomial (verified bit-identical vs
        # the native slice-by-8 across sizes/seeds) and beats it at every
        # size: no ctypes FFI overhead on small records (~2x) and a
        # hardware-accelerated inner loop on large ones (~2.4x at 1MB)
        return zlib.crc32(data, seed)

    def close(self, fd: int) -> None:
        if self.native:
            self.lib.ra_wal_close(fd)
        else:
            os.close(fd)


IO = NativeIO()
