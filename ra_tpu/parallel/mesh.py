"""Device-mesh sharding for the lane engine.

Parallelism axes of this framework (the honest mapping from SURVEY.md §2.4):

* ``lanes`` — cluster-level data parallelism, the reference's "thousands of
  co-hosted clusters per node" (docs/internals/INTERNALS.md:12-19) turned
  into the batch axis.  Lanes are fully independent: sharding them over a
  mesh needs **zero** cross-lane collectives, so throughput scales linearly
  over ICI-connected chips.
* ``members`` — the replication axis.  Sharding member slots across devices
  places each cluster member on a different chip, so the lockstep step's
  cross-member operations (leader gather, match/commit reductions, the
  quorum median) lower to XLA collectives over ICI — the tensorized
  equivalent of the reference shipping #append_entries_rpc{} over Erlang
  distribution (ra_server_proc.erl:1317-1341).

Use a 1-D ``lanes`` mesh for co-hosted deployment (default), or a 2-D
``(members, lanes)`` mesh to emulate/run the distributed deployment where
chips stand in for hosts.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine.lockstep import LaneState


def lane_mesh(devices=None, member_axis: int = 1) -> Mesh:
    """Build a (members, lanes) mesh.  member_axis=1 gives the pure
    lane-parallel deployment."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    assert n % member_axis == 0, (n, member_axis)
    arr = np.asarray(devices).reshape(member_axis, n // member_axis)
    return Mesh(arr, axis_names=("members", "lanes"))


def state_shardings(mesh: Mesh, state: LaneState) -> LaneState:
    """Sharding pytree for a LaneState, dispatched by field (not rank):
    [N] fields over 'lanes', [N,P] fields over ('lanes','members'), the
    [N,R,C] ring lane-sharded only (entries flow to member chips on demand),
    and machine state over ('lanes','members', replicated...) whatever its
    per-member rank."""
    def by_shape(leaf, member_axis: bool):
        leaf = jax.numpy.asarray(leaf)
        if leaf.ndim == 0:
            return NamedSharding(mesh, P())
        dims = ["lanes"]
        if member_axis and leaf.ndim >= 2:
            dims.append("members")
        dims += [None] * (leaf.ndim - len(dims))
        return NamedSharding(mesh, P(*dims))

    mac_specs = jax.tree.map(lambda l: by_shape(l, member_axis=True),
                             state.mac)
    specs = {}
    for name in LaneState._fields:
        if name == "mac":
            continue
        leaf = getattr(state, name)
        if name == "telem":
            # the telemetry plane is a nested pytree of [N] accumulators
            # (LaneTelemetry): each leaf shards over 'lanes' like any
            # per-lane vector — the device holding a lane holds its
            # telemetry, so the jitted summary's reductions/top_k lower
            # to cross-device collectives (the per-device aggregation +
            # cross-device merge of the sharded observability path)
            specs[name] = jax.tree.map(
                lambda l: by_shape(l, member_axis=False), leaf)
            continue
        member_axis = name != "ring"
        specs[name] = by_shape(leaf, member_axis=member_axis)
    return LaneState(mac=mac_specs, **specs)


def shard_engine_state(engine, mesh: Optional[Mesh] = None):
    """Place an engine's state on a mesh; subsequent jitted steps run
    SPMD with XLA-inserted collectives."""
    if mesh is None:
        mesh = lane_mesh()
    shardings = state_shardings(mesh, engine.state)
    engine.state = jax.device_put(engine.state, shardings)
    return mesh


def superstep_block_shardings(mesh: Mesh) -> dict:
    """Shardings for the ``[K, ...]`` superstep staging block (the
    dispatch-ahead driver's device_put targets, ISSUE 5).  The leading
    inner-step axis is TIME, not data — it is never sharded; lanes
    shard as everywhere else, so a fused dispatch over a sharded
    engine consumes the staged block with zero resharding copies:

      n_new    int32[K, N]        -> P(None, 'lanes')
      payloads [K, N, Kc, C]      -> P(None, 'lanes', None, None)
      query    bool[K, N]         -> P(None, 'lanes')

    No ``elect`` entry on purpose: elect schedules are HOST data —
    the engine keeps any-election bookkeeping on the host
    (``LockstepEngine._host_mask``) so the hot path never reads the
    mask back from device; pre-staging it would reintroduce exactly
    that sync."""
    vec = NamedSharding(mesh, P(None, "lanes"))
    return {
        "n_new": vec,
        "payloads": NamedSharding(mesh, P(None, "lanes", None, None)),
        "query": vec,
    }
