"""Device-mesh sharding for the lane engine.

Parallelism axes of this framework (the honest mapping from SURVEY.md §2.4):

* ``lanes`` — cluster-level data parallelism, the reference's "thousands of
  co-hosted clusters per node" (docs/internals/INTERNALS.md:12-19) turned
  into the batch axis.  Lanes are fully independent: sharding them over a
  mesh needs **zero** cross-lane collectives, so throughput scales linearly
  over ICI-connected chips.
* ``members`` — the replication axis.  Sharding member slots across devices
  places each cluster member on a different chip, so the lockstep step's
  cross-member operations (leader gather, match/commit reductions, the
  quorum median) lower to XLA collectives over ICI — the tensorized
  equivalent of the reference shipping #append_entries_rpc{} over Erlang
  distribution (ra_server_proc.erl:1317-1341).

Use a 1-D ``lanes`` mesh for co-hosted deployment (default), or a 2-D
``(members, lanes)`` mesh to emulate/run the distributed deployment where
chips stand in for hosts.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import devicewatch
from ..engine.lockstep import DispatchAheadDriver, LaneState


def lane_mesh(devices=None, member_axis: int = 1) -> Mesh:
    """Build a (members, lanes) mesh.  member_axis=1 gives the pure
    lane-parallel deployment."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    assert n % member_axis == 0, (n, member_axis)
    arr = np.asarray(devices).reshape(member_axis, n // member_axis)
    return Mesh(arr, axis_names=("members", "lanes"))


def state_shardings(mesh: Mesh, state: LaneState) -> LaneState:
    """Sharding pytree for a LaneState, dispatched by field (not rank):
    [N] fields over 'lanes', [N,P] fields over ('lanes','members'), the
    [N,R,C] ring lane-sharded only (entries flow to member chips on demand),
    and machine state over ('lanes','members', replicated...) whatever its
    per-member rank.

    Rule RA15 derives the state schema from this function's ``state``
    annotation and statically requires every ``LaneState`` field to be
    covered by the dispatch below — the generic ``_fields`` loop is
    full coverage, and a by-name special case (``"mac"``/``"telem"``/
    ``"ring"``) naming a non-field is flagged as a stale arm.  The PR 6
    shape (a new pytree field the tree-map didn't cover, rejected by
    ``device_put`` one mesh boot later) cannot reland silently."""
    def by_shape(leaf, member_axis: bool):
        leaf = jax.numpy.asarray(leaf)
        if leaf.ndim == 0:
            return NamedSharding(mesh, P())
        dims = ["lanes"]
        if member_axis and leaf.ndim >= 2:
            dims.append("members")
        dims += [None] * (leaf.ndim - len(dims))
        return NamedSharding(mesh, P(*dims))

    mac_specs = jax.tree.map(lambda l: by_shape(l, member_axis=True),
                             state.mac)
    specs = {}
    for name in LaneState._fields:
        if name == "mac":
            continue
        leaf = getattr(state, name)
        if name == "telem":
            # the telemetry plane is a nested pytree of [N] accumulators
            # (LaneTelemetry): each leaf shards over 'lanes' like any
            # per-lane vector — the device holding a lane holds its
            # telemetry, so the jitted summary's reductions/top_k lower
            # to cross-device collectives (the per-device aggregation +
            # cross-device merge of the sharded observability path)
            specs[name] = jax.tree.map(
                lambda l: by_shape(l, member_axis=False), leaf)
            continue
        # ring [N,R,C] and read_buf [N,Kr,Cq] are LANE-local planes:
        # axis 1 is ring depth / pending-read slots, never members
        member_axis = name not in ("ring", "read_buf")
        specs[name] = by_shape(leaf, member_axis=member_axis)
    return LaneState(mac=mac_specs, **specs)


def shard_engine_state(engine, mesh: Optional[Mesh] = None):
    """Place an engine's state on a mesh; subsequent jitted steps run
    SPMD with XLA-inserted collectives.

    Beyond the state pytree itself (ISSUE 11, the mesh-native pipeline):

    * the engine's cached zero masks (``_zero_fail``/``_zero_elect``/
      ``_zero_confirm``) are re-placed with matching shardings — every
      dispatch consumes them, and leaving them single-device would
      either recompile the step for a mixed-sharding signature or pay a
      broadcast copy per dispatch;
    * ``engine._mesh`` records the mesh so downstream wiring
      (:class:`~ra_tpu.engine.lockstep.DispatchAheadDriver` via
      :func:`mesh_superstep_driver`, ``IngressPlane``) picks up the
      matching :func:`superstep_block_shardings` automatically — the
      SNIPPETS.md pjit rule that out/in axis resources of chained
      jitted calls must MATCH so staged blocks never repartition.
    """
    if mesh is None:
        mesh = lane_mesh()
    shardings = state_shardings(mesh, engine.state)
    engine.state = jax.device_put(engine.state, shardings)
    lane_sh = NamedSharding(mesh, P("lanes"))
    engine._zero_elect = jax.device_put(engine._zero_elect, lane_sh)
    engine._zero_confirm = jax.device_put(engine._zero_confirm, lane_sh)
    engine._zero_fail = jax.device_put(
        engine._zero_fail, NamedSharding(mesh, P("lanes", "members")))
    engine._mesh = mesh
    # transfer ledger (ISSUE 16): the one-time resharding of the full
    # state pytree + zero masks is the mesh path's h2d budget — it
    # must show up ONCE at shard time, never again per dispatch (a
    # per-window h2d delta at this site is the repartition bug RA15
    # guards statically).  .nbytes reads are host metadata.
    devicewatch.record_h2d(
        "mesh_shard",
        sum(getattr(leaf, "nbytes", 0)
            for leaf in jax.tree.leaves(engine.state))
        + engine._zero_elect.nbytes + engine._zero_confirm.nbytes
        + engine._zero_fail.nbytes,
        events=len(jax.tree.leaves(engine.state)) + 3)
    return mesh


def superstep_block_shardings(mesh: Mesh) -> dict:
    """Shardings for the ``[K, ...]`` superstep staging block (the
    dispatch-ahead driver's device_put targets, ISSUE 5).  The leading
    inner-step axis is TIME, not data — it is never sharded; lanes
    shard as everywhere else, so a fused dispatch over a sharded
    engine consumes the staged block with zero resharding copies:

      n_new    int32[K, N]        -> P(None, 'lanes')
      payloads [K, N, Kc, C]      -> P(None, 'lanes', None, None)
      query    bool[K, N]         -> P(None, 'lanes')
      n_read   int32[K, N]        -> P(None, 'lanes')
      read_q   [K, N, Kr, Cq]     -> P(None, 'lanes', None, None)

    No ``elect`` entry on purpose: elect schedules are HOST data —
    the engine keeps any-election bookkeeping on the host
    (``LockstepEngine._host_mask``) so the hot path never reads the
    mask back from device; pre-staging it would reintroduce exactly
    that sync.  Rule RA15 pins the other direction: every key the
    dispatch-ahead staging path reads (``shardings.get("n_new")`` in
    ``DispatchAheadDriver._stage``) must have an entry here, so a new
    staged block component cannot silently repartition per dispatch
    (the SNIPPETS.md matching-axis-resources rule, as a lint)."""
    vec = NamedSharding(mesh, P(None, "lanes"))
    return {
        "n_new": vec,
        "payloads": NamedSharding(mesh, P(None, "lanes", None, None)),
        "query": vec,
        "n_read": vec,
        "read_q": NamedSharding(mesh, P(None, "lanes", None, None)),
    }


#: the multichip lane ladder shared by ``bench.py --multichip`` and
#: the dryrun throughput/chaos phases (ISSUE 11): low rungs are
#: dispatch-bound (fusion wins), the top rung shows where the mesh
#: goes compute-bound.  ONE definition so tools/bench_diff.py's
#: per-rung row keys (``multichip/<mesh>/lanes<N>``) pair across the
#: two capture formats.
DEFAULT_LANE_LADDER = (1024, 8192, 65536)


def lane_ladder(env: Optional[str] = None) -> list:
    """Resolve the multichip lane ladder: an explicit ``env`` string >
    the shared ``RA_TPU_MULTICHIP_LANES`` env > the default.  Spaces
    tolerated; an empty or unparsable spec degrades to the default
    ladder — a sweep must fall back to the standard rungs, never crash
    on a malformed override."""
    import os
    raw = env if env is not None else \
        os.environ.get("RA_TPU_MULTICHIP_LANES", "")
    try:
        rungs = [int(x.strip()) for x in raw.split(",") if x.strip()]
    except ValueError:
        rungs = []
    return rungs or list(DEFAULT_LANE_LADDER)


def mesh_shapes(n_devices: int) -> list:
    """``[(member_axis, lane_axis, members), ...]`` the multichip
    sweeps enumerate: pure lane-parallel ``1xD`` (3 members), plus the
    ``2x(D/2)`` member-replicated deployment (4 members) when the
    device count allows — the MULTICHIP_r05 shapes.  Shared by
    ``bench.py --multichip`` and ``dryrun_multichip`` so per-shape
    capture keys pair across formats."""
    shapes = [(1, n_devices, 3)]
    if n_devices % 2 == 0 and n_devices >= 4:
        shapes.append((2, n_devices // 2, 4))
    return shapes


def ladder_rungs(ladder, lane_devices: int) -> list:
    """Clamp each ladder rung to the mesh's minimum useful width
    (>= 16 lanes per lane-axis device) and DEDUPE: on a wide mesh the
    clamp can collapse adjacent rungs, and both capture formats must
    emit identical ``multichip/<mesh>/lanes<N>`` keys for the same
    config or tools/bench_diff.py silently skips the pairing."""
    return sorted({max(int(r), 16 * lane_devices) for r in ladder})


def per_device_wal_shards(mesh: Mesh) -> int:
    """WAL shard count for a per-device durable layout: one shard per
    LANE-axis device.  ``EngineDurability`` slices lanes into S equal
    contiguous ranges (``bounds[i] = round(i*N/S)``) — exactly the lane
    slices an even ``P('lanes')`` sharding places per device — so each
    device's committed rows are encoded+fsynced by its own shard and
    fsync parallelism scales with the mesh instead of serializing on
    one writer.  RTB2 recovery merges ANY shard layout, so reopening
    the same dir under a different mesh shape needs no migration."""
    return int(mesh.shape["lanes"])


def mesh_superstep_driver(engine, mesh: Optional[Mesh] = None,
                          max_in_flight: int = 2) -> DispatchAheadDriver:
    """A :class:`DispatchAheadDriver` whose staged blocks are placed
    with :func:`superstep_block_shardings` — the mesh-native form of
    the PR 5 host pipeline: device_put partitions block i+1 across the
    mesh while dispatch i executes, and because the staging shardings
    match the fused step's input shardings the dispatch consumes the
    staged block with zero resharding copies."""
    mesh = mesh or getattr(engine, "_mesh", None)
    if mesh is None:
        mesh = shard_engine_state(engine)
    return DispatchAheadDriver(engine, max_in_flight=max_in_flight,
                               shardings=superstep_block_shardings(mesh))


def drive_uniform_window(driver: DispatchAheadDriver, n_new_blk,
                         payloads_blk, seconds: float, *,
                         observe=None):
    """The mesh driver's measured dispatch loop: staged superstep
    submits back to back for ``seconds``, with NO device->host sync
    anywhere in the loop — the in-flight cap's async committed-
    watermark readbacks (inside ``driver.submit``) are the only
    synchronization, exactly the PR 5 window discipline.  Lint rule
    RA04's same-module call closure covers this function (see
    tools/lint.py): a blocking sync moved into a helper here cannot
    escape the gate, the same way the bench loops are policed.

    ``observe()`` runs between dispatches (host-side dict work only —
    an Observatory snapshot, an autotuner tick); it may return a new
    ``(n_new_blk, payloads_blk)`` pair to restage the schedule at a
    different fusion depth (how the autotuner-driven frontier sweep
    applies K decisions between dispatches).  Returns
    ``(dispatches, inner_steps, elapsed_s)``; the caller drains."""
    dispatches = 0
    inner = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        driver.submit(n_new_blk, payloads_blk)
        dispatches += 1
        inner += int(n_new_blk.shape[0])
        if observe is not None:
            nxt = observe()
            if nxt is not None:
                n_new_blk, payloads_blk = nxt
    return dispatches, inner, time.perf_counter() - t0


def ingress_submit_wave(plane, handles, seqnos, payloads):
    """Mesh-side ingress pump: one vectorized submission wave into a
    SHARDED engine's plane — dedup -> admission -> coalesce -> staged
    fused dispatch, returning the per-row status.  All per-session
    work stays inside the plane's vectorized sweeps; lint rule RA08's
    no-per-session-Python gate covers this function and every
    same-module helper it reaches (a mesh-side loop or per-row dict
    here would reintroduce the per-command host work the dense-block
    path removed)."""
    status = plane.submit(handles, seqnos, payloads)
    plane.pump(force=True)
    return status
