from .mesh import lane_mesh, shard_engine_state, state_shardings
