from .mesh import (lane_mesh, mesh_superstep_driver, per_device_wal_shards,
                   shard_engine_state, state_shardings,
                   superstep_block_shardings)
