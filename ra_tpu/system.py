"""RaSystem — a named instance of the full durable-log stack.

The reference's 'system' (ra_system.erl) is one isolated set of log
infrastructure: WAL + segment writer + registries, hosting many servers.
Multiple systems can coexist with separate data dirs/tunables
(ra_system.erl:18-63).  This is exactly that, minus supervision trees:
component threads are owned by this object and restarted by it.
"""
from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from collections import deque
from typing import Optional

from .blackbox import RECORDER, record, stamp_recovery
from .core.types import (Membership, SNAPSHOT_TUNABLE_KEYS,
                         ServerConfig, ServerId)
from .directory import Directory
from .log.durable import DurableLog
from .log.segment import SegmentWriter
from .log.wal import DEFAULT_MAX_BATCH, DEFAULT_MAX_SIZE, Wal


def _config_snapshot(cfg: ServerConfig) -> dict:
    """The reconstructable (picklable) parts of a server config, persisted
    in the directory for recover_servers — the ra_server_sup_sup
    recover_config role (:80-103).  The machine is resolved at recovery
    time."""
    from .machines import spec_of
    return {
        "server_id": tuple(cfg.server_id),
        "uid": cfg.uid,
        "cluster_name": cfg.cluster_name,
        "initial_members": tuple(tuple(m) for m in cfg.initial_members),
        "election_timeout_ms": cfg.election_timeout_ms,
        "tick_interval_ms": cfg.tick_interval_ms,
        "broadcast_time_ms": cfg.broadcast_time_ms,
        # the remaining tunables round-trip too — a restart-applied
        # mutable-config change (RaNode.MUTABLE_CONFIG_KEYS) must
        # survive node/system recovery, not silently revert
        **{k: getattr(cfg, k) for k in SNAPSHOT_TUNABLE_KEYS},
        "membership": cfg.membership.value,
        "system_name": cfg.system_name,
        # spec-built machines persist their recipe so a restart (local
        # boot recovery OR the cross-node control plane) can rebuild
        # them from disk alone; None for machines passed as live objects
        "machine_spec": spec_of(cfg.machine),
    }


#: System-level lane-engine dispatch-pipeline tunables (ISSUE 5).
#: ``superstep_k`` is how many engine rounds fuse into one XLA dispatch
#: (the lax.scan superstep, ra_tpu/engine/lockstep.py) and
#: ``dispatch_ahead`` how many dispatches the host may keep in flight
#: before the staging driver waits on a commit watermark.  These are
#: deployment knobs, not per-engine constants: a node co-hosting the
#: classic plane and the lane engine sizes them against the SAME host
#: budget that sizes wal shards/batching, which is why they live here
#: with the other system tunables.  Resolution order: explicit RaSystem
#: kwarg > RA_TPU_SUPERSTEP_K / RA_TPU_DISPATCH_AHEAD env > defaults.
ENGINE_SUPERSTEP_K = 8
ENGINE_DISPATCH_AHEAD = 2


def engine_pipeline_defaults() -> dict:
    """The system-level superstep/dispatch-ahead defaults after env
    overrides — what bench.py's ``--superstep auto`` and embedding
    nodes resolve against."""
    return {
        "superstep_k": int(os.environ.get("RA_TPU_SUPERSTEP_K",
                                          ENGINE_SUPERSTEP_K)),
        "dispatch_ahead": int(os.environ.get("RA_TPU_DISPATCH_AHEAD",
                                             ENGINE_DISPATCH_AHEAD)),
    }


#: WAL supervisor restart intensity: (max restarts, window seconds).
#: Beyond it the supervisor backs off for the window instead of
#: hot-looping (OTP's intensity/period shape, ra_log_sup.erl:26-51 — but
#: where OTP escalates and kills the subtree, a whole-process teardown
#: here would lose every co-hosted cluster member, so we throttle and
#: keep trying: a transient fault like a full disk stays recoverable).
WAL_RESTART_INTENSITY = (10, 5.0)


class RaSystem:
    def __init__(self, data_dir: str, *, name: str = "default",
                 wal_sync_mode: int = 1,
                 wal_max_size: int = DEFAULT_MAX_SIZE,
                 wal_max_batch: int = DEFAULT_MAX_BATCH,
                 wal_max_entries: int = 0,
                 wal_max_batch_bytes: int = 0,
                 wal_max_batch_interval_ms: float = 0.0,
                 segment_max_count: int = 4096,
                 wal_supervise: bool = True,
                 superstep_k: Optional[int] = None,
                 dispatch_ahead: Optional[int] = None) -> None:
        self.name = name
        self.data_dir = data_dir
        # lane-engine pipeline tunables carried by the system so an
        # embedding node configures both planes in one place (surfaced
        # in overview(); the engine/bench read them via
        # engine_pipeline_defaults when not set explicitly)
        defaults = engine_pipeline_defaults()
        self.superstep_k = defaults["superstep_k"] \
            if superstep_k is None else superstep_k
        self.dispatch_ahead = defaults["dispatch_ahead"] \
            if dispatch_ahead is None else dispatch_ahead
        #: the WAL group-commit wait budget this system was configured
        #: with — an autotuner-tunable knob, so it is stamped in the
        #: engine_pipeline overview next to superstep_k (rule RA07)
        self.wal_max_batch_interval_ms = wal_max_batch_interval_ms
        os.makedirs(data_dir, exist_ok=True)
        self.segment_max_count = segment_max_count
        self._logs: dict[str, DurableLog] = {}
        self._lock = threading.Lock()
        self.directory = Directory(data_dir)
        #: flush-escalation handler: called as fn(uid, exc) when a
        #: server's segment flush exhausted its retry budget (the
        #: server-restart rung of the degradation ladder — a node that
        #: hosts the server can install a kill+restart hook here;
        #: the default just records the event, which is safe: the WAL
        #: file is kept, so the entries stay recoverable)
        self.on_flush_escalation = None
        self.segment_writer = SegmentWriter(resolve=self._resolve,
                                            on_escalate=self._escalate)
        #: classic-plane phase attribution (ISSUE 18): one accumulator
        #: for every co-hosted server — the WAL stamps fsync_wait /
        #: confirm_publish, the DurableLogs stamp encode — surfaced via
        #: node.classic_stats() as encode_share_pct in bench tails
        from .telemetry import PhaseStats
        self.phase_stats = PhaseStats()
        # group-commit tunables ride through to the node-wide WAL (flush
        # on bytes OR interval; 0/0 keeps the drain-the-mailbox policy)
        self.wal = Wal(data_dir, sync_mode=wal_sync_mode,
                       max_size=wal_max_size, max_batch=wal_max_batch,
                       max_entries=wal_max_entries,
                       max_batch_bytes=wal_max_batch_bytes,
                       max_batch_interval_ms=wal_max_batch_interval_ms,
                       segment_writer=self.segment_writer,
                       phase_stats=self.phase_stats)
        # Recovered WAL entries are purged at boot ONLY for uids with an
        # explicit force-delete tombstone.  Absence from the registry is
        # not proof of deletion (the directory file may predate the
        # record, or may have failed to load), so unknown uids keep their
        # fsync-acknowledged data conservatively — their recovered files
        # stay pinned until the server re-registers, matching the
        # reference's keep-unresolvable-WAL behaviour.
        if self.wal._recovered_files:
            # this boot re-read surviving WAL files: stamp a recovery
            # report joining any post-mortem bundle the crash left
            # (crash + recovery read as one incident, ISSUE 7)
            stamp_recovery(
                {"plane": "classic_wal", "system": name,
                 "files": len(self.wal._recovered_files),
                 "uids": sorted(self.wal._recovered)},
                data_dir=data_dir)
        if not self.directory.load_failed:
            spent = set()
            for uid in self.directory.tombstones():
                if self.directory.is_registered_uid(uid):
                    # the uid was re-registered after the force-delete:
                    # the tombstone's authorisation is superseded by the
                    # live server — prune it, or it lingers forever
                    spent.add(uid)
                    continue
                # wal.purge only drops in-memory tables — the uid's bytes
                # stay in shared WAL files and may be re-recovered at the
                # next boot, when the tombstone must still authorise
                # purging them again; capture that BEFORE purging
                had_wal = uid in self.wal._recovered
                if had_wal:
                    self.wal.purge(uid)
                # a crash between wal.purge and rmtree in force_delete can
                # leave the uid's data dir behind: finish the job here, or
                # the orphan leaks forever once the tombstone is pruned
                tomb_dir = os.path.join(data_dir, uid)
                if os.path.isdir(tomb_dir):
                    shutil.rmtree(tomb_dir, ignore_errors=True)
                # spent only when neither WAL data nor an on-disk dir
                # remains to authorise cleaning at the next boot
                if not had_wal and not os.path.isdir(tomb_dir):
                    spent.add(uid)
            self.directory.prune_tombstones(spent)
        # WAL supervisor: restart a dead batch thread and run the writers'
        # resend hooks (the ra_log_sup/ra_log_wal_sup role; disabled in
        # tests that assert raw WalDown behaviour)
        self._sup_stop = threading.Event()
        self._wal_restarts: deque = deque()
        self._sup_thread: Optional[threading.Thread] = None
        if wal_supervise:
            self._sup_thread = threading.Thread(
                target=self._supervise_wal, daemon=True,
                name=f"ra-wal-sup-{name}")
            self._sup_thread.start()

    def _supervise_wal(self) -> None:
        max_r, period = WAL_RESTART_INTENSITY
        log = logging.getLogger("ra_tpu")
        while not self._sup_stop.wait(0.02):
            wal = self.wal
            if wal._stop or wal.alive:
                continue
            now = time.monotonic()
            while self._wal_restarts and \
                    now - self._wal_restarts[0] > period:
                self._wal_restarts.popleft()
            if len(self._wal_restarts) >= max_r:
                log.error("wal supervisor (%s): restart intensity "
                          "exceeded (%d in %.0fs); backing off %.0fs",
                          self.name, max_r, period, period)
                record("sup.giveup", plane="wal", system=self.name)
                RECORDER.dump(
                    "wal_supervisor_giveup",
                    what=f"WAL restart intensity exceeded ({max_r} in "
                         f"{period:.0f}s)",
                    where=self.name, data_dir=self.data_dir)
                if self._sup_stop.wait(period):
                    return
                continue
            self._wal_restarts.append(now)
            log.warning("wal supervisor (%s): restarting dead WAL",
                        self.name)
            # a failing restart (e.g. ENOSPC opening the fresh file) must
            # not kill the supervisor itself — it already counted against
            # the intensity window, so the loop retries with backoff once
            # the window fills
            try:
                wal.restart()
                record("sup.restart", plane="wal", system=self.name)
                with self._lock:
                    logs = list(self._logs.values())
                for dlog in logs:
                    dlog.wal_restarted()
            except Exception:
                log.exception("wal supervisor (%s): restart attempt "
                              "failed; will retry", self.name)

    def _resolve(self, uid: str) -> Optional[DurableLog]:
        with self._lock:
            return self._logs.get(uid)

    def _escalate(self, uid: str, exc: BaseException) -> None:
        """Segment-flush escalation (retry budget exhausted).  With no
        installed handler this only logs: the flush job kept the WAL
        file, so every entry remains recoverable from disk — the
        degraded state is 'WAL files accumulate', not data loss.  A
        node-level handler (on_flush_escalation) may stop+restart the
        owning server so it re-recovers from memtable + segments, the
        reference's supervisor semantics."""
        handler = self.on_flush_escalation
        if handler is not None:
            handler(uid, exc)
        else:
            logging.getLogger("ra_tpu").error(
                "segment flush escalation for %s (%s): WAL file kept, "
                "no restart handler installed", uid, exc)

    @staticmethod
    def validate_uid(uid: str) -> bool:
        """UIDs name on-disk directories and WAL records: restrict to
        base64url-safe characters, non-empty (ra_lib:validate_base64uri,
        ra_lib.erl:254-268; start_server refuses invalid UIDs the same
        way, ra_2_SUITE:start_server_uid_validation)."""
        import re
        return bool(uid) and re.fullmatch(r"[A-Za-z0-9_\-=]+", uid) \
            is not None

    def log_factory(self, cfg: ServerConfig) -> DurableLog:
        """Factory handed to RaNode: per-server durable log over the shared
        WAL/segment-writer.  The log is the server's *storage identity* and
        survives server crashes within a running system — a restarted
        server reuses it (the ra_log_ets role: memtables outlive the
        processes that fill them)."""
        if not self.validate_uid(cfg.uid):
            raise ValueError(
                f"invalid uid {cfg.uid!r}: must be non-empty base64url "
                "(it names a data directory)")
        # every uid that owns a log MUST be in the durable directory — the
        # boot purge treats absence as "force-deleted".  Log-only configs
        # (no server_id; tests/tools) register under their uid with an
        # empty config snapshot, which recover_servers skips.
        if cfg.server_id is not None:
            self.directory.register(cfg.uid, cfg.server_id.name,
                                    cfg.cluster_name, _config_snapshot(cfg))
        else:
            self.directory.register(cfg.uid, cfg.uid, cfg.cluster_name, {})
        with self._lock:
            log = self._logs.get(cfg.uid)
            if log is not None:
                log.take_events()  # drop confirms addressed to the old shell
                self.wal.register(cfg.uid, log._wal_notify)
                return log
            # create under the lock: two concurrent starts for one uid must
            # not build two logs over one directory
            log = DurableLog(cfg.uid, self.data_dir, self.wal,
                             segment_max_count=self.segment_max_count)
            self._logs[cfg.uid] = log
            return log

    # -- recovery / deletion (ra_system_recover + force_delete) ------------

    def recover_servers(self, node, machine_for=None) -> list:
        """Restart every registered server on ``node`` — the boot-time
        `server_recovery_strategy: registered` (ra_system_recover.erl:
        34-68).  ``machine_for(cluster_name, server_name) -> Machine``
        resolves the user machine (the durable equivalent of the module
        reference the reference persists); when it is None or returns
        None, a persisted machine_spec in the config snapshot resolves
        through the machine registry instead.  Servers with neither are
        skipped; already-running servers are left alone."""
        from .machines import resolve_machine, spec_of
        started = []
        for uid in self.directory.uids():
            snap = self.directory.config_of(uid)
            if not snap:
                continue
            name = self.directory.name_of(uid)
            if name is None or name in node.shells:
                continue
            machine = machine_for(snap["cluster_name"], name) \
                if machine_for is not None else None
            spec = snap.get("machine_spec")
            if machine is None and spec is not None:
                machine = resolve_machine(spec)
            if machine is None:
                continue
            if spec is not None and spec_of(machine) is None:
                # carry the persisted spec onto a machine_for-supplied
                # machine: the re-register below snapshots spec_of(), and
                # erasing it would break later disk-based control-plane
                # restarts of this member
                machine._machine_spec = spec
            cfg = ServerConfig(
                server_id=ServerId(*snap["server_id"]),
                uid=uid,
                cluster_name=snap["cluster_name"],
                initial_members=tuple(ServerId(*m)
                                      for m in snap["initial_members"]),
                machine=machine,
                election_timeout_ms=snap["election_timeout_ms"],
                tick_interval_ms=snap["tick_interval_ms"],
                broadcast_time_ms=snap["broadcast_time_ms"],
                membership=Membership(snap["membership"]),
                system_name=snap.get("system_name", "default"),
                **{k: snap[k] for k in SNAPSHOT_TUNABLE_KEYS
                   if k in snap},
            )
            started.append(node.start_server(cfg))
        return started

    def delete_server_data(self, uid: str) -> None:
        """Wipe a server's durable footprint (the data-dir half of
        ra:force_delete_server).  The caller stops the process first.
        Includes the member's uid-scoped machine_ets side tables — the
        system owns them like the reference's ra_machine_ets service
        under ra_sup (ra_sup.erl:33-35)."""
        from . import machine_ets
        machine_ets.drop_scope(uid)
        with self._lock:
            log = self._logs.pop(uid, None)
        if log is not None:
            log.close()
        self.wal.purge(uid)
        # tombstone: authorises a later boot to purge any WAL remnants of
        # this uid that a crash resurrects (see __init__)
        self.directory.unregister(uid, tombstone=True)
        target = os.path.join(self.data_dir, uid)
        if os.path.isdir(target):
            shutil.rmtree(target, ignore_errors=True)

    def registered_uids(self) -> list:
        with self._lock:
            return list(self._logs)

    def close(self) -> None:
        self._sup_stop.set()
        if self._sup_thread is not None:
            self._sup_thread.join(timeout=5)
        self.wal.close()
        self.segment_writer.close()
        with self._lock:
            for log in self._logs.values():
                log.close()
            self._logs.clear()

    def counters(self) -> dict:
        """Node-wide infra counters: the WAL's (ra_log_wal.erl:32-43,
        plus derived fsync latency p50/p99 and records-per-fsync from
        Wal.stats), the segment writer's
        (ra_log_segment_writer.erl:37-52), and the storage-plane fault
        counters (metrics.DISK_FAULT_FIELDS)."""
        from .log import faults
        return {"wal": self.wal.stats(),
                "segment_writer": dict(self.segment_writer.counters),
                "disk_faults": faults.disk_fault_counters()}

    def observatory(self, *, counters=None, router=None,
                    ring_capacity: int = 256):
        """The unified host-side observability surface for this system
        (ra_tpu.telemetry.Observatory): one merged snapshot of WAL/
        segment-writer/disk-fault counters + the pipeline tunables,
        optionally a node's Counters registry and a TcpRouter (whose
        reliable-RPC counters then reach the exposition/ring);
        Prometheus exposition and the bounded per-window time-series
        ring ride on it."""
        from .telemetry import Observatory
        return Observatory.for_system(self, counters=counters,
                                      router=router,
                                      ring_capacity=ring_capacity)

    def overview(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "data_dir": self.data_dir,
                "servers": {uid: log.overview()
                            for uid, log in self._logs.items()},
                "directory": self.directory.overview(),
                "counters": self.counters(),
                "engine_pipeline": {
                    "superstep_k": self.superstep_k,
                    "dispatch_ahead": self.dispatch_ahead,
                    "wal_max_batch_interval_ms":
                        self.wal_max_batch_interval_ms,
                },
            }
