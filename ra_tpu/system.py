"""RaSystem — a named instance of the full durable-log stack.

The reference's 'system' (ra_system.erl) is one isolated set of log
infrastructure: WAL + segment writer + registries, hosting many servers.
Multiple systems can coexist with separate data dirs/tunables
(ra_system.erl:18-63).  This is exactly that, minus supervision trees:
component threads are owned by this object and restarted by it.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

from .core.types import ServerConfig
from .log.durable import DurableLog
from .log.segment import SegmentWriter
from .log.wal import DEFAULT_MAX_BATCH, DEFAULT_MAX_SIZE, Wal


class RaSystem:
    def __init__(self, data_dir: str, *, name: str = "default",
                 wal_sync_mode: int = 1,
                 wal_max_size: int = DEFAULT_MAX_SIZE,
                 wal_max_batch: int = DEFAULT_MAX_BATCH,
                 segment_max_count: int = 4096) -> None:
        self.name = name
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.segment_max_count = segment_max_count
        self._logs: dict[str, DurableLog] = {}
        self._lock = threading.Lock()
        self.segment_writer = SegmentWriter(resolve=self._resolve)
        self.wal = Wal(data_dir, sync_mode=wal_sync_mode,
                       max_size=wal_max_size, max_batch=wal_max_batch,
                       segment_writer=self.segment_writer)

    def _resolve(self, uid: str) -> Optional[DurableLog]:
        with self._lock:
            return self._logs.get(uid)

    def log_factory(self, cfg: ServerConfig) -> DurableLog:
        """Factory handed to RaNode: per-server durable log over the shared
        WAL/segment-writer.  The log is the server's *storage identity* and
        survives server crashes within a running system — a restarted
        server reuses it (the ra_log_ets role: memtables outlive the
        processes that fill them)."""
        with self._lock:
            log = self._logs.get(cfg.uid)
            if log is not None:
                log.take_events()  # drop confirms addressed to the old shell
                self.wal.register(cfg.uid, log._wal_notify)
                return log
            # create under the lock: two concurrent starts for one uid must
            # not build two logs over one directory
            log = DurableLog(cfg.uid, self.data_dir, self.wal,
                             segment_max_count=self.segment_max_count)
            self._logs[cfg.uid] = log
            return log

    def registered_uids(self) -> list:
        with self._lock:
            return list(self._logs)

    def close(self) -> None:
        self.wal.close()
        self.segment_writer.close()
        with self._lock:
            for log in self._logs.values():
                log.close()
            self._logs.clear()

    def overview(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "data_dir": self.data_dir,
                "servers": {uid: log.overview()
                            for uid, log in self._logs.items()},
            }
