"""Client session for :class:`ra_tpu.models.fifo.FifoMachine`.

The reference pairs its fifo machine with ``test/ra_fifo_client.erl``: a
stateful client that assigns per-sender sequence numbers, pipelines
enqueues with applied-notifications, resends unapplied commands after a
leader change, and demultiplexes deliveries.  This is the ra_tpu
equivalent, built on the public API (ra_tpu.api).

A client owns a :class:`Mailbox` — the opaque "pid" the machine monitors
and delivers to.  The node shell routes SendMsg effects to callables, so
Mailbox is callable and thread-safe by way of deque's atomic appends.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Optional

from .. import api
from ..core.types import Priority, ServerId
# the ONE verdict enum (ISSUE 12): FifoClient's ok → slow →
# StopSending ladder speaks the same values the ingress CreditLadder
# and the wire credit frame serialize (imported from the enum's home
# module to keep this import cycle-free; ra_tpu.wire re-exports it)
from ..ingress.backpressure import OK, REJECT, SLOW, STATUS_NAMES

_mailbox_ids = itertools.count()


class Mailbox:
    """An addressable message sink standing in for an Erlang pid.

    Identity is the *name*, not the object: machine state keys enqueuers
    and consumers by pid, and pids cross pickle boundaries (WAL replay,
    snapshot install, TCP relays).  Identity-based hashing would make
    every unpickled copy a distinct enqueuer and silently break seqno
    dedup after recovery."""

    def __init__(self, name: str = "", node: str = "") -> None:
        self.name = name or f"mbox-{next(_mailbox_ids)}"
        #: node tag used by the machine's nodeup/noconnection handling
        self.node = node
        self.queue: deque = deque()

    def __call__(self, msg: Any) -> None:
        self.queue.append(msg)

    def drain(self) -> list:
        out = []
        while self.queue:
            out.append(self.queue.popleft())
        return out

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Mailbox) and other.name == self.name

    def __hash__(self) -> int:
        return hash(("Mailbox", self.name))

    def __repr__(self) -> str:
        return f"<Mailbox {self.name}>"


class StopSending(RuntimeError):
    """enqueue() refused: unapplied commands reached max_pending (the
    reference's `{error, stop_sending}`, ra_fifo_client.erl:106-110) —
    drain with flush()/poll_applied() before sending more.

    On the unified verdict surface (ISSUE 12) this IS the ``reject``
    tier: :attr:`VERDICT` carries the shared enum value the wire
    plane's credit frames serialize for the same condition."""

    #: the shared-admission-enum value this exception represents —
    #: one verdict enum for FifoClient, the ingress ladder and the
    #: wire credit frame
    VERDICT: int = REJECT


class FifoClient:
    """Enqueue/checkout session against one fifo cluster."""

    #: in-flight window where enqueue() starts answering "slow"
    #: (?SOFT_LIMIT, ra_fifo_client.erl:21)
    SOFT_LIMIT = 256

    def __init__(self, servers: list, router=None, tag: str = "c1",
                 node: str = "", soft_limit: int = SOFT_LIMIT,
                 max_pending: int = 0) -> None:
        assert servers, "need at least one member"
        self.servers = list(servers)
        self.router = router
        self.tag = tag
        self.soft_limit = soft_limit
        # hard ceiling defaults to 4x the soft signal so the graduated
        # ok -> slow -> StopSending protocol cannot invert
        self.max_pending = max_pending or 4 * soft_limit
        assert self.soft_limit <= self.max_pending
        # globally unique pid name: two clients sharing a tag must not
        # alias each other's enqueuer/consumer identity
        self.mailbox = Mailbox(name=f"{tag}.{next(_mailbox_ids)}", node=node)
        self.next_seqno = 1
        #: seqno -> raw msg, unacknowledged pipelined enqueues
        self.pending: dict[int, Any] = {}
        #: monotonic ts of the FIRST refused enqueue of the current
        #: StopSending episode (None when the window is open) — the
        #: client-side shed-decision input the ingress ladder
        #: generalizes (ISSUE 10 satellite): how LONG a session has
        #: been blocked, not just that it is
        self.blocked_since: Optional[float] = None
        #: enqueues refused by the hard window across the client's
        #: lifetime (the StopSending analogue of INGRESS_FIELDS
        #: ``rejected``)
        self.ingress_rejections = 0
        self._applied = Mailbox(name=f"{tag}-applied")
        self.deliveries: list = []       # [(msg_id, header, raw)]
        self._seed = servers[0]

    # -- enqueue ------------------------------------------------------------

    def enqueue(self, msg: Any) -> tuple:
        """Pipeline an enqueue; returns ``(status, seqno)`` where status
        is "ok", or "slow" once the unapplied window passes soft_limit
        (keep sending, but ease off — the reference's `{slow, State}`
        backpressure signal).  Raises :class:`StopSending` at
        max_pending.  Delivery/apply is asynchronous — track with
        :meth:`pending_count` / :meth:`flush`."""
        self.poll_applied()                  # status must see fresh acks
        if len(self.pending) >= self.max_pending:
            # observable shed input: stamp when THIS blocked episode
            # began (first refusal only) and count every refusal, so a
            # caller deciding to shed/defer can read "blocked for 2s,
            # 40 refusals" instead of a bare exception
            if self.blocked_since is None:
                self.blocked_since = time.monotonic()
            self.ingress_rejections += 1
            raise StopSending(f"{len(self.pending)} enqueues unapplied")
        self.blocked_since = None            # window open again
        seqno = self.next_seqno
        self.next_seqno += 1
        self.pending[seqno] = msg
        self._pipeline(seqno, msg)
        # status strings derive from the ONE shared verdict enum
        # (ra_tpu.wire.framing / ingress.backpressure): "ok"/"slow"
        # exactly as before, now spelled by the wire plane's names
        status = STATUS_NAMES[SLOW] \
            if len(self.pending) >= self.soft_limit else STATUS_NAMES[OK]
        return status, seqno

    def current_verdict(self) -> int:
        """The session's admission verdict on the shared enum: OK
        below soft_limit, SLOW past it, REJECT (= StopSending) at
        max_pending — what a credit frame would say about this
        session right now."""
        self.poll_applied()
        n = len(self.pending)
        if n >= self.max_pending:
            return REJECT
        return SLOW if n >= self.soft_limit else OK

    def credit_frame(self) -> bytes:
        """Serialize the session's current verdict with the wire
        plane's ONE credit-frame encoder (the ISSUE 12 unification):
        a FifoClient backpressure episode and a wire credit frame are
        the same protocol, byte for byte."""
        from ..wire.framing import encode_credit
        return encode_credit(0, [0], [max(0, self.next_seqno - 1)],
                             [self.current_verdict()])

    def _trace_ctx(self, seqno: int) -> str:
        """Deterministic ingress trace id for one enqueue (ISSUE 7):
        session tag + seqno, STABLE across resends — a post-leader-
        change resend of the same seqno records under the same id, so
        the duplicate committed entry the machine dedups is visible in
        the command's timeline rather than a mystery second lifecycle."""
        return f"{self.mailbox.name}/{seqno}"

    def _pipeline(self, seqno: int, msg: Any) -> None:
        target = self._leader_hint()
        try:
            api.pipeline_command(
                target, ("enqueue", self.mailbox, seqno, msg),
                correlation=seqno, notify_to=self._applied,
                priority=Priority.LOW, router=self.router,
                trace_ctx=self._trace_ctx(seqno))
        except RuntimeError:
            pass  # node down: stays pending, resend() recovers

    def enqueue_sync(self, msg: Any, timeout: float = 5.0) -> None:
        """Enqueue with consensus await (for tests needing certainty).
        The seqno stays in pending until the call succeeds so a timeout
        never leaves a permanent sequence gap — resend()/flush() retry it
        with the machine's dedup absorbing any duplicate."""
        seqno = self.next_seqno
        self.next_seqno += 1
        self.pending[seqno] = msg
        api.process_command(self._leader_hint(),
                            ("enqueue", self.mailbox, seqno, msg),
                            router=self.router, timeout=timeout,
                            trace_ctx=self._trace_ctx(seqno))
        self.pending.pop(seqno, None)

    def poll_applied(self) -> None:
        """Fold applied-notifications into the pending set."""
        for batch in self._applied.drain():
            for (corr, _reply) in batch:
                self.pending.pop(corr, None)

    def pending_count(self) -> int:
        self.poll_applied()
        return len(self.pending)

    def resend(self) -> None:
        """Re-pipeline all unacknowledged enqueues in seqno order — the
        post-leader-change recovery step (ra_fifo_client resends)."""
        self.poll_applied()
        for seqno in sorted(self.pending):
            self._pipeline(seqno, self.pending[seqno])

    def flush(self, timeout: float = 10.0) -> None:
        """Block until every pipelined enqueue has been applied.  Resends
        only when no acks have landed for a while (the reference client
        resends on leader change, not on a poll timer) — resending every
        poll would flood the log with duplicate committed entries."""
        deadline = time.monotonic() + timeout
        last_progress = time.monotonic()
        last_count = self.pending_count()
        while time.monotonic() < deadline:
            n = self.pending_count()
            if n == 0:
                return
            now = time.monotonic()
            if n < last_count:
                last_count, last_progress = n, now
            elif now - last_progress > 0.5:
                self.resend()
                last_progress = now
            time.sleep(0.02)
        raise TimeoutError(
            f"fifo client: {len(self.pending)} enqueues unapplied")

    # -- consume ------------------------------------------------------------

    @property
    def consumer_id(self) -> tuple:
        return (self.tag, self.mailbox)

    def checkout(self, lifetime: str = "auto", credit: int = 10,
                 timeout: float = 5.0) -> Any:
        return api.process_command(
            self._leader_hint(), ("checkout", (lifetime, credit),
                                  self.consumer_id),
            router=self.router, timeout=timeout)

    def cancel_checkout(self, timeout: float = 5.0) -> Any:
        return api.process_command(
            self._leader_hint(), ("checkout", "cancel", self.consumer_id),
            router=self.router, timeout=timeout)

    def dequeue(self, settled: bool = True, timeout: float = 5.0) -> Any:
        res = api.process_command(
            self._leader_hint(),
            ("checkout", ("dequeue", "settled" if settled else "unsettled"),
             self.consumer_id),
            router=self.router, timeout=timeout)
        return res.reply if hasattr(res, "reply") else res

    def settle(self, msg_ids, timeout: float = 5.0) -> Any:
        return api.process_command(
            self._leader_hint(), ("settle", tuple(msg_ids),
                                  self.consumer_id),
            router=self.router, timeout=timeout)

    def return_(self, msg_ids, timeout: float = 5.0) -> Any:
        return api.process_command(
            self._leader_hint(), ("return", tuple(msg_ids),
                                  self.consumer_id),
            router=self.router, timeout=timeout)

    def discard(self, msg_ids, timeout: float = 5.0) -> Any:
        return api.process_command(
            self._leader_hint(), ("discard", tuple(msg_ids),
                                  self.consumer_id),
            router=self.router, timeout=timeout)

    def poll_deliveries(self) -> list:
        """Drain the mailbox; returns newly delivered (msg_id, header, raw)
        and accumulates them in :attr:`deliveries`."""
        new = []
        for msg in self.mailbox.drain():
            if isinstance(msg, tuple) and msg and msg[0] == "delivery":
                _, _tag, batch = msg
                new.extend(batch)
        self.deliveries.extend(new)
        return new

    # -- leader tracking ----------------------------------------------------

    def _leader_hint(self) -> ServerId:
        """Best local guess at the leader: ask any reachable member for its
        leader_id; fall back to the member itself (process_command's
        redirect loop finishes the job; pipeline_command needs the guess
        to be right to avoid follower drops)."""
        from ..node import DEFAULT_ROUTER
        router = self.router or DEFAULT_ROUTER
        for sid in self.servers:
            node = router.nodes.get(sid.node)
            if node is None:
                continue
            shell = node.shells.get(sid.name)
            if shell is None:
                continue
            leader = shell.server.leader_id
            if leader is not None and leader.node in router.nodes:
                self._seed = leader
                return leader
            return sid
        return self._seed
