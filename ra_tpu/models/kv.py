"""Replicated key-value store machine — the ra-kv-store role.

The reference ecosystem's capability proof for linearizability is a
Raft-backed KV store driven by Jepsen (README.md:33-35 points at
ra-kv-store).  This is that machine for ra_tpu: put/delete/cas with
old-value replies, linearizable reads via consistent_query, and key
watchers built on the monitor effect vocabulary (ra_machine.erl:121-142
— send_msg + monitor/demonitor), so watcher death cleans up server
state exactly like ra_fifo's consumer monitors.

Snapshotting: a release_cursor is emitted every ``snapshot_interval``
applied commands (the ra_bench noop machine's release-cursor policy,
ra_bench.erl:43-49) — the whole KV map is the snapshot state.

Commands (all picklable tuples):
  ("put", key, value)          -> old value | None
  ("delete", key)              -> old value | None
  ("cas", key, expect, new)    -> ("ok", old) | ("failed", current)
                                  (new=None deletes on success)
  ("watch", key, pid)          -> "ok"; pid gets ("kv_event", key, value)
  ("unwatch", key, pid)        -> "ok"
  ("down", pid, reason)        -> builtin: drops every watch held by pid
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ..core.machine import ApplyMeta, Machine
from ..core.types import Demonitor, Monitor, ReleaseCursor, SendMsg


@dataclasses.dataclass(frozen=True)
class KvState:
    data: dict
    #: key -> tuple of watcher pids
    watchers: dict

    def evolve(self, **kw: Any) -> "KvState":
        return dataclasses.replace(self, **kw)


class KvMachine(Machine):
    version = 0

    def __init__(self, snapshot_interval: int = 4096) -> None:
        self.snapshot_interval = snapshot_interval

    def init(self, config: dict) -> KvState:
        return KvState(data={}, watchers={})

    # -- helpers -----------------------------------------------------------

    def _notify(self, state: KvState, key: Any, value: Any,
                effects: list) -> None:
        for pid in state.watchers.get(key, ()):
            effects.append(SendMsg(pid, ("kv_event", key, value)))

    def _maybe_cursor(self, meta: ApplyMeta, state: KvState,
                      effects: list) -> None:
        if meta.index % self.snapshot_interval == 0:
            effects.append(ReleaseCursor(meta.index, state))

    # -- apply -------------------------------------------------------------

    def apply(self, meta: ApplyMeta, command: Any, state: KvState):
        effects: list = []
        reply: Any = "ok"
        op = command[0] if isinstance(command, tuple) and command else None

        if op == "put":
            _, key, value = command
            reply = state.data.get(key)
            data = dict(state.data)
            data[key] = value
            state = state.evolve(data=data)
            self._notify(state, key, value, effects)
        elif op == "delete":
            _, key = command
            reply = state.data.get(key)
            if key in state.data:
                data = dict(state.data)
                del data[key]
                state = state.evolve(data=data)
                self._notify(state, key, None, effects)
        elif op == "cas":
            _, key, expect, new = command
            current = state.data.get(key)
            if current == expect:
                data = dict(state.data)
                if new is None:
                    data.pop(key, None)
                else:
                    data[key] = new
                state = state.evolve(data=data)
                reply = ("ok", current)
                self._notify(state, key, new, effects)
            else:
                reply = ("failed", current)
        elif op == "watch":
            _, key, pid = command
            watchers = dict(state.watchers)
            if pid not in watchers.get(key, ()):
                watchers[key] = tuple(watchers.get(key, ())) + (pid,)
            state = state.evolve(watchers=watchers)
            effects.append(Monitor("process", pid))
        elif op == "unwatch":
            _, key, pid = command
            state = self._drop_watch(state, key, pid)
            if not any(pid in pids for pids in state.watchers.values()):
                effects.append(Demonitor("process", pid))
        elif op == "down":
            _, pid, _reason = command
            for key in [k for k, pids in state.watchers.items()
                        if pid in pids]:
                state = self._drop_watch(state, key, pid)
            reply = None
        else:
            # unknown/misspelled op: surface it instead of acking "ok"
            reply = ("error", "unknown_command")
        self._maybe_cursor(meta, state, effects)
        return state, reply, effects

    @staticmethod
    def _drop_watch(state: KvState, key: Any, pid: Any) -> KvState:
        pids = tuple(p for p in state.watchers.get(key, ()) if p != pid)
        watchers = dict(state.watchers)
        if pids:
            watchers[key] = pids
        else:
            watchers.pop(key, None)
        return state.evolve(watchers=watchers)

    def overview(self, state: KvState) -> Any:
        return {"num_keys": len(state.data),
                "num_watched_keys": len(state.watchers)}


# -- query functions (use with local/leader/consistent_query) --------------

def _get(key: Any, state: KvState) -> Optional[Any]:
    return state.data.get(key)


def query_get(key: Any):
    """Build a query fun reading one key.  functools.partial of a
    module-level function, NOT a lambda: query funs cross pickle
    boundaries on TCP-transport clusters."""
    import functools
    return functools.partial(_get, key)


def query_keys(state: KvState) -> list:
    return sorted(state.data)


def query_size(state: KvState) -> int:
    return len(state.data)
