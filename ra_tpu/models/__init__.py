from .counter import CounterMachine
from .fifo import FifoMachine
from .fifo_client import FifoClient, Mailbox
from .kv import KvMachine
from .registers import RegisterMachine
from .queue import QueueMachine

__all__ = ["CounterMachine", "FifoMachine", "FifoClient", "KvMachine",
           "Mailbox", "QueueMachine", "RegisterMachine"]
