from .counter import CounterMachine
from .fifo import FifoMachine
from .fifo_client import FifoClient, Mailbox
from .queue import QueueMachine

__all__ = ["CounterMachine", "FifoMachine", "FifoClient", "Mailbox",
           "QueueMachine"]
