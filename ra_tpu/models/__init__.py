from .counter import CounterMachine
