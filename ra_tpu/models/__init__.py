from .counter import CounterMachine
from .fifo import FifoMachine
from .fifo_client import FifoClient, Mailbox, StopSending
from .jit_fifo import JitFifoMachine
from .jit_kv import JitKvMachine
from .kv import KvMachine
from .registers import RegisterMachine
from .queue import QueueMachine
from .stream import StreamMachine
from .ttl_kv import TtlKvMachine

__all__ = ["CounterMachine", "FifoMachine", "FifoClient", "JitFifoMachine",
           "JitKvMachine", "KvMachine", "Mailbox", "QueueMachine",
           "RegisterMachine", "StopSending", "StreamMachine",
           "TtlKvMachine"]
