"""StreamMachine — an offset-addressed log with consumer cursors.

The RabbitMQ-streams shape: an append-only log addressed by absolute
offset, a retention window (oldest ``capacity`` entries survive; older
offsets fall off the tail), and named consumer GROUPS whose committed
cursors advance monotonically through the log — stream consumers track
their own position, the machine only stores the committed cursor.  This
is the second machine of the ISSUE 20 read library: the interesting
workload is read-dominated (consumers replaying offsets), which is
exactly what the engine's lease/read-index plane serves with zero log
appends.

State per lane: ``buf int32[capacity]`` ring (slot = offset % capacity),
``tail`` (next offset to write), ``base`` (oldest retained offset —
``base <= offset < tail`` is readable), ``cursors int32[groups]``.

Command encoding (command_spec int32[3]): ``[op, a, b]``

  op 0 noop                   (term-opening entry)
  op 1 append(value)          reply [1, offset]        (value >= 0)
  op 2 commit_cursor(g, off)  reply [1, cursor]   (max-merge, clamped
                               to tail — a cursor never outruns the log)
  op 3 truncate(upto)         reply [1, base]     (advance retention)

Reply is int32[2].  Bad group / negative value degrade to a no-op with
reply [-2, -1].

Query encoding (query_spec int32[2]): ``[op, a]`` — the ISSUE 20
vectorized read path:

  op 0 bounds()        reply [tail, base]
  op 1 read(offset)    reply [1, value] if base <= offset < tail
                              else [0, -1]
  op 2 cursor(g)       reply [1, cursor]         (bad g -> [0, -1])

Batch apply: a window of only noop/append — the firehose steady state —
folds in one vectorized pass (append positions are an exclusive cumsum
of the admit flags; values land via the exact one-hot matmul, and when
the window is wider than the ring only the LAST append aliasing each
slot survives, as in jit_fifo's fold).  Windows containing cursor/
truncate ops fall back to the in-order masked sequential fold.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine
from ..ops.exact import place16

_I32 = jnp.int32


class StreamMachine(JitMachine):
    command_spec = ("int32", (3,))
    reply_spec = ("int32", (2,))
    query_spec = ("int32", (2,))
    query_reply_spec = ("int32", (2,))
    version = 0
    #: append order IS offset order — batch apply stays sound because
    #: jit_apply_batch folds the window IN ORDER (vectorized fast path
    #: for append-only windows, masked sequential fold else)
    supports_batch_apply = True

    def __init__(self, capacity: int = 64, groups: int = 4) -> None:
        self.capacity = capacity
        self.groups = groups

    def jit_init(self, n_lanes: int):
        N, Q, G = n_lanes, self.capacity, self.groups
        return {
            "buf": jnp.zeros((N, Q), _I32),
            "tail": jnp.zeros((N,), _I32),
            "base": jnp.zeros((N,), _I32),
            "cursors": jnp.zeros((N, G), _I32),
        }

    def jit_apply(self, meta, command, state):
        Q, G = self.capacity, self.groups
        op = command[..., 0]
        a = command[..., 1]
        b = command[..., 2]
        buf, tail, base = state["buf"], state["tail"], state["base"]
        cursors = state["cursors"]

        app = (op == 1) & (a >= 0)
        slot = jnp.mod(tail, Q)
        hot = (jnp.arange(Q) == slot[..., None]) & app[..., None]
        buf = jnp.where(hot, a[..., None], buf)
        new_tail = tail + app.astype(_I32)

        g_ok = (a >= 0) & (a < G)
        commit = (op == 2) & g_ok
        g = jnp.clip(a, 0, G - 1)
        cur = jnp.take_along_axis(cursors, g[..., None], axis=-1)[..., 0]
        # max-merge clamped to tail: replayed/duplicate commits are
        # no-ops and a cursor can never point past the log end
        new_cur = jnp.clip(jnp.maximum(cur, b), 0, new_tail)
        chot = (jnp.arange(G) == g[..., None]) & commit[..., None]
        cursors = jnp.where(chot, new_cur[..., None], cursors)

        trunc = op == 3
        new_base = jnp.where(trunc,
                             jnp.clip(jnp.maximum(base, a), 0, new_tail),
                             base)
        # retention: an append that laps the ring evicts the oldest offset
        new_base = jnp.maximum(new_base, new_tail - Q)

        reply_v = jnp.where(op == 1, tail,
                            jnp.where(commit, new_cur,
                                      jnp.where(trunc, new_base, 0)))
        ok = (op == 0) | app | commit | trunc
        code = jnp.where(ok, jnp.where(op == 0, 0, 1), -2)
        reply = jnp.stack([code, jnp.where(ok, reply_v, -1)], axis=-1)
        new_state = {"buf": buf, "tail": new_tail, "base": new_base,
                     "cursors": cursors}
        return new_state, reply

    # -- one-shot window fold (engine batch path) --------------------------

    def jit_apply_batch(self, meta, commands, mask, state):
        # fast only for noop/append windows (the firehose steady state);
        # cursor commits and truncates read evolving state in order
        fast_ok = ~jnp.any(mask & (commands[..., 0] >= 2))
        return self.window_fold_dispatch(meta, commands, mask, state,
                                         fast_ok)

    def _batch_fast(self, commands, mask, state):
        """Vectorized append-only window fold."""
        Q = self.capacity
        op = jnp.where(mask, commands[..., 0], 0)           # [..., A]
        val = commands[..., 1]
        app = (op == 1) & (val >= 0)
        rank = jnp.cumsum(app.astype(_I32), axis=-1) \
            - app.astype(_I32)                               # exclusive
        n_app = jnp.sum(app.astype(_I32), axis=-1)
        tail = state["tail"]

        # scatter-free ring write (see jit_fifo._batch_fast): written
        # slots are offsets tail0..tail0+n_app-1; when A > Q several
        # appends alias one slot mod Q and only the LAST survives, so
        # each slot selects the maximal aliasing rank
        qr = jnp.arange(Q)
        jd = jnp.mod(qr - tail[..., None], Q)                # [..., Q]
        written = jd < n_app[..., None]
        rank_win = jd + Q * ((n_app[..., None] - 1 - jd) // Q)
        onehot = (app[..., None, :] &
                  (rank[..., None, :] == rank_win[..., None])
                  ).astype(jnp.float32)                      # [..., Q, A]
        placed = place16(onehot, val)

        new_tail = tail + n_app
        new_state = dict(state)
        new_state["buf"] = jnp.where(written, placed, state["buf"])
        new_state["tail"] = new_tail
        new_state["base"] = jnp.maximum(state["base"], new_tail - Q)
        return new_state

    # -- vectorized read path (ISSUE 20) -----------------------------------

    def jit_query(self, queries, state):
        # queries: [..., Kr, 2]; state buf [..., Q], tail/base [...],
        # cursors [..., G] — pure gathers, no state mutation (consumer
        # replay reads never enter the log)
        Q, G = self.capacity, self.groups
        op = queries[..., 0]
        a = queries[..., 1]
        tail = state["tail"][..., None]                      # [..., 1]
        base = state["base"][..., None]

        off_ok = (a >= base) & (a < tail)
        slot = jnp.mod(jnp.clip(a, 0, None), Q)
        val = jnp.take_along_axis(state["buf"][..., None, :],
                                  slot[..., None], axis=-1)[..., 0]
        g_ok = (a >= 0) & (a < G)
        g = jnp.clip(a, 0, G - 1)
        cur = jnp.take_along_axis(state["cursors"][..., None, :],
                                  g[..., None], axis=-1)[..., 0]

        code = jnp.where(op == 0, tail,
                         jnp.where(op == 1, off_ok.astype(_I32),
                                   g_ok.astype(_I32)))
        value = jnp.where(op == 0, base,
                          jnp.where(op == 1,
                                    jnp.where(off_ok, val, -1),
                                    jnp.where(g_ok, cur, -1)))
        return jnp.stack([code, value], axis=-1)

    # -- host protocol -----------------------------------------------------

    def encode_command(self, command):
        try:
            if isinstance(command, tuple) and command:
                kind = command[0]
                if kind == "append" and len(command) == 2:
                    return jnp.asarray([1, int(command[1]), 0], _I32)
                if kind == "commit" and len(command) == 3:
                    return jnp.asarray([2, int(command[1]),
                                        int(command[2])], _I32)
                if kind == "truncate" and len(command) == 2:
                    return jnp.asarray([3, int(command[1]), 0], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((3,), _I32)

    def decode_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)

    def encode_query(self, query):
        try:
            if isinstance(query, tuple) and query:
                kind = query[0]
                if kind == "read" and len(query) == 2:
                    return jnp.asarray([1, int(query[1])], _I32)
                if kind == "cursor" and len(query) == 2:
                    return jnp.asarray([2, int(query[1])], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((2,), _I32)  # bounds()

    def decode_query_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)


def query_bounds(state) -> tuple:
    """(base, tail) readable-offset window (host-path query fun)."""
    return (int(state["base"]), int(state["tail"]))
