"""FIFO queue state machine — the capability-proof machine.

The reference ships ``test/ra_fifo.erl`` (1,520 LoC), a full quorum-queue
state machine with per-enqueuer sequence deduplication, consumer checkout
credit, settlement/return/discard, process-down handling, and periodic
release-cursor emission — both a test fixture and the proof that the
machine behaviour contract is rich enough for real workloads
(SURVEY.md §4.6).  This module is the same capability proof for ra_tpu,
designed fresh around :class:`ra_tpu.core.machine.Machine`:

* commands are plain tuples (picklable — they travel through the WAL and
  snapshots),
* consumer/enqueuer "pids" are opaque hashable tokens; deliveries go out
  as :class:`SendMsg` effects which the node shell routes to callables
  (see ra_tpu/models/fifo_client.py:Mailbox),
* process lifecycle uses the Monitor/Demonitor machine effects plus the
  ``("down", pid, reason)`` / ``("nodeup", node)`` builtin commands
  (ra_machine.erl builtin_command; ra_fifo.erl:308-368),
* the release cursor is emitted whenever the queue drains empty and every
  ``shadow_copy_interval`` raft indexes (ra_fifo.erl SHADOW_COPY_INTERVAL,
  :289-307 — there 4096).

Protocol (command tuples):

    ("enqueue", pid_or_None, seqno_or_None, raw_msg)
    ("checkout", spec, (tag, pid))     spec: ("auto", n) | ("once", n)
                                            | ("dequeue", "settled")
                                            | ("dequeue", "unsettled")
                                            | "cancel"
    ("settle", (msg_id, ...), (tag, pid))
    ("return", (msg_id, ...), (tag, pid))
    ("discard", (msg_id, ...), (tag, pid))
    ("purge",)
    ("down", pid, reason)              builtin, appended on monitor DOWN
    ("nodeup", node) / ("nodedown", node)

Deliveries sent to consumer pids:  ("delivery", tag, [(msg_id, header, msg)])
where header is a dict with "delivery_count".
"""
from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core.machine import ApplyMeta, Machine
from ..core.types import Demonitor, Monitor, ReleaseCursor, SendMsg

#: emit a release cursor at least every this many raft indexes
SHADOW_COPY_INTERVAL = 4096


@dataclass
class Enqueuer:
    """Per-sender dedup/ordering state (ra_fifo.erl enqueuer record)."""

    next_seqno: Optional[int] = None     # next expected; None until first
    pending: dict = field(default_factory=dict)  # seqno -> (raft_idx, msg)
    status: str = "up"                   # up | suspected


@dataclass
class Consumer:
    """Per-consumer checkout state (ra_fifo.erl customer record)."""

    checked_out: dict = field(default_factory=dict)
    # msg_id -> (msg_in_id, raft_idx, header, raw_msg)
    next_msg_id: int = 0
    credit: int = 0                      # max simultaneous unsettled msgs
    seen: int = 0                        # lifetime deliveries (for "once")
    lifetime: str = "auto"               # auto | once
    suspected: bool = False


@dataclass
class FifoState:
    name: str = "fifo"
    # ready messages: msg_in_id -> (raft_idx, header, raw_msg); insertion
    # order of an OrderedDict is FIFO order (returns re-insert at the front
    # via a sorted rebuild, which is rare)
    messages: OrderedDict = field(default_factory=OrderedDict)
    next_msg_in_id: int = 0
    enqueuers: dict = field(default_factory=dict)      # pid -> Enqueuer
    consumers: dict = field(default_factory=dict)      # (tag,pid) -> Consumer
    service_queue: deque = field(default_factory=deque)  # (tag,pid) rotation
    # raft indexes still referenced by live (ready or unsettled) messages
    live: set = field(default_factory=set)
    last_release_cursor: int = 0


def _has_capacity(con: Consumer) -> bool:
    if con.suspected:
        return False
    if con.lifetime == "once" and con.seen >= con.credit:
        return False
    return len(con.checked_out) < con.credit


class FifoMachine(Machine):
    """A FIFO queue with consumer checkout semantics."""

    version = 1

    def __init__(self, name: str = "fifo",
                 shadow_copy_interval: int = SHADOW_COPY_INTERVAL) -> None:
        self.name = name
        self.shadow_copy_interval = shadow_copy_interval

    # -- lifecycle ----------------------------------------------------------

    def init(self, config: dict) -> FifoState:
        return FifoState(name=config.get("name", self.name))

    def state_enter(self, raft_state: str, state: FifoState) -> list:
        if raft_state == "leader":
            # re-establish monitors on every known external process
            # (ra_fifo.erl:370-380)
            effs: list = []
            for pid in set(state.enqueuers) | {p for _, p in state.consumers}:
                effs.append(Monitor("process", pid))
            return effs
        if raft_state == "eol":
            # cluster deleted: tell every attached process (ra_fifo.erl:381)
            pids = set(state.enqueuers) | {p for _, p in state.consumers}
            return [SendMsg(pid, ("eol", state.name))
                    for pid in pids]
        return []

    # -- apply --------------------------------------------------------------

    def apply(self, meta: ApplyMeta, command: Any, state: FifoState):
        effects: list = []
        reply: Any = "ok"
        kind = command[0] if isinstance(command, tuple) and command else None

        was_live = bool(state.messages) or bool(state.live)
        if kind == "enqueue":
            _, pid, seqno, raw = command
            self._enqueue(state, meta.index, pid, seqno, raw, effects)
        elif kind == "checkout":
            _, spec, cid = command
            reply = self._checkout(state, spec, cid, effects)
        elif kind in ("settle", "discard"):
            _, msg_ids, cid = command
            self._settle(state, msg_ids, cid, effects)
        elif kind == "return":
            _, msg_ids, cid = command
            self._return(state, msg_ids, cid)
        elif kind == "purge":
            count = len(state.messages)
            for (idx, _h, _m) in state.messages.values():
                state.live.discard(idx)
            state.messages.clear()
            reply = ("purge", count)
        elif kind == "down":
            _, pid, reason = command
            self._down(state, pid, reason, effects)
        elif kind == "nodeup":
            _, node = command
            for pid, enq in state.enqueuers.items():
                if getattr(pid, "node", None) == node:
                    enq.status = "up"
                    effects.append(Monitor("process", pid))
            for (tag, pid), con in state.consumers.items():
                if getattr(pid, "node", None) == node:
                    con.suspected = False
                    effects.append(Monitor("process", pid))
                    self._maybe_serve(state, (tag, pid))
        elif kind == "nodedown":
            pass
        # every state change may have freed capacity or added messages
        self._deliver_ready(state, effects)
        self._maybe_release_cursor(meta, state, effects, was_live)
        return state, reply, effects

    # -- enqueue path -------------------------------------------------------

    def _enqueue(self, state: FifoState, raft_idx: int, pid: Any,
                 seqno: Optional[int], raw: Any, effects: list) -> None:
        if pid is None or seqno is None:
            # untracked enqueue: no ordering/dedup guarantees
            self._add_ready(state, raft_idx, {"delivery_count": 0}, raw)
            return
        enq = state.enqueuers.get(pid)
        if enq is None:
            enq = state.enqueuers[pid] = Enqueuer()
            effects.append(Monitor("process", pid))
        if enq.next_seqno is None:
            # client seqnos start at 1 by contract (FifoClient); baselining
            # at the first *seen* seqno would silently drop seqno 1 when a
            # later enqueue commits first (resends can reorder commits)
            enq.next_seqno = 1
        if seqno < enq.next_seqno:
            return  # duplicate delivery of an applied enqueue: drop
        if seqno > enq.next_seqno:
            # out of order (an earlier enqueue is still in flight):
            # stash until the gap fills (ra_fifo pending enqueues)
            enq.pending[seqno] = (raft_idx, raw)
            return
        self._add_ready(state, raft_idx, {"delivery_count": 0}, raw)
        enq.next_seqno += 1
        while enq.next_seqno in enq.pending:
            idx, msg = enq.pending.pop(enq.next_seqno)
            self._add_ready(state, idx, {"delivery_count": 0}, msg)
            enq.next_seqno += 1

    def _add_ready(self, state: FifoState, raft_idx: int, header: dict,
                   raw: Any) -> None:
        state.messages[state.next_msg_in_id] = (raft_idx, header, raw)
        state.next_msg_in_id += 1
        state.live.add(raft_idx)

    # -- checkout path ------------------------------------------------------

    def _checkout(self, state: FifoState, spec: Any, cid: tuple,
                  effects: list) -> Any:
        tag, pid = cid
        if spec == "cancel":
            con = state.consumers.pop(cid, None)
            if con is not None:
                self._requeue_checked_out(state, con)
                if pid not in {p for _, p in state.consumers} and \
                        pid not in state.enqueuers:
                    effects.append(Demonitor("process", pid))
            return "ok"
        if isinstance(spec, tuple) and spec[0] == "dequeue":
            # one-shot pop, no standing consumer (ra_fifo.erl:254-279)
            mid = next(iter(state.messages), None)
            if mid is None:
                return ("dequeue", "empty")
            raft_idx, header, raw = state.messages.pop(mid)
            if spec[1] == "settled":
                state.live.discard(raft_idx)
                return ("dequeue", (header, raw))
            con = state.consumers.setdefault(cid, Consumer(lifetime="once"))
            con.credit = max(con.credit, 1)
            msg_id = con.next_msg_id
            con.next_msg_id += 1
            con.seen += 1
            con.checked_out[msg_id] = (mid, raft_idx, header, raw)
            effects.append(Monitor("process", pid))
            return ("dequeue", (msg_id, header, raw))
        lifetime, num = spec
        con = state.consumers.get(cid)
        if con is None:
            con = state.consumers[cid] = Consumer()
            effects.append(Monitor("process", pid))
        con.lifetime = lifetime
        con.credit = num
        con.suspected = False
        self._maybe_serve(state, cid)
        return "ok"

    def _maybe_serve(self, state: FifoState, cid: tuple) -> None:
        if cid not in state.service_queue and \
                cid in state.consumers and \
                _has_capacity(state.consumers[cid]):
            state.service_queue.append(cid)

    def _deliver_ready(self, state: FifoState, effects: list) -> None:
        """Round-robin ready messages to consumers with spare credit,
        batching one delivery effect per consumer (ra_fifo checkout loop)."""
        batches: dict = {}
        while state.messages and state.service_queue:
            cid = state.service_queue[0]
            con = state.consumers.get(cid)
            if con is None or not _has_capacity(con):
                state.service_queue.popleft()
                continue
            mid, (raft_idx, header, raw) = next(iter(state.messages.items()))
            del state.messages[mid]
            msg_id = con.next_msg_id
            con.next_msg_id += 1
            con.seen += 1
            con.checked_out[msg_id] = (mid, raft_idx, header, raw)
            batches.setdefault(cid, []).append((msg_id, header, raw))
            # rotate for fairness across consumers
            state.service_queue.rotate(-1)
        # prune exhausted consumers from the rotation
        state.service_queue = deque(
            cid for cid in state.service_queue
            if cid in state.consumers and _has_capacity(state.consumers[cid]))
        for (tag, pid), msgs in batches.items():
            effects.append(SendMsg(pid, ("delivery", tag, msgs)))

    # -- settlement ---------------------------------------------------------

    def _settle(self, state: FifoState, msg_ids: tuple, cid: tuple,
                effects: list) -> None:
        """Settle and discard share semantics until a dead-letter target
        exists (ra_fifo discard drops the message the same way)."""
        con = state.consumers.get(cid)
        if con is None:
            return
        for msg_id in msg_ids:
            entry = con.checked_out.pop(msg_id, None)
            if entry is not None:
                _mid, raft_idx, _header, _raw = entry
                state.live.discard(raft_idx)
        if con.lifetime == "once" and con.seen >= con.credit and \
                not con.checked_out:
            state.consumers.pop(cid, None)
            pid = cid[1]
            if pid not in {p for _, p in state.consumers} and \
                    pid not in state.enqueuers:
                effects.append(Demonitor("process", pid))
        else:
            self._maybe_serve(state, cid)

    def _return(self, state: FifoState, msg_ids: tuple, cid: tuple) -> None:
        con = state.consumers.get(cid)
        if con is None:
            return
        entries = []
        for msg_id in msg_ids:
            entry = con.checked_out.pop(msg_id, None)
            if entry is not None:
                entries.append(entry)
                con.seen = max(0, con.seen - 1)
        self._return_entries(state, entries)
        self._maybe_serve(state, cid)

    def _requeue_checked_out(self, state: FifoState, con: Consumer) -> None:
        if con.checked_out:
            self._return_entries(state, con.checked_out.values())
            con.checked_out.clear()

    def _return_entries(self, state: FifoState, entries) -> None:
        returned = []
        for (mid, raft_idx, header, raw) in entries:
            header = dict(header)
            header["delivery_count"] = header.get("delivery_count", 0) + 1
            returned.append((mid, (raft_idx, header, raw)))
        if returned:
            merged = sorted(list(state.messages.items()) + returned)
            state.messages = OrderedDict(merged)

    # -- process lifecycle --------------------------------------------------

    def _down(self, state: FifoState, pid: Any, reason: Any,
              effects: list) -> None:
        if reason == "noconnection":
            # connection loss is not death: suspect and await nodeup
            # (ra_fifo.erl:308-328)
            enq = state.enqueuers.get(pid)
            if enq is not None:
                enq.status = "suspected"
            for (tag, p), con in state.consumers.items():
                if p == pid:
                    con.suspected = True
            return
        state.enqueuers.pop(pid, None)
        dead = [cid for cid in state.consumers if cid[1] == pid]
        for cid in dead:
            con = state.consumers.pop(cid)
            self._requeue_checked_out(state, con)
            try:
                state.service_queue.remove(cid)
            except ValueError:
                pass

    # -- snapshots ----------------------------------------------------------

    def _maybe_release_cursor(self, meta: ApplyMeta, state: FifoState,
                              effects: list, was_live: bool) -> None:
        interval_hit = (meta.index - state.last_release_cursor >=
                        self.shadow_copy_interval)
        # only the command that *drained* the queue emits a cursor, and at
        # most every interval/8 indexes — a depth-0/1 request-reply
        # workload drains on every settle and must not snapshot per message
        drained = (was_live and not state.messages and not state.live and
                   meta.index - state.last_release_cursor >=
                   max(1, self.shadow_copy_interval // 8))
        if interval_hit or drained:
            state.last_release_cursor = meta.index
            effects.append(ReleaseCursor(meta.index, self.dehydrate(state)))

    def dehydrate(self, state: FifoState) -> FifoState:
        """Snapshot copy (ra_fifo:dehydrate_state) — deep enough that later
        mutation never aliases the snapshot."""
        import copy
        return copy.deepcopy(state)

    def live_indexes(self, state: FifoState) -> list:
        return sorted(state.live)

    # -- introspection ------------------------------------------------------

    def overview(self, state: FifoState) -> dict:
        return {
            "type": "fifo",
            "name": state.name,
            "messages_ready": len(state.messages),
            "messages_checked_out": sum(len(c.checked_out)
                                        for c in state.consumers.values()),
            "num_consumers": len(state.consumers),
            "num_enqueuers": len(state.enqueuers),
        }


# -- query functions for ra.local_query / leader_query ----------------------

def query_messages_ready(state: FifoState) -> int:
    return len(state.messages)


def query_messages_checked_out(state: FifoState) -> int:
    return sum(len(c.checked_out) for c in state.consumers.values())


def query_consumer_count(state: FifoState) -> int:
    return len(state.consumers)


def query_processes(state: FifoState) -> list:
    return sorted({repr(p) for p in state.enqueuers} |
                  {repr(p) for _, p in state.consumers})
