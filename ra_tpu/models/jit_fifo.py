"""JitFifoMachine — the FIFO capability machine on the device apply path.

The host :class:`~ra_tpu.models.fifo.FifoMachine` mirrors the reference's
``test/ra_fifo.erl`` (1,520 LoC) with unbounded Python state, consumer
processes, and delivery effects.  That shape cannot fold on-device.  This
machine is the TPU-native counterpart for the BASELINE.md "5,000 clusters
x 5 members, fifo machine" row: a **fixed-capacity** per-lane queue whose
state is a handful of dense arrays, covering the ra_fifo command
vocabulary — ordered enqueue, settled and unsettled dequeue, settlement,
return-with-redelivery-count, purge, **registered consumers with
per-consumer credit, consumer cancel, and consumer-down requeue**
(ra_fifo.erl apply clauses :254-368) — as a shape-stable fold.

Queue ops do not commute, but the machine still supports the engine's
one-shot window fold (``jit_apply_batch``): a window of only noop/
enqueue/dequeue-settled commands — the ra_bench workload and the
quorum-queue steady state — folds vectorized via a clamped-add
``associative_scan`` (see the method comment); anything else falls back
to an in-order masked ``lax.scan`` of ``jit_apply`` under a
``lax.cond``.

Scope split vs the host machine: pull-style checkout (the device cannot
emit delivery effects), death == cancel (the host's ``noconnection``
suspect/nodeup dance and enqueuer seq-dedup stay host-side), and a
bounded consumer table.  Everything that IS here is differentially
tested against the host oracle (tests/test_jit_fifo.py).

State (leading lane axis added by ``jit_init``; the engine broadcasts a
member axis):

* ``buf/dc/mid int32[Q]`` — ready-message ring: payload value, delivery
  count, and enqueue ticket (the host machine's ``msg_in_id``).  The
  window is always ticket-sorted: enqueues append fresh tickets, returns
  re-insert at ticket rank.
* ``head/tail int32`` — ready window is ``head..tail-1`` (slot = idx % Q)
* ``co_id/co_val/co_dc/co_mid int32[K]`` — checked-out (unsettled) table;
  ``co_id < 0`` marks a free row
* ``co_owner int32[K]`` — consumer slot owning the row; ``C`` (the
  consumer-table size) marks an anonymous (op 3) checkout
* ``con_pid/con_credit int32[C]`` — registered consumers; pid < 0 free
* ``next_id int32`` — monotonic message-id source for unsettled dequeues
* ``next_mid int32`` — monotonic enqueue-ticket source
* ``n_dropped int32`` — messages discarded by the drop_head policy

**Capacity contract**: ``capacity`` bounds LIVE messages (ready +
checked-out), so a return/cancel requeue can never overflow the ring.
``overflow`` picks the full-queue enqueue policy: ``"reject"`` replies
-2 (ra_fifo's implicit backpressure); ``"drop_head"`` discards the
oldest READY message and admits the new one (the quorum-queue
max-length drop-head policy), counting drops in ``n_dropped``.

Command encoding (command_spec int32[3]): ``[op, a, b]``

  op 0  noop                       (term-opening entry)
  op 1  enqueue(value)             reply  1 ok | -2 queue full (reject)
  op 2  dequeue settled            reply  value | -1 empty
  op 3  dequeue unsettled (anon)   reply  msg_id | -1 empty | -3 table full
  op 4  settle(msg_id)             reply  1 | 0 unknown id
  op 5  return(msg_id)             reply  1 | 0 unknown id
  op 6  purge                      reply  number of ready messages dropped
  op 7  attach(pid, credit)        reply  1 | -4 consumer table full
  op 8  cancel(pid)                reply  #messages requeued (0 unknown)
  op 9  down(pid)                  alias of cancel (death semantics)
  op 10 checkout(pid)              reply  msg_id | -4 unknown consumer |
                                          -1 empty | -5 no credit |
                                          -3 checkout table full
  op 11 set_credit(pid, credit)    reply  1 | 0 unknown consumer

A returned/requeued message re-enters the ready window at its **original
enqueue position** relative to the other ready messages (insert at
ticket rank), exactly like the host machine's sorted re-insert
(fifo.py ``_return_entries``), with delivery_count+1.  Return and
cancel share one rank-merge: each requeued row lands at its ticket rank
and ready entries gather from their shifted source slot — O(Q*K)
comparisons plus one gather per array, shape-stable, no sequential
loop.  Payload values and pids must be >= 0 so they never collide with
error replies / free markers.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.machine import JitMachine, cond_concrete
from ..ops.exact import place16

_I32 = jnp.int32


def _take(arr, idx):
    return jnp.take_along_axis(arr, idx[..., None], axis=-1)[..., 0]


class JitFifoMachine(JitMachine):
    command_spec = ("int32", (3,))
    reply_spec = ("int32", ())
    version = 0
    #: queue ops do NOT commute — batch apply is still sound because
    #: jit_apply_batch folds the window IN ORDER (vectorized fast path
    #: for noop/enqueue/dequeue windows, masked sequential fold else)
    supports_batch_apply = True

    def __init__(self, capacity: int = 64, checkout_slots: int = 8,
                 consumer_slots: int = 4,
                 overflow: str = "reject") -> None:
        if overflow not in ("reject", "drop_head"):
            raise ValueError(f"unknown overflow policy {overflow!r}")
        self.capacity = capacity
        self.checkout_slots = checkout_slots
        self.consumer_slots = consumer_slots
        self.overflow = overflow

    def jit_init(self, n_lanes: int):
        N, Q, K, C = (n_lanes, self.capacity, self.checkout_slots,
                      self.consumer_slots)
        return {
            "buf": jnp.zeros((N, Q), _I32),
            "dc": jnp.zeros((N, Q), _I32),
            "mid": jnp.zeros((N, Q), _I32),
            "head": jnp.zeros((N,), _I32),
            "tail": jnp.zeros((N,), _I32),
            "co_id": jnp.full((N, K), -1, _I32),
            "co_val": jnp.zeros((N, K), _I32),
            "co_dc": jnp.zeros((N, K), _I32),
            "co_mid": jnp.zeros((N, K), _I32),
            "co_owner": jnp.zeros((N, K), _I32),
            "con_pid": jnp.full((N, C), -1, _I32),
            "con_credit": jnp.zeros((N, C), _I32),
            "next_id": jnp.zeros((N,), _I32),
            "next_mid": jnp.zeros((N,), _I32),
            "n_dropped": jnp.zeros((N,), _I32),
        }

    def jit_apply(self, meta, command, state):
        Q, K, C = self.capacity, self.checkout_slots, self.consumer_slots
        op = command[..., 0]
        a = command[..., 1]
        b = command[..., 2]
        head, tail = state["head"], state["tail"]
        next_id, next_mid = state["next_id"], state["next_mid"]
        buf, dc, mid = state["buf"], state["dc"], state["mid"]
        co_id, co_val = state["co_id"], state["co_val"]
        co_dc, co_mid = state["co_dc"], state["co_mid"]
        co_owner = state["co_owner"]
        con_pid, con_credit = state["con_pid"], state["con_credit"]
        n_dropped = state["n_dropped"]

        size = tail - head
        empty = size <= 0
        checked = jnp.sum((co_id >= 0).astype(_I32), axis=-1)
        full = (size + checked) >= Q          # capacity bounds LIVE msgs

        # -- consumer-table resolution (ops 7-11) -------------------------
        cr = jnp.arange(C)
        pid_match = (con_pid == a[..., None]) & (a[..., None] >= 0)
        pid_found = jnp.any(pid_match, axis=-1)
        pid_slot = jnp.argmax(pid_match, axis=-1).astype(_I32)
        con_free = con_pid < 0
        have_con_free = jnp.any(con_free, axis=-1)
        free_con_slot = jnp.argmax(con_free, axis=-1).astype(_I32)

        # -- enqueue -------------------------------------------------------
        drop_head = self.overflow == "drop_head"
        enq_ok = (op == 1) & ~full
        enq_drop = ((op == 1) & full & (size > 0)) if drop_head \
            else jnp.zeros_like(enq_ok)
        enq = enq_ok | enq_drop
        tail_slot = jnp.mod(tail, Q)
        n_dropped = n_dropped + enq_drop.astype(_I32)

        # -- dequeue (settled / unsettled / consumer checkout) ------------
        head_slot = jnp.mod(head, Q)
        head_val = _take(buf, head_slot)
        head_dc = _take(dc, head_slot)
        head_mid = _take(mid, head_slot)
        free_mask = co_id < 0                              # [..., K]
        have_free = jnp.any(free_mask, axis=-1)
        free_slot = jnp.argmax(free_mask, axis=-1).astype(_I32)
        deq_s = (op == 2) & ~empty
        deq_u = (op == 3) & ~empty & have_free
        owned = (co_id >= 0) & (co_owner == pid_slot[..., None])
        used = jnp.sum(owned.astype(_I32), axis=-1)
        credit = _take(con_credit, pid_slot)
        deq_c = ((op == 10) & pid_found & ~empty & have_free &
                 (used < credit))
        take = deq_u | deq_c
        pop = deq_s | take

        # -- settle / return: locate the checked-out row -------------------
        match = (co_id == a[..., None]) & (a[..., None] >= 0)
        found = jnp.any(match, axis=-1)
        match_slot = jnp.argmax(match, axis=-1).astype(_I32)
        m_val = _take(co_val, match_slot)
        m_dc = _take(co_dc, match_slot)
        m_mid = _take(co_mid, match_slot)
        settle = (op == 4) & found
        # return never overflows: live count is unchanged by a requeue
        ret = (op == 5) & found

        purge = op == 6
        cancel = ((op == 8) | (op == 9)) & pid_found
        req_n = jnp.where(cancel, used, 0)    # messages this cancel requeues

        # -- cursor updates ------------------------------------------------
        head = head + pop.astype(_I32) + enq_drop.astype(_I32)
        head = jnp.where(purge, tail, head)
        new_tail = tail + enq.astype(_I32)

        # -- enqueue ring write -------------------------------------------
        qr = jnp.arange(Q)
        enq_hot = (qr == tail_slot[..., None]) & enq[..., None]
        buf = jnp.where(enq_hot, a[..., None], buf)
        dc = jnp.where(enq_hot, 0, dc)
        mid = jnp.where(enq_hot, next_mid[..., None], mid)
        new_next_mid = next_mid + enq.astype(_I32)

        # -- unified requeue merge (op-5 return AND cancel/down) ----------
        # Source rows: the returned row, or every row owned by the
        # canceled consumer.  Each lands at its global ticket rank in
        # the merged window (host _return_entries sorted rebuild); ready
        # entries shift back by the number of requeued tickets below
        # them.  One rank computation + one gather per array — O(Q*K)
        # comparisons, no sequential loop (a masked-per-row fori_loop
        # was ~9x this cost and ran for EVERY command).  The whole merge
        # sits behind a lax.cond: its [..., K, Q] intermediates dominate
        # the apply (~25x on TPU at Q=256) yet are dead work for every
        # command that is not a return/cancel/down — the common case.
        kr = jnp.arange(K)
        req = (cancel[..., None] & owned) | \
            (ret[..., None] & (kr == match_slot[..., None]))
        n_req = jnp.sum(req.astype(_I32), axis=-1)
        new_head = head - n_req

        def _requeue_merge(ops):
            buf, dc, mid, co_val, co_dc, co_mid = ops
            size2 = new_tail - head
            in_win = jnp.mod(qr - head[..., None], Q) < size2[..., None]
            # rank over ready mids [...,K,Q] + fellow requeues [...,K,K]
            rank = jnp.sum((in_win[..., None, :] &
                            (mid[..., None, :] < co_mid[..., :, None]))
                           .astype(_I32), axis=-1)
            rank = rank + jnp.sum(
                (req[..., None, :] &
                 (co_mid[..., None, :] < co_mid[..., :, None]))
                .astype(_I32), axis=-1)
            rank = jnp.where(req, rank, -1)      # inactive rows never land
            jd = jnp.mod(qr - new_head[..., None], Q)        # [..., Q]
            valid = jd < (size2 + n_req)[..., None]
            eq = rank[..., :, None] == jd[..., None, :]      # [..., K, Q]
            land = jnp.any(eq, axis=-2)
            req_val_at = jnp.sum(jnp.where(eq, co_val[..., :, None], 0),
                                 axis=-2)
            req_dc_at = jnp.sum(jnp.where(eq, (co_dc + 1)[..., :, None], 0),
                                axis=-2)
            req_mid_at = jnp.sum(jnp.where(eq, co_mid[..., :, None], 0),
                                 axis=-2)
            cnt_lt = jnp.sum(((rank[..., :, None] >= 0) &
                              (rank[..., :, None] < jd[..., None, :]))
                             .astype(_I32), axis=-2)
            src_slot = jnp.mod(head[..., None] + jd - cnt_lt, Q)
            g_buf = jnp.take_along_axis(buf, src_slot, axis=-1)
            g_dc = jnp.take_along_axis(dc, src_slot, axis=-1)
            g_mid = jnp.take_along_axis(mid, src_slot, axis=-1)
            buf = jnp.where(valid, jnp.where(land, req_val_at, g_buf), buf)
            dc = jnp.where(valid, jnp.where(land, req_dc_at, g_dc), dc)
            mid = jnp.where(valid, jnp.where(land, req_mid_at, g_mid), mid)
            return buf, dc, mid

        buf, dc, mid = cond_concrete(
            jnp.any(n_req > 0), _requeue_merge, lambda ops: ops[:3],
            (buf, dc, mid, co_val, co_dc, co_mid))
        head = new_head

        # -- checkout-table writes ----------------------------------------
        take_hot = (kr == free_slot[..., None]) & take[..., None]
        rel_hot = (kr == match_slot[..., None]) & (settle | ret)[..., None]
        co_val = jnp.where(take_hot, head_val[..., None], co_val)
        co_dc = jnp.where(take_hot, head_dc[..., None], co_dc)
        co_mid = jnp.where(take_hot, head_mid[..., None], co_mid)
        co_owner = jnp.where(
            take_hot,
            jnp.where(deq_c, pid_slot, jnp.full_like(pid_slot, C))[..., None],
            co_owner)
        co_id = jnp.where(take_hot, next_id[..., None], co_id)
        co_id = jnp.where(rel_hot | (cancel[..., None] & owned), -1, co_id)
        new_next_id = next_id + take.astype(_I32)

        # -- consumer attach / credit / cancel ----------------------------
        attach_ok = (op == 7) & (pid_found | have_con_free)
        attach_slot = jnp.where(pid_found, pid_slot, free_con_slot)
        attach_hot = (cr == attach_slot[..., None]) & attach_ok[..., None]
        setc = (op == 11) & pid_found
        setc_hot = (cr == pid_slot[..., None]) & setc[..., None]
        con_pid = jnp.where(attach_hot, a[..., None], con_pid)
        con_credit = jnp.where(attach_hot | setc_hot, b[..., None],
                               con_credit)
        cancel_hot = (cr == pid_slot[..., None]) & cancel[..., None]
        con_pid = jnp.where(cancel_hot, -1, con_pid)

        # -- reply ---------------------------------------------------------
        reply = jnp.where(op == 1, jnp.where(enq, 1, -2), 0)
        reply = jnp.where(op == 2, jnp.where(deq_s, head_val, -1), reply)
        reply = jnp.where(op == 3,
                          jnp.where(deq_u, next_id,
                                    jnp.where(empty, -1, -3)), reply)
        reply = jnp.where(op == 4, settle.astype(_I32), reply)
        reply = jnp.where(op == 5, ret.astype(_I32), reply)
        reply = jnp.where(op == 6, size, reply)
        reply = jnp.where(op == 7, jnp.where(attach_ok, 1, -4), reply)
        reply = jnp.where((op == 8) | (op == 9), req_n, reply)
        reply = jnp.where(
            op == 10,
            jnp.where(deq_c, next_id,
                      jnp.where(~pid_found, -4,
                                jnp.where(empty, -1,
                                          jnp.where(used >= credit, -5,
                                                    -3)))), reply)
        reply = jnp.where(op == 11, setc.astype(_I32), reply)

        new_state = {"buf": buf, "dc": dc, "mid": mid, "head": head,
                     "tail": new_tail, "co_id": co_id, "co_val": co_val,
                     "co_dc": co_dc, "co_mid": co_mid,
                     "co_owner": co_owner, "con_pid": con_pid,
                     "con_credit": con_credit, "next_id": new_next_id,
                     "next_mid": new_next_mid, "n_dropped": n_dropped}
        return new_state, reply

    # -- one-shot window fold (engine batch path) --------------------------
    #
    # supports_batch_apply is True NOT because queue ops commute (they do
    # not) but because a window whose commands are all noop/enqueue/
    # dequeue-settled — the ra_bench workload shape and the common
    # quorum-queue steady state — folds in one vectorized pass:
    #
    #   * the ready-size recurrence  s' = clamp(s + d, 0, Qeff)  is a
    #     composition of clamped-add maps  x -> clamp(x+a, lo, hi),
    #     a family closed under composition, so a log-depth
    #     lax.associative_scan yields every command's pre-state;
    #   * ring positions are exclusive cumsums of the admit/pop flags;
    #   * ring writes are scatter-free: positional wheres plus one
    #     exact one-hot matmul for the payload values (see the
    #     _batch_fast comment — TPU's scatter lowering was ~70ms/step
    #     here, the matmul form ~3ms).
    #
    # Windows containing any consumer/settlement op fall back to
    # sequential_window_fold (an in-order masked lax.scan of jit_apply)
    # under the same lax.cond.  The engine discards per-command replies
    # on this path (lockstep.py step 5), so the fold only has to
    # produce the new state.
    #
    # Measured on TPU v5e, 5,000 lanes x 5 members, Q=256, window 130.
    # Before this fold existed, the engine's representative-scan branch
    # (supports_batch_apply=False) paid the [K,Q] requeue merge on
    # every command and ran 5.42 s/step (0.12M cmds/s) even on a pure
    # enqueue/dequeue workload.  Now: the vectorized fast path runs
    # ~0.026 s/step (~25M cmds/s) on that workload, and the fallback
    # scan ~0.50 s/step on a worst-case consumer-mix window (~10x the
    # old branch, despite folding per member, because the lax.cond
    # inside jit_apply pays the requeue merge only on the commands
    # that actually return/cancel).

    def jit_apply_batch(self, meta, commands, mask, state):
        # fast only for noop/enqueue/dequeue-settled windows.
        # DEMOTION CLIFF: this gate is all-or-nothing per window — one
        # consumer/settlement op (opcode > 2) anywhere in the window
        # demotes the WHOLE window to the sequential fold, a measured
        # ~19x step cost (~0.026s -> ~0.50s at 5k lanes; docs/
        # BENCHMARKS.md "demotion cliff").  Throughput therefore scales
        # with the fraction of CLEAN windows, not the per-op mix —
        # callers who can batch consumer ops into dedicated windows
        # keep the fast path for the rest.
        fast_ok = ~jnp.any(mask & (commands[..., 0] > 2))
        return self.window_fold_dispatch(meta, commands, mask, state,
                                         fast_ok)

    def _batch_fast(self, commands, mask, state):
        """Vectorized noop/enqueue/dequeue-settled window fold."""
        Q = self.capacity
        BIG = jnp.int32(1 << 20)
        op = jnp.where(mask, commands[..., 0], 0)           # [..., A]
        val = commands[..., 1]
        head, tail = state["head"], state["tail"]           # [...]
        checked = jnp.sum((state["co_id"] >= 0).astype(_I32), axis=-1)
        qeff = Q - checked                                  # live-msg room
        size0 = tail - head

        is_enq = op == 1
        is_deq = op == 2
        # clamped-add element per command: enqueue tops out at qeff
        # (reject AND drop_head both leave the ready size pinned there),
        # dequeue floors at 0, noop is the identity.
        a_el = is_enq.astype(_I32) - is_deq.astype(_I32)
        lo_el = jnp.broadcast_to(jnp.int32(0), a_el.shape)
        hi_el = jnp.where(is_enq, qeff[..., None], Q)

        def combine(c1, c2):                     # c2 AFTER c1
            a1, l1, h1 = c1
            a2, l2, h2 = c2
            return (a1 + a2,
                    jnp.clip(l1 + a2, l2, h2),
                    jnp.clip(h1 + a2, l2, h2))

        a_in, lo_in, hi_in = lax.associative_scan(
            combine, (a_el, lo_el, hi_el), axis=-1)
        # exclusive prefix: command i sees the composition of 0..i-1
        ident = (jnp.zeros_like(a_el[..., :1]),
                 jnp.full_like(a_el[..., :1], -BIG),
                 jnp.full_like(a_el[..., :1], BIG))
        a_ex = jnp.concatenate([ident[0], a_in[..., :-1]], axis=-1)
        lo_ex = jnp.concatenate([ident[1], lo_in[..., :-1]], axis=-1)
        hi_ex = jnp.concatenate([ident[2], hi_in[..., :-1]], axis=-1)
        s = jnp.clip(size0[..., None] + a_ex, lo_ex, hi_ex)  # pre-cmd size

        drop_head = self.overflow == "drop_head"
        at_cap = s >= qeff[..., None]
        if drop_head:
            enq_adm = is_enq & (~at_cap | (s > 0))
            enq_drop = is_enq & at_cap & (s > 0)
        else:
            enq_adm = is_enq & ~at_cap
            enq_drop = jnp.zeros_like(enq_adm)
        deq_ok = is_deq & (s > 0)
        head_adv = deq_ok.astype(_I32) + enq_drop.astype(_I32)

        w_rank = jnp.cumsum(enq_adm.astype(_I32), axis=-1) \
            - enq_adm.astype(_I32)                           # exclusive
        n_enq = jnp.sum(enq_adm.astype(_I32), axis=-1)

        # Ring writes WITHOUT a scatter (TPU scatter lowering costs
        # ~70ms/step at this scale; this form ~3ms): written slots are
        # ring indexes tail0..tail0+n_enq-1, so a slot's window offset
        # jd = (q - tail0) mod Q says everything positional — dc is 0
        # and the enqueue tickets are CONSECUTIVE in ring order, so
        # only buf needs real value placement: an exact one-hot matmul
        # (ops/exact.py place16) contracting the admitted-enqueue rank
        # one-hot against the payload column on the MXU.
        #
        # Windows WIDER than the queue (A > Q) are fine: when several
        # admitted enqueues alias one slot mod Q, only the LAST can
        # survive (its predecessors were dequeued within the window —
        # the live count never exceeds Q — and pops read nothing on
        # this reply-free path), so each slot selects the maximal
        # aliasing rank rank_win = jd + Q*floor((n_enq-1-jd)/Q), which
        # degenerates to jd when A <= Q.
        qr2 = jnp.arange(Q)
        jd = jnp.mod(qr2 - tail[..., None], Q)               # [..., Q]
        written = jd < n_enq[..., None]
        rank_win = jd + Q * ((n_enq[..., None] - 1 - jd) // Q)
        onehot = (enq_adm[..., None, :] &
                  (w_rank[..., None, :] == rank_win[..., None])
                  ).astype(jnp.float32)                      # [..., Q, A]
        placed = place16(onehot, val)

        new_state = dict(state)
        new_state["buf"] = jnp.where(written, placed, state["buf"])
        new_state["dc"] = jnp.where(written, 0, state["dc"])
        new_state["mid"] = jnp.where(
            written, state["next_mid"][..., None] + rank_win,
            state["mid"])
        new_state["head"] = head + jnp.sum(head_adv, axis=-1)
        new_state["tail"] = tail + n_enq
        new_state["next_mid"] = state["next_mid"] + n_enq
        new_state["n_dropped"] = state["n_dropped"] + \
            jnp.sum(enq_drop.astype(_I32), axis=-1)
        return new_state

    # -- host protocol -----------------------------------------------------

    def encode_command(self, command):
        try:
            if isinstance(command, tuple) and command:
                kind = command[0]
                if kind == "enqueue" and len(command) == 2:
                    v = int(command[1])
                    if v >= 0:
                        return jnp.asarray([1, v, 0], _I32)
                elif kind == "dequeue" and len(command) == 2:
                    if command[1] == "settled":
                        return jnp.asarray([2, 0, 0], _I32)
                    if command[1] == "unsettled":
                        return jnp.asarray([3, 0, 0], _I32)
                elif kind == "settle" and len(command) == 2:
                    return jnp.asarray([4, int(command[1]), 0], _I32)
                elif kind == "return" and len(command) == 2:
                    return jnp.asarray([5, int(command[1]), 0], _I32)
                elif kind == "purge":
                    return jnp.asarray([6, 0, 0], _I32)
                elif kind == "attach" and len(command) == 3:
                    return jnp.asarray([7, int(command[1]),
                                        int(command[2])], _I32)
                elif kind == "cancel" and len(command) == 2:
                    return jnp.asarray([8, int(command[1]), 0], _I32)
                elif kind == "down" and len(command) == 2:
                    return jnp.asarray([9, int(command[1]), 0], _I32)
                elif kind == "checkout" and len(command) == 2:
                    return jnp.asarray([10, int(command[1]), 0], _I32)
                elif kind == "credit" and len(command) == 3:
                    return jnp.asarray([11, int(command[1]),
                                        int(command[2])], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((3,), _I32)

    def decode_reply(self, reply) -> int:
        return int(reply)


def query_depth(state) -> int:
    """Ready-message count (host-path query fun)."""
    return int(state["tail"]) - int(state["head"])


def query_checked_out(state) -> int:
    import numpy as np
    return int((np.asarray(state["co_id"]) >= 0).sum())


def query_consumers(state) -> int:
    import numpy as np
    return int((np.asarray(state["con_pid"]) >= 0).sum())


def query_dropped(state) -> int:
    return int(state["n_dropped"])
