"""JitFifoMachine — the FIFO capability machine on the device apply path.

The host :class:`~ra_tpu.models.fifo.FifoMachine` mirrors the reference's
``test/ra_fifo.erl`` (1,520 LoC) with unbounded Python state, consumer
processes, and delivery effects.  That shape cannot fold on-device.  This
machine is the TPU-native counterpart for the BASELINE.md "5,000 clusters
x 5 members, fifo machine, enqueue/dequeue" row: a **fixed-capacity**
per-lane queue whose state is a handful of dense arrays, covering the
core ra_fifo verbs — ordered enqueue, settled and unsettled dequeue,
settlement, return-with-redelivery-count, and purge
(ra_fifo.erl apply clauses :254-368) — as a shape-stable ``lax.scan``
fold (order matters, so ``supports_batch_apply = False``).

State (leading lane axis added by ``jit_init``; the engine broadcasts a
member axis):

* ``buf/dc/mid int32[Q]`` — ready-message ring: payload value, delivery
  count, and enqueue ticket (the host machine's ``msg_in_id``)
* ``head/tail int32`` — ready window is ``head..tail-1`` (slot = idx % Q)
* ``co_id/co_val/co_dc/co_mid int32[K]`` — checked-out (unsettled) table;
  ``co_id < 0`` marks a free row
* ``next_id int32`` — monotonic message-id source for unsettled dequeues
* ``next_mid int32`` — monotonic enqueue-ticket source

Command encoding (command_spec int32[2]): ``[op, arg]``

  op 0 noop                       (term-opening entry)
  op 1 enqueue(value)             reply  1 ok | -2 queue full
  op 2 dequeue settled            reply  value | -1 empty
  op 3 dequeue unsettled          reply  msg_id | -1 empty | -3 table full
  op 4 settle(msg_id)             reply  1 | 0 unknown id
  op 5 return(msg_id)             reply  1 | 0 unknown id or queue full
  op 6 purge                      reply  number of ready messages dropped

A returned message re-enters the ready window at its **original enqueue
position** relative to the other ready messages (sorted insert by
ticket), exactly like the host machine's sorted re-insert
(fifo.py ``_return_entries``), with delivery_count+1.  The insert is a
masked ``roll`` of the window prefix — shape-stable, O(Q) VPU work.
Payload values must be >= 0 so they never collide with error replies.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine

_I32 = jnp.int32


def _take(arr, idx):
    return jnp.take_along_axis(arr, idx[..., None], axis=-1)[..., 0]


class JitFifoMachine(JitMachine):
    command_spec = ("int32", (2,))
    reply_spec = ("int32", ())
    version = 0
    supports_batch_apply = False  # queue ops do not commute

    def __init__(self, capacity: int = 64, checkout_slots: int = 8) -> None:
        self.capacity = capacity
        self.checkout_slots = checkout_slots

    def jit_init(self, n_lanes: int):
        N, Q, K = n_lanes, self.capacity, self.checkout_slots
        return {
            "buf": jnp.zeros((N, Q), _I32),
            "dc": jnp.zeros((N, Q), _I32),
            "mid": jnp.zeros((N, Q), _I32),
            "head": jnp.zeros((N,), _I32),
            "tail": jnp.zeros((N,), _I32),
            "co_id": jnp.full((N, K), -1, _I32),
            "co_val": jnp.zeros((N, K), _I32),
            "co_dc": jnp.zeros((N, K), _I32),
            "co_mid": jnp.zeros((N, K), _I32),
            "next_id": jnp.zeros((N,), _I32),
            "next_mid": jnp.zeros((N,), _I32),
        }

    def jit_apply(self, meta, command, state):
        Q, K = self.capacity, self.checkout_slots
        op = command[..., 0]
        arg = command[..., 1]
        head, tail = state["head"], state["tail"]
        next_id, next_mid = state["next_id"], state["next_mid"]
        buf, dc, mid = state["buf"], state["dc"], state["mid"]
        co_id, co_val = state["co_id"], state["co_val"]
        co_dc, co_mid = state["co_dc"], state["co_mid"]

        size = tail - head
        empty = size <= 0
        full = size >= Q

        # -- enqueue -------------------------------------------------------
        enq = (op == 1) & ~full
        tail_slot = jnp.mod(tail, Q)

        # -- dequeue (settled / unsettled) --------------------------------
        head_slot = jnp.mod(head, Q)
        head_val = _take(buf, head_slot)
        head_dc = _take(dc, head_slot)
        head_mid = _take(mid, head_slot)
        free_mask = co_id < 0                              # [..., K]
        have_free = jnp.any(free_mask, axis=-1)
        free_slot = jnp.argmax(free_mask, axis=-1).astype(_I32)
        deq_s = (op == 2) & ~empty
        deq_u = (op == 3) & ~empty & have_free
        pop = deq_s | deq_u

        # -- settle / return: locate the checked-out row -------------------
        match = (co_id == arg[..., None]) & (arg[..., None] >= 0)
        found = jnp.any(match, axis=-1)
        match_slot = jnp.argmax(match, axis=-1).astype(_I32)
        m_val = _take(co_val, match_slot)
        m_dc = _take(co_dc, match_slot)
        m_mid = _take(co_mid, match_slot)
        settle = (op == 4) & found
        ret = (op == 5) & found & ~full

        purge = op == 6

        # -- cursor updates ------------------------------------------------
        new_head = head + pop.astype(_I32) - ret.astype(_I32)
        new_head = jnp.where(purge, tail, new_head)
        new_tail = tail + enq.astype(_I32)

        # -- enqueue ring write -------------------------------------------
        qr = jnp.arange(Q)
        enq_hot = (qr == tail_slot[..., None]) & enq[..., None]
        buf = jnp.where(enq_hot, arg[..., None], buf)
        dc = jnp.where(enq_hot, 0, dc)
        mid = jnp.where(enq_hot, next_mid[..., None], mid)
        new_next_mid = next_mid + enq.astype(_I32)

        # -- return: sorted insert by enqueue ticket ----------------------
        # The returned message goes at window position p = number of ready
        # messages with an older ticket; ready entries before p shift one
        # slot toward the (new) front at head-1, entries at/after p stay.
        # For destination slot d with new-window position jd, the shifted
        # content is the old slot d+1 — i.e. roll(-1).
        in_window = jnp.mod(qr - head[..., None], Q) < size[..., None]
        p = jnp.sum((in_window & (mid < m_mid[..., None])).astype(_I32),
                    axis=-1)
        jd = jnp.mod(qr - (head[..., None] - 1), Q)
        rolled_buf = jnp.roll(buf, -1, axis=-1)
        rolled_dc = jnp.roll(dc, -1, axis=-1)
        rolled_mid = jnp.roll(mid, -1, axis=-1)
        shift = ret[..., None] & (jd < p[..., None])
        place = ret[..., None] & (jd == p[..., None])
        buf = jnp.where(place, m_val[..., None],
                        jnp.where(shift, rolled_buf, buf))
        dc = jnp.where(place, (m_dc + 1)[..., None],
                       jnp.where(shift, rolled_dc, dc))
        mid = jnp.where(place, m_mid[..., None],
                        jnp.where(shift, rolled_mid, mid))

        # -- checkout-table writes ----------------------------------------
        kr = jnp.arange(K)
        take_hot = (kr == free_slot[..., None]) & deq_u[..., None]
        rel_hot = (kr == match_slot[..., None]) & (settle | ret)[..., None]
        co_val = jnp.where(take_hot, head_val[..., None], co_val)
        co_dc = jnp.where(take_hot, head_dc[..., None], co_dc)
        co_mid = jnp.where(take_hot, head_mid[..., None], co_mid)
        co_id = jnp.where(take_hot, next_id[..., None], co_id)
        co_id = jnp.where(rel_hot, -1, co_id)
        new_next_id = next_id + deq_u.astype(_I32)

        # -- reply ---------------------------------------------------------
        reply = jnp.where(op == 1, jnp.where(enq, 1, -2), 0)
        reply = jnp.where(op == 2, jnp.where(deq_s, head_val, -1), reply)
        reply = jnp.where(op == 3,
                          jnp.where(deq_u, next_id,
                                    jnp.where(empty, -1, -3)), reply)
        reply = jnp.where(op == 4, settle.astype(_I32), reply)
        reply = jnp.where(op == 5, ret.astype(_I32), reply)
        reply = jnp.where(op == 6, size, reply)

        new_state = {"buf": buf, "dc": dc, "mid": mid, "head": new_head,
                     "tail": new_tail, "co_id": co_id, "co_val": co_val,
                     "co_dc": co_dc, "co_mid": co_mid,
                     "next_id": new_next_id, "next_mid": new_next_mid}
        return new_state, reply

    # -- host protocol -----------------------------------------------------

    def encode_command(self, command):
        try:
            if isinstance(command, tuple) and command:
                kind = command[0]
                if kind == "enqueue" and len(command) == 2:
                    v = int(command[1])
                    if v >= 0:
                        return jnp.asarray([1, v], _I32)
                elif kind == "dequeue" and len(command) == 2:
                    if command[1] == "settled":
                        return jnp.asarray([2, 0], _I32)
                    if command[1] == "unsettled":
                        return jnp.asarray([3, 0], _I32)
                elif kind == "settle" and len(command) == 2:
                    return jnp.asarray([4, int(command[1])], _I32)
                elif kind == "return" and len(command) == 2:
                    return jnp.asarray([5, int(command[1])], _I32)
                elif kind == "purge":
                    return jnp.asarray([6, 0], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((2,), _I32)

    def decode_reply(self, reply) -> int:
        return int(reply)


def query_depth(state) -> int:
    """Ready-message count (host-path query fun)."""
    return int(state["tail"]) - int(state["head"])


def query_checked_out(state) -> int:
    import numpy as np
    return int((np.asarray(state["co_id"]) >= 0).sum())
